#!/usr/bin/env python
"""Throughput regression gate over the committed BENCH_r*.json trajectory.

The BENCH trajectory (r01 → r05: 3.44M → 11.18M step pairs/s) is the repo's
perf ground truth, but until now nothing CHECKED a fresh bench line against
it — a regression would land silently and surface rungs later as "huh, r06
is slower". This gate compares a fresh ``bench.py`` JSON line against the
committed trajectory with EXPLICIT per-metric tolerance bands and fails
loudly when a gated metric falls below band.

Gate rule, per metric: ``new >= (1 - band) * latest_rung`` — the latest
committed rung is the CURRENT claim a fresh line must hold. The historical
best is reported beside it as an advisory ``drift_from_best`` (the
committed trajectory itself is not monotonic: r03's f32 step row beats
r05's by ~12% — a real drift the rungs absorbed while the headline moved
to bf16 — so gating on the all-time best would fail the genuine current
line; the advisory keeps that drift visible instead of burying it).

Tolerance-band provenance (docs/observability.md has the full table): the
bands come from the measured trial spread of the bench harness itself —
bench.py step rows report min/median/max over 3 interleaved trials
(BENCH r04+), where the committed rungs show up to ~6% median-to-min spread
on the step metrics and wider spread on the e2e row (host-pipeline noise,
PERF.md §3/§5). Bands are set ≥ 2x the observed spread so the gate fires on
regressions, not on weather; tighten them on a quieter host, in the JSON,
with provenance.

Modes::

    python tools/perfgate.py --bench fresh_bench.json   # gate a real run
    python tools/perfgate.py --smoke                    # self-test (CI)

``--smoke`` is machine-independent (CI containers cannot reproduce
capable-host numbers): it proves the GATE works — the genuine latest
committed rung must pass against the trajectory, and a seeded regression
(every gated metric scaled by --seed-factor, default 0.7 — below every
band) must fire. A
real ``--bench`` run belongs on the host class the baselines came from.

Prints exactly ONE JSON line on stdout (graftlint R7); chatter to stderr.
Exit 0 iff the gate holds (or, under --smoke, iff genuine-passes AND
seeded-fires).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# gated metric -> tolerance band (fraction below trajectory-best tolerated).
# Provenance: >= 2x the observed cross-trial/cross-rung spread (module doc).
GATED: Dict[str, float] = {
    # headline single-chip step throughput; step_trials_ms spread <= ~6%
    "value": 0.12,
    # f32 step twin, same harness
    "step_f32_pairs_per_sec": 0.12,
    # e2e trainer row folds the host pipeline in — noisier (PERF.md §5)
    "e2e_pairs_per_sec": 0.25,
    # large-vocab step row (scatter-bound regime)
    "v1m_step_pairs_per_sec": 0.15,
    # CBOW step row
    "cbow_examples_per_sec": 0.20,
    # --- ISSUE-14 restructured step rows (gated only once a rung carries
    # them — r01-r05 predate the knobs). Same harness/trial structure as
    # the step rows above, so the same 0.12 band; the hot-row arm adds the
    # slab-scan/flush structure whose relative cost is geometry-sensitive,
    # hence the step-row-widest 0.15 ---
    "step_fused_pairs_per_sec": 0.12,
    "step_bf16_chain_pairs_per_sec": 0.12,
    "step_hotrow_pairs_per_sec": 0.15,
    # --- flat per-row scalars (ISSUE 17 satellite): bench.py now emits one
    # `step_<row>_pairs_per_sec` per step row as a top-level scalar, the
    # PREFERRED gate names going forward — every step row gets gated by a
    # stable flat name instead of only the hand-picked subset above.
    # _load_parsed back-fills them for older rungs from the legacy aliases
    # (same harness, same number), so history exists from r04 on. Bands
    # mirror the per-row counterparts; the `step_<row>_step_ms` flats ride
    # in the bench line for dashboards but are NOT gated here (the gate
    # rule is higher-is-better) ---
    "step_f32_p512_pairs_per_sec": 0.12,
    "step_bf16_p512_pairs_per_sec": 0.12,
    "step_bf16_p1024_pairs_per_sec": 0.12,
    "step_bf16_fused_pairs_per_sec": 0.12,
    "step_bf16_hot_pairs_per_sec": 0.15,
}

# legacy top-level name -> flat per-row name (back-fill for rungs that
# predate the flats; the pairs are the SAME measurement, so aliasing is
# honest). bf16_chain already used the flat-style name, so it needs no alias.
_FLAT_ALIASES = {
    "step_f32_pairs_per_sec": "step_f32_p512_pairs_per_sec",
    "step_fused_pairs_per_sec": "step_bf16_fused_pairs_per_sec",
    "step_hotrow_pairs_per_sec": "step_bf16_hot_pairs_per_sec",
}

# the SERVING trajectory's bands (--kind serve, SERVEBENCH_r*.json from
# tools/servebench.py — ISSUE 10). All higher-is-better, same gate rule.
# Thread-scheduling noise on closed/offered-loop latency arms is wider than
# the step benches', hence the looser throughput bands; recall is a
# deterministic property of (matrix, seed, nprobe), so its band is tight —
# a recall drop means the index or its auto rules changed, not weather.
SERVE_GATED: Dict[str, float] = {
    # closed-loop ANN capacity (qps) through the full service path
    "ann_qps": 0.30,
    # the acceptance headline: exact per-query p50 / ANN operating-point p50
    "ann_speedup_p50": 0.35,
    # oracle-checked index recall at the auto operating point
    "ann_recall_at_10": 0.03,
    # highest offered load with < 1% refusals
    "offered_qps_sustained": 0.30,
    # --- fleet tier (ISSUE 12, servebench --fleet; gated only once a rung
    # carries them — r01 predates the fleet). Router-path N=3 ANN capacity,
    # and the hedge A/B's p99 cut under the injected straggler (off/on
    # ratio, higher is better; < 1 would mean hedging HURT) ---
    "fleet3_ann_qps": 0.35,
    "fleet_hedge_p99_cut": 0.35,
    # --- quantized arms (ISSUE 18, servebench arm 5; gated only once a
    # rung carries them — r01/r02 predate quantization). qps bands mirror
    # the f32 ANN arm's scheduling noise; recall is deterministic per
    # (matrix, seed, arm) so the bands stay tight — a drop means the
    # quantizer or its auto rules changed, not weather; bytes_cut (f32
    # bytes over quant bytes, higher is better) is a pure layout property,
    # tightest of all ---
    "int8_qps": 0.30,
    "pq_qps": 0.35,
    "int8_recall_at_10": 0.03,
    "pq_recall_at_10": 0.05,
    "int8_bytes_cut": 0.05,
    "pq_bytes_cut": 0.05,
    # the acceptance ratio: int8 closed-loop qps over the f32 ANN arm's
    # (both arms measured in the same process minutes apart, so the band
    # can be tighter than either qps alone)
    "int8_qps_ratio": 0.25,
}


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _load_parsed(path: str) -> dict:
    """A bench JSON: either the raw one-line bench.py output (the metric
    dict itself) or a driver capture wrapping it under 'parsed'. Back-fills
    the flat per-row scalars for rungs that predate them (BENCH r04-r06):
    legacy aliases are the same measurement under an older name, and
    `step_<row>_step_ms` is the nested trial median — so the aliased flat
    gates have history instead of silently skipping every old rung. Rows
    that never had a top-level name (bf16_p512/bf16_p1024) start gating at
    the first rung that carries the flats, like the ISSUE-14 rows did."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    doc = doc.get("parsed", doc)
    for old, new in _FLAT_ALIASES.items():
        if doc.get(new) is None and doc.get(old) is not None:
            doc[new] = doc[old]
    trials = doc.get("step_trials_ms") or {}
    for k, st in trials.items():
        if isinstance(st, dict) and st.get("ms_median") is not None:
            doc.setdefault(f"step_{k}_step_ms", st["ms_median"])
    return doc


def load_trajectory(pattern: str) -> List[dict]:
    paths = sorted(glob.glob(pattern))
    rungs = []
    for p in paths:
        try:
            parsed = _load_parsed(p)
        except (OSError, json.JSONDecodeError) as e:
            log(f"skipping unreadable baseline {p}: {e}")
            continue
        rungs.append({"path": os.path.basename(p), "parsed": parsed})
    return rungs


def gate(new: dict, rungs: List[dict],
         bands: Optional[Dict[str, float]] = None) -> dict:
    """Compare one fresh parsed bench dict against the trajectory. Metrics
    absent from the new line are reported (a vanished metric is itself
    suspicious) but only gated when at least one rung carries them."""
    bands = bands or GATED
    metrics = {}
    ok = True
    for name, band in bands.items():
        # None-valued metrics are treated as absent: servebench emits null
        # for legitimately unmeasurable values (recall below 11 rows, p50 of
        # an empty offered row) — the gate must FAIL on them with a report,
        # not crash on float(None) past the R7 one-JSON-line contract
        history = [(r["path"], float(r["parsed"][name]))
                   for r in rungs
                   if r["parsed"].get(name) is not None]
        if not history:
            continue
        ref_path, ref = history[-1]           # the latest rung: the claim
        best_path, best = max(history, key=lambda kv: kv[1])
        floor = (1.0 - band) * ref
        entry = {"ref": ref, "ref_rung": ref_path, "band": band,
                 # 4 decimals: serving gates fractional metrics (recall)
                 # where 1-decimal display rounded the floor to 1.0
                 "floor": round(floor, 4),
                 # advisory: how far the current claim itself sits below the
                 # all-time best (non-monotonic trajectory drift)
                 "best": best, "best_rung": best_path,
                 "drift_from_best": round(1.0 - ref / best, 4)}
        if new.get(name) is None:
            metrics[name] = {**entry, "new": None, "ok": False,
                             "why": "metric missing/null in the fresh line"}
            ok = False
            continue
        val = float(new[name])
        passed = val >= floor
        metrics[name] = {**entry, "new": val,
                         "ratio_to_ref": round(val / ref, 4), "ok": passed}
        ok = ok and passed
    return {"ok": ok, "metrics": metrics,
            "rungs": [r["path"] for r in rungs]}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--bench", default="",
                    help="fresh bench.py/servebench.py JSON (raw line or "
                         "driver capture) to gate against the trajectory")
    ap.add_argument("--kind", choices=["train", "serve"], default="train",
                    help="which trajectory/bands: 'train' = bench.py vs "
                         "BENCH_r*.json (GATED), 'serve' = servebench.py vs "
                         "SERVEBENCH_r*.json (SERVE_GATED)")
    ap.add_argument("--baselines", default="",
                    help="glob of committed trajectory rungs (default "
                         "derives from --kind)")
    ap.add_argument("--smoke", action="store_true",
                    help="machine-independent self-test: the genuine latest "
                         "rung must pass, a seeded regression must fire")
    ap.add_argument("--seed-factor", type=float, default=0.7,
                    help="--smoke: scale factor of the seeded regression "
                         "(must sit below every band to prove firing)")
    args = ap.parse_args()

    result, rc = _run(args)
    print(json.dumps(result))  # the ONE stdout line (graftlint R7)
    return rc


def _run(args) -> tuple:
    """All modes funnel through here so main() keeps exactly one
    ``print(json.dumps(...))`` (the R7 stdout contract)."""
    bands = SERVE_GATED if args.kind == "serve" else GATED
    if not args.baselines:
        args.baselines = os.path.join(
            _REPO, "SERVEBENCH_r*.json" if args.kind == "serve"
            else "BENCH_r*.json")
    rungs = load_trajectory(args.baselines)
    # the serving trajectory legitimately starts at one rung (r01 is the
    # subsystem's birth); the training trajectory predates the gate and
    # must never regress to a single readable rung
    min_rungs = 1 if args.kind == "serve" else 2
    if len(rungs) < min_rungs:
        return {"ok": False,
                "error": f"need >= {min_rungs} baseline rungs at "
                         f"{args.baselines}, found {len(rungs)}"}, 2

    if args.smoke:
        genuine = rungs[-1]["parsed"]
        g = gate(genuine, rungs, bands)
        seeded = {k: float(genuine[k]) * args.seed_factor
                  for k in bands if genuine.get(k) is not None}
        s = gate(seeded, rungs, bands)
        fired_on = sorted(k for k, m in s["metrics"].items()
                          if not m["ok"])
        # the recall gates specifically must prove they fire (ISSUE 18):
        # a seeded RECALL regression is the silent-degradation failure
        # mode the quantized arms exist to refuse, so whenever the rungs
        # carry a recall metric, the seeded line must trip at least one
        recall_carried = sorted(
            k for k in bands if "recall" in k
            and any(r["parsed"].get(k) is not None for r in rungs))
        recall_fired = sorted(set(fired_on)
                              & set(recall_carried))
        recall_ok = not recall_carried or bool(recall_fired)
        result = {
            # the gate is proven iff the real current line is inside band
            # AND the seeded regression trips it (including its recall
            # gates, when the trajectory carries any)
            "ok": bool(g["ok"] and not s["ok"] and recall_ok),
            "mode": "smoke",
            "kind": args.kind,
            "genuine": {"rung": rungs[-1]["path"], "ok": g["ok"],
                        "metrics": g["metrics"]},
            "seeded": {"factor": args.seed_factor, "ok": s["ok"],
                       "fired_on": fired_on,
                       "recall_fired": recall_fired},
            "rungs": g["rungs"],
        }
        log(f"perfgate --smoke: genuine {rungs[-1]['path']} "
            f"{'PASS' if g['ok'] else 'FAIL'}; seeded x{args.seed_factor} "
            f"{'fired on ' + ','.join(fired_on) if fired_on else 'DID NOT FIRE'}"
            + (f"; recall gates fired: {','.join(recall_fired) or 'NONE'}"
               if recall_carried else ""))
        return result, 0 if result["ok"] else 1

    if not args.bench:
        return {"ok": False,
                "error": "pass --bench FRESH.json or --smoke"}, 2
    try:
        new = _load_parsed(args.bench)
    except (OSError, json.JSONDecodeError) as e:
        return {"ok": False,
                "error": f"unreadable --bench {args.bench}: {e}"}, 2
    result = gate(new, rungs, bands)
    result["mode"] = "gate"
    result["kind"] = args.kind
    result["bench"] = args.bench
    for name, m in result["metrics"].items():
        log(f"perfgate {name}: new {m['new']} vs ref {m['ref']} "
            f"({m['ref_rung']}), floor {m['floor']} -> "
            f"{'ok' if m['ok'] else 'REGRESSION'}")
    return result, 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
