"""Round-5 step A/B: trimming the NON-scatter ~40% of the stable bf16 step.

PERF.md §4's cost model says the two B-row scatters are the floor (~4.2 ms at
B=64k bf16) and everything else — gathers, pool matmuls, the [B,P] logit chain,
the loss reduction — is the remaining ~2.1 ms. The VERDICT r4 target is a
bf16 B=64k/pool=512 step at ~5 ms. Variants (all identical update math; only
metric/loss side-channels differ where named):

    shipped        — sgns_step_shared_core, bf16 params/compute/logits
    nometrics      — update math only, loss/metrics skipped entirely: the
                     UPPER BOUND of what metric elision can buy
    lastloss       — full metrics on the LAST step of the K-step scan only
                     (the production candidate: heartbeat telemetry needs one
                     loss sample per dispatch, not K)
    pos-loss       — per-step loss from the positive term only (a [B] chain);
                     the [B,P] negative loss pass skipped
    fused          — nometrics + the g_neg chain restructured into one where()
                     expression (alpha·n/P folded to one scalar, no separate
                     neg_valid array) — tests whether XLA's fusion already got
                     this (expect ~no delta)

Scatter-drop probe (gates the hot-row-carry design, VERDICT r4 item 2): pure
scatter-adds at the production shape where the rows hitting the top-H vocab ids
are redirected OOB (mode=drop). If dropped rows cost full emitter time (the §3
claim, measured at 50% uniform drops), a dense hot-row accumulator can never
pay for itself — the cold scatter still processes B rows.

Run: python tools/step_lean.py [--b 65536] [--pool 512] [--repeats 3]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V, D, NEG, K = 200_000, 384, 5, 16


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--b", type=int, default=65536)
    ap.add_argument("--pool", type=int, default=512)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--skip-probe", action="store_true")
    ap.add_argument("--probe-only", action="store_true")
    args = ap.parse_args()
    B, P = args.b, args.pool

    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, _log_sigmoid, _sigmoid, init_embeddings,
        sgns_step_shared_core)

    dt = jnp.bfloat16
    print(f"device: {jax.devices()[0]}  bf16 B={B} pool={P}", file=sys.stderr)

    rng = np.random.default_rng(0)
    counts = np.maximum(1e9 / (np.arange(V) + 10.0) ** 1.07, 5.0)
    p = counts / counts.sum()
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    syn0_0 = init_embeddings(V, D, jax.random.key(0)).syn0.astype(dt)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (V, D)), dt)

    batches = []
    for i in range(12):
        r = np.random.default_rng(1000 + i)
        batches.append({
            "centers": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "contexts": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "mask": jnp.ones((K, B), jnp.float32),
        })

    ALPHA = 0.025

    def updates(syn0, syn1, centers, contexts, mask, negatives, fused=False):
        """The shared update math (bf16 end to end), returning the three deltas
        plus the logit arrays the loss variants may consume."""
        e_in = syn0[centers]                      # [B, D] bf16
        e_pos = syn1[contexts]
        Z = syn1[negatives]                       # [P, D]
        f_pos = jnp.sum(e_in * e_pos, axis=-1).astype(jnp.float32)
        f_neg = e_in @ Z.T                        # [B, P] bf16 — MXU
        g_pos = ((1.0 - _sigmoid(f_pos, "exact")) * ALPHA
                 * mask).astype(dt)               # [B] f32 chain, cast once
        if fused:
            scale = jnp.asarray(ALPHA * NEG / P, dt)
            g_neg = jnp.where(
                (negatives[None, :] != contexts[:, None])
                & (mask[:, None] > 0),
                (0.0 - _sigmoid(f_neg, "exact")) * scale,
                jnp.asarray(0.0, dt))
        else:
            neg_valid = (negatives[None, :] != contexts[:, None]).astype(dt) \
                * mask[:, None].astype(dt)
            g_neg = ((0.0 - _sigmoid(f_neg, "exact"))
                     * jnp.asarray(ALPHA, dt) * neg_valid
                     * jnp.asarray(NEG / P, dt))
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        return d_in, d_pos, d_Z, f_pos, f_neg

    def full_loss(f_pos, f_neg, mask, negatives, contexts):
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(jnp.float32) \
            * mask[:, None]
        return (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg.astype(jnp.float32)) * neg_valid,
                          axis=-1) * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)

    def make_runner(kind):
        def chunk(params, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng, i = inp
                if kind == "shipped":
                    new_p, m = sgns_step_shared_core(
                        s, b["centers"], b["contexts"], b["mask"], ng,
                        jnp.float32(ALPHA), NEG, "exact", dt, False, dt)
                    return new_p, m.loss
                syn0, syn1 = s
                d_in, d_pos, d_Z, f_pos, f_neg = updates(
                    syn0, syn1, b["centers"], b["contexts"], b["mask"], ng,
                    fused=(kind == "fused"))
                new_syn0 = syn0.at[b["centers"]].add(d_in)
                new_syn1 = syn1.at[b["contexts"]].add(d_pos)
                new_syn1 = new_syn1.at[ng].add(d_Z)
                if kind in ("nometrics", "fused"):
                    loss = jnp.float32(0.0)
                elif kind == "pos-loss":
                    loss = (-_log_sigmoid(f_pos) * b["mask"]).sum() \
                        / jnp.maximum(b["mask"].sum(), 1.0)
                elif kind == "lastloss":
                    loss = jax.lax.cond(
                        i == K - 1,
                        lambda: full_loss(f_pos, f_neg, b["mask"], ng,
                                          b["contexts"]),
                        lambda: jnp.float32(0.0))
                else:
                    raise ValueError(kind)
                return EmbeddingPair(new_syn0, new_syn1), loss

            return jax.lax.scan(body, params,
                                (batch, negs, jnp.arange(K)))

        f = jax.jit(chunk, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (batches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    if not args.probe_only:
        runners = {
            "shipped (bf16/logits-bf16)": make_runner("shipped"),
            "nometrics": make_runner("nometrics"),
            "lastloss (metrics 1/K)": make_runner("lastloss"),
            "pos-loss": make_runner("pos-loss"),
            "fused-gneg": make_runner("fused"),
        }
        times = {k: [] for k in runners}
        for _ in range(args.repeats):
            for name, run in runners.items():
                spc = run()
                times[name].append(spc / K * 1e3)
        print(f"\nlean-step A/B (B={B}, pool={P}, bf16, median of "
              f"{args.repeats} interleaved repeats):", file=sys.stderr)
        for name, ts in times.items():
            med = float(np.median(ts))
            print(f"  {name:28s} median {med:7.3f} ms/step  "
                  f"[{min(ts):7.3f} .. {max(ts):7.3f}]  "
                  f"{B / (med / 1e3):13,.0f} pairs/s", file=sys.stderr)

    if args.skip_probe:
        return

    # ---- scatter-drop probe: do OOB-dropped rows cost emitter time? ----------
    # Redirect the rows whose target id < H (the Zipf-hot head) to V (dropped).
    # If the emitter charged per APPLIED row, the dropped variants would speed
    # up by the hot-row share; §3's claim is they do not.
    print("\nscatter-drop probe (pure scatter-add, [B,D] bf16 updates, "
          "Zipf indices):", file=sys.stderr)
    # ONE [B, D] update array, passed as a jit ARGUMENT and reused every scan
    # step — a [K, B, D] closure constant ships inside the remote compile
    # request and breaks the tunnel (the ops/prng.py footgun, relearned here)
    upd = jnp.asarray(rng.normal(0, 1e-4, (B, D)), dt)

    def make_scatter(drop_h, sort=False):
        def chunk(mat, idx, up):
            def body(m, ix):
                return m.at[ix].add(up, mode="drop"), jnp.float32(0)
            return jax.lax.scan(body, mat, idx)

        f = jax.jit(chunk, donate_argnums=(0,))
        idxs = []
        for i in range(12):
            ix = np.asarray(batches[i]["centers"])
            if drop_h:
                ix = np.where(ix < drop_h, V, ix)
            if sort:
                ix = np.sort(ix, axis=-1)
            idxs.append(jnp.asarray(ix, jnp.int32))

        def run():
            return time_chunked(
                f, lambda: syn0_0 + 0,
                lambda i: (idxs[i % 12], upd),
                n_lo=2, n_hi=8,
                # the scan output is constant zeros — the barrier must fetch
                # from the updated carry
                fetch=lambda c, out: c[0, 0].astype(jnp.float32))
        return run

    hot_share = {h: float(np.mean(np.asarray(batches[0]["centers"]) < h))
                 for h in (256, 2048, 16384)}
    probe = {"plain (0% dropped)": make_scatter(0)}
    for h in (256, 2048, 16384):
        probe[f"drop id<{h} ({hot_share[h]:.0%} rows)"] = make_scatter(h)
    probe["drop id<2048, host-sorted"] = make_scatter(2048, sort=True)
    ptimes = {k: [] for k in probe}
    for _ in range(args.repeats):
        for name, run in probe.items():
            spc = run()
            ptimes[name].append(spc / K * 1e3)
    for name, ts in ptimes.items():
        med = float(np.median(ts))
        print(f"  {name:32s} median {med:7.3f} ms  "
              f"[{min(ts):7.3f} .. {max(ts):7.3f}]", file=sys.stderr)


if __name__ == "__main__":
    main()
