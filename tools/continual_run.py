#!/usr/bin/env python
"""Continual-training driver CLI (docs/continual.md): watch an append-only
corpus directory, extend the vocabulary when it drifts, train incremental
fits, and publish each one through the atomic checkpoint-swap signal the
serving tier hot-reloads from — the closed train→serve loop, as a process.

Stdout carries exactly ONE JSON line (graftlint R7 — the driver contract);
human progress goes to stderr.

Usage::

    # drive a real deployment: poll corpus-dir until bounds trip
    python tools/continual_run.py --checkpoint CK --corpus-dir DIR \
        --work-dir WORK [--max-increments N] [--idle-polls N] [--poll-s S]

    # the self-contained end-to-end drill (tier-1 + CI): base fit → corpus
    # append with unseen words → incremental fit grows V (lineage recorded)
    # → publish → a LIVE EmbeddingService hot-reloads and answers a query
    # for a new-vocab word with zero failed queries
    python tools/continual_run.py --smoke

Exit code 0 iff the run (or the drill's every assertion) passed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --- the smoke drill corpus: two co-occurrence clusters, so "neighbors
# intact" is a checkable structure, not a vibe -------------------------------

_CLUSTER_A = [f"a{i}" for i in range(6)]
_CLUSTER_B = [f"b{i}" for i in range(6)]
_NEW_WORDS = ["n0", "n1", "n2"]


def _write_cluster_segment(path: str, n_sentences: int, seed: int,
                           extra_a_words=()) -> None:
    """Sentences drawn from ONE cluster each; ``extra_a_words`` join cluster
    A's draws (the appended segment's unseen words co-occur with A, so the
    drill can check a new word's neighbors land in A)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    a = list(_CLUSTER_A) + list(extra_a_words)
    with open(path, "w", encoding="utf-8") as f:
        for _ in range(n_sentences):
            ws = a if rng.integers(0, 2) == 0 else _CLUSTER_B
            f.write(" ".join(ws[i] for i in rng.integers(0, len(ws), 12))
                    + "\n")


def run_smoke(workdir: str, n_sentences: int = 400) -> dict:
    """The end-to-end drill. Returns the report dict; raises AssertionError
    with a named failure on any broken invariant."""
    import threading

    import numpy as np

    from glint_word2vec_tpu.continual import ContinualRunner
    from glint_word2vec_tpu.serve import EmbeddingService
    from glint_word2vec_tpu.train.checkpoint import load_model_header

    corpus_dir = os.path.join(workdir, "corpus")
    work_dir = os.path.join(workdir, "work")
    ck = os.path.join(workdir, "publish", "ck")
    os.makedirs(corpus_dir, exist_ok=True)
    _write_cluster_segment(
        os.path.join(corpus_dir, "seg-000.txt"), n_sentences, seed=1)

    overrides = dict(
        vector_size=16, min_count=2, window=3, num_iterations=2,
        pairs_per_batch=128, subsample_ratio=0.0, seed=1, prefetch_chunks=0,
        steps_per_dispatch=2, heartbeat_every_steps=4,
        continual_lr_rewarm=0.8, continual_iterations=2)
    runner = ContinualRunner(
        ck, corpus_dir, work_dir, config_overrides=overrides,
        checkpoint_every_steps=8,
        telemetry_path=os.path.join(workdir, "continual.jsonl"))
    base = runner.ensure_base()
    log(f"[smoke] base fit: {base}")
    assert base["action"] == "base", "bootstrap did not run a base fit"
    v_base = base["vocab_size"]

    # the serve replica: watches the SAME publish path the runner writes
    service = EmbeddingService(
        checkpoint=ck, ann=True, watch=True, reload_poll_s=0.05,
        max_batch=16, max_delay_ms=1.0)
    query_errs: list = []
    queries = [0]
    storm_on = threading.Event()
    storm_on.set()

    def storm():
        known = list(_CLUSTER_A) + list(_CLUSTER_B)
        i = 0
        while storm_on.is_set() or i == 0:
            w = known[i % len(known)]
            i += 1
            try:
                res = service.synonyms(w, 4)
                if not res or not all(np.isfinite(s) for _, s in res):
                    query_errs.append(f"bad result for {w!r}: {res}")
            except Exception as e:  # noqa: BLE001 — any raise is a failure
                query_errs.append(f"{w!r}: {type(e).__name__}: {e}")
            queries[0] += 1

    client = threading.Thread(target=storm)
    client.start()
    try:
        # the drift: an appended segment whose unseen words co-occur with
        # cluster A
        _write_cluster_segment(
            os.path.join(corpus_dir, "seg-001.txt"), n_sentences, seed=2,
            extra_a_words=_NEW_WORDS)
        inc = runner.run_once()
        log(f"[smoke] increment: {inc}")
        assert inc["action"] == "increment", "increment did not run"
        assert inc["grew"] and inc["new_words"] >= len(_NEW_WORDS), \
            f"vocab did not grow ({inc})"
        v_new = inc["vocab_size"]
        assert v_new > v_base, "vocab_size did not increase"

        # the live replica must observe the grown publish and answer a
        # query for a NEW word — bounded wait on the reload watcher
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            info = service.info()
            if info["num_words"] == v_new:
                break
            time.sleep(0.05)
        info = service.info()
        assert info["num_words"] == v_new, (
            f"service never reloaded the grown model "
            f"(serving {info['num_words']} words, want {v_new})")
        new_syn = service.synonyms(_NEW_WORDS[0], 4)
        assert new_syn and all(np.isfinite(s) for _, s in new_syn), \
            f"new-word query failed: {new_syn}"
        # old-word neighbors intact: cluster A words still neighbor cluster
        # A (the forgetting smoke check; the measured gate is
        # eval_quality.py --continual-ab)
        old_syn = service.synonyms(_CLUSTER_A[0], 4)
        a_like = set(_CLUSTER_A) | set(_NEW_WORDS)
        hits = sum(1 for w, _ in old_syn if w in a_like)
        assert hits >= 2, (
            f"old word {_CLUSTER_A[0]!r} lost its cluster after the "
            f"increment: {old_syn}")
    finally:
        storm_on.clear()
        client.join()
        stats = service.stats()
        service.close()
        runner.close()
    assert not query_errs, (
        f"{len(query_errs)} failed queries during the continual publishes "
        f"(first: {query_errs[0]})")
    assert stats["refused"] == 0, f"{stats['refused']} refused queries"
    assert stats["reloads"] >= 1, "no hot-reload observed"
    assert stats["vocab_change_reloads"] >= 1, \
        "the V-grew reload was not detected"
    header = load_model_header(ck)
    lineage = header["vocab_lineage"]
    assert len(lineage) == 1 and lineage[0]["new_words"] == inc["new_words"], \
        f"lineage chain wrong: {lineage}"
    return {
        "ok": True,
        "vocab_base": v_base,
        "vocab_grown": v_new,
        "new_words": inc["new_words"],
        "lineage_depth": len(lineage),
        "reloads": stats["reloads"],
        "vocab_change_reloads": stats["vocab_change_reloads"],
        "queries": queries[0],
        "failed_queries": 0,
        "refused": stats["refused"],
        "new_word_top1": (new_syn[0][0] if new_syn else None),
        "increment_train_seconds": inc["train_seconds"],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--checkpoint", default="",
                    help="publish path (the directory serving replicas "
                         "watch); bootstrapped with a base fit if absent")
    ap.add_argument("--corpus-dir", default="",
                    help="append-only segment directory (*.txt)")
    ap.add_argument("--work-dir", default="",
                    help="cursor + encode-cache directory")
    ap.add_argument("--max-increments", type=int, default=None,
                    help="stop after this many completed increments")
    ap.add_argument("--idle-polls", type=int, default=None,
                    help="stop after this many consecutive empty polls")
    ap.add_argument("--poll-s", type=float, default=None,
                    help="poll cadence (default: the continual_poll_s knob)")
    ap.add_argument("--checkpoint-every-steps", type=int, default=None)
    ap.add_argument("--telemetry", default="",
                    help="write continual_* telemetry records here (JSONL)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-contained end-to-end drill "
                         "(tier-1/CI) in a temp dir")
    ap.add_argument("--workdir", default="",
                    help="--smoke working directory (default: fresh temp)")
    args = ap.parse_args()

    # single-print shape: exactly one JSON line leaves this function on
    # every path (graftlint R7 — the rule that forced perfgate into the
    # same shape)
    if args.smoke:
        workdir = args.workdir or tempfile.mkdtemp(prefix="glint_continual_")
        try:
            out, rc = run_smoke(workdir), 0
        except AssertionError as e:
            out, rc = {"ok": False, "error": str(e)}, 1
        finally:
            if not args.workdir:
                shutil.rmtree(workdir, ignore_errors=True)
    else:
        if not (args.checkpoint and args.corpus_dir and args.work_dir):
            ap.error("--checkpoint, --corpus-dir and --work-dir are "
                     "required (or use --smoke)")
        from glint_word2vec_tpu.continual import ContinualRunner
        runner = ContinualRunner(
            args.checkpoint, args.corpus_dir, args.work_dir,
            checkpoint_every_steps=args.checkpoint_every_steps,
            telemetry_path=args.telemetry)
        try:
            base = runner.ensure_base()
            if base["action"] == "base":
                log(f"[continual] bootstrapped base model: {base}")
            result = runner.run_forever(
                max_increments=args.max_increments,
                max_idle_polls=args.idle_polls,
                poll_s=args.poll_s)
        finally:
            runner.close()
        out, rc = {"ok": True, **result,
                   "bootstrapped": base["action"] == "base"}, 0
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    sys.exit(main())
