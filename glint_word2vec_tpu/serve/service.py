"""The embedding service: batching + ANN + hot-reload behind one handle.

The production serving tier ROADMAP item 1 names (the reference's mode-B
standalone-PS-cluster deployment, PAPER.md §G1, re-imagined for the
checkpoint-serving design): ONE object that

- loads a checkpoint through the swap-window-safe single owner
  (:func:`.reload.load_with_retry`),
- builds the IVF ANN index at load/publish time (:mod:`.ann`), keeping the
  exact sharded top-k as the ground-truth oracle arm,
- coalesces concurrent queries into batched dispatches with bounded-queue
  backpressure (:mod:`.batcher`),
- hot-reloads on the trainer's publish signal with zero downtime
  (:mod:`.reload` — in-flight batches finish on the old model, its buffers
  release when the last lease drains),
- and rides the existing obs layer: additive ``serve_*`` record kinds into
  the telemetry sink (obs/schema.py) and ``glint_serve_*`` Prometheus
  gauges through statusd (obs/statusd.serve_prometheus_text).

Knob resolution: the ``serve_*`` fields on :class:`Word2VecConfig` (they
travel with the checkpoint, like every other knob) are the defaults;
constructor arguments override per process. The trainer never reads them —
serving is a separate process in the deployment story (tests co-locate for
convenience; nothing requires it).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from glint_word2vec_tpu.serve.ann import build_ivf
from glint_word2vec_tpu.serve.batcher import BatchingScheduler
from glint_word2vec_tpu.serve.reload import (
    CheckpointWatcher,
    ServingHandle,
    load_with_retry,
    publish_signature,
    publish_signature_str as _sig_str,
)

logger = logging.getLogger("glint_word2vec_tpu")

Query = Union[str, np.ndarray]


def _knob(model, name: str, override):
    """Constructor override, else the checkpoint config's serve_* field,
    else the dataclass default (old checkpoints deserialize with defaults
    filled in, so getattr always resolves)."""
    if override is not None:
        return override
    return getattr(model.config, name)


class EmbeddingService:
    """Batched, ANN-indexed, hot-reloading synonym/vector service."""

    def __init__(
        self,
        checkpoint: Optional[str] = None,
        model=None,
        plan=None,
        ann: bool = True,
        nprobe: Optional[int] = None,
        ann_centroids: Optional[int] = None,
        ann_seed: int = 0,
        ann_quant: Optional[str] = None,
        ann_pq_m: Optional[int] = None,
        ann_rerank: Optional[int] = None,
        ann_recall_floor: Optional[float] = None,
        ann_max_densify_bytes: Optional[int] = None,
        ann_from_shards: bool = False,
        max_batch: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        queue_depth: Optional[int] = None,
        watch: bool = False,
        reload_poll_s: Optional[float] = None,
        telemetry_path: str = "",
        status_port: int = 0,
        straggle_every: int = 0,
        straggle_ms: float = 0.0,
        ann_index=None,
        process_name: str = "",
    ):
        """``straggle_every``/``straggle_ms``: fault injection passed through
        to the batcher (its docstring has the contract) — the fleet hedge
        A/B's deterministic tail-latency straggler. Off by default.

        ``ann_quant``/``ann_pq_m``/``ann_rerank``/``ann_recall_floor``:
        the quantized-index family (docs/serving.md §6) — which storage
        arm the build uses (``f32``/``int8``/``pq``), the PQ subspace
        count, the exact-re-rank shortlist, and the recall-refusal floor.
        None defers to the checkpoint's ``serve_ann_*`` knobs (the usual
        resolution rule); every hot-reload rebuilds at the SAME resolved
        arm and re-measures recall, and a reload whose rebuild lands
        below floor is refused by the watcher's catch — the old model
        keeps serving.

        ``ann_max_densify_bytes``: refuse an in-memory index build whose
        dense normalized copy would exceed this many bytes (0 =
        unlimited) — the legacy ``np.asarray(model.syn0)`` path OOMs the
        host long past the point the shard-native build
        (``ann_from_shards=True``, serve/quant.py) handles fine.

        ``ann_from_shards``: build the index straight from the
        checkpoint's row-shards files (never materializing dense [V, D]
        f32; quantized arms only). Requires ``checkpoint=`` with a
        row-shards layout.

        ``ann_index``: a prebuilt :class:`~.ann.IvfIndex` to serve instead
        of building one at init (``ann=True`` only; ``attach_ann``'s
        row-count refusal still guards it). For N in-process fleet replicas
        over one matrix (tools/servebench.py --fleet) the build is paid
        once, not N times. Checkpoint-watching services ignore it on
        reload — a reload always rebuilds at the new matrix.

        ``process_name``: the fleet-timeline track label stamped on this
        service's clock anchor, trace spans, and blackbox dump (default
        ``serve-<pid>``; the fleet spawner passes the replica name so the
        collector's tracks read r0/r1/... instead of pids)."""
        # pure argument validation FIRST — nothing acquired yet
        if (checkpoint is None) == (model is None):
            raise ValueError("pass exactly one of checkpoint= or model=")
        if watch and checkpoint is None:
            raise ValueError("watch=True needs a checkpoint path to poll")
        if ann_from_shards and checkpoint is None:
            raise ValueError(
                "ann_from_shards=True builds from the checkpoint's shard "
                "files — it needs checkpoint=, not an in-memory model")
        self._checkpoint = checkpoint
        # a checkpoint-loaded model is ours to release on close; an
        # in-memory model= stays the caller's (handle.detach on close)
        self._owns_model = checkpoint is not None
        self._plan = plan
        self._ann_enabled = bool(ann)
        self._ann_seed = int(ann_seed)
        self._prebuilt_index = ann_index if ann else None
        self._batcher = None
        self._sink = None
        self._statusd = None
        self._watcher = None
        self._handle = None
        self._closed = False
        self._leaked_threads = 0
        self._blackbox = None
        self._span_emitter = None
        self._dispatch_count = 0
        t0 = time.perf_counter()
        # signature BEFORE the load: a publish landing during the slow
        # load/index build below must still read as unserved afterwards
        # (reload.publish_signature has the capture rule)
        pre_sig = (publish_signature(checkpoint)
                   if checkpoint is not None else None)
        if model is None:
            model = load_with_retry(checkpoint, plan=plan)
        self._nprobe = (int(nprobe) if nprobe
                        else _knob(model, "serve_ann_nprobe", None)) or None
        self._ann_centroids = int(
            _knob(model, "serve_ann_centroids", ann_centroids))
        # quantized-index knobs (docs/serving.md §6): resolved ONCE here,
        # then every reload rebuilds at the same arm — a V-grew publish
        # must not silently change quantization mid-fleet
        self._ann_quant = str(_knob(model, "serve_ann_quant", ann_quant))
        self._ann_pq_m = int(_knob(model, "serve_ann_pq_m", ann_pq_m))
        self._ann_rerank = int(_knob(model, "serve_ann_rerank", ann_rerank))
        self._ann_recall_floor = float(
            _knob(model, "serve_ann_recall_floor", ann_recall_floor))
        self._ann_max_densify = int(
            _knob(model, "serve_ann_max_densify_bytes",
                  ann_max_densify_bytes))
        self._ann_from_shards = bool(ann_from_shards)
        try:
            index = self._build_index(model)
            self._handle = ServingHandle(model, index)
            self._load_seconds = time.perf_counter() - t0
            # the publish generation this replica serves (the fleet
            # router's staleness channel): the signature captured BEFORE
            # the load that produced the live model
            self._served_sig = _sig_str(pre_sig)
            self.reloads = 0
            # cross-publish vocab-change tracking (continual training grows
            # V; docs/continual.md): count reloads that changed the size
            self.vocab_change_reloads = 0
            self._served_vocab_size = model.num_words
            if telemetry_path:
                # sink + trace emitter + flight recorder BEFORE the batcher:
                # the worker thread's span/observer hooks must find them
                # armed from the very first dispatched batch
                from glint_word2vec_tpu.obs.blackbox import FlightRecorder
                from glint_word2vec_tpu.obs.sink import TelemetrySink
                from glint_word2vec_tpu.obs.trace import (
                    SpanEmitter, clock_anchor, service_process_name)
                self.process_name = (process_name
                                     or service_process_name("serve"))
                self._sink = TelemetrySink(telemetry_path)
                self._span_emitter = SpanEmitter(self._sink,
                                                 self.process_name)
                # the serving flight recorder (ISSUE-13 satellite): before
                # this, a dying replica left NO dump — the fleet-kill
                # drill's SIGTERM leg now finds `<telemetry>.blackbox.json`
                # with a serve-scoped cause + the recent serve records
                self._blackbox = FlightRecorder(
                    f"{telemetry_path}.blackbox.json")
                self._blackbox.begin_run(self.process_name)
                self._emit("serve_start",
                           checkpoint=checkpoint or "<in-memory>",
                           vocab_size=model.num_words,
                           vector_size=model.vector_size,
                           **clock_anchor(), process=self.process_name,
                           **({"publish_sig": self._served_sig}
                              if self._served_sig else {}),
                           **({"ann": index.stats} if index else {}))
            self._batcher = BatchingScheduler(
                self._dispatch,
                max_batch=int(_knob(model, "serve_max_batch", max_batch)),
                max_delay_ms=float(_knob(model, "serve_max_delay_ms",
                                         max_delay_ms)),
                max_queue=int(_knob(model, "serve_queue_depth", queue_depth)),
                straggle_every=straggle_every, straggle_ms=straggle_ms,
                span_emit=(self._batch_span if self._span_emitter is not None
                           else None),
                batch_observer=(self._note_batch
                                if self._blackbox is not None else None),
            ).start()
            if status_port:
                from glint_word2vec_tpu.obs.statusd import (
                    StatusServer, serve_prometheus_text)
                self._statusd = StatusServer(
                    status_port, self.status_snapshot,
                    metrics_fn=serve_prometheus_text).start()
            if watch:
                self._watcher = CheckpointWatcher(
                    checkpoint, self._on_publish,
                    poll_s=float(_knob(model, "serve_reload_poll_s",
                                       reload_poll_s)),
                    loaded_signature=pre_sig).start()
        except BaseException:
            # a failed init must not leak the batcher thread, the bound
            # status socket, the sink file, or the loaded model's buffers
            # (the caller has no service reference to close())
            if self._handle is None:
                if self._owns_model:
                    model.stop()
            self.close()
            raise

    # -- obs plumbing ------------------------------------------------------------------

    def _emit(self, kind: str, **fields) -> None:
        """One serving telemetry record to the sink AND the flight
        recorder's ring — the same single-owner rule as Trainer._emit, so
        the blackbox dump's entries are byte-for-byte the records the JSONL
        carries (obs/blackbox.py)."""
        if self._sink is not None:
            self._sink.emit(kind, **fields)
        if self._blackbox is not None:
            self._blackbox.observe(kind, fields)

    def _batch_span(self, trace: dict, name: str, start_ns: int,
                    dur_ns: int) -> None:
        """The batcher's span hook: queue_wait/batch_service children of the
        trace context the request carried across the wire."""
        self._span_emitter.emit(trace["tid"], name, start_ns, dur_ns,
                                parent=trace.get("ps"))

    def _note_batch(self, batch_size: int, service_s: float,
                    wait_s: float) -> None:
        """The batcher's per-dispatch observer: feeds the flight recorder's
        dispatch ring (the finest-grained trace of what the replica was
        doing right before death — the serving analog of the trainer's
        per-dispatch records; worker thread only, so the counter is safe)."""
        self._dispatch_count += 1
        self._blackbox.note_dispatch(self._dispatch_count, batch_size,
                                     service_s, wait_s)

    def dump_blackbox(self, cause: Optional[dict] = None,
                      include_stats: bool = True) -> Optional[str]:
        """Write the serving flight-recorder dump (telemetry on only; None
        otherwise/on failure). ``cause`` is a FlightRecorder cause record —
        the serve_checkpoint.py SIGTERM handler and its fatal-exception
        unwind both land here; first cause wins per process, and the dump
        carries an at-death stats snapshot when the service can still take
        one (best-effort: forensics must never mask the original failure).

        ``include_stats=False`` is REQUIRED from a signal handler: the
        stats snapshot acquires the batcher's non-reentrant condition lock,
        which the interrupted main thread may be holding inside
        submit_async — every lock on a handler's dump path must be
        reentrant (the obs/blackbox.py rule), and that one is not. The
        rings alone (fed lock-free relative to _cv) are the forensics."""
        if self._blackbox is None:
            return None
        extra = {}
        if include_stats:
            try:
                extra["serve"] = self.stats()
            except Exception:  # noqa: BLE001 — a wedged service still dumps
                pass
        return self._blackbox.dump(cause=cause, extra=extra)

    # -- index / reload ----------------------------------------------------------------

    def _build_index(self, model):
        if not self._ann_enabled:
            return None
        if self._prebuilt_index is not None:
            # one-shot: only the INIT model may use it (attach_ann still
            # hard-refuses a row-count mismatch); reloads rebuild fresh
            index, self._prebuilt_index = self._prebuilt_index, None
        elif self._ann_from_shards:
            # shard-native build (serve/quant.py): streams the checkpoint's
            # row-shards straight into quantized codes — never a dense
            # [V, D] f32 copy, so it is also the V-grew hot-reload path at
            # host-exceeding vocabularies (same quant arm every rebuild)
            from glint_word2vec_tpu.serve.quant import build_ivf_from_shards
            index = build_ivf_from_shards(
                self._checkpoint,
                quant=self._ann_quant,
                num_centroids=self._ann_centroids,
                nprobe=self._nprobe or 0,
                seed=self._ann_seed,
                pq_m=self._ann_pq_m,
                rerank=self._ann_rerank,
                recall_floor=self._ann_recall_floor)
        else:
            # legacy in-memory path: densifies model.syn0 into one f32
            # normalized copy first — guard BEFORE the allocation (today's
            # alternative is the host OOMing mid-build)
            would_be = int(model.num_words) * int(model.vector_size) * 4
            if 0 < self._ann_max_densify < would_be:
                raise RuntimeError(
                    f"refusing in-memory ANN build: densifying the "
                    f"[{model.num_words}, {model.vector_size}] matrix "
                    f"needs {would_be} bytes of host RAM > "
                    f"serve_ann_max_densify_bytes={self._ann_max_densify}"
                    f" — migrate to the shard-native build "
                    f"(ann_from_shards=True / serve.quant."
                    f"build_ivf_from_shards, docs/serving.md §6) or "
                    f"raise the knob explicitly")
            index = build_ivf(np.asarray(model.syn0),
                              num_centroids=self._ann_centroids,
                              nprobe=self._nprobe or 0,
                              seed=self._ann_seed,
                              quant=self._ann_quant,
                              pq_m=self._ann_pq_m,
                              rerank=self._ann_rerank,
                              recall_floor=self._ann_recall_floor)
        model.attach_ann(index)
        return index

    def _load_and_swap(self) -> Any:
        """Load the newest checkpoint + build its index IN THE BACKGROUND
        (the current model keeps serving), then atomically swap.

        A vocab-size change across publishes (the continual-training loop
        grows V, docs/continual.md) is detected and counted: the index is
        rebuilt from scratch at the new V on every reload by construction
        (never carried over — ``attach_ann`` additionally refuses a
        row-count mismatch as the hard guard), and the count surfaces in
        :meth:`stats` so a fleet dashboard can see growth propagating."""
        t0 = time.perf_counter()
        # signature BEFORE the load (publish_signature's capture rule): the
        # generation this reload serves is at LEAST this one — a publish
        # landing mid-load re-fires the watcher and bumps it again
        pre_sig = publish_signature(self._checkpoint)
        model = load_with_retry(self._checkpoint, plan=self._plan)
        index = self._build_index(model)
        prev_v = self._served_vocab_size
        vocab_changed = prev_v is not None and model.num_words != prev_v
        self._handle.swap(model, index)
        self._served_sig = _sig_str(pre_sig)
        self._served_vocab_size = model.num_words
        if vocab_changed:
            self.vocab_change_reloads += 1
            logger.info(
                "hot-reload: vocabulary changed %d -> %d words; ANN index "
                "fully rebuilt at the new vocabulary", prev_v,
                model.num_words)
        self.reloads += 1
        self._load_seconds = time.perf_counter() - t0
        if self._sink is not None:
            self._emit("serve_reload",
                       vocab_size=model.num_words,
                       reloads=self.reloads,
                       load_seconds=round(self._load_seconds, 3),
                       # the generation this reload installed: joins the
                       # publisher's `publish` record on the fleet timeline
                       **({"publish_sig": self._served_sig}
                          if self._served_sig else {}),
                       **({"vocab_grew_from": prev_v}
                          if vocab_changed else {}),
                       **({"ann": index.stats} if index else {}))
        logger.info("hot-reload %d: %d words in %.2fs (in-flight batches "
                    "finished on the old model)", self.reloads,
                    model.num_words, self._load_seconds)
        return model

    def _on_publish(self) -> None:
        self._load_and_swap()

    def reload_now(self):
        """Explicit synchronous reload (the CLI ``reload`` op). Returns the
        new model."""
        if self._checkpoint is None:
            raise RuntimeError("in-memory service has no checkpoint to reload")
        # signature before the load (reload.publish_signature's capture
        # rule): a publish racing this reload stays visible to the watcher
        pre_sig = publish_signature(self._checkpoint)
        model = self._load_and_swap()
        if self._watcher is not None:
            self._watcher.mark_loaded(pre_sig)
        return model

    # -- the batched dispatch (runs on the batcher worker thread) ----------------------

    def _dispatch(self, payloads: List[Tuple]) -> List[Any]:
        """One coalesced batch under ONE lease: every request in the batch
        is answered by the same model generation, and a swap landing
        mid-batch waits for the lease to drain before the old buffers go.

        A ``syn`` payload may carry a 4th element — the cross-process trace
        context (obs/trace.py) — in which case the scan's wall time is
        emitted as an ``ann_probe``/``exact_scan`` child span for each
        traced request (siblings of the batcher's batch_service span under
        the same wire parent; the duration is the BATCH's scan — per-query
        attribution below one device dispatch does not exist by design)."""
        with self._handle.lease() as (model, index):
            results: List[Any] = [None] * len(payloads)
            syn_pos: List[int] = []
            syn_q: List[Query] = []
            syn_num: List[int] = []
            syn_trace: List[Optional[dict]] = []
            for i, p in enumerate(payloads):
                op = p[0]
                if op == "syn":
                    q, num = p[1], p[2]
                    if isinstance(q, str) and model.vocab.get(q) < 0:
                        # per-request failure: an OOV word fails ITS caller,
                        # never the batch (the batcher re-raises it there)
                        results[i] = KeyError(f"{q} not in vocabulary")
                        continue
                    syn_pos.append(i)
                    syn_q.append(q)
                    syn_num.append(int(num))
                    syn_trace.append(p[3] if len(p) > 3 else None)
                elif op == "vec":
                    try:
                        results[i] = model.transform(p[1])
                    except KeyError as e:
                        results[i] = e
                else:
                    results[i] = ValueError(f"unknown op {op!r}")
            if syn_pos:
                kmax = max(syn_num)
                use_ann = self._ann_enabled and index is not None
                traced = (self._span_emitter is not None
                          and any(t is not None for t in syn_trace))
                t0_ns = time.monotonic_ns() if traced else 0
                try:
                    rows = model.find_synonyms_batch(
                        syn_q, kmax, ann=use_ann, nprobe=self._nprobe)
                except Exception as e:  # noqa: BLE001 — delivered per caller
                    for i in syn_pos:
                        results[i] = e
                else:
                    for i, res, num in zip(syn_pos, rows, syn_num):
                        results[i] = res[:num]
                if traced:
                    dur_ns = time.monotonic_ns() - t0_ns
                    name = "ann_probe" if use_ann else "exact_scan"
                    for tr in syn_trace:
                        if tr is not None:
                            self._span_emitter.emit(
                                tr["tid"], name, t0_ns, dur_ns,
                                parent=tr.get("ps"))
            return results

    # -- client surface ----------------------------------------------------------------

    def synonyms(self, query: Query, num: int = 10,
                 timeout: float = 60.0,
                 trace: Optional[dict] = None) -> List[Tuple[str, float]]:
        """``trace``: the cross-process trace context a fleet router bore at
        submit (``{"tid", "ps"}``, obs/trace.py) — None (the default, and
        the only value when telemetry is off) keeps the payload tuple and
        the submit path byte-identical to the untraced protocol."""
        return self._batcher.submit(
            ("syn", query, num) if trace is None
            else ("syn", query, num, trace), timeout)

    def synonyms_batch(self, queries: Sequence[Query], num: int = 10,
                       timeout: float = 60.0,
                       trace: Optional[dict] = None
                       ) -> List[List[Tuple[str, float]]]:
        """Submit many queries at once — they coalesce into device-batch-
        sized dispatches with any other in-flight traffic. A traced wire
        batch attributes its spans to the FIRST query only (one
        representative span set per wire request, not num_queries copies)."""
        tickets = [self._batcher.submit_async(
            ("syn", q, num) if (trace is None or i)
            else ("syn", q, num, trace),
            trace=trace if i == 0 else None)
            for i, q in enumerate(queries)]
        return [self._batcher.wait(t, timeout) for t in tickets]

    def vector(self, word: str, timeout: float = 60.0) -> np.ndarray:
        return self._batcher.submit(("vec", word), timeout)

    # non-blocking surface (the fleet router's hedging primitive: submit to
    # one replica, wait a p99-derived delay on the ticket's event, then
    # race a second replica — serve/fleet.py): the returned ticket's
    # ``done`` is a threading.Event; pass it to :meth:`wait_result`.
    def synonyms_async(self, query: Query, num: int = 10,
                       trace: Optional[dict] = None):
        return self._batcher.submit_async(
            ("syn", query, num) if trace is None
            else ("syn", query, num, trace), trace=trace)

    def wait_result(self, ticket, timeout: float = 60.0):
        return self._batcher.wait(ticket, timeout)

    # -- observability -----------------------------------------------------------------

    def info(self) -> Dict[str, Any]:
        with self._handle.lease() as (model, index):
            return {
                "num_words": model.num_words,
                "vector_size": model.vector_size,
                "iteration": (model.train_state.iteration
                              if model.train_state else None),
                "finished": (model.train_state.finished
                             if model.train_state else None),
                "ann": dict(index.stats) if index else None,
                "reloads": self.reloads,
            }

    def stats(self) -> Dict[str, Any]:
        snap = self._batcher.stats()
        snap["reloads"] = self.reloads
        snap["vocab_change_reloads"] = self.vocab_change_reloads
        snap["models_released"] = self._handle.models_released
        snap["load_seconds"] = round(self._load_seconds, 3)
        snap["leaked_threads"] = self._leaked_threads
        # the served publish generation (None for in-memory models): the
        # fleet health prober compares this against the on-disk signature —
        # a replica a generation behind its peers is DEGRADED, not dead
        snap["publish_sig"] = self._served_sig
        with self._handle.lease() as (model, index):
            snap["vocab_size"] = model.num_words
            if index is not None:
                snap["ann"] = dict(index.stats)
        return snap

    def status_snapshot(self) -> Dict[str, Any]:
        snap = self.stats()
        snap["status"] = "closed" if self._closed else "serving"
        return snap

    def emit_stats(self) -> None:
        """Write one ``serve_stats`` telemetry record (periodic callers own
        the cadence; the service never spawns a timer thread for it)."""
        if self._sink is None:
            return
        s = self.stats()
        self._emit(
            "serve_stats",
            submitted=s["submitted"], refused=s["refused"],
            batches=s["batches"], queue_depth=s["queue_depth"],
            reloads=s["reloads"],
            **{k: s[k] for k in ("latency_ms", "occupancy_mean", "ann")
               if s.get(k) is not None})

    def close(self) -> int:
        """Drain the batcher, stop the watcher/statusd, release the model,
        close the sink. Idempotent, and safe on a partially-initialized
        service (the failed-__init__ cleanup path calls this). Returns the
        number of owned threads that missed their join bound (also
        surfaced as ``leaked_threads`` in :meth:`stats`)."""
        if self._closed:
            return self._leaked_threads
        self._closed = True
        if self._watcher is not None:
            self._leaked_threads += self._watcher.stop()
        if self._batcher is not None:
            self._leaked_threads += self._batcher.stop()
        if self._statusd is not None:
            self._leaked_threads += self._statusd.stop()
        if self._sink is not None:
            if self._batcher is not None:
                s = self._batcher.stats()
                self._emit("serve_end", submitted=s["submitted"],
                           refused=s["refused"], reloads=self.reloads)
            self._sink.close()
        if self._handle is not None:
            if self._owns_model:
                self._handle.stop()
            else:
                self._handle.detach()
        return self._leaked_threads

    def __enter__(self) -> "EmbeddingService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
