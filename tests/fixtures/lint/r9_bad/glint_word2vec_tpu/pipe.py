"""R9 bad fixture: one rank inversion (which also closes a cycle), one raw
primitive construction, and one unregistered factory call."""
import threading

from glint_word2vec_tpu.lockcheck import make_lock

_raw = threading.Lock()


class Pipe:
    def __init__(self):
        self._outer = make_lock("outer")
        self._inner = make_lock("inner")
        self._rogue = make_lock("unregistered")

    def forward(self):
        with self._outer:
            with self._inner:
                pass

    def backward(self):
        with self._inner:
            with self._outer:
                pass
