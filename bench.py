"""Benchmark: fused SGNS training throughput (word-pairs/sec + MFU) on one chip.

Round-4 contract (VERDICT r3 items 2/10): every published number comes from a config
with *stability evidence* — the headline step config must appear in EVAL_RUNS.jsonl
(written by tools/eval_quality.py) as a ≥60M-word run that did NOT diverge, or the
bench refuses to headline it and falls back. The r3 headline (B=64k/pool=64) trained
to NaN in EVAL; its row is kept below as frontier context only, clearly marked.

Measured rows (stderr; e2e first — step benches leave allocator state behind that
throttles the host producer):

    e2e trainer (device feed) — Word2Vec-style end-to-end incl. vocab/windowing;
        on-device pair generation (ops/pairgen.py): the host ships kept-token blocks
        (~1 byte/pair), the jitted chunk derives subsample/window draws itself.
        Medians of 3 trials (single trials scatter 2x through the remote tunnel).
    e2e trainer (host feed)  — the packed-uint16-pairs feed, for comparison.
    step rows — the trainer-shaped jitted step (scan-chunked, hash-PRNG negatives)
        at EVAL-stable geometries: pool scaled to batch per the load<=600 rule the
        60M-word runs validated. f32 and bf16 storage; bf16 negative-logit chain
        (config.logits_dtype) on the bf16 row — PERF.md §4's one real lever.
    cbow rows — scatter (shipped default) and banded (cbow_update="banded",
        ops/cbow_banded.py) CBOW steps at the same pool list as the SGNS rows;
        the JSON line records cbow_step_ms / cbow_banded_examples_per_sec /
        cbow_banded_step_ms so the trajectory captures the banded win.
    step pool=64 (UNSTABLE) — the r3 headline geometry, context only: fastest
        per-step but EVAL-measured divergent at scale. Never the headline.
    V=1M scaling — the same step at a 1M-row vocabulary (~3 GB pair at f32; run at
        bf16), plus alias-table build and find_synonyms top-k timings: BASELINE
        config 3's single-chip shadow (no data above 200k vocab existed before).
    cpu-torch — identical step math on the host CPU at the SAME batch as e2e, so
        vs_baseline is one honest basis: TPU end-to-end vs CPU compute-only loop
        (the CPU number has no host pipeline, which *flatters* the baseline).
    host rows — tools/hostbench.py small tier (interleaved serial-vs-parallel
        medians): producer_tokens_per_sec, ckpt_save_s/ckpt_load_s/export_s,
        vocab_build_s/alias_build_s — the ISSUE-3 host data-plane trajectory.

Timing: two-point slopes over donated, data-dependent chunk chains with a final
device→host fetch (tools/microbench.py) — block_until_ready lies through the
remote-TPU tunnel. MFU is reported because BASELINE names it; the step is
scatter-emitter-bound (~27 ns/update-row), not FLOP-bound — see PERF.md for the
measured cost model and why the ≥50% MFU north star cannot apply to SGNS.

Prints exactly ONE JSON line on stdout; all tables go to stderr.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))

V, D, NEG = 200_000, 300, 5
PAD_D = 384        # lane-padded physical dim (config.pad_vector_to_lanes)
K = 16             # steps per dispatch chunk (step rows)
B_MAIN = 65536
E2E_K = 32
E2E_POOL = 512     # EVAL_RUNS-validated at 60M words (load 640, bf16+f32)
E2E_SUBSAMPLE = 1e-4  # the stability-evidence subsample ratio: the SAME key at
                      # 1e-3 is measured-divergent (EVAL round-4 addendum), so
                      # the headline gate matches on it too
CPU_STEPS = 3
PEAK_FLOPS = 197e12  # v5e bf16 peak / chip
V_SCALE = 1_000_000


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# the ONE dtype short-label map: "bfloat16"[:4] truncation drifted into the
# r01-r05 "bflo" label typo ("e2e_feed": "device_bflo"); every label and JSON
# key goes through here so it cannot drift again. Perfgate gates only the
# numeric fields, so the archived rungs stay comparable.
_SHORT_DTYPE = {"float32": "f32", "bfloat16": "bf16"}


def zipf_counts(v: int) -> np.ndarray:
    return np.maximum(1e9 / (np.arange(v) + 10.0) ** 1.07, 5.0)


def step_flops(pool: int, b: int) -> float:
    """Matmul FLOPs per step of the shared-pool path: f_neg (B,D)x(D,P),
    d_in += g_neg@Z (B,P)x(P,D), d_Z = g_negT@e_in (P,B)x(B,D), plus elementwise."""
    return 3 * 2.0 * b * pool * PAD_D + 10.0 * b * PAD_D


_ZIPF_P = {}


def _zipf_indices(rng, shape, v=V) -> np.ndarray:
    """Batch indices with the corpus's own frequency profile — uniform indices
    understate the real step cost (duplicate handling inside XLA's scatter)."""
    if v not in _ZIPF_P:
        c = zipf_counts(v)
        _ZIPF_P[v] = c / c.sum()
    return rng.choice(v, size=shape, p=_ZIPF_P[v])


def load_eval_stability(repo_root: str) -> list:
    path = os.path.join(repo_root, "EVAL_RUNS.jsonl")
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
    return rows


def eval_stable(rows: list, batch: int, pool: int, param_dtype: str,
                logits_dtype: str, subsample_ratio: float) -> bool:
    """True iff tools/eval_quality.py trained this geometry on >=60M words without
    divergence. The bench REFUSES to headline configs without this evidence.
    The match key is the FULL stability-relevant config — (batch, pool,
    param_dtype, logits_dtype, subsample_ratio) — because EVAL_RUNS holds both a
    stable (64k, 512, bf16, subsample 1e-4) and a divergent (same, 1e-3) row:
    matching on the first three alone would bless the measured-NaN config
    (VERDICT r4 weak #3). Rescored rows don't count: their config metadata comes
    from CLI flags, unverified against the saved model they re-scored."""
    for r in rows:
        if (not r.get("rescored")
                and r.get("pairs_per_batch") == batch
                and r.get("negative_pool") == pool
                and r.get("param_dtype") == param_dtype
                and r.get("logits_dtype") == logits_dtype
                and r.get("subsample_ratio") == subsample_ratio
                and r.get("corpus_words", 0) >= 60_000_000
                and not r.get("diverged")):
            return True
    return False


def bench_step(counts, b: int, pool: int, dtype: str = "float32",
               param_dtype: str = "float32", logits_dtype: str = "float32",
               v: int = V, label_extra: str = "", fused: bool = False,
               chain: bool = False, hot_rows: int = 0) -> tuple:
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, hot_flush, init_embeddings, sgns_step_shared_core)

    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    pdt = jnp.dtype(param_dtype)
    cdt = jnp.dtype(dtype)
    ldt = jnp.dtype(logits_dtype)
    syn0_0 = init_embeddings(v, PAD_D, jax.random.key(0)).syn0.astype(pdt)
    rng = np.random.default_rng(0)
    syn1_0 = jnp.asarray(rng.standard_normal((v, PAD_D), np.float32) * 0.05, pdt)

    def chunk(params, batches, base_step, prob, alias):
        negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, pool))

        if hot_rows:
            # the trainer's hot-row chunk shape (trainer._run_hot_scan at the
            # AUTO cadence): slabs carried through the scan, ONE dense prefix
            # flush at chunk end
            slabs = (jnp.zeros((hot_rows, PAD_D), jnp.float32),
                     jnp.zeros((hot_rows, PAD_D), jnp.float32))

            def body_hot(carry, inp):
                p, s = carry
                batch, ng = inp
                new_p, m, s = sgns_step_shared_core(
                    p, batch["centers"], batch["contexts"], batch["mask"],
                    ng, jnp.float32(0.025), NEG, "exact", cdt, False, ldt,
                    with_metrics=False, fused=fused, bf16_chain=chain,
                    hot_slabs=s)
                return (new_p, s), m.loss

            (p, (s0, s1)), losses = jax.lax.scan(
                body_hot, (params, slabs), (batches, negs))
            p = EmbeddingPair(hot_flush(p.syn0, s0), hot_flush(p.syn1, s1))
            return p, losses

        def body(p, inp):
            batch, ng = inp
            # with_metrics=False: the production steady state — the trainer
            # dispatches the metrics-elided twin for every chunk without a
            # heartbeat (~6 of 7 dispatches at the default cadence); the fetch
            # below pulls from the PARAMS carry, which depends on every update
            new_p, m = sgns_step_shared_core(
                p, batch["centers"], batch["contexts"], batch["mask"],
                ng, jnp.float32(0.025), NEG, "exact", cdt, False, ldt,
                with_metrics=False, fused=fused, bf16_chain=chain)
            return new_p, m.loss

        return jax.lax.scan(body, params, (batches, negs))

    f = jax.jit(chunk, donate_argnums=(0,))

    all_batches = []
    for i in range(8):
        r = np.random.default_rng(1000 + i)
        all_batches.append({
            "centers": jnp.asarray(_zipf_indices(r, (K, b), v), jnp.int32),
            "contexts": jnp.asarray(_zipf_indices(r, (K, b), v), jnp.int32),
            "mask": jnp.ones((K, b), jnp.float32),
        })

    def run(p, batches, base):
        return f(p, batches, base, prob, alias)

    ts = []
    for _ in range(3):
        spc = time_chunked(
            run,
            make_carry=lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
            args_for_iter=lambda i: (all_batches[i % 8], np.int32(100 + i)),
            n_lo=2, n_hi=8,
            # the loss channel is elided (constant 0) — the barrier fetch MUST
            # depend on the updated params or the chain can be elided
            fetch=lambda c, out: c.syn0[0, 0].astype(jnp.float32))
        ts.append(spc / K)
    spp = float(np.median(ts))
    ms = spp * 1e3
    pps = b / spp
    mfu = step_flops(pool, b) / spp / PEAK_FLOPS
    # min/median/max across the interleaved trials (VERDICT r8 item 4): the
    # published number is the median; the spread is the honesty bar for it
    stats = {"ms_min": round(min(ts) * 1e3, 4),
             "ms_median": round(ms, 4),
             "ms_max": round(max(ts) * 1e3, 4)}
    label = (f"xla {_SHORT_DTYPE.get(param_dtype)}"
             f"/logits-{_SHORT_DTYPE.get(logits_dtype)}{label_extra}")
    log(f"step {label:26s} V={v:8,d} B={b:6d} pool={pool:5d}: {ms:7.3f} ms/step"
        f" [{stats['ms_min']:.3f}-{stats['ms_max']:.3f}]"
        f" -> {pps:13,.0f} pairs/s  mfu={mfu * 100:5.2f}%")
    return pps, mfu, stats


def bench_cbow_step(counts, b: int, pools, param_dtype: str = "bfloat16",
                    window: int = 5) -> dict:
    """CBOW shared-pool SCATTER step (BASELINE config 5): grouped [B, 2w] context
    windows, hidden = masked context mean, negatives from the shared pool.
    Benches every pool in ``pools`` (the same list the SGNS step rows use, so
    CBOW and SGNS geometry stay comparable round to round) over one shared
    batch/embedding setup. Returns {pool: (examples_per_sec, ms_per_step)}."""
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, cbow_step_shared_core, init_embeddings)

    C = 2 * window
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    pdt = jnp.dtype(param_dtype)
    syn0_0 = init_embeddings(V, PAD_D, jax.random.key(0)).syn0.astype(pdt)
    rng = np.random.default_rng(0)
    syn1_0 = jnp.asarray(rng.standard_normal((V, PAD_D), np.float32) * 0.05, pdt)

    all_batches = []
    for i in range(6):
        r = np.random.default_rng(3000 + i)
        nctx = r.integers(1, C + 1, (K, b))
        all_batches.append({
            "centers": jnp.asarray(_zipf_indices(r, (K, b)), jnp.int32),
            "contexts": jnp.asarray(_zipf_indices(r, (K, b, C)), jnp.int32),
            "ctx_mask": jnp.asarray(
                np.arange(C)[None, None, :] < nctx[..., None], jnp.float32),
            "mask": jnp.ones((K, b), jnp.float32),
        })

    out = {}
    for pool in pools:
        def chunk(params, batches, base_step, prob, alias, pool=pool):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, pool))

            def body(p, inp):
                batch, ng = inp
                # with_metrics=False + params-carry fetch below: the same
                # metrics-elided production regime bench_step measures — the
                # trainer dispatches the elided twin on the CBOW shared-pool
                # path too, so the CBOW and SGNS step rows stay comparable
                new_p, m = cbow_step_shared_core(
                    p, batch["centers"], batch["contexts"], batch["ctx_mask"],
                    batch["mask"], ng, jnp.float32(0.025), NEG, "exact", pdt,
                    jnp.bfloat16 if param_dtype == "bfloat16" else jnp.float32,
                    with_metrics=False)
                return new_p, m.loss

            return jax.lax.scan(body, params, (batches, negs))

        f = jax.jit(chunk, donate_argnums=(0,))
        ts = []
        for _ in range(3):
            spc = time_chunked(
                lambda p, bt, base: f(p, bt, base, prob, alias),
                make_carry=lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                args_for_iter=lambda i: (all_batches[i % 6], np.int32(100 + i)),
                n_lo=2, n_hi=8,
                # loss is elided — the barrier fetch must depend on the updated
                # params or the whole chain can be elided (same as bench_step)
                fetch=lambda c, out: c.syn0[0, 0].astype(jnp.float32))
            ts.append(spc / K)
        spp = float(np.median(ts))
        # a CBOW "example" trains ~mean(nctx) positive word-context links;
        # report examples/s (the step unit) and links/s for pair comparison
        eps = b / spp
        short = _SHORT_DTYPE[param_dtype]
        log(f"step cbow scatter {short:9s} V={V:8,d} B={b:6d} "
            f"pool={pool:5d}: {spp * 1e3:7.3f} ms/step -> {eps:13,.0f} "
            f"examples/s (~{eps * (C + 1) / 2:,.0f} word-link/s)")
        out[pool] = (eps, spp * 1e3)
    return out


def bench_cbow_banded_step(counts, b: int, pools, param_dtype: str = "bfloat16",
                           window: int = 5) -> dict:
    """Banded CBOW step (config.cbow_update="banded", ops/cbow_banded.py):
    sentence-contiguous halo token blocks, window intervals derived on device
    from the hash lattice, context traffic via prefix sums — ~B update rows
    instead of B·C. Trainer-shaped chunk (scan + hash-PRNG negatives +
    metrics-elided), same pool list as the scatter row. Examples/s counts the
    REAL examples trained (~(w−1)/w of the B core slots; the scatter row's
    batches are dense, so the two rows are comparable on examples/s, not
    ms/step). Returns {pool: (examples_per_sec, ms_per_step)}."""
    import jax
    import jax.numpy as jnp
    from cbow_feed import make_banded_chunk, pack_banded_feeds
    from microbench import time_chunked

    from glint_word2vec_tpu.data.hashrng import (
        STREAM_WINDOW, hash_mod_at, stream_base)
    from glint_word2vec_tpu.ops.sampler import build_alias_table
    from glint_word2vec_tpu.ops.sgns import EmbeddingPair, init_embeddings

    H = window
    T = b + 2 * H
    n_sets = 6
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    pdt = jnp.dtype(param_dtype)
    ldt = jnp.bfloat16 if param_dtype == "bfloat16" else jnp.float32
    syn0_0 = init_embeddings(V, PAD_D, jax.random.key(0)).syn0.astype(pdt)
    rng = np.random.default_rng(0)
    syn1_0 = jnp.asarray(rng.standard_normal((V, PAD_D), np.float32) * 0.05, pdt)

    # one synthetic kept-token stream with the corpus's frequency profile,
    # 40-token sentences, cut into halo blocks exactly like the trainer feed
    stream_len = n_sets * K * b + 2 * H
    toks = _zipf_indices(rng, stream_len).astype(np.int32)
    starts = np.zeros(stream_len, bool)
    starts[::40] = True
    win_base = stream_base(1234, STREAM_WINDOW, 1, 0)
    feeds = pack_banded_feeds(toks, starts, T, H, n_sets, K)
    # real examples per step: live window draws among the core tokens
    bdraw = hash_mod_at(
        win_base, np.arange(n_sets * K * b, dtype=np.uint64), window)
    live_rate = float((bdraw >= 1).mean())  # boundary clipping ~negligible @40
    real_per_step = b * live_rate

    out = {}
    for pool in pools:
        f = jax.jit(make_banded_chunk(window, pool, NEG, pdt, ldt,
                                      win_base, K),
                    donate_argnums=(0,))
        ts = []
        for _ in range(3):
            spc = time_chunked(
                lambda p, bt, base: f(p, bt, base, prob, alias),
                make_carry=lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                args_for_iter=lambda i: (feeds[i % n_sets], np.int32(100 + i)),
                n_lo=2, n_hi=8,
                fetch=lambda c, out: c.syn0[0, 0].astype(jnp.float32))
            ts.append(spc / K)
        spp = float(np.median(ts))
        eps = real_per_step / spp
        short = _SHORT_DTYPE[param_dtype]
        log(f"step cbow banded  {short:9s} V={V:8,d} B={b:6d} "
            f"pool={pool:5d}: {spp * 1e3:7.3f} ms/step -> {eps:13,.0f} "
            f"examples/s ({real_per_step:,.0f} real ex/step)")
        out[pool] = (eps, spp * 1e3)
    return out


_E2E_CORPUS = None


def e2e_corpus():
    """The shared e2e corpus (vocab + encoded sentences) — built once; both feed
    modes and every trial reuse it (building it twice cost ~1 min of bench wall)."""
    global _E2E_CORPUS
    if _E2E_CORPUS is None:
        from glint_word2vec_tpu.data.pipeline import encode_sentences
        from glint_word2vec_tpu.data.vocab import build_vocab
        rng = np.random.default_rng(0)
        n_words, sent_len, vocab_sz = 4_000_000, 40, 50_000
        zipf = 1.0 / (np.arange(vocab_sz) + 10.0) ** 1.05
        ids = rng.choice(vocab_sz, size=n_words, p=zipf / zipf.sum())
        words = np.char.add("w", ids.astype("U8"))
        sentences = [list(words[i:i + sent_len])
                     for i in range(0, n_words, sent_len)]
        vocab = build_vocab(sentences, min_count=5)
        encoded = encode_sentences(sentences, vocab, 1000)
        _E2E_CORPUS = (vocab, encoded)
    return _E2E_CORPUS


def bench_e2e(device_pairgen: bool, param_dtype: str, logits_dtype: str,
              pool: int) -> tuple:
    """End-to-end Word2Vec-style fit on a synthetic Zipf corpus — includes vocab
    build, subsampling, window generation, feed transfer. Returns
    (median pairs/s, host_wait_fraction)."""
    import jax.numpy as jnp

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, encoded = e2e_corpus()
    cfg = Word2VecConfig(
        vector_size=D, min_count=5, pairs_per_batch=B_MAIN, num_iterations=1,
        window=5, negatives=NEG, negative_pool=pool, steps_per_dispatch=E2E_K,
        seed=1, subsample_ratio=E2E_SUBSAMPLE, device_pairgen=device_pairgen,
        param_dtype=param_dtype, compute_dtype=param_dtype,
        logits_dtype=logits_dtype)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encoded[:400])  # warm the jit cache
    rates, hw = [], []
    for trial in range(3):
        trainer.state = type(trainer.state)()
        trainer.pairs_trained = 0.0
        t0 = time.perf_counter()
        trainer.fit(encoded)
        # dependent fetch, not block_until_ready (which lies through the tunnel)
        float(jnp.sum(trainer.params.syn0[:128].astype(jnp.float32)))
        dt = time.perf_counter() - t0
        rates.append(trainer.pairs_trained / dt)
        hw.append(trainer.host_wait_time / dt)
        if not np.isfinite(float(jnp.sum(
                trainer.params.syn0[:1024].astype(jnp.float32)))):
            raise RuntimeError("e2e training diverged (NaN params) — the bench "
                               "must measure a run that actually learns")
        log(f"  e2e trial {trial}: {trainer.pairs_trained:,.0f} pairs in {dt:.1f}s"
            f" -> {rates[-1]:,.0f} pairs/s  [host-wait {trainer.host_wait_time:.2f}s"
            f" dispatch {trainer.dispatch_time:.2f}s]")
    med = int(np.argsort(rates)[1])  # index of the median-rate trial
    feed = "device feed" if device_pairgen else "host feed"
    log(f"e2e trainer ({feed}, {param_dtype}, pool={pool}): median "
        f"{float(np.median(rates)):,.0f} pairs/s over 3 trials")
    return float(np.median(rates)), float(hw[med])


def bench_scale_1m() -> dict:
    """V=1M rows (BASELINE config 3's single-chip shadow): alias build,
    step throughput, find_synonyms top-k — none of which had data above 200k."""
    import jax
    import jax.numpy as jnp

    out = {}
    counts = zipf_counts(V_SCALE)
    t0 = time.perf_counter()
    from glint_word2vec_tpu.ops.sampler import build_alias_table
    build_alias_table(counts)
    out["alias_build_s"] = time.perf_counter() - t0
    log(f"V=1M alias table build: {out['alias_build_s']:.2f}s (host, O(2V))")

    pps, _, stats = bench_step(counts, b=B_MAIN, pool=E2E_POOL, dtype="bfloat16",
                               param_dtype="bfloat16", logits_dtype="bfloat16",
                               v=V_SCALE)
    out["step_bf16_pairs_per_sec"] = pps
    out["step_trials_ms"] = stats

    # find_synonyms: sharded matvec + top-k over 1M rows (model ops G5/C8)
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    words = np.char.add("w", np.arange(V_SCALE).astype("U8"))
    vocab = Vocabulary.from_words_and_counts(list(words), counts.astype(np.int64))
    # create the 1.2 GB test embedding ON device — a host array here would ride
    # the (slow) host->device link and time the wire, not the model op
    syn0 = jax.random.normal(jax.random.key(1), (V_SCALE, D), jnp.float32) * 0.1
    syn0.block_until_ready()
    model = Word2VecModel(vocab, syn0, syn1=None,
                          config=Word2VecConfig(vector_size=D))
    model.find_synonyms("w0", 10)  # compile + warm
    t0 = time.perf_counter()
    for i in range(5):
        model.find_synonyms(f"w{i + 1}", 10)
    out["find_synonyms_ms"] = (time.perf_counter() - t0) / 5 * 1e3
    log(f"V=1M find_synonyms(top-10): {out['find_synonyms_ms']:.1f} ms/query "
        "(matvec + top-k over 1M rows)")
    # batched variant: per-query round trips dominate through the tunnel; one
    # [64, V] dispatch amortizes them (models/word2vec.py find_synonyms_batch)
    qs = [f"w{i + 10}" for i in range(64)]
    model.find_synonyms_batch(qs, 10, chunk=64)  # compile + warm
    t0 = time.perf_counter()
    model.find_synonyms_batch(qs, 10, chunk=64)
    out["find_synonyms_batch_ms"] = (time.perf_counter() - t0) / 64 * 1e3
    log(f"V=1M find_synonyms_batch(64 queries): "
        f"{out['find_synonyms_batch_ms']:.1f} ms/query")
    model.stop()
    return out


def bench_cpu_torch(b: int) -> float:
    """Same step math on host CPU with torch at the SAME batch as e2e — the
    vs_baseline denominator (compute-only: no host pipeline, flatters the CPU)."""
    import torch

    vocab_sz = 50_000
    counts = zipf_counts(vocab_sz)
    torch.manual_seed(0)
    g = torch.Generator().manual_seed(0)
    syn0 = (torch.rand(vocab_sz, D, generator=g) - 0.5) / D
    syn1 = torch.zeros(vocab_sz, D)
    probs = torch.tensor(counts ** 0.75, dtype=torch.float64)
    probs /= probs.sum()
    alpha = 0.025
    rng = np.random.default_rng(0)
    centers = torch.tensor(_zipf_indices(rng, b, vocab_sz), dtype=torch.long)
    contexts = torch.tensor(_zipf_indices(rng, b, vocab_sz), dtype=torch.long)

    def step():
        negatives = torch.multinomial(probs.float(), E2E_POOL, replacement=True)
        e_in = syn0[centers]
        e_pos = syn1[contexts]
        Z = syn1[negatives]
        f_pos = (e_in * e_pos).sum(-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).float()
        g_pos = (1 - torch.sigmoid(f_pos)) * alpha
        g_neg = (0 - torch.sigmoid(f_neg)) * alpha * neg_valid * (NEG / E2E_POOL)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        syn0.index_add_(0, centers, d_in)
        syn1.index_add_(0, contexts, g_pos[:, None] * e_in)
        syn1.index_add_(0, negatives, g_neg.T @ e_in)

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(CPU_STEPS):
        step()
    dt = time.perf_counter() - t0
    pps = CPU_STEPS * b / dt
    log(f"cpu-torch baseline (B={b}, pool={E2E_POOL}): {CPU_STEPS} steps in "
        f"{dt:.2f}s -> {pps:,.0f} pairs/s (compute only, no host pipeline)")
    return pps


def main() -> None:
    import jax
    dev = jax.devices()[0]
    log(f"device: {dev} ({dev.platform})")
    repo_root = os.path.dirname(os.path.abspath(__file__))
    eval_rows = load_eval_stability(repo_root)
    counts = zipf_counts(V)

    # e2e rows FIRST (allocator state from step benches throttles the producer)
    e2e = {}
    for dp, pdt, ldt in ((True, "bfloat16", "bfloat16"),
                         (False, "float32", "float32")):
        key = f"{'device' if dp else 'host'}_{_SHORT_DTYPE[pdt]}"
        try:
            e2e[key] = bench_e2e(dp, pdt, ldt, E2E_POOL)
        except Exception as e:
            log(f"e2e {key} failed: {type(e).__name__}: {e}")

    rows = {}
    rows["f32_p512"] = bench_step(counts, B_MAIN, E2E_POOL)
    rows["bf16_p512"] = bench_step(counts, B_MAIN, E2E_POOL, dtype="bfloat16",
                                   param_dtype="bfloat16",
                                   logits_dtype="bfloat16")
    # logits bf16 on the p1024 row too: that is the config EVAL_RUNS holds
    # stability evidence for (the gate matches on logits_dtype now)
    rows["bf16_p1024"] = bench_step(counts, B_MAIN, 1024, dtype="bfloat16",
                                    param_dtype="bfloat16",
                                    logits_dtype="bfloat16")
    # ISSUE-14 step-restructuring rows at the headline geometry, LAYERED so
    # the trajectory shows which layer pays (PERF.md §11): the fused
    # coefficient chain alone, + the end-to-end bf16 chain, + cross-step
    # hot-row accumulation (K=4096 ≈ where the Zipf mass knee sits at
    # V=200k; flush once per chunk, the trainer's AUTO cadence). Never the
    # headline until their geometry carries its own EVAL evidence — the
    # hot-row arm is gated by eval_quality --hotrow-ab.
    bf16kw = dict(dtype="bfloat16", param_dtype="bfloat16",
                  logits_dtype="bfloat16")
    try:
        rows["bf16_fused"] = bench_step(
            counts, B_MAIN, E2E_POOL, fused=True,
            label_extra=" +fused", **bf16kw)
        rows["bf16_chain"] = bench_step(
            counts, B_MAIN, E2E_POOL, fused=True, chain=True,
            label_extra=" +fused+chain", **bf16kw)
        rows["bf16_hot"] = bench_step(
            counts, B_MAIN, E2E_POOL, fused=True, chain=True, hot_rows=4096,
            label_extra=" +fused+chain+hot", **bf16kw)
    except Exception as e:
        log(f"restructured step rows failed: {type(e).__name__}: {e}")
    # CBOW rows at the same pool list as the SGNS step rows (comparable
    # geometry round to round): scatter (shipped default) and banded
    # (cbow_update="banded" — the ISSUE-2 prefix-sum path; step_ab.py --cbow
    # is the same-session interleaved A/B of the two)
    cbow_pools = (E2E_POOL, 1024)
    cbow_rows, cbow_banded_rows = {}, {}
    try:
        cbow_rows = bench_cbow_step(counts, B_MAIN, cbow_pools)
    except Exception as e:
        log(f"cbow step rows failed: {type(e).__name__}: {e}")
    try:
        cbow_banded_rows = bench_cbow_banded_step(counts, B_MAIN, cbow_pools)
    except Exception as e:
        log(f"cbow banded step rows failed: {type(e).__name__}: {e}")
    # frontier context ONLY: EVAL-measured divergent at training scale
    try:
        bench_step(counts, B_MAIN, 64, label_extra=" [UNSTABLE @64]")
        log("  ^ pool=64 row is frontier context only: EVAL measured this "
            "geometry training to NaN — never the headline")
    except Exception as e:
        log(f"pool=64 context row failed: {e}")

    scale = {}
    try:
        scale = bench_scale_1m()
    except Exception as e:
        log(f"V=1M scaling rows failed: {type(e).__name__}: {e}")

    # host data-plane rows (ISSUE-3): producer tokens/s + checkpoint/export/
    # cold-start wall clock via the interleaved hostbench harness, so
    # BENCH_r06+ tracks the host trajectory alongside the step/e2e rows
    host = {}
    try:
        import hostbench
        # hostbench.run (not .main): the bench's contract is ONE JSON line on
        # stdout, so the host row merges into the result instead of printing
        host = hostbench.run(["--scale", "small",
                              "--workers", str(min(os.cpu_count() or 1, 8))])
    except Exception as e:
        log(f"host-path rows failed: {type(e).__name__}: {e}")

    try:
        cpu_pps = bench_cpu_torch(B_MAIN)
    except Exception as e:
        log(f"cpu baseline failed: {e}")
        cpu_pps = None

    # headline: fastest STEP row whose geometry has >=60M-word non-divergent
    # EVAL evidence (the r3 failure mode: headlining a config that NaNs)
    dtype_of = {"f32_p512": ("float32", E2E_POOL, "float32"),
                "bf16_p512": ("bfloat16", E2E_POOL, "bfloat16"),
                "bf16_p1024": ("bfloat16", 1024, "bfloat16")}
    stable_keys = [k for k in rows
                   if k in dtype_of  # restructured rows never headline (they
                                     # need their own EVAL evidence per arm)
                   and eval_stable(eval_rows, B_MAIN, dtype_of[k][1],
                                   dtype_of[k][0], dtype_of[k][2],
                                   E2E_SUBSAMPLE)]
    if not stable_keys:
        log("WARNING: no step row has 60M-word EVAL evidence; refusing a step "
            "headline, publishing the e2e number instead")
    head_key = (max(stable_keys, key=lambda k: rows[k][0])
                if stable_keys else None)

    e2e_best_key = max(e2e, key=lambda k: e2e[k][0]) if e2e else None
    e2e_pps = e2e[e2e_best_key][0] if e2e_best_key else None
    result = {
        "metric": "sgns_word_pairs_per_sec_per_chip",
        "value": round(rows[head_key][0]) if head_key else round(e2e_pps or 0),
        "unit": "pairs/s",
        # ONE consistent basis: TPU end-to-end vs CPU-torch compute loop at the
        # SAME batch and pool (VERDICT r3 item 10)
        "vs_baseline": (round(e2e_pps / cpu_pps, 2)
                        if (cpu_pps and e2e_pps) else None),
        "vs_baseline_basis": "e2e_tpu_over_cpu_torch_step_loop_same_batch",
        "config": head_key,
        "headline_eval_evidence": "EVAL_RUNS.jsonl >=60M words, no divergence",
        "mfu": round(rows[head_key][1], 4) if head_key else None,
        "step_f32_pairs_per_sec": round(rows["f32_p512"][0]),
        # per-row min/median/max ms across the 3 interleaved trials (VERDICT
        # r8 item 4): the spread that qualifies every step number above
        "step_trials_ms": {k: rows[k][2] for k in rows},
        # flat per-row scalars (ADDITIVE beside the nested spread dict): one
        # `step_<row>_pairs_per_sec` + `step_<row>_step_ms` pair per step row
        # above, so tools/perfgate.py gates every row by a stable top-level
        # name instead of digging step_trials_ms (rows absent this run —
        # e.g. a failed restructured arm — simply emit no key, and the gate
        # skips metrics missing from the rung)
        **{f"step_{k}_pairs_per_sec": round(rows[k][0]) for k in rows},
        **{f"step_{k}_step_ms": rows[k][2]["ms_median"] for k in rows},
        "v1m_step_trials_ms": scale.get("step_trials_ms"),
        "e2e_pairs_per_sec": round(e2e_pps) if e2e_pps else None,
        "e2e_feed": e2e_best_key,
        # ISSUE-14 restructured step rows (same harness/geometry as the
        # bf16_p512 row, so ratios are in-run honest; perfgate gates them
        # from the first rung that carries them)
        "step_fused_pairs_per_sec": (round(rows["bf16_fused"][0])
                                     if "bf16_fused" in rows else None),
        "step_bf16_chain_pairs_per_sec": (round(rows["bf16_chain"][0])
                                          if "bf16_chain" in rows else None),
        "step_hotrow_pairs_per_sec": (round(rows["bf16_hot"][0])
                                      if "bf16_hot" in rows else None),
        "v1m_step_pairs_per_sec": (round(scale["step_bf16_pairs_per_sec"])
                                   if "step_bf16_pairs_per_sec" in scale
                                   else None),
        "cbow_examples_per_sec": (round(cbow_rows[E2E_POOL][0])
                                  if E2E_POOL in cbow_rows else None),
        "cbow_step_ms": (round(cbow_rows[E2E_POOL][1], 3)
                         if E2E_POOL in cbow_rows else None),
        "cbow_banded_examples_per_sec": (
            round(cbow_banded_rows[E2E_POOL][0])
            if E2E_POOL in cbow_banded_rows else None),
        "cbow_banded_step_ms": (round(cbow_banded_rows[E2E_POOL][1], 3)
                                if E2E_POOL in cbow_banded_rows else None),
        # host data plane (tools/hostbench.py small tier, interleaved medians)
        "producer_tokens_per_sec": host.get("producer_tokens_per_sec"),
        "producer_speedup": host.get("producer_speedup"),
        "ckpt_save_s": host.get("ckpt_save_s"),
        "ckpt_save_speedup": host.get("ckpt_save_speedup"),
        "ckpt_load_s": host.get("ckpt_load_s"),
        "export_s": host.get("export_s"),
        "vocab_build_s": host.get("vocab_build_s"),
        "alias_build_s": host.get("alias_build_s"),
    }
    print(json.dumps(result))


def run_smoke() -> None:
    """``--smoke``: the fast gate only — the interleaved telemetry-off/on
    trainer A/B (tools/telemetry_run.measure_overhead, 3 trials each arm at
    heartbeat cadence). The acceptance bar for the observability layer is
    telemetry_overhead_frac < 0.02; the full bench rows are untouched (run
    without flags for BENCH_r* artifacts). One JSON line on stdout (R7)."""
    import jax
    dev = jax.devices()[0]
    log(f"device: {dev} ({dev.platform}) — smoke mode (telemetry overhead A/B)")
    import telemetry_run
    res = telemetry_run.measure_overhead(600)
    print(json.dumps({
        "metric": "telemetry_overhead_frac",
        "value": res["telemetry_overhead_frac"],
        "acceptance": "< 0.02 at heartbeat cadence (docs/observability.md)",
        **res,
    }))


if __name__ == "__main__":
    if "--smoke" in sys.argv[1:]:
        run_smoke()
    else:
        main()
