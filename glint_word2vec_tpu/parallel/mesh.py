"""Device mesh and sharding layout — the TPU-native replacement for the Glint PS topology.

The reference shards the two embedding matrices across ``numParameterServers`` JVMs
(README.md:69) and moves data to them over Akka/Aeron RPC (G1/G8). Here the "servers" are
the devices of one ``jax.sharding.Mesh`` and the "transport" is XLA collectives over ICI:

- mesh axis ``"model"`` — embedding rows sharded ``P("model", None)`` (the BASELINE north
  star's row-sharding; each device owns ``V / num_model_shards`` rows in HBM, the analog of
  "each PS holds 1/n of the matrix").
- mesh axis ``"data"``  — the batch sharded ``P("data")``: synchronous data parallelism
  replacing the reference's async Hogwild partitions (mllib:392, accuracy caveat mllib:120).

Under ``jit``, GSPMD inserts the collectives the reference did by hand over RPC: the
minibatch row gather becomes an all-gather/all-to-all over ICI, gradient scatter-adds are
reduce-scattered back — no payload caps, no message chunking (G6 is deleted, not ported).

Multi-host: the same mesh spans processes (``jax.distributed.initialize``); per-host batch
slices are assembled into one global array with ``make_array_from_process_local_data`` so
the input pipe rides DCN while the training collectives ride ICI.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the canonical shardings for this workload."""

    mesh: Mesh

    @property
    def num_data(self) -> int:
        return self.mesh.shape[DATA_AXIS]

    @property
    def num_model(self) -> int:
        return self.mesh.shape[MODEL_AXIS]

    @property
    def embedding(self) -> NamedSharding:
        """Row-sharded [V, D] embeddings over the model axis, replicated over data."""
        return NamedSharding(self.mesh, P(MODEL_AXIS, None))

    @property
    def embedding_cols(self) -> NamedSharding:
        """Column-sharded [V, D] embeddings — the CIKM'16 scheme the reference's PS
        uses (G2: each server computes partial dot products over its slice of every
        vector; SURVEY §7.4 asks for both layouts). Under GSPMD the per-shard partial
        dots become a psum over the model axis instead of row gathers/scatters
        crossing devices. Same math, different collective profile:

        - rows: minibatch row fetch/update is an all-to-all over the model axis
          (each device owns V/N full rows); collective bytes scale with the number
          of OFF-SHARD rows touched.
        - cols: every device computes f_pos/f_neg partials on its D/N slice of every
          touched row, then one psum of [B(, P)] scalars; row access is device-local.

        Which wins depends on batch size vs vector width and the interconnect —
        measure on real multi-chip hardware via config.embedding_partition."""
        return NamedSharding(self.mesh, P(None, MODEL_AXIS))

    @property
    def batch(self) -> NamedSharding:
        """[B, ...] batches split over the data axis, replicated over model."""
        return NamedSharding(self.mesh, P(DATA_AXIS))

    @property
    def batch_stacked(self) -> NamedSharding:
        """[K, B, ...] chunk-of-batches: leading scan axis replicated, batch axis split
        over data."""
        return NamedSharding(self.mesh, P(None, DATA_AXIS))

    @property
    def pairs_stacked(self) -> NamedSharding:
        """[K, 2, B] packed (centers, contexts) chunk: scan and stream axes replicated,
        batch axis split over data. One contiguous transfer per dispatch — through a
        narrow host→device link (tunnel, DCN feed), per-transfer overhead dominates
        small puts, so the whole chunk ships as a single array."""
        return NamedSharding(self.mesh, P(None, None, DATA_AXIS))

    @property
    def ctx_stacked(self) -> NamedSharding:
        """[K, B, C] CBOW context chunk: batch axis split over data."""
        return NamedSharding(self.mesh, P(None, DATA_AXIS, None))

    @property
    def tokens_stacked(self) -> NamedSharding:
        """[K, S, T] raw-token chunk for the on-device pair generator
        (ops/pairgen.py): scan axis replicated, segment axis split over data (each
        data shard expands its own token blocks into pairs locally — no cross-shard
        traffic in the generator), token axis local."""
        return NamedSharding(self.mesh, P(None, DATA_AXIS, None))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())


def make_mesh(
    num_data: int = 1,
    num_model: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> MeshPlan:
    """Build a (data, model) mesh over the given (default: all) devices.

    ``num_model=None`` uses all remaining devices. This is the replacement for the Glint
    client's executor introspection (``Client.getNumExecutors/getExecutorCores``,
    mllib:356,718): topology comes from ``jax.devices()``, not Spark.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if num_model is None:
        if n % num_data:
            raise ValueError(f"{n} devices not divisible by num_data={num_data}")
        num_model = n // num_data
    if num_data * num_model > n:
        raise ValueError(
            f"mesh {num_data}x{num_model} needs {num_data * num_model} devices, have {n}")
    grid = np.array(devices[: num_data * num_model]).reshape(num_data, num_model)
    return MeshPlan(mesh=Mesh(grid, (DATA_AXIS, MODEL_AXIS)))


def embedding_sharding(plan: MeshPlan) -> NamedSharding:
    return plan.embedding


def batch_sharding(plan: MeshPlan) -> NamedSharding:
    return plan.batch


def replicated_sharding(plan: MeshPlan) -> NamedSharding:
    return plan.replicated


def shard_params(params, plan: MeshPlan):
    """Place an EmbeddingPair (or any pytree of [V, ...] arrays) row-sharded on the mesh."""
    return jax.tree.map(
        lambda a: jax.device_put(a, plan.embedding if a.ndim == 2 else plan.replicated),
        params)


def shard_batch(batch, plan: MeshPlan):
    """Place a pytree of [B, ...] host arrays on the mesh, split over the data axis."""
    return jax.tree.map(lambda a: jax.device_put(a, plan.batch), batch)


def pad_dim_to_lanes(vector_size: int, enabled: bool = True) -> int:
    """Physical embedding minor dim: padded up to the TPU lane width (128) when
    enabled. Trainer and every streamed-load path MUST agree on this value — a
    mismatch silently falls back to host-side re-padding of the full matrices."""
    return -(-vector_size // 128) * 128 if enabled else vector_size


def classify_replica_groups(
    num_data: int, num_model: int, groups: Sequence[Sequence[int]],
) -> str:
    """Which mesh axis a collective's replica groups span — the bridge between
    compiled-HLO collectives and the (data, model) mesh for the collective
    audit (tools/collectives.py).

    Devices are laid out row-major ``arange(nd*nm).reshape(nd, nm)``
    (:func:`make_mesh`), so a collective over:

    - ``model``: groups are the mesh ROWS — ``{0..nm-1}, {nm..2nm-1}, ...``
    - ``data``:  groups are the mesh COLUMNS — ``{0, nm, 2nm, ...}, ...``
    - ``all``:   one group covering every device (either axis trivial, or a
      collective over both axes)
    - ``other``: anything else (a partitioner rewrite this audit must surface,
      not silently bucket)

    Groups are compared as SETS: XLA may order ids within a group arbitrarily.
    """
    n = num_data * num_model
    got = sorted((frozenset(int(i) for i in g) for g in groups),
                 key=lambda s: min(s) if s else -1)
    grid = np.arange(n).reshape(num_data, num_model)
    if got == [frozenset(range(n))]:
        return "all"
    rows = sorted(frozenset(int(i) for i in r) for r in grid)
    if got == rows:
        return "model"
    cols = sorted(frozenset(int(i) for i in c) for c in grid.T)
    if got == cols:
        return "data"
    return "other"


def pad_vocab_for_sharding(vocab_size: int, num_model: int, multiple: int = 8) -> int:
    """Smallest padded row count divisible by num_model (and a lane-friendly multiple).

    Padded rows are real but never referenced by any index the pipeline emits, so they
    train to nothing and are dropped on export.
    """
    lcm = np.lcm(num_model, multiple)
    return int(-(-vocab_size // lcm) * lcm)
