"""``python -m tools.graftlint`` entry point."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.graftlint.engine import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
