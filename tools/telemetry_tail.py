#!/usr/bin/env python
"""Tail / summarize a telemetry sink JSONL from another terminal.

The human half of live inspection (docs/observability.md): while a trainer
writes its run log, this tool — run in a second terminal, or against a
copied file after the fact — renders the stream as compact per-record lines
and keeps a rolling summary, so "what is the run doing" needs neither a
Perfetto load nor the status endpoint. The machine half is
``tools/run_report.py`` (one JSON line, R7); this tool is deliberately
human-facing and NOT on the one-JSON-line contract.

Usage::

    python tools/telemetry_tail.py run.jsonl            # summarize + exit
    python tools/telemetry_tail.py run.jsonl --follow   # live tail (ctrl-C)
    python tools/telemetry_tail.py run.jsonl --last 20  # tail of the log

Handles records this build doesn't know (additive schema evolution) by
printing their kind; a rotated log's older segments are just more files —
pass them first.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional


def _fmt_hb(r: dict) -> str:
    norms = r.get("norms") or {}
    syn0 = norms.get("syn0") or {}
    extra = ""
    if syn0:
        extra = (f"  norm max {syn0.get('max_norm', 0):.3g}"
                 f" p99 {syn0.get('p99_norm', 0):.3g}")
    rec = r.get("recoveries", 0)
    scale = r.get("lr_scale", 1.0)
    state = ""
    if rec:
        state = f"  RECOVERIES {rec} lr x{scale:g}"
    elif scale is not None and scale != 1.0:
        state = f"  lr x{scale:g}"
    pps = r.get("pairs_per_sec") or 0.0
    return (f"hb    step {r.get('step', -1):>9}  "
            f"{pps:>12,.0f} pairs/s  alpha {r.get('alpha') or 0:.5f}"
            f"{extra}{state}")


def _fmt(r: dict) -> str:
    kind = r.get("kind", "?")
    if kind == "heartbeat":
        return _fmt_hb(r)
    if kind == "run_start":
        return (f"start run {r.get('run_id')}  vocab {r.get('vocab_size')}  "
                f"mesh {r.get('mesh')}")
    if kind == "run_end":
        return (f"end   run {r.get('run_id')}  status {r.get('status')}  "
                f"steps {r.get('steps')}  "
                f"{(r.get('pairs_trained') or 0):,.0f} pairs  "
                f"host-wait {r.get('host_wait_s_total')}s  "
                f"dispatch {r.get('dispatch_s_total')}s")
    if kind == "watchdog":
        return (f"WATCH step {r.get('step')}  [{r.get('policy')}] "
                f"{r.get('reason')}")
    if kind == "recovery":
        return (f"RECOV step {r.get('step')}  action {r.get('action')}  "
                f"lr x{r.get('lr_scale')}  clamp {r.get('max_row_norm')}  "
                f"({r.get('recoveries_performed')}/{r.get('max_recoveries')})")
    return f"{kind:5s} {json.dumps({k: v for k, v in r.items() if k not in ('schema', 'kind', 't')})[:120]}"


class Summary:
    """Rolling per-kind aggregation mirroring run_report.py's fields."""

    def __init__(self):
        self.kinds: dict = {}
        self.pps: list = []
        self.last_hb: Optional[dict] = None
        self.last_end: Optional[dict] = None
        self.bad_lines = 0

    def feed(self, r: dict) -> None:
        kind = r.get("kind", "?")
        self.kinds[kind] = self.kinds.get(kind, 0) + 1
        if kind == "heartbeat":
            self.last_hb = r
            if r.get("pairs_per_sec"):
                self.pps.append(float(r["pairs_per_sec"]))
        elif kind == "run_end":
            self.last_end = r

    def render(self) -> str:
        lines = [f"records: {sum(self.kinds.values())}  "
                 f"({', '.join(f'{k}={v}' for k, v in sorted(self.kinds.items()))})"]
        if self.bad_lines:
            lines.append(f"unparseable lines: {self.bad_lines} "
                         f"(truncated tail is normal on a live file)")
        if self.pps:
            s = sorted(self.pps)
            lines.append(
                f"pairs/s: median {s[len(s) // 2]:,.0f}  "
                f"p10 {s[int(len(s) * 0.1)]:,.0f}  "
                f"p90 {s[min(int(len(s) * 0.9), len(s) - 1)]:,.0f}  "
                f"last {self.pps[-1]:,.0f}")
        if self.last_hb is not None:
            lines.append("last " + _fmt_hb(self.last_hb))
            phases = self.last_hb.get("phases") or {}
            for name, ph in sorted(phases.items()):
                lines.append(
                    f"  phase {name:14s} count {ph.get('count', 0):>6}  "
                    f"total {ph.get('total_s', 0):8.3f}s  "
                    f"p50 {ph.get('p50_s', 0):.2e}s  "
                    f"p99 {ph.get('p99_s', 0):.2e}s")
        if self.last_end is not None:
            lines.append(_fmt(self.last_end))
        return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("paths", nargs="+", help="sink JSONL file(s), oldest "
                                             "rotated segment first")
    ap.add_argument("--follow", "-f", action="store_true",
                    help="keep tailing the LAST path for appended records")
    ap.add_argument("--last", type=int, default=0, metavar="N",
                    help="also print the last N records before the summary")
    ap.add_argument("--poll", type=float, default=0.5,
                    help="--follow poll interval in seconds")
    args = ap.parse_args()

    summary = Summary()
    tail: list = []
    pos = 0
    for path in args.paths:
        try:
            # readline (not iteration) so f.tell() stays legal — the follow
            # loop resumes from the last COMPLETE line's end
            with open(path, "r", encoding="utf-8") as f:
                while True:
                    line = f.readline()
                    if not line:
                        break
                    if path == args.paths[-1] and line.endswith("\n"):
                        pos = f.tell()
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        r = json.loads(line)
                    except json.JSONDecodeError:
                        summary.bad_lines += 1
                        continue
                    summary.feed(r)
                    if args.last:
                        tail.append(_fmt(r))
                        del tail[:-args.last]
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2

    for line in tail:
        print(line)
    print(summary.render())

    if not args.follow:
        return 0
    path = args.paths[-1]
    print(f"-- following {path} (ctrl-C to stop) --", file=sys.stderr)
    try:
        while True:
            try:
                # rotation: the sink renames the active file aside and
                # recreates it (sink._rotate) — a file SMALLER than our
                # offset is the new segment, so restart from 0 instead of
                # seeking past its end (which would silently drop every
                # record below the stale offset once it regrows)
                if os.path.getsize(path) < pos:
                    print(f"-- {path} rotated, restarting from its top --",
                          file=sys.stderr)
                    pos = 0
                with open(path, "r", encoding="utf-8") as f:
                    f.seek(pos)
                    while True:
                        line = f.readline()
                        # a partial line (writer mid-append) stays unparsed
                        # and is retried whole on the next poll
                        if not line or not line.endswith("\n"):
                            break
                        pos = f.tell()
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            r = json.loads(line)
                        except json.JSONDecodeError:
                            summary.bad_lines += 1
                            continue
                        summary.feed(r)
                        print(_fmt(r), flush=True)
            except FileNotFoundError:
                pass  # rotation window — the writer will recreate it
            time.sleep(args.poll)
    except KeyboardInterrupt:
        print(summary.render())
        return 0


if __name__ == "__main__":
    sys.exit(main())
