"""Benchmark: fused SGNS training throughput (word-pairs/sec + MFU) on one chip.

Measures the framework's production hot path — the Trainer's scan-chunked jitted step
(glint_word2vec_tpu/train/trainer.py): gather → batched dots → sigmoid → scatter-add,
negatives from the counter-based hash PRNG drawn once per chunk — on a realistic
single-chip config:

    vocab 200k (Zipf counts), d=300 (lane-padded to 384), 5 negatives over a shared
    64-pool, 8192 and 32768 pairs/step (BASELINE configs 2-3 territory; the reference's
    per-minibatch RPC budget capped it at ~65 pairs per round-trip, mllib:83-85)

Timing methodology (tools/microbench.py): through the remote-TPU tunnel,
``block_until_ready`` can return before device execution finishes, so naive loops
report fantasy numbers (we observed "0.007 ms/step" for a step whose scatter traffic
alone needs ~0.5 ms). Every number here is a two-point SLOPE over donated, data-dependent
chunk chains ending in a device→host fetch — constant overheads cancel, elision is
impossible. Profiling with this harness shows the step is scatter-add bound
(~66 ns/row; gathers ~23 ns/row; the pool matmuls are noise), which is why larger
batches win: per-row scatter cost drops ~40% from B=8k to B=32k.

Reported rows (stderr):
    step xla  B=8192/32768, f32 — step-only device throughput + MFU
    step pallas                 — the fused-kernel tier (ops/pallas/sgns_kernel.py)
    e2e trainer                 — Word2Vec-style end-to-end incl. the host pipeline

MFU = executed matmul FLOPs / v5e peak (197 TFLOP/s bf16). This workload is
row-access bound by nature — MFU is reported because BASELINE names it, pairs/s is the
decision metric.

The reference publishes no numbers (BASELINE.md: "none"), so ``vs_baseline`` is measured,
not quoted: the identical step math implemented with torch on the host CPU (gather +
einsum + index_add_), i.e. "what this machine could do without the accelerator". Values
> 1 mean the TPU path wins.

Prints exactly one JSON line on stdout with the headline step metric; the full row table
goes to stderr.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"))

V, D, NEG = 200_000, 300, 5
POOL = 64
PAD_D = 384        # lane-padded physical dim (config.pad_vector_to_lanes)
K = 16             # steps per dispatch chunk (config.steps_per_dispatch)
E2E_B = 65536      # e2e trainer batch: geometry sweep winner (bigger batches
                   # amortize both scatter row cost and feed transfers)
E2E_K = 32         # e2e steps per dispatch: bigger chunks -> fewer, larger feed
                   # transfers (the tunnel/DCN link rewards both)
CPU_STEPS = 10
CPU_B = 8192
PEAK_FLOPS = 197e12  # v5e bf16 peak / chip


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def zipf_counts(v: int) -> np.ndarray:
    return np.maximum(1e9 / (np.arange(v) + 10.0) ** 1.07, 5.0)


def step_flops(pool: int, b: int) -> float:
    """Matmul FLOPs per step of the shared-pool path: f_neg (B,D)x(D,P),
    d_in += g_neg@Z (B,P)x(P,D), d_Z = g_negT@e_in (P,B)x(B,D), plus elementwise."""
    return 3 * 2.0 * b * pool * PAD_D + 10.0 * b * PAD_D


def bench_step(counts, b: int, dtype: str = "float32",
               use_pallas: bool = False) -> tuple:
    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, init_embeddings, sgns_step_shared_core)

    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    syn0_0 = init_embeddings(V, PAD_D, jax.random.key(0)).syn0
    rng = np.random.default_rng(0)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (V, PAD_D)), jnp.float32)

    if use_pallas:
        from glint_word2vec_tpu.ops.pallas.sgns_kernel import make_pallas_sgns_step
        core = make_pallas_sgns_step(NEG, POOL, "exact", jnp.float32)
    else:
        cdt = jnp.dtype(dtype)

        def core(p, batch, negs, alpha):
            return sgns_step_shared_core(
                p, batch["centers"], batch["contexts"], batch["mask"],
                negs, alpha, NEG, "exact", cdt)

    def chunk(params, batches, base_step, prob, alias):
        negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, POOL))

        def body(p, inp):
            batch, ng = inp
            new_p, m = core(p, batch, ng, jnp.float32(0.025))
            return new_p, m.loss

        return jax.lax.scan(body, params, (batches, negs))

    f = jax.jit(chunk, donate_argnums=(0,))

    all_batches = []
    for i in range(24):
        r = np.random.default_rng(1000 + i)
        all_batches.append({
            "centers": jnp.asarray(r.integers(0, V, (K, b)), jnp.int32),
            "contexts": jnp.asarray(r.integers(0, V, (K, b)), jnp.int32),
            "mask": jnp.ones((K, b), jnp.float32),
        })

    def run(p, batches, base):
        return f(p, batches, base, prob, alias)

    spc = time_chunked(
        run,
        make_carry=lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
        args_for_iter=lambda i: (all_batches[i % 24], np.int32(100 + i)),
        n_lo=4, n_hi=16,
        fetch=lambda c, out: out[-1])
    ms = spc / K * 1e3
    pps = b / (spc / K)
    mfu = step_flops(POOL, b) / (spc / K) / PEAK_FLOPS
    label = "pallas" if use_pallas else f"xla {dtype}"
    log(f"step {label:12s} B={b:6d}: {ms:7.3f} ms/step -> "
        f"{pps:13,.0f} pairs/s  mfu={mfu * 100:5.2f}%")
    return pps, mfu


def bench_e2e() -> float:
    """End-to-end Word2Vec.fit on a synthetic Zipf corpus — includes vocab build,
    subsampling, window generation, batch packing, host→device transfer."""
    import jax

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    n_words, sent_len, vocab_sz = 4_000_000, 40, 50_000
    zipf = 1.0 / (np.arange(vocab_sz) + 10.0) ** 1.05
    ids = rng.choice(vocab_sz, size=n_words, p=zipf / zipf.sum())
    words = np.char.add("w", ids.astype("U8"))
    sentences = [list(words[i:i + sent_len])
                 for i in range(0, n_words, sent_len)]
    vocab = build_vocab(sentences, min_count=5)
    cfg = Word2VecConfig(
        vector_size=D, min_count=5, pairs_per_batch=E2E_B, num_iterations=1,
        window=5, negatives=NEG, negative_pool=POOL, steps_per_dispatch=E2E_K, seed=1)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    # warm the jit cache on the SAME trainer: one tiny fit would change train state, so
    # drive one dispatch-shaped call through the step fn directly
    trainer.fit(encoded[:400])
    trainer.state = type(trainer.state)()  # reset progress; params warm-start is fine
    trainer.pairs_trained = 0.0
    t0 = time.perf_counter()
    trainer.fit(encoded)
    # a dependent device->host fetch, not block_until_ready: through the remote-TPU
    # tunnel the latter can return before execution finishes (see tools/microbench.py)
    float(jnp.sum(trainer.params.syn0[:128]))
    dt = time.perf_counter() - t0
    pps = trainer.pairs_trained / dt
    log(f"e2e trainer (host pipeline incl.): {trainer.pairs_trained:,.0f} pairs "
        f"in {dt:.1f}s -> {pps:,.0f} pairs/s  "
        f"[host-wait {trainer.host_wait_time:.2f}s, dispatch {trainer.dispatch_time:.2f}s]")
    return pps


def bench_cpu_torch(counts: np.ndarray) -> float:
    """Same step math on host CPU with torch (gather/einsum/index_add_)."""
    import torch

    B = CPU_B
    torch.manual_seed(0)
    g = torch.Generator().manual_seed(0)
    syn0 = (torch.rand(V, D, generator=g) - 0.5) / D
    syn1 = torch.zeros(V, D)
    probs = torch.tensor(counts ** 0.75, dtype=torch.float64)
    probs /= probs.sum()
    alpha = 0.025
    rng = np.random.default_rng(0)
    centers = torch.tensor(rng.integers(0, V, B), dtype=torch.long)
    contexts = torch.tensor(rng.integers(0, V, B), dtype=torch.long)

    def step():
        negatives = torch.multinomial(probs.float(), POOL, replacement=True)
        e_in = syn0[centers]
        e_pos = syn1[contexts]
        Z = syn1[negatives]
        f_pos = (e_in * e_pos).sum(-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).float()
        g_pos = (1 - torch.sigmoid(f_pos)) * alpha
        g_neg = (0 - torch.sigmoid(f_neg)) * alpha * neg_valid * (NEG / POOL)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        syn0.index_add_(0, centers, d_in)
        syn1.index_add_(0, contexts, g_pos[:, None] * e_in)
        syn1.index_add_(0, negatives, g_neg.T @ e_in)

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(CPU_STEPS):
        step()
    dt = time.perf_counter() - t0
    pps = CPU_STEPS * B / dt
    log(f"cpu-torch baseline: {CPU_STEPS} steps in {dt:.3f}s -> {pps:,.0f} pairs/s")
    return pps


def main() -> None:
    import jax
    dev = jax.devices()[0]
    log(f"device: {dev} ({dev.platform})")
    counts = zipf_counts(V)

    pps8, mfu8 = bench_step(counts, b=8192, dtype="float32")
    pps32, mfu32 = bench_step(counts, b=32768, dtype="float32")
    pps64, mfu64 = bench_step(counts, b=65536, dtype="float32")
    if pps64 > pps32:
        pps32, mfu32 = pps64, mfu64
    try:
        bench_step(counts, b=8192, use_pallas=True)
    except Exception as e:
        log(f"pallas step failed: {type(e).__name__}: {e}")
    try:
        e2e_pps = bench_e2e()
    except Exception as e:
        log(f"e2e bench failed: {type(e).__name__}: {e}")
        e2e_pps = None

    try:
        cpu_pps = bench_cpu_torch(counts)
    except Exception as e:  # torch missing or OOM: report absolute number only
        log(f"cpu baseline failed: {e}")
        cpu_pps = None
    main_pps, main_mfu = (pps32, mfu32) if pps32 > pps8 else (pps8, mfu8)
    result = {
        "metric": "sgns_word_pairs_per_sec_per_chip",
        "value": round(main_pps),
        "unit": "pairs/s",
        "vs_baseline": round(main_pps / cpu_pps, 2) if cpu_pps else 1.0,
        "mfu": round(main_mfu, 4),
        "e2e_pairs_per_sec": round(e2e_pps) if e2e_pps else None,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
