"""Continual training subsystem (docs/continual.md, ROADMAP item 5).

Turns one-shot fits into a continuous train→publish→serve loop:

- :mod:`.extend` — incremental vocabulary extension on a checkpoint
  (identity-prefix growth, seeded new rows, per-shard for row-shards, the
  ``vocab_lineage`` fingerprint chain);
- :mod:`.stream` — the append-only corpus: fingerprinted segments, a
  persisted consumed-offset cursor, a delta encode pass that reuses cached
  encodes of old segments;
- :mod:`.loop` — :class:`~glint_word2vec_tpu.continual.loop.ContinualRunner`,
  the watch→extend→fit→publish driver whose atomic publishes feed the
  serving tier's ``CheckpointWatcher`` (docs/serving.md).

CLI: ``tools/continual_run.py`` (R7 one-JSON-line contract; ``--smoke`` runs
the self-contained end-to-end drill).
"""

from glint_word2vec_tpu.continual.extend import (
    VocabDelta,
    compute_vocab_delta,
    extend_checkpoint,
    extended_vocabulary,
    grow_arrays,
    lineage_fingerprints,
    seed_new_rows,
)
from glint_word2vec_tpu.continual.loop import ContinualRunner
from glint_word2vec_tpu.continual.stream import (
    ConcatCorpus,
    CorpusStream,
    StreamCursor,
    encode_delta,
    segment_fingerprint,
)

__all__ = [
    "VocabDelta",
    "compute_vocab_delta",
    "extended_vocabulary",
    "extend_checkpoint",
    "grow_arrays",
    "seed_new_rows",
    "lineage_fingerprints",
    "ContinualRunner",
    "ConcatCorpus",
    "CorpusStream",
    "StreamCursor",
    "encode_delta",
    "segment_fingerprint",
]
