#!/usr/bin/env python
"""graftrace dynamic half (ISSUE 20, docs/static-analysis.md layer 4): run
the concurrent serving/obs stack under instrumented lock wrappers
(``GLINT_LOCKCHECK=1``) plus a seeded schedule perturber, and gate on the
EXECUTED lock-discipline evidence:

- every acquisition-order edge actually taken is recorded per-thread;
- rank inversions against the static table (lockcheck.LOCK_TABLE) are
  findings — the gate is ZERO inversions beyond the committed baseline
  (tools/racecheck_baseline.json, normally empty);
- held-while-blocking windows (a thread blocking while holding another
  lock) are counted and reported;
- runtime edges the static R9 graph did not predict are reported
  (callbacks and closures the AST walk cannot see) — informational, since
  the rank check already judged them;
- checking OFF is proven zero-cost first, in the same process: the
  factories must return the RAW threading primitives (no wrapper objects
  allocated) and an interleaved min-of-k A/B of factory-made vs raw lock
  acquire/release must sit at parity (the telemetry_run methodology:
  min-of-k kills scheduler noise, parity threshold leaves headroom for
  timer jitter).

``--smoke`` builds an in-process stack — batcher + reload watcher +
statusd + telemetry sink — and hammers it from query/scrape/dump/publish
threads for a bounded, seeded burst (tier-1 + the CI concurrency job).
The full run additionally drives the serve-reload and fleet-kill chaos
phases (tools/chaos_run.py) with instrumentation on, exported to replica
subprocesses via the environment.

Prints exactly ONE JSON line on stdout (the R7 contract); exit 0 iff ok.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

BASELINE = os.path.join(REPO, "tools", "racecheck_baseline.json")

# parity threshold for the off-mode A/B: the factories return the raw
# primitive so the true ratio is 1.0; min-of-k still jitters a few percent
# on a busy host, and anything under 1.25x is indistinguishable from
# rerunning the same loop twice. A wrapper would cost 3-10x.
_ZERO_COST_RATIO = 1.25


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _zero_cost_probe() -> dict:
    """With checking off (the process default), the factories must hand back
    raw primitives — type-identical, zero wrappers — and cost the same."""
    from glint_word2vec_tpu import lockcheck

    raw_types = (
        type(lockcheck.make_lock("serve.handle"))  # graftlint: disable=R9 -- off-mode probe: off-site construction is the test
        is type(threading.Lock())
        and type(lockcheck.make_rlock("obs.sink"))  # graftlint: disable=R9 -- off-mode probe: off-site construction is the test
        is type(threading.RLock())
        and isinstance(
            lockcheck.make_condition("serve.batcher.cv"),  # graftlint: disable=R9 -- off-mode probe: off-site construction is the test
            threading.Condition))

    def bench(lk, n: int = 20000) -> int:
        t0 = time.perf_counter_ns()
        for _ in range(n):
            with lk:
                pass
        return time.perf_counter_ns() - t0

    raw = threading.Lock()  # graftlint: disable=R9 -- raw primitive is the A/B control
    made = lockcheck.make_lock("serve.handle")  # graftlint: disable=R9 -- off-mode probe: off-site construction is the test
    bench(raw), bench(made)  # warm both code paths before timing
    a = min(bench(raw) for _ in range(7))
    b = min(bench(made) for _ in range(7))
    ratio = b / a if a else float("inf")
    return {
        "raw_types": raw_types,
        "wrappers_allocated": lockcheck.wrappers_allocated(),
        "ns_raw_min": a, "ns_factory_min": b,
        "ratio": round(ratio, 3),
        "ok": (raw_types and lockcheck.wrappers_allocated() == 0
               and ratio < _ZERO_COST_RATIO),
    }


def _smoke_stack(workdir: str, seed: int, perturb: float,
                 duration_s: float) -> dict:
    """Build the batcher/reload/statusd/sink stack with instrumentation ON
    and hammer it from four threads: queries, status scrapes, blackbox
    dumps + stats emission, and checkpoint publishes (hot reloads)."""
    from glint_word2vec_tpu import lockcheck

    lockcheck.configure(enabled=True, seed=seed, perturb=perturb)
    lockcheck.reset()

    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.serve import EmbeddingService
    from glint_word2vec_tpu.train.trainer import Trainer
    from tools.chaos_run import toy_config, toy_sentences

    sents = toy_sentences(120, seed=seed)
    vocab = build_vocab(sents, min_count=1)
    trainer = Trainer(toy_config(), vocab)
    ck = os.path.join(workdir, "ck")
    trainer.save_checkpoint(ck)

    port = _free_port()
    service = EmbeddingService(
        checkpoint=ck, ann=False, watch=True, reload_poll_s=0.02,
        max_batch=8, max_delay_ms=0.5, status_port=port,
        telemetry_path=os.path.join(workdir, "tele.jsonl"))
    errors: list = []
    stop = threading.Event()
    words = [w for w in vocab.words[:8] if w]

    def _guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except Exception as e:  # noqa: BLE001 — any raise fails the run
                errors.append(f"{type(e).__name__}: {e}")
        return run

    def queries():
        for w in words:
            service.vector(w, timeout=30.0)

    def scrapes():
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status.json", timeout=5).read()
        time.sleep(0.002)

    def dumps():
        service.dump_blackbox({"kind": "racecheck"}, include_stats=False)
        service.stats()
        service.emit_stats()
        time.sleep(0.002)

    def publishes():
        trainer.save_checkpoint(ck)
        time.sleep(0.05)

    threads = [threading.Thread(target=_guard(f), name=f"racecheck-{f.__name__}")
               for f in (queries, scrapes, dumps, publishes)]
    try:
        for t in threads:
            t.start()
        time.sleep(duration_s)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        leaked = service.close()
    if any(t.is_alive() for t in threads):
        errors.append("racecheck hammer thread failed to join")
    if leaked:
        errors.append(f"service leaked {leaked} thread(s) on close")
    rep = lockcheck.report()
    rep["errors"] = errors
    rep["reloads_observed"] = service.reloads
    return rep


def _chaos_phases(workdir: str, n_sentences: int) -> dict:
    """The full run's second leg: the two thread-heaviest chaos phases with
    instrumentation exported to subprocess replicas via the environment."""
    from tools.chaos_run import phase_fleet_kill, phase_serve_reload

    out = {}
    for name, fn, sub in [
            ("serve-reload", phase_serve_reload, "p_reload"),
            ("fleet-kill", phase_fleet_kill, "p_fleet")]:
        d = os.path.join(workdir, sub)
        os.makedirs(d, exist_ok=True)
        try:
            out[name] = fn(d, n_sentences)
        except Exception as e:  # noqa: BLE001 — any raise is the failure
            out[name] = f"{type(e).__name__}: {e}"
    return out


def _static_cross_check(runtime_edges: list) -> dict:
    """Edges the schedule executed but the static R9 graph did not predict:
    informational (the rank gate already judged them), but reported so a
    statically-invisible nesting (a callback through a stored closure) is
    at least VISIBLE in the artifact."""
    from tools.graftlint.concurrency import R9LockOrder, _TreeIndex

    index = _TreeIndex(REPO)
    edges: dict = {}
    memo: dict = {}

    def record(outer, inner, path, line, via):
        edges.setdefault((outer, inner), (path, line, via))

    r9 = R9LockOrder()
    for fn in index.fns.values():
        r9._walk_fn(index, fn, [], record, memo)
    static = {f"{a}->{b}" for a, b in edges}
    return {
        "static_edges": sorted(static),
        "edges_unexplained": sorted(set(runtime_edges) - static),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="in-process stack only (tier-1 / CI concurrency)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--perturb", type=float, default=0.05,
                    help="per-acquire yield probability (seeded)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="hammer seconds (default 1.5 smoke / 3.0 full)")
    ap.add_argument("--sentences", type=int, default=300)
    ap.add_argument("--workdir", default="")
    ap.add_argument("--baseline", default=BASELINE)
    args = ap.parse_args()

    mode = "smoke" if args.smoke else "full"
    duration = args.duration or (1.5 if args.smoke else 3.0)
    workdir = args.workdir or tempfile.mkdtemp(prefix="glint_racecheck_")
    os.makedirs(workdir, exist_ok=True)

    # 1) zero-cost off, proven BEFORE anything enables checking
    zero_cost = _zero_cost_probe()

    # 2) the instrumented in-process stack
    os.environ["JAX_PLATFORMS"] = os.environ.get("JAX_PLATFORMS", "cpu")
    rep = _smoke_stack(workdir, args.seed, args.perturb, duration)

    # 3) full mode: chaos phases with instrumentation exported to children
    phases: dict = {}
    if mode == "full":
        os.environ["GLINT_LOCKCHECK"] = "1"
        os.environ["GLINT_LOCKCHECK_SEED"] = str(args.seed)
        os.environ["GLINT_LOCKCHECK_PERTURB"] = str(args.perturb)
        phases = _chaos_phases(workdir, args.sentences)
        from glint_word2vec_tpu import lockcheck
        rep = lockcheck.report()  # accumulated across smoke + phases
        rep["errors"] = []

    cross = _static_cross_check(rep["edges"])

    try:
        with open(args.baseline, "r", encoding="utf-8") as f:
            allowed = json.load(f).get("inversions", [])
        baseline_ok = True
    except OSError:
        allowed, baseline_ok = [], False
    allowed_keys = {(i["held"], i["acquiring"]) for i in allowed}
    unbaselined = [i for i in rep["inversions"]
                   if (i["held"], i["acquiring"]) not in allowed_keys]

    ok = (zero_cost["ok"] and baseline_ok and not unbaselined
          and not rep["errors"] and rep["acquisitions"] > 0
          and all(v == "" for v in phases.values()))
    print(json.dumps({
        "tool": "racecheck", "schema": 1, "mode": mode, "ok": ok,
        "seed": args.seed, "perturb": args.perturb,
        "zero_cost": zero_cost,
        "lockcheck": rep,
        "inversions_unbaselined": unbaselined,
        "baseline_found": baseline_ok,
        "phases": phases,
        **cross,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
