"""Integration suite on the reference's toy corpus with its semantic quality gates.

The analog of the reference's only test suite (ServerSideGlintWord2VecSpec, SURVEY §4):
train once on the German-Wikipedia country/capital corpus, then assert the same gates —
top-10("österreich") contains "wien" with cosine > 0.9 (it spec:290-305) and the
wien − österreich + deutschland ≈ berlin analogy with cosine > 0.9 (it spec:327-352) —
plus transform/getVectors/persistence scenarios (it spec:137-415).

Where the reference needed a Docker Spark+HDFS cluster and a detached PS app
(build.sbt:48-77), this runs in-process: the corpus is read straight from the read-only
reference checkout, and the "cluster" is the virtual device mesh from conftest.
"""

import os

import numpy as np
import pytest

from glint_word2vec_tpu import (
    ServerSideGlintWord2VecModel,
    Word2Vec,
)
from glint_word2vec_tpu.data.vocab import read_corpus

pytestmark = pytest.mark.skipif(
    not os.path.exists("/root/reference/de_wikipedia_articles_country_capitals.txt"),
    reason="reference toy corpus not available")

# Hyperparameters mirror the reference's training test (it spec:83-106: seed 1,
# stepSize 0.025, defaults elsewhere) with the TPU-native batching knobs; subsampling is
# on (the reference's is a silent no-op — see pipeline.py) and 4 iterations substitute
# for the extra effective updates its async 50-pair minibatches got from one pass.
FIT = dict(vector_size=100, learning_rate=0.025, window=5, negatives=5, min_count=5,
           pairs_per_batch=256, seed=1, subsample_ratio=3e-3, num_iterations=4)


@pytest.fixture(scope="module")
def corpus(toy_corpus_path):
    sents = list(read_corpus(toy_corpus_path))
    assert len(sents) > 3000
    return sents


@pytest.fixture(scope="module")
def model(corpus):
    return Word2Vec(**FIT).fit(corpus)


def test_corpus_stats(corpus, model):
    # vocab 3,609–3,611 at minCount 5 (it spec:22-37 reports 3,611 incl. tokenizer diffs)
    assert sum(len(s) for s in corpus) == 161_676
    assert abs(model.num_words - 3611) < 10
    assert model.vector_size == 100


def test_synonym_gate(model):
    """top-10("österreich") contains "wien", cosine > 0.9 (it spec:290-305)."""
    syns = model.find_synonyms("österreich", 10)
    assert len(syns) == 10
    d = dict(syns)
    assert "wien" in d
    assert d["wien"] > 0.9


def test_analogy_gate(model):
    """wien − österreich + deutschland ≈ berlin, cosine > 0.9 (it spec:327-352).

    Built exactly as the reference does: sentence-transform each single-word sentence,
    then vector arithmetic and a top-10 vector query."""
    vecs = model.transform_sentences([["österreich"], ["deutschland"],
                                      ["wien"], ["berlin"]])
    analogy_vec = vecs[2] - vecs[0] + vecs[1]
    res = model.find_synonyms(analogy_vec, 10)
    assert len(res) == 10
    d = dict(res)
    assert "berlin" in d
    assert d["berlin"] > 0.9


def test_transform_single_words(model):
    """Per-word vectors: nonzero, right length (it spec:198-238)."""
    for w in ["österreich", "wien", "deutschland", "berlin"]:
        v = model.transform(w)
        assert v.shape == (100,)
        assert np.abs(v).sum() > 0


def test_transform_batched_iterator(model):
    """Batched iterator path (it spec:240-258)."""
    out = list(model.transform_words(["wien", "berlin", "paris"]))
    assert len(out) == 3
    assert all(v.shape == (100,) for v in out)


def test_sentence_transform_preserves_columns(model):
    """DataFrame-transform analog keeps extra columns + appends output (it spec:260-288)."""
    wrapped = ServerSideGlintWord2VecModel(model)
    rows = [{"sentence": ["wien", "ist"], "extra": 1}]
    out = wrapped.transform(rows)
    assert set(out[0]) == {"sentence", "extra", "vector"}
    assert out[0]["extra"] == 1
    assert out[0]["vector"].shape == (100,)


def test_get_vectors_count(model):
    """getVectors: one row per vocab word (it spec:384-398)."""
    vecs = model.get_vectors()
    assert len(vecs) == model.num_words
    assert vecs["wien"].shape == (100,)


def test_save_load_roundtrip_preserves_gates(model, tmp_path):
    """Persistence round-trip (it spec:137-155): params and vectors survive."""
    path = str(tmp_path / "toy-model")
    model.save(path)
    loaded = ServerSideGlintWord2VecModel.load(path)
    d = dict(loaded.findSynonyms("österreich", 10))
    assert "wien" in d and d["wien"] > 0.9
    cfg = loaded.inner.config
    assert cfg.seed == 1 and cfg.vector_size == 100
    np.testing.assert_allclose(
        loaded.inner.transform("wien"), model.transform("wien"), rtol=1e-6)


def test_to_local(model):
    """toLocal dense export (it spec:400-415)."""
    words, mat = model.to_local()
    assert mat.shape == (model.num_words, 100)
    assert "wien" in words


@pytest.mark.slow
def test_semantic_gates_bfloat16(corpus):
    """Both reference gates hold with bf16-STORED embeddings (the measured fast path:
    rows are 768 B instead of 1536 B and the step is row-byte-bound, bench.py). This is
    the quality evidence behind offering param_dtype="bfloat16"; f32 stays the default."""
    m = Word2Vec(**FIT, param_dtype="bfloat16", compute_dtype="bfloat16").fit(corpus)
    syns = dict(m.find_synonyms("österreich", 10))
    assert "wien" in syns and syns["wien"] > 0.9
    vecs = m.transform_sentences([["österreich"], ["deutschland"],
                                  ["wien"], ["berlin"]])
    res = dict(m.find_synonyms(vecs[2] - vecs[0] + vecs[1], 10))
    assert "berlin" in res and res["berlin"] > 0.9
