"""R7 good: humans read stderr; stdout carries exactly one JSON line."""
import json
import sys


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    log("starting benchmark")
    print(json.dumps({"ok": True}))
