"""Finite-blowup watchdog: the guardrail ROADMAP item 2 says is missing.

The non-finite guardrail (``config.nonfinite_policy``, round 6) only fires
when the carry reaches NaN/inf — and the measured 1.6M-vocab quality
collapse never does: purity falls 0.99 → 0.14 through a FINITE norm blowup
(EVAL.md round-5 ladder), so the only trace today is a construction-time
warning. This watchdog consumes the fused health probe's channels
(:mod:`.probe`) at the same heartbeat cadence and fires on either of two
measured signatures, per matrix:

- ``frac_over`` — the fraction of rows past ``config.norm_watch_threshold``
  exceeds ``config.norm_watch_frac``: the round-5 collapse is visible here
  long before the max (a subset of hot rows blows up first — the pool-load
  mechanism in trainer._stability_warnings);
- ``max_norm`` — any single row past ``config.norm_watch_max``: the hard
  ceiling, catching a lone runaway row the fraction channel would dilute at
  large vocabularies.

Policy (``config.norm_watch``): ``warn`` logs + emits a telemetry record per
firing probe (training continues — the research posture while the ROADMAP
item 2 ladder correlates norm trajectory with quality); ``recover`` returns
the firing reason to the trainer, which runs the detect→mitigate→recover
ladder (snapshot-ring rollback + lr backoff + ``max_row_norm`` engagement
under a ``max_recoveries`` budget — trainer._watchdog_check,
docs/robustness.md); ``halt`` raises
:class:`~glint_word2vec_tpu.train.faults.NormBlowupError` with the channels
and the measured mitigations, the same fail-fast contract as
``nonfinite_policy="halt"``. Thresholds and their provenance:
docs/observability.md.
"""

from __future__ import annotations

import logging
from typing import Optional

from glint_word2vec_tpu.train.faults import NormBlowupError

logger = logging.getLogger("glint_word2vec_tpu")


class NormWatchdog:
    """Stateful checker over successive probe channel dicts (one Trainer run)."""

    def __init__(self, policy: str, threshold: float, max_norm: float,
                 frac: float):
        if policy not in ("off", "warn", "recover", "halt"):
            raise ValueError(f"norm_watch policy must be 'off', 'warn', "
                             f"'recover', or 'halt' but got {policy!r}")
        self.policy = policy
        self.threshold = threshold
        self.max_norm = max_norm
        self.frac = frac
        self.fires = 0
        self.last_reason: Optional[str] = None

    def would_fire(self, channels: dict) -> Optional[str]:
        """Pure threshold evaluation: the firing reason for one probe channel
        dict, or None. No state is touched and no policy applies — the
        trainer also consults this to keep a state the watchdog would flag
        OUT of the snapshot ring (a blown carry must never become the
        'good' restore point)."""
        reasons = []
        for name in ("syn0", "syn1"):
            ch = channels.get(name) or {}
            mx = ch.get("max_norm", 0.0)
            fo = ch.get("frac_over", 0.0)
            if fo >= self.frac:
                reasons.append(
                    f"{name}: {fo:.2%} of rows exceed norm "
                    f"{self.threshold:g} (limit {self.frac:.2%})")
            if mx >= self.max_norm:
                reasons.append(
                    f"{name}: max row norm {mx:.3g} >= {self.max_norm:g}")
        return "; ".join(reasons) if reasons else None

    def check(self, channels: dict, step: int) -> Optional[str]:
        """Evaluate one probe result. Returns the firing reason (also stored
        on :attr:`last_reason`) or None; raises under ``policy="halt"``.
        Under ``"recover"`` the reason is returned for the trainer to act on
        (rollback/backoff/clamp-engage — the ladder lives in the trainer,
        which owns the snapshot ring and the step functions)."""
        if self.policy == "off":
            return None
        reason = self.would_fire(channels)
        if reason is None:
            return None
        self.fires += 1
        self.last_reason = reason
        diag = (
            f"finite norm blowup at global step {step}: {reason}. This is "
            f"the measured large-vocab collapse channel (EVAL.md round-5 "
            f"ladder: purity 0.99 -> 0.14 with NO NaN, so nonfinite_policy "
            f"never fires). Measured mitigations, in order: grow "
            f"negative_pool (keep load B*n/P <= ~160 at large vocab), lower "
            f"subsample_ratio (~1e-4), lower the learning rate, or "
            f"duplicate_scaling=True")
        if self.policy == "halt":
            raise NormBlowupError(diag)
        if self.policy == "recover":
            # one line per firing — the trainer logs the recovery action
            # itself (snapshot step, lr scale, engaged clamp) right after
            logger.warning(
                "norm watchdog (firing %d) at step %d: %s — recovering",
                self.fires, step, reason)
            return reason
        if self.fires == 1:
            logger.warning("norm watchdog: %s", diag)
        else:
            # the full diagnostic fired once; a still-blown carry re-fires
            # every probe, so later firings log one line (the sink keeps the
            # full channel record per firing regardless)
            logger.warning(
                "norm watchdog (firing %d) at step %d: %s",
                self.fires, step, reason)
        return reason
