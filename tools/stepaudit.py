"""Compiled-step contract auditor — layer 2 of the static-analysis subsystem.

Where graftlint (layer 1, tools/graftlint/) checks what the SOURCE promises,
this tool checks what the COMPILED ARTIFACT actually does. It builds
production ``Trainer`` objects for the four step variants — rows-GSPMD,
explicit shard_map, cols layout, banded CBOW — runs a scripted multi-chunk
fit through the real feed plumbing, captures the exact per-dispatch argument
avals, AOT-lowers the production step with them, and asserts four contracts
that prose and reviewers used to carry alone:

(a) **donation** — the params carry is ACTUALLY donated in the compiled
    executable (``input_output_alias`` present for both matrices). A silently
    dropped ``donate_argnums`` doubles peak HBM at the headline [V, D] pair;
    nothing else in the repo would notice.
(b) **transfers** — the scripted fit runs under
    ``jax.transfer_guard("disallow")``: every host→device byte moves through
    the explicit staging discipline (put_global / _stage_dispatch_meta), zero
    implicit transfers anywhere in the steady-state loop.
(c) **dtype** — no f64 anywhere in the lowered step module (x64 creep), and
    in bf16 mode no dense ``[V_padded, D_padded]`` f32 intermediate (a dense
    upcast would silently double the step's HBM traffic). Checked on the
    platform-neutral lowered module, NOT the CPU-compiled one — the CPU
    backend's float-normalization pass rewrites bf16 compute to f32 and would
    poison the check (same caveat as tools/collectives.py).
(d) **recompilation** — the scripted fit performs EXACTLY one jit compilation
    across both step twins: shape/static-arg churn (a new pad shape, a meta
    row added without staging, an accidental python-scalar argument) fails
    tier-1 here instead of surfacing as mystery recompiles in a hardware
    session.

Baseline: the committed ``STEPAUDIT.json`` snapshot pins the structural
fields; tests/test_stepaudit.py fails on drift. The dryrun_multichip artifact
embeds the same fields so every MULTICHIP JSON certifies the compiled-step
contracts next to the collective-bytes fields.

Run:  python tools/stepaudit.py [--smoke] [--mesh 2x4] [--json-out F]
Prints progress on stderr and exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# self-provision the virtual multi-device CPU mesh BEFORE jax initializes
if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VARIANTS = ("rows_gspmd", "shard_map", "cols", "cbow_banded",
            # stabilizer-on twins (ISSUE 7): the clamp/clip/decay ops ride
            # inside the jitted chunk, so they must hold the same four
            # contracts — donation (the touched-row scatter-set must not
            # break aliasing), transfers, dtype (stabilizer norm math is
            # promote(dtype, f32) — no f64 creep), one-compile
            "rows_gspmd_stab", "shard_map_stab",
            # ISSUE-14 step restructurings: the fused coefficient chain, the
            # cross-step hot-row slab scan (segmented scans + prefix flush
            # must keep donation/transfers/one-compile), and the end-to-end
            # bf16 chain twin, which additionally carries the NEW dtype
            # contract — no dense f32 [B, D] intermediate in the lowered
            # bf16 module (dense_f32_bd_free)
            "rows_gspmd_fused", "rows_gspmd_hot", "rows_gspmd_bf16_chain",
            # ISSUE-17 local-SGD: the sync_every=k owner-local window — the
            # k-step unrolled shard_map body plus the delta-merge psum must
            # keep donation (window params carry aliased), transfers, dtype,
            # and one-compile (the window is ONE jitted program, never a
            # separate merge dispatch)
            "localsgd")
# the bf16 twin of the rows step carries the dense-f32 check (contract c)
BF16_VARIANT = "rows_gspmd_bf16"


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def donation_summary(compiled_text: str) -> dict:
    """Contract (a) parser: input/output aliasing from a compiled module's
    one-line HloModule header::

        input_output_alias={ {0}: (0, {}, may-alias), {1}: (1, ...), ... }

    A dropped ``donate_argnums`` (or a donation silently discarded by an
    aval/sharding mismatch) leaves the header absent → 0 aliased params.
    Exposed standalone so tests can assert the auditor catches exactly that
    on a toy step."""
    header = next((ln for ln in compiled_text.splitlines()
                   if "input_output_alias" in ln), "")
    aliased = len(re.findall(r"(?:may|must)-alias", header))
    return {"present": bool(header), "aliased_params": aliased,
            "ok": aliased >= 2}   # the params carry = syn0 + syn1


def _variant_config_kwargs(variant: str) -> dict:
    if variant == "rows_gspmd":
        return {}
    if variant == "shard_map":
        return dict(step_lowering="shard_map", negative_pool=16)
    if variant == "cols":
        return dict(embedding_partition="cols")
    if variant == "cbow_banded":
        return dict(cbow=True, cbow_update="banded", negative_pool=16)
    if variant == "rows_gspmd_stab":
        return dict(negative_pool=16, max_row_norm=50.0, update_clip=0.5,
                    row_l2=1e-4)
    if variant == "shard_map_stab":
        return dict(step_lowering="shard_map", negative_pool=16,
                    max_row_norm=50.0, update_clip=0.5, row_l2=1e-4)
    if variant == "rows_gspmd_fused":
        return dict(negative_pool=16, fused_logits=True)
    if variant == "rows_gspmd_hot":
        return dict(negative_pool=16, hot_rows=8, hot_flush_every=2)
    if variant == "rows_gspmd_bf16_chain":
        return dict(negative_pool=16, param_dtype="bfloat16",
                    compute_dtype="bfloat16", logits_dtype="bfloat16",
                    fused_logits=True, bf16_chain=True)
    if variant == "localsgd":
        # sync_every must divide the audit cfg's steps_per_dispatch=2
        return dict(step_lowering="shard_map", negative_pool=16, sync_every=2)
    if variant == BF16_VARIANT:
        return dict(param_dtype="bfloat16", compute_dtype="bfloat16")
    raise ValueError(f"unknown variant {variant!r}")


def _toy_problem(geom: dict):
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import Vocabulary

    rng = np.random.default_rng(0)
    V = geom["v"]
    words = [f"w{i}" for i in range(V)]
    vocab = Vocabulary.from_words_and_counts(words, rng.integers(1, 100, V))
    sents = [[f"w{i}" for i in rng.integers(0, V, 12)]
             for _ in range(geom["sentences"])]
    return vocab, encode_sentences(sents, vocab, 1000)


def _capture_wrap(trainer):
    """Replace the trainer's step twins with wrappers that record the aval
    (ShapeDtypeStruct + sharding) pytree of the first dispatch's arguments —
    the exact production signature the AOT lowering re-traces below."""
    import jax

    orig_full, orig_fast = trainer._step_fn, trainer._step_fn_fast
    cap = {}

    def to_sds(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        # a non-device leaf here IS the regression the transfer guard then
        # reports — keep capturing so the other contracts still run
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)

    def wrap(fn):
        def wrapped(*args):
            if "sds" not in cap:
                cap["sds"] = jax.tree.map(to_sds, args)
            return fn(*args)
        return wrapped

    trainer._step_fn = wrap(orig_full)
    trainer._step_fn_fast = (trainer._step_fn if orig_fast is orig_full
                             else wrap(orig_fast))
    return orig_full, orig_fast, cap


def audit_variant(variant: str, mesh_shape, geom: dict) -> dict:
    """Run the four contract checks for one step variant; returns the result
    dict (every leaf JSON-serializable). Raises nothing on contract failure —
    callers assert on the ``ok`` fields so one broken contract still reports
    the other three."""
    import jax

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, enc = _toy_problem(geom)
    if variant == "rows_gspmd_hot":
        # the hot-row restructuring is the single-chip path by contract
        # (config refuses multi-shard meshes, the trainer refuses
        # multi-device plans — PERF.md §11); audit it where it runs
        mesh_shape = (1, 1)
    plan = make_mesh(*mesh_shape)
    cfg = Word2VecConfig(
        vector_size=geom["d"], min_count=1, pairs_per_batch=geom["b"],
        num_iterations=1, window=2, steps_per_dispatch=2,
        **_variant_config_kwargs(variant))
    trainer = Trainer(cfg, vocab, plan=plan)
    orig_full, orig_fast, cap = _capture_wrap(trainer)

    # (b) transfers: the scripted fit must be implicit-transfer-free
    transfer_ok, transfer_err = True, None
    try:
        with jax.transfer_guard("disallow"):
            trainer.fit(enc)
    except Exception as e:  # noqa: BLE001 — reported, not raised (see docstring)
        transfer_ok, transfer_err = False, f"{type(e).__name__}: {e}"[:500]
    trainer._step_fn, trainer._step_fn_fast = orig_full, orig_fast

    # (d) recompilation tripwire: exactly ONE compile across both twins.
    # Reported independently of contract (b): when the guarded fit aborted
    # the count is not meaningful, so (d) reports ok=None ("not assessed"),
    # never a phantom violation — one broken contract must not masquerade
    # as another.
    compiles = orig_full._cache_size()
    if orig_fast is not orig_full:
        compiles += orig_fast._cache_size()
    recompile = {"compiles": int(compiles), "expected": 1,
                 "ok": (compiles == 1) if transfer_ok else None}

    donation = {"present": False, "aliased_params": 0, "ok": False}
    dtype = {"f64_free": None, "dense_f32_vd_free": None, "ok": False}
    if "sds" in cap:
        dispatched = (orig_full if orig_full._cache_size() else orig_fast)
        lowered = dispatched.lower(*cap["sds"])

        # (c) dtype audit on the platform-neutral lowered module
        lowered_text = lowered.as_text()
        dtype["f64_free"] = "f64" not in lowered_text
        dtype["ok"] = dtype["f64_free"]
        if cfg.param_dtype == "bfloat16":
            dense = f"tensor<{trainer.padded_vocab}x{trainer.padded_dim}xf32>"
            dtype["dense_f32_vd_free"] = dense not in lowered_text
            dtype["ok"] = dtype["ok"] and dtype["dense_f32_vd_free"]
        if cfg.bf16_chain:
            # the ISSUE-14 dtype-contract row: the end-to-end bf16 chain
            # must leave NO dense f32 [B, D] intermediate in the lowered
            # module (the classic chain's f_pos path converts the [B, D]
            # product to f32 before its reduce; the chain accumulates in
            # the dot via preferred_element_type instead)
            dense_bd = f"tensor<{geom['b']}x{trainer.padded_dim}xf32>"
            dtype["dense_f32_bd_free"] = dense_bd not in lowered_text
            dtype["ok"] = dtype["ok"] and dtype["dense_f32_bd_free"]

        # (a) donation: input/output aliasing in the compiled artifact
        donation = donation_summary(lowered.compile().as_text())

    return {
        "variant": variant,
        "mesh": list(mesh_shape),
        "steps": int(trainer.global_step),
        "donation": donation,
        "transfers": {"ok": transfer_ok, "error": transfer_err,
                      "dispatches": int(trainer.global_step)
                      // cfg.steps_per_dispatch},
        "dtype": dtype,
        "recompile": recompile,
        "ok": bool(donation["ok"] and transfer_ok and dtype["ok"]
                   and recompile["ok"] is True),
    }


def audit_recover_rebuild(geom: dict) -> dict:
    """ISSUE 8 satellite: the ``norm_watch="recover"`` escalation ladder
    auto-engages ``max_row_norm`` on first firing, which REBUILDS the step
    twins — documented as "one recompile per engagement, logged"
    (trainer._perform_recovery), but until now nothing machine-checked it.
    This audit drives a real recovery through a scripted finite blowup
    (train.faults scale injection — the same deterministic hook the chaos
    schedule uses) and asserts the one-logged-recompile contract:

    - exactly ONE recovery fires and the step twins are rebuilt once;
    - the pre-recovery twins hold the usual one-compile contract;
    - the REBUILT twins compile exactly once more — total 2 compiles for the
      whole blowup-and-recover fit, not a recompile-per-dispatch storm;
    - the engaged clamp is the watchdog threshold (the boundary the firing
      measured health by).
    """
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train import faults
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, enc = _toy_problem(geom)
    cfg = Word2VecConfig(
        vector_size=geom["d"], min_count=1, pairs_per_batch=geom["b"],
        num_iterations=2, window=2, steps_per_dispatch=2,
        heartbeat_every_steps=2, prefetch_chunks=0, subsample_ratio=0.0,
        norm_watch="recover", nonfinite_policy="halt")
    trainer = Trainer(cfg, vocab, plan=make_mesh(1, 1))
    pre_full, pre_fast = trainer._step_fn, trainer._step_fn_fast

    rebuilds = []
    orig_build = trainer._build_step

    def counting_build(with_metrics: bool = True):
        rebuilds.append(with_metrics)
        return orig_build(with_metrics)

    trainer._build_step = counting_build

    error = None
    faults.configure(scale_params_at_step=8)
    try:
        trainer.fit(enc)
    except Exception as e:  # noqa: BLE001 — reported, not raised (audit style)
        error = f"{type(e).__name__}: {e}"[:500]
    finally:
        faults.reset()
        trainer._build_step = orig_build

    post_full, post_fast = trainer._step_fn, trainer._step_fn_fast
    rebuilt = post_full is not pre_full

    def twin_compiles(full, fast):
        n = full._cache_size()
        if fast is not full:
            n += fast._cache_size()
        return int(n)

    compiles_before = twin_compiles(pre_full, pre_fast)
    compiles_after = twin_compiles(post_full, post_fast) if rebuilt else 0
    engaged = float(trainer._stabilizers.max_row_norm)
    result = {
        "error": error,
        "recoveries": int(trainer.recoveries_performed),
        "watchdog_fires": int(trainer.norm_watchdog.fires),
        "rebuilt": bool(rebuilt),
        "rebuild_calls": len(rebuilds),
        "compiles_before": compiles_before,
        "compiles_after": compiles_after,
        "total_compiles": compiles_before + compiles_after,
        "engaged_max_row_norm": engaged,
        "expected_total_compiles": 2,
    }
    result["ok"] = bool(
        error is None
        and result["recoveries"] == 1
        and rebuilt
        and compiles_before == 1
        and compiles_after == 1
        and engaged == cfg.norm_watch_threshold)
    return result


def audit(mesh_shape=(2, 4), geom=None, variants=None) -> dict:
    """Audit the given variants (default: all four + the bf16 dtype twin) at
    one mesh shape. Importable — __graft_entry__.dryrun_multichip embeds a
    two-variant subset in the MULTICHIP JSON line."""
    geom = geom or smoke_geometry()
    variants = variants or (VARIANTS + (BF16_VARIANT,))
    out = {"geometry": geom, "mesh": list(mesh_shape), "variants": {}}
    for v in variants:
        log(f"stepaudit: auditing {v} at mesh "
            f"{mesh_shape[0]}x{mesh_shape[1]} ...")
        res = audit_variant(v, mesh_shape, geom)
        out["variants"][v] = res
        log(f"  {v:16s} donation={res['donation']['ok']} "
            f"transfers={res['transfers']['ok']} dtype={res['dtype']['ok']} "
            f"recompile={res['recompile']['ok']}")
    out["ok"] = all(r["ok"] for r in out["variants"].values())
    return out


def smoke_geometry() -> dict:
    return dict(v=64, d=16, b=16, sentences=64)


def full_geometry() -> dict:
    # still CPU-feasible; a larger vocab exercises real padding geometry
    return dict(v=1000, d=32, b=64, sentences=192)


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny geometry (the tier-1 wiring)")
    ap.add_argument("--mesh", default="2x4", help="'NDxNM', e.g. 2x4")
    ap.add_argument("--only", default="",
                    help="comma-separated variant subset (e.g. 'localsgd'); "
                         "skips the recover-rebuild audit — the full run "
                         "(and the STEPAUDIT.json baseline) covers all "
                         "variants")
    ap.add_argument("--json-out", default="",
                    help="also write the JSON result to this path")
    args = ap.parse_args(argv)

    import jax
    n = len(jax.devices())
    shape = tuple(int(x) for x in args.mesh.split("x"))
    if n < shape[0] * shape[1]:
        raise SystemExit(
            f"need {shape[0] * shape[1]} devices (have {n}); run as a script "
            "so the CPU mesh self-provisions, or set "
            "--xla_force_host_platform_device_count")

    geom = smoke_geometry() if args.smoke else full_geometry()
    only = None
    if args.only:
        only = tuple(s.strip() for s in args.only.split(",") if s.strip())
        known = VARIANTS + (BF16_VARIANT,)
        bad = [v for v in only if v not in known]
        if bad:
            raise SystemExit(f"unknown variant(s) {bad}; known: {known}")
    result = audit(shape, geom, variants=only)
    if only is None:
        log("stepaudit: auditing the norm_watch='recover' rebuild "
            "contract ...")
        result["recover_rebuild"] = audit_recover_rebuild(geom)
        rr = result["recover_rebuild"]
        log(f"  recover_rebuild  recoveries={rr['recoveries']} "
            f"rebuilt={rr['rebuilt']} total_compiles={rr['total_compiles']} "
            f"ok={rr['ok']}")
        result["ok"] = bool(result["ok"] and rr["ok"])
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main(argv=None) -> None:
    result = run(argv)
    print(json.dumps(result))
    if not result["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
