"""Native C++ pair generator: bit-equivalence with the numpy pipeline, determinism,
thread-count independence. The stream contract lives in data/hashrng.py; the C++ side
must reproduce it exactly or silently corrupt training — hence bit-level assertions."""

import numpy as np
import pytest

from glint_word2vec_tpu.data.native import (
    block_pairs_native, native_available)
from glint_word2vec_tpu.data.pipeline import _block_pairs, epoch_batches
from glint_word2vec_tpu.data.vocab import Vocabulary

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native pairgen not built (no g++?)")


def _rand_block(rng, n_sent=200, maxlen=60, V=5000):
    lens = rng.integers(1, maxlen + 1, n_sent).astype(np.int64)
    tokens = rng.integers(0, V, lens.sum()).astype(np.int32)
    keep = np.minimum(rng.random(V) + 0.2, 1.0).astype(np.float32)
    return tokens, lens, keep


@pytest.mark.parametrize("window,legacy", [(5, True), (5, False), (1, True), (12, True)])
def test_bit_identical_to_numpy(window, legacy):
    rng = np.random.default_rng(3)
    tokens, lens, keep = _rand_block(rng)
    for seed, it, shard, tb in [(1, 1, 0, 0), (99, 4, 3, 2**33 + 17)]:
        a = _block_pairs(tokens, lens, keep, window, seed, it, shard, tb, legacy)
        b = block_pairs_native(tokens, lens, keep, window, seed, it, shard, tb, legacy)
        for i in range(3):
            np.testing.assert_array_equal(a[i], b[i])
        assert a[3] == b[3]


def test_thread_count_does_not_change_stream(monkeypatch):
    rng = np.random.default_rng(4)
    tokens, lens, keep = _rand_block(rng, n_sent=500)
    outs = []
    for n in ("1", "3", "7"):
        monkeypatch.setenv("GLINT_NATIVE_THREADS", n)
        outs.append(block_pairs_native(tokens, lens, keep, 5, 2, 1, 0, 0, True))
    for o in outs[1:]:
        for i in range(3):
            np.testing.assert_array_equal(outs[0][i], o[i])


def test_epoch_batches_backends_agree():
    rng = np.random.default_rng(5)
    V = 2000
    sentences = [rng.integers(0, V, rng.integers(2, 50)).astype(np.int32)
                 for _ in range(300)]
    counts = np.bincount(np.concatenate(sentences), minlength=V) + 1
    vocab = Vocabulary.from_words_and_counts([f"w{i}" for i in range(V)], counts)
    kw = dict(pairs_per_batch=512, window=4, subsample_ratio=1e-3, seed=11,
              iteration=2)
    for a, b in zip(epoch_batches(sentences, vocab, backend="numpy", **kw),
                    epoch_batches(sentences, vocab, backend="native", **kw)):
        np.testing.assert_array_equal(a.centers, b.centers)
        np.testing.assert_array_equal(a.contexts, b.contexts)
        np.testing.assert_array_equal(a.mask, b.mask)
        assert a.words_seen == b.words_seen


def test_stream_independent_of_block_size():
    """Position-keyed randomness: the pair stream must not depend on how sentences
    are grouped into blocks (block_words is a perf knob, not a semantic one)."""
    rng = np.random.default_rng(6)
    V = 1000
    sentences = [rng.integers(0, V, 30).astype(np.int32) for _ in range(200)]
    counts = np.bincount(np.concatenate(sentences), minlength=V) + 1
    vocab = Vocabulary.from_words_and_counts([f"w{i}" for i in range(V)], counts)
    kw = dict(pairs_per_batch=256, window=3, subsample_ratio=1e-2, seed=2,
              iteration=1, shuffle=False)
    a = list(epoch_batches(sentences, vocab, block_words=100, **kw))
    b = list(epoch_batches(sentences, vocab, block_words=10**9, **kw))
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x.centers, y.centers)
        np.testing.assert_array_equal(x.contexts, y.contexts)
