"""Finite-blowup watchdog: the guardrail ROADMAP item 2 says is missing.

The non-finite guardrail (``config.nonfinite_policy``, round 6) only fires
when the carry reaches NaN/inf — and the measured 1.6M-vocab quality
collapse never does: purity falls 0.99 → 0.14 through a FINITE norm blowup
(EVAL.md round-5 ladder), so the only trace today is a construction-time
warning. This watchdog consumes the fused health probe's channels
(:mod:`.probe`) at the same heartbeat cadence and fires on either of two
measured signatures, per matrix:

- ``frac_over`` — the fraction of rows past ``config.norm_watch_threshold``
  exceeds ``config.norm_watch_frac``: the round-5 collapse is visible here
  long before the max (a subset of hot rows blows up first — the pool-load
  mechanism in trainer._stability_warnings);
- ``max_norm`` — any single row past ``config.norm_watch_max``: the hard
  ceiling, catching a lone runaway row the fraction channel would dilute at
  large vocabularies.

Policy (``config.norm_watch``): ``warn`` logs + emits a telemetry record per
firing probe (training continues — the research posture while the ROADMAP
item 2 ladder correlates norm trajectory with quality); ``halt`` raises
:class:`~glint_word2vec_tpu.train.faults.NormBlowupError` with the channels
and the measured mitigations, the same fail-fast contract as
``nonfinite_policy="halt"``. Thresholds and their provenance:
docs/observability.md.
"""

from __future__ import annotations

import logging
from typing import Optional

from glint_word2vec_tpu.train.faults import NormBlowupError

logger = logging.getLogger("glint_word2vec_tpu")


class NormWatchdog:
    """Stateful checker over successive probe channel dicts (one Trainer run)."""

    def __init__(self, policy: str, threshold: float, max_norm: float,
                 frac: float):
        if policy not in ("off", "warn", "halt"):
            raise ValueError(f"norm_watch policy must be 'off', 'warn', or "
                             f"'halt' but got {policy!r}")
        self.policy = policy
        self.threshold = threshold
        self.max_norm = max_norm
        self.frac = frac
        self.fires = 0
        self.last_reason: Optional[str] = None

    def check(self, channels: dict, step: int) -> Optional[str]:
        """Evaluate one probe result. Returns the firing reason (also stored
        on :attr:`last_reason`) or None; raises under ``policy="halt"``."""
        if self.policy == "off":
            return None
        reasons = []
        for name in ("syn0", "syn1"):
            ch = channels.get(name) or {}
            mx = ch.get("max_norm", 0.0)
            fo = ch.get("frac_over", 0.0)
            if fo >= self.frac:
                reasons.append(
                    f"{name}: {fo:.2%} of rows exceed norm "
                    f"{self.threshold:g} (limit {self.frac:.2%})")
            if mx >= self.max_norm:
                reasons.append(
                    f"{name}: max row norm {mx:.3g} >= {self.max_norm:g}")
        if not reasons:
            return None
        self.fires += 1
        reason = "; ".join(reasons)
        self.last_reason = reason
        diag = (
            f"finite norm blowup at global step {step}: {reason}. This is "
            f"the measured large-vocab collapse channel (EVAL.md round-5 "
            f"ladder: purity 0.99 -> 0.14 with NO NaN, so nonfinite_policy "
            f"never fires). Measured mitigations, in order: grow "
            f"negative_pool (keep load B*n/P <= ~160 at large vocab), lower "
            f"subsample_ratio (~1e-4), lower the learning rate, or "
            f"duplicate_scaling=True")
        if self.policy == "halt":
            raise NormBlowupError(diag)
        if self.fires == 1:
            logger.warning("norm watchdog: %s", diag)
        else:
            # the full diagnostic fired once; a still-blown carry re-fires
            # every probe, so later firings log one line (the sink keeps the
            # full channel record per firing regardless)
            logger.warning(
                "norm watchdog (firing %d) at step %d: %s",
                self.fires, step, reason)
        return reason
