#!/usr/bin/env python
"""Serving-tier QPS/latency bench: exact vs ANN arms through the real service.

The serving twin of bench.py (ROADMAP item 1 / ISSUE 10): measures the
production query path — the request batcher coalescing concurrent clients,
the IVF ANN index vs the exact full-vocab oracle, backpressure under
offered load — and prints exactly ONE JSON line on stdout (graftlint R7)
for tools/perfgate.py's serving bands (``--kind serve``).

Arms:

1. **exact per-query** — sequential ``find_synonyms`` calls, one device
   dispatch each: the pre-subsystem baseline (the 230-375 ms/query regime
   at V=1M through a thin link; smaller here, same shape).
2. **exact batched (service)** — closed loop: N client threads hammer the
   service, the micro-batcher coalesces into batched exact dispatches.
3. **ANN batched (service)** — the same closed loop over the IVF arm; the
   index's oracle-checked ``recall@10`` (measured at build against the
   exact full scan, serve/ann.py) rides the JSON line.
4. **offered load** — open loop at target arrival rates derived from the
   ANN closed-loop capacity (0.5x/1.0x/1.5x): workers fire at scheduled
   arrival times, refusals (ServerOverloaded, the 429 analog) and p99 are
   counted per target; ``offered_qps_sustained`` is the highest target
   with < 1% refusals.
5. **quantized arms** (ISSUE 18) — the int8 and PQ index builds through
   the same closed loop, with footprint columns: ``*_index_bytes``,
   ``*_bytes_cut`` (f32-index bytes over quant bytes — higher is better,
   so perfgate can band it), ``int8_qps_ratio`` vs the f32 ANN arm, and
   each arm's own oracle-measured recall@10. ``--shard-native`` adds a
   smoke build straight from a row-shards checkpoint
   (serve/quant.build_ivf_from_shards) with a code-parity check against
   the in-memory build.

Latency vs throughput reporting: closed-loop percentiles at saturation are
a QUEUEING artifact (Little's law: N clients / capacity), so the headline
``ann_p50_ms``/``ann_p99_ms`` quote the HALF-CAPACITY offered-load row —
the latency a deployment sees at a sane utilization — and the closed-loop
row keeps its own ``ann_closed_*`` keys as the capacity measurement. The
acceptance headline ``ann_speedup_p50`` is exact PER-QUERY p50 (the path
this subsystem replaces) over that operating-point ANN p50.

Model: ``--checkpoint`` serves a real trained model; the default is a
synthetic CLUSTERED matrix (mixture of unit gaussian cells — trained
embedding geometry is clustered; a uniform-random matrix has no structure
for ANY index and would bench an assumption no deployment makes). Queries
are vocabulary words (self-exclusion semantics included), drawn uniformly.

Usage::

    python tools/servebench.py                 # full tier on this host
    python tools/servebench.py --smoke         # small + fast (CI)
    python tools/servebench.py --checkpoint /path/to/ckpt
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Dict, List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
from glint_word2vec_tpu.lockcheck import make_lock


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def pct(lats_ms: List[float], p: float) -> float:
    if not lats_ms:
        return float("nan")
    s = sorted(lats_ms)
    return round(s[min(len(s) - 1, int(p * len(s)))], 3)


def make_model(vocab_size: int, dim: int, clusters: int, seed: int):
    """Synthetic clustered embedding matrix (module doc) wrapped as a model."""
    import jax.numpy as jnp
    from glint_word2vec_tpu.data.vocab import Vocabulary
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    rng = np.random.default_rng(seed)
    cents = rng.standard_normal((clusters, dim)).astype(np.float32)
    cents /= np.maximum(np.linalg.norm(cents, axis=1, keepdims=True), 1e-12)
    # noise norm ~0.35 RELATIVE to the unit centroid at any dim (a fixed
    # per-dim sigma would swamp the structure as dim grows — and trained
    # embeddings are tightly clustered: the eval ladder measures topic
    # purity@10 ~1.0 on healthy runs, tools/eval_quality.py)
    noise = rng.standard_normal((vocab_size, dim)).astype(np.float32)
    m = cents[rng.integers(0, clusters, vocab_size)] + 0.35 * noise / np.sqrt(dim)
    words = [f"w{i}" for i in range(vocab_size)]
    vocab = Vocabulary.from_words_and_counts(
        words, np.ones(vocab_size, np.int64))
    return Word2VecModel(vocab, jnp.asarray(m))


def closed_loop(service, words: List[str], num: int, clients: int,
                duration_s: float) -> Dict:
    """N client threads issue queries back-to-back for ``duration_s``;
    returns qps + latency percentiles (the service's max sustainable
    throughput proxy at this client count)."""
    from glint_word2vec_tpu.serve import ServerOverloaded
    lats: List[List[float]] = [[] for _ in range(clients)]
    errs = [0] * clients
    stop_at = time.monotonic() + duration_s

    def client(ci: int) -> None:
        rng = np.random.default_rng(1000 + ci)
        while time.monotonic() < stop_at:
            w = words[int(rng.integers(0, len(words)))]
            t0 = time.monotonic()
            try:
                service.synonyms(w, num)
            except ServerOverloaded:
                errs[ci] += 1
                continue
            lats[ci].append((time.monotonic() - t0) * 1000)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0
    flat = [x for l in lats for x in l]
    return {"qps": round(len(flat) / wall, 1), "completed": len(flat),
            "refused": sum(errs), "p50_ms": pct(flat, 0.50),
            "p95_ms": pct(flat, 0.95), "p99_ms": pct(flat, 0.99)}


def offered_load(service, words: List[str], num: int, target_qps: float,
                 duration_s: float, workers: int = 16) -> Dict:
    """Open loop: arrivals scheduled at 1/target_qps intervals; a late
    worker pool means queueing shows up as latency/refusals, not as a
    silently slower arrival process."""
    from glint_word2vec_tpu.serve import ServerOverloaded
    n = max(1, int(target_qps * duration_s))
    start = time.monotonic() + 0.05
    arrivals = [start + i / target_qps for i in range(n)]
    lock = make_lock("tools.servebench.tickets")
    nxt = [0]
    lats: List[float] = []
    refused = [0]
    failed = [0]

    def worker() -> None:
        rng = np.random.default_rng(17)
        while True:
            with lock:
                i = nxt[0]
                if i >= n:
                    return
                nxt[0] += 1
            wait = arrivals[i] - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            w = words[int(rng.integers(0, len(words)))]
            t0 = time.monotonic()
            try:
                service.synonyms(w, num)
            except ServerOverloaded:
                with lock:
                    refused[0] += 1
                continue
            except Exception:  # noqa: BLE001 — counted, not raised
                with lock:
                    failed[0] += 1
                continue
            dt = (time.monotonic() - t0) * 1000
            with lock:
                lats.append(dt)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - start
    done = len(lats)
    return {"target_qps": round(target_qps, 1),
            "achieved_qps": round(done / max(wall, 1e-9), 1),
            "offered": n, "completed": done, "refused": refused[0],
            "failed": failed[0],
            "refused_frac": round(refused[0] / max(n, 1), 4),
            "p50_ms": pct(lats, 0.50), "p99_ms": pct(lats, 0.99)}


def fleet_tier(args) -> Dict:
    """The fleet arms (ISSUE 12): N in-process replicas (each its own
    model instance + batcher; ONE shared IVF index — search is read-only)
    behind a FleetRouter. Reported at the half-capacity offered operating
    point like the single-service headline, N=1 vs N=3 on exact and ANN;
    then the hedge A/B: the same N=3 ANN fleet under a deterministic
    1-in-``--straggle-every`` batch stall of ``--straggle-ms``, hedge off
    vs hedge at the measured HEALTHY p99 (the provenance rule: hedge past
    the healthy tail, so duplicates stay rare — deriving from the
    straggled p99 would fire after the stall already resolved)."""
    import jax.numpy as jnp

    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.serve import (
        EmbeddingService, FleetRouter, ReplicaSet, build_ivf)

    v, d, n_rep = args.fleet_vocab, args.dim, args.fleet_replicas
    base = make_model(v, d, min(args.clusters, max(8, v // 64)), args.seed)
    matrix = np.array(base.syn0)  # forced copy: base's buffer is released
    vocab = base.vocab
    base.stop()
    index = build_ivf(matrix, nprobe=args.nprobe or 0, seed=args.seed)
    log(f"[fleet] shared IVF built: C={index.stats['centroids']} "
        f"recall@10={index.stats.get('recall_at_10')}")
    rng = np.random.default_rng(args.seed + 2)
    qwords = [vocab.words[i] for i in rng.integers(0, v, 2048)]
    num, dur = args.num, args.duration

    def build_fleet(n: int, ann: bool, hedge_ms: float,
                    straggle: bool):
        models = [Word2VecModel(vocab, jnp.asarray(matrix))
                  for _ in range(n)]
        # max_delay_ms=0: the router already spreads concurrency across N
        # batchers, so per-replica occupancy is low and the coalescing
        # deadline is pure added latency — the latency-critical setting
        # docs/serving.md §1 documents (queued requests still coalesce).
        # The straggler injection hits REPLICA 0 ONLY: one degraded node
        # in an otherwise healthy fleet is the scenario hedging exists
        # for (a fleet where EVERY replica stalls is a capacity problem,
        # not a tail problem — hedging provably cannot fix that)
        svcs = [EmbeddingService(
            model=m, ann=ann, ann_index=(index if ann else None),
            nprobe=args.nprobe or None, max_delay_ms=0.0,
            straggle_every=(args.straggle_every
                            if straggle and i == 0 else 0),
            straggle_ms=(args.straggle_ms
                         if straggle and i == 0 else 0.0))
            for i, m in enumerate(models)]
        router = FleetRouter(
            ReplicaSet.adopt(svcs), hedge_ms=hedge_ms, probe_s=0.25,
            retry_deadline_s=60.0)
        return router, models

    def run_arm(n: int, ann: bool, hedge_ms: float = 0.0,
                straggle: bool = False, target_qps: float = 0.0) -> Dict:
        router, models = build_fleet(n, ann, hedge_ms, straggle)
        try:
            router.synonyms(qwords[0], num)  # warm
            row: Dict = {}
            if not target_qps:
                cl = closed_loop(router, qwords, num, args.clients, dur)
                row["qps"] = cl["qps"]
                target_qps = max(cl["qps"], 1.0) / 2
            off = offered_load(router, qwords, num, target_qps,
                               min(dur, 2.0))
            row.update(target_qps=off["target_qps"], p50_ms=off["p50_ms"],
                       p99_ms=off["p99_ms"], refused=off["refused"],
                       failed=off["failed"])
            st = router.stats()
            row["hedges"] = st["hedges"]
            row["hedge_wins"] = st["hedge_wins"]
            return row
        finally:
            router.close()
            for m in models:
                m.stop()

    out: Dict = {"fleet_vocab": v, "fleet_replicas": n_rep,
                 "fleet_recall_at_10": index.stats.get("recall_at_10"),
                 # in-process replicas SHARE one read-only index; a real
                 # deployment pays one copy per replica host — both numbers
                 # derive from this (statusd's fleet scrape sums what each
                 # replica actually reports)
                 "fleet_index_bytes": index.stats.get("index_bytes")}
    half_targets: Dict = {}
    for ann in (False, True):
        arm = "ann" if ann else "exact"
        for n in (1, n_rep):
            row = run_arm(n, ann)
            half_targets[(n, ann)] = row["target_qps"]
            out[f"fleet{n}_{arm}_qps"] = row["qps"]
            out[f"fleet{n}_{arm}_p50_ms"] = row["p50_ms"]
            out[f"fleet{n}_{arm}_p99_ms"] = row["p99_ms"]
            log(f"[fleet] N={n} {arm}: {row['qps']} qps closed, half-cap "
                f"p50 {row['p50_ms']} ms p99 {row['p99_ms']} ms")
    # hedge A/B: same N=3 ANN fleet + injected straggler, same offered
    # target, hedge off vs hedge at the measured HEALTHY p99 (floored at
    # 5 ms): past the 99th percentile of the healthy distribution so
    # duplicates stay rare (~1% + the straggled fraction), but BEFORE the
    # straggler tail — deriving from the STRAGGLED p99 would fire after
    # the stall already resolved. This is the provenance rule documented
    # in docs/serving.md §5.
    healthy_p99 = out[f"fleet{n_rep}_ann_p99_ms"]
    hedge_delay = (max(5.0, healthy_p99)
                   if healthy_p99 == healthy_p99 else 5.0)  # NaN-safe
    target = half_targets[(n_rep, True)]
    offrow = run_arm(n_rep, True, hedge_ms=0.0, straggle=True,
                     target_qps=target)
    onrow = run_arm(n_rep, True, hedge_ms=hedge_delay, straggle=True,
                    target_qps=target)
    out["fleet_straggle"] = (
        f"r0:1/{args.straggle_every}x{args.straggle_ms}ms")
    out["fleet_hedge_delay_ms"] = round(hedge_delay, 3)
    out["fleet_hedge_off_p99_ms"] = offrow["p99_ms"]
    out["fleet_hedge_on_p99_ms"] = onrow["p99_ms"]
    out["fleet_hedges"] = onrow["hedges"]
    out["fleet_hedge_wins"] = onrow["hedge_wins"]
    out["fleet_hedge_p99_cut"] = (
        round(offrow["p99_ms"] / onrow["p99_ms"], 2)
        if onrow["p99_ms"] and onrow["p99_ms"] == onrow["p99_ms"] else None)
    log(f"[fleet] hedge A/B under straggler {out['fleet_straggle']}: "
        f"p99 {offrow['p99_ms']} ms (off) -> {onrow['p99_ms']} ms (on, "
        f"delay {hedge_delay:.1f} ms), {onrow['hedges']} hedges "
        f"({onrow['hedge_wins']} wins)")
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--checkpoint", default="",
                    help="serve a real checkpoint instead of the synthetic "
                         "clustered matrix")
    ap.add_argument("--vocab", type=int, default=400_000,
                    help="synthetic vocabulary rows — sized so the exact "
                         "per-query arm sits in the regime the subsystem "
                         "exists to replace (tens of ms per query)")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--clusters", type=int, default=512)
    ap.add_argument("--num", type=int, default=10, help="top-k per query")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=4.0,
                    help="seconds per closed-loop arm")
    ap.add_argument("--per-query", type=int, default=30,
                    help="sequential queries for the exact per-query arm")
    ap.add_argument("--nprobe", type=int, default=0, help="0 = auto")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fleet", action="store_true",
                    help="add the fleet tier (ISSUE 12): N=1 vs N=3 "
                         "in-process replicas behind a FleetRouter on the "
                         "exact and ANN arms (half-capacity operating "
                         "point), plus the hedge A/B under an injected "
                         "1-in-N straggler")
    ap.add_argument("--fleet-replicas", type=int, default=3)
    ap.add_argument("--fleet-vocab", type=int, default=100_000,
                    help="fleet-tier vocabulary rows (N replica copies of "
                         "the matrix must coexist — smaller than the "
                         "single-service arms by design, recorded in the "
                         "JSON)")
    ap.add_argument("--straggle-every", type=int, default=3,
                    help="hedge A/B fault injection: every Nth batch of "
                         "REPLICA 0 (one degraded node) stalls "
                         "--straggle-ms (serve/batcher.py)")
    ap.add_argument("--straggle-ms", type=float, default=60.0)
    ap.add_argument("--shard-native", action="store_true",
                    help="add the shard-native build leg: save the bench "
                         "matrix as a row-shards checkpoint, build the "
                         "int8 index via build_ivf_from_shards (bounded "
                         "blocks, no dense [V,D] f32), and parity-check "
                         "its codes against the in-memory build")
    ap.add_argument("--smoke", action="store_true",
                    help="small + fast (CI): proves the harness, not the host")
    args = ap.parse_args()

    if args.smoke:
        args.vocab = min(args.vocab, 20_000)
        args.dim = min(args.dim, 64)
        args.clusters = min(args.clusters, 128)
        args.duration = min(args.duration, 1.0)
        args.clients = min(args.clients, 4)
        args.per_query = min(args.per_query, 8)
        args.fleet_vocab = min(args.fleet_vocab, 8_000)
        args.straggle_ms = min(args.straggle_ms, 40.0)

    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.serve import EmbeddingService

    if args.checkpoint:
        model = Word2VecModel.load(args.checkpoint)
        log(f"serving checkpoint {args.checkpoint}: V={model.num_words:,} "
            f"D={model.vector_size}")
    else:
        model = make_model(args.vocab, args.dim, args.clusters, args.seed)
        log(f"synthetic clustered matrix: V={args.vocab:,} D={args.dim} "
            f"({args.clusters} cells)")
    rng = np.random.default_rng(args.seed + 1)
    qwords = [model.vocab.words[i] for i in
              rng.integers(0, model.num_words, 4096)]

    # -- arm 1: exact per-query (the pre-subsystem baseline) ----------------
    model.norms  # materialize the cached norms outside the timed region
    for w in qwords[:3]:
        model.find_synonyms(w, args.num)  # warm the jit cache
    per_lats = []
    for w in qwords[:args.per_query]:
        t0 = time.monotonic()
        model.find_synonyms(w, args.num)
        per_lats.append((time.monotonic() - t0) * 1000)
    exact_pq = {"p50_ms": pct(per_lats, 0.50), "p95_ms": pct(per_lats, 0.95),
                "p99_ms": pct(per_lats, 0.99), "n": len(per_lats)}
    log(f"exact per-query: p50 {exact_pq['p50_ms']} ms over {len(per_lats)}")

    # -- arm 2: exact batched through the service ---------------------------
    svc = EmbeddingService(model=model, ann=False)
    svc.synonyms(qwords[0], args.num)  # warm
    exact_cl = closed_loop(svc, qwords, args.num, args.clients, args.duration)
    occupancy = svc.stats().get("occupancy_mean")
    svc.close()  # in-memory model= stays alive for the next arm
    log(f"exact batched: {exact_cl['qps']} qps, p50 {exact_cl['p50_ms']} ms, "
        f"p99 {exact_cl['p99_ms']} ms, occupancy {occupancy}")

    # -- arm 3: ANN batched through the service -----------------------------
    svc = EmbeddingService(model=model, ann=True,
                           nprobe=args.nprobe or None)
    ann_stats = dict(model.ann.stats)
    log(f"IVF built in {ann_stats['build_seconds']}s: "
        f"C={ann_stats['centroids']} nprobe={ann_stats['nprobe']} "
        f"recall@10={ann_stats.get('recall_at_10')}")
    svc.synonyms(qwords[0], args.num)  # warm
    ann_cl = closed_loop(svc, qwords, args.num, args.clients, args.duration)
    ann_occ = svc.stats().get("occupancy_mean")
    log(f"ann batched: {ann_cl['qps']} qps, p50 {ann_cl['p50_ms']} ms, "
        f"p99 {ann_cl['p99_ms']} ms, occupancy {ann_occ}")

    # -- arm 4: offered load (targets derived from the ANN capacity) --------
    offered_rows = []
    sustained = 0.0
    base = max(ann_cl["qps"], 1.0)
    for frac in (0.5, 1.0, 1.5):
        row = offered_load(svc, qwords, args.num, base * frac,
                           min(args.duration, 2.0))
        offered_rows.append(row)
        log(f"offered {row['target_qps']} qps: achieved "
            f"{row['achieved_qps']}, refused {row['refused_frac']:.1%}, "
            f"p50 {row['p50_ms']} ms, p99 {row['p99_ms']} ms")
        if row["refused_frac"] < 0.01 and row["failed"] == 0:
            sustained = max(sustained, row["achieved_qps"])
    svc.close()

    # -- arm 5: quantized indexes (ISSUE 18) --------------------------------
    # same closed loop over the int8 and PQ arms; recall floors stay AUTO
    # (the documented per-arm gates — a full-bench refusal here IS the
    # signal) except under --smoke, where toy-scale probe loss would fire
    # the floor about the host, not the code
    from glint_word2vec_tpu.serve import build_ivf
    matrix = np.asarray(model.syn0)
    quant_floor = 0.0 if args.smoke else -1.0
    quant_fields: Dict = {}
    f32_bytes = ann_stats.get("index_bytes") or 1
    for quant in ("int8", "pq"):
        qix = build_ivf(matrix, nprobe=args.nprobe or 0, seed=args.seed,
                        quant=quant, recall_floor=quant_floor)
        qstats = dict(qix.stats)
        qsvc = EmbeddingService(model=model, ann=True, ann_index=qix,
                                nprobe=args.nprobe or None)
        qsvc.synonyms(qwords[0], args.num)  # warm
        qcl = closed_loop(qsvc, qwords, args.num, args.clients,
                          args.duration)
        qsvc.close()
        quant_fields.update({
            f"{quant}_qps": qcl["qps"],
            f"{quant}_closed_p50_ms": qcl["p50_ms"],
            f"{quant}_closed_p99_ms": qcl["p99_ms"],
            f"{quant}_recall_at_10": qstats.get("recall_at_10"),
            f"{quant}_index_bytes": qstats["index_bytes"],
            f"{quant}_bytes_per_vector": qstats["bytes_per_vector"],
            f"{quant}_bytes_ratio": round(
                qstats["index_bytes"] / f32_bytes, 4),
            # the gateable direction: f32 bytes over quant bytes
            f"{quant}_bytes_cut": round(
                f32_bytes / max(qstats["index_bytes"], 1), 2),
            f"{quant}_qps_ratio": round(
                qcl["qps"] / max(ann_cl["qps"], 1e-9), 3),
            f"{quant}_build_s": qstats["build_seconds"],
        })
        if quant == "pq":
            quant_fields["pq_m"] = qstats.get("pq_m")
            quant_fields["pq_rerank"] = qstats.get("rerank")
        log(f"{quant} batched: {qcl['qps']} qps ("
            f"{quant_fields[f'{quant}_qps_ratio']}x f32-ann), recall@10 "
            f"{qstats.get('recall_at_10')}, "
            f"{qstats['bytes_per_vector']} B/vec "
            f"({quant_fields[f'{quant}_bytes_ratio']}x f32 bytes)")

    # -- shard-native build leg (--shard-native) ----------------------------
    if args.shard_native:
        import shutil
        import tempfile

        import jax.numpy as jnp

        from glint_word2vec_tpu.config import Word2VecConfig
        from glint_word2vec_tpu.serve import build_ivf_from_shards
        from glint_word2vec_tpu.train.checkpoint import save_model_sharded
        tmp = tempfile.mkdtemp(prefix="servebench-shards-")
        try:
            ck = os.path.join(tmp, "ck")
            cfg = Word2VecConfig(vector_size=model.vector_size, min_count=1)
            save_model_sharded(ck, model.vocab.words,
                               np.asarray(model.vocab.counts),
                               jnp.asarray(matrix), None, cfg,
                               vocab_size=model.num_words,
                               vector_size=model.vector_size)
            six = build_ivf_from_shards(
                ck, quant="int8", nprobe=args.nprobe or 0, seed=args.seed,
                recall_floor=quant_floor)
            # proof the stream is the same index: the in-memory int8 build
            # at the same seed produced bit-identical codes
            mem = build_ivf(matrix, nprobe=args.nprobe or 0,
                            seed=args.seed, quant="int8",
                            recall_floor=quant_floor)
            parity = bool(
                np.array_equal(mem._ids, six._ids)
                and np.array_equal(mem._storage._codes,
                                   six._storage._codes))
            quant_fields.update({
                "shard_native_build_s": six.stats["build_seconds"],
                "shard_native_recall_at_10": six.stats.get("recall_at_10"),
                "shard_native_index_bytes": six.stats["index_bytes"],
                "shard_native_parity": parity,
            })
            log(f"shard-native int8 build: "
                f"{six.stats['build_seconds']}s, recall@10 "
                f"{six.stats.get('recall_at_10')}, parity={parity}")
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    # operating-point latency: the half-capacity offered row (module doc)
    op = offered_rows[0]
    speedup = (round(exact_pq["p50_ms"] / op["p50_ms"], 2)
               if op["p50_ms"] == op["p50_ms"] and op["p50_ms"] else None)
    result = {
        "metric": "serving_qps_p99",
        "vocab_size": model.num_words,
        "dim": model.vector_size,
        "num": args.num,
        "clients": args.clients,
        "smoke": bool(args.smoke),
        "exact_per_query_p50_ms": exact_pq["p50_ms"],
        "exact_per_query_p99_ms": exact_pq["p99_ms"],
        "exact_qps": exact_cl["qps"],
        "exact_closed_p50_ms": exact_cl["p50_ms"],
        "exact_closed_p99_ms": exact_cl["p99_ms"],
        "exact_occupancy_mean": occupancy,
        "ann_qps": ann_cl["qps"],
        "ann_p50_ms": op["p50_ms"],
        "ann_p99_ms": op["p99_ms"],
        "ann_closed_p50_ms": ann_cl["p50_ms"],
        "ann_closed_p99_ms": ann_cl["p99_ms"],
        "ann_occupancy_mean": ann_occ,
        "ann_recall_at_10": ann_stats.get("recall_at_10"),
        "ann_centroids": ann_stats["centroids"],
        "ann_nprobe": ann_stats["nprobe"],
        "ann_build_s": ann_stats["build_seconds"],
        "ann_index_bytes": ann_stats.get("index_bytes"),
        "ann_bytes_per_vector": ann_stats.get("bytes_per_vector"),
        **quant_fields,
        # the ISSUE-10 acceptance headline: the batched ANN arm's
        # operating-point p50 vs the exact PER-QUERY p50 it replaces
        # (>= 10x at recall@10 >= 0.95)
        "ann_speedup_p50": speedup,
        "offered_qps_sustained": round(sustained, 1),
        "offered": offered_rows,
    }
    if args.fleet:
        model.stop()  # release the single-service matrix before N copies
        result.update(fleet_tier(args))
    print(json.dumps(result))  # the ONE stdout line (graftlint R7)
    return 0


if __name__ == "__main__":
    sys.exit(main())
