"""Continual training (ISSUE 11, docs/continual.md): vocab extension with the
identity-prefix contract + lineage chain, per-shard row-shards growth, the
streaming corpus cursor + delta encode reuse, the driver loop end-to-end, the
alias rebuild distribution-exactness caveat, the resume migration path, and
the serve-side vocab-growth guards."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.continual import (
    ConcatCorpus,
    ContinualRunner,
    CorpusStream,
    StreamCursor,
    compute_vocab_delta,
    extend_checkpoint,
    extended_vocabulary,
    lineage_fingerprints,
    seed_new_rows,
)
from glint_word2vec_tpu.data.corpus import vocab_fingerprint
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.train.checkpoint import (
    CheckpointCorruptError,
    TrainState,
    load_model,
    load_model_header,
    save_model,
    verify_checkpoint,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_vocab():
    return Vocabulary.from_words_and_counts(
        ["the", "cat", "sat", "mat"], [40, 20, 10, 5])


def save_toy_checkpoint(path, vocab, dim=8, seed=3, cfg=None):
    rng = np.random.default_rng(seed)
    syn0 = rng.normal(size=(vocab.size, dim)).astype(np.float32)
    syn1 = rng.normal(size=(vocab.size, dim)).astype(np.float32)
    cfg = cfg or Word2VecConfig(vector_size=dim, min_count=2)
    save_model(path, vocab.words, vocab.counts, syn0, syn1, cfg,
               TrainState(global_step=17, finished=True))
    return syn0, syn1


# -- vocab delta + identity-prefix extension -----------------------------------------


def test_vocab_delta_merges_and_promotes():
    v = small_vocab()
    delta = compute_vocab_delta(
        v, {"cat": 7, "dog": 9, "bird": 3, "rare": 1}, min_count=2)
    assert delta.new_words == ["dog", "bird"]          # desc tail count
    assert delta.new_counts.tolist() == [9, 3]
    assert delta.merged_counts.tolist() == [40, 27, 10, 5]
    assert delta.tail_words_total == 20


def test_extended_vocabulary_is_identity_prefix():
    v = small_vocab()
    delta = compute_vocab_delta(v, {"dog": 9, "sat": 1}, min_count=2)
    v2 = extended_vocabulary(v, delta)
    # old words keep their EXACT indices even though merged counts would
    # re-rank them; new words append
    assert v2.words[: v.size] == v.words
    assert v2.words[v.size:] == ["dog"]
    assert v2.get("dog") == v.size
    assert v2.counts[2] == 11                          # sat merged
    assert v2.train_words_count == v.train_words_count + 10


def test_seed_new_rows_deterministic_and_bounded():
    a = seed_new_rows(5, 16, seed=7, old_vocab_size=100)
    b = seed_new_rows(5, 16, seed=7, old_vocab_size=100)
    np.testing.assert_array_equal(a, b)
    c = seed_new_rows(5, 16, seed=7, old_vocab_size=200)
    assert not np.array_equal(a, c)                    # later extension: new stream
    assert np.abs(a).max() <= 0.5 / 16


# -- dense checkpoint extension ------------------------------------------------------


def test_extend_checkpoint_dense_carries_rows_bit_identically(tmp_path):
    v = small_vocab()
    ck = str(tmp_path / "ck")
    syn0, syn1 = save_toy_checkpoint(ck, v)
    rep = extend_checkpoint(ck, {"dog": 9, "cat": 5}, min_count=2)
    assert rep["new_words"] == 1 and rep["new_vocab_size"] == 5
    data = load_model(ck)
    np.testing.assert_array_equal(data["syn0"][: v.size], syn0)
    np.testing.assert_array_equal(data["syn1"][: v.size], syn1)
    # new syn0 row is the seeded init, new syn1 row zero
    np.testing.assert_array_equal(
        data["syn0"][v.size:],
        seed_new_rows(1, 8, seed=Word2VecConfig(vector_size=8).seed,
                      old_vocab_size=v.size))
    np.testing.assert_array_equal(data["syn1"][v.size:], np.zeros((1, 8)))
    assert data["counts"].tolist() == [40, 25, 10, 5, 9]
    header = load_model_header(ck)
    (entry,) = header["vocab_lineage"]
    assert entry["remap"] == "identity-prefix"
    assert entry["parent_fingerprint"] == vocab_fingerprint(v)
    assert entry["fingerprint"] == vocab_fingerprint(
        Vocabulary.from_words_and_counts(data["words"], data["counts"]))
    verify_checkpoint(ck)                              # digests consistent


def test_extend_checkpoint_zero_growth_still_links_lineage(tmp_path):
    v = small_vocab()
    ck = str(tmp_path / "ck")
    save_toy_checkpoint(ck, v)
    rep = extend_checkpoint(ck, {"cat": 5}, min_count=2)
    assert rep["new_words"] == 0
    header = load_model_header(ck)
    (entry,) = header["vocab_lineage"]
    assert entry["new_words"] == 0
    # the fingerprint changed with the merged counts; the chain records it
    assert entry["parent_fingerprint"] != entry["fingerprint"]
    fps = lineage_fingerprints(header["vocab_lineage"])
    assert vocab_fingerprint(v) in fps


def test_extend_checkpoint_growth_threshold(tmp_path):
    v = small_vocab()
    ck = str(tmp_path / "ck")
    save_toy_checkpoint(ck, v)
    rep = extend_checkpoint(ck, {"dog": 9, "fox": 3}, min_count=2,
                            min_new_words=3)
    assert rep["new_words"] == 0                       # below threshold
    assert load_model_header(ck)["vocab_size"] == v.size


def test_extend_checkpoint_chains_across_increments(tmp_path):
    v = small_vocab()
    ck = str(tmp_path / "ck")
    save_toy_checkpoint(ck, v)
    extend_checkpoint(ck, {"dog": 9}, min_count=2)
    extend_checkpoint(ck, {"fox": 4}, min_count=2)
    header = load_model_header(ck)
    chain = header["vocab_lineage"]
    assert [e["new_vocab_size"] for e in chain] == [5, 6]
    assert chain[1]["parent_fingerprint"] == chain[0]["fingerprint"]
    assert len(lineage_fingerprints(chain)) == 3       # base + two children


# -- row-shards checkpoint extension (per-shard, no densify) -------------------------


def _sharded_checkpoint(tmp_path, V=10, dim=8, shards=2):
    """A row-shards checkpoint with a padded boundary shard: V=10 padded to
    12 over 2 shard files, so rows 10-12 are padding the extension must
    slice off."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.checkpoint import save_model_sharded

    plan = make_mesh(1, shards, devices=jax.devices()[:shards])
    # force genuine padding: pad to the next multiple of `shards` past V
    Vp = (V // shards + 1) * shards
    rng = np.random.default_rng(0)
    syn0 = np.zeros((Vp, dim), np.float32)
    syn1 = np.zeros((Vp, dim), np.float32)
    syn0[:V] = rng.normal(size=(V, dim))
    syn1[:V] = rng.normal(size=(V, dim))
    sh = NamedSharding(plan.mesh, PartitionSpec("model", None))
    ck = str(tmp_path / "ck-sharded")
    words = [f"w{i}" for i in range(V)]
    counts = np.arange(V, 0, -1) * 10
    save_model_sharded(
        ck, words, counts,
        jax.device_put(syn0, sh), jax.device_put(syn1, sh),
        Word2VecConfig(vector_size=dim, min_count=2),
        TrainState(global_step=5, finished=True),
        vocab_size=V, vector_size=dim)
    return ck, words, counts, syn0[:V], syn1[:V]


def test_extend_row_shards_per_shard_growth(tmp_path):
    ck, words, counts, syn0, syn1 = _sharded_checkpoint(tmp_path)
    V = len(words)
    rep = extend_checkpoint(ck, {"dog": 9, "fox": 4}, min_count=2)
    assert rep["layout"] == "row-shards" and rep["new_words"] == 2
    verify_checkpoint(ck)
    data = load_model(ck)
    assert data["syn0"].shape == (V + 2, 8)
    np.testing.assert_array_equal(data["syn0"][:V], syn0)
    np.testing.assert_array_equal(data["syn1"][:V], syn1)
    np.testing.assert_array_equal(data["syn1"][V:], np.zeros((2, 8)))
    assert data["words"][-2:] == ["dog", "fox"]
    # the shard files really are per-span: the boundary shard was sliced at
    # V and the new rows live in their own span
    names = sorted(os.listdir(os.path.join(ck, "syn0.shards")))
    assert names[-1] == f"rows-{V:010d}-{V + 2:010d}.npy"
    spans = [tuple(int(x) for x in n[len("rows-"):-len(".npy")].split("-"))
             for n in names]
    assert spans[-2][1] == V                           # sliced at V_old
    # loadable onto a mesh too (the serving / resume path)
    import jax
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    model = Word2VecModel.load(
        ck, plan=make_mesh(1, 2, devices=jax.devices()[:2]))
    assert model.num_words == V + 2
    np.testing.assert_array_equal(np.asarray(model.syn0)[:V], syn0)


def test_extend_row_shards_refuses_corrupt_carried_shard(tmp_path):
    ck, words, *_ = _sharded_checkpoint(tmp_path)
    shard0 = sorted(os.listdir(os.path.join(ck, "syn0.shards")))[0]
    p = os.path.join(ck, "syn0.shards", shard0)
    raw = bytearray(open(p, "rb").read())
    raw[-1] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises(CheckpointCorruptError):
        extend_checkpoint(ck, {"dog": 9}, min_count=2,
                          out_path=str(tmp_path / "out"))


# -- alias table: merged-counts rebuild is distribution-exact ------------------------


def test_alias_rebuild_distribution_exact_at_extended_vocab():
    """The PR 3 cross-release caveat, pinned for continual increments
    (docs/continual.md): rebuilding from merged counts yields a table whose
    IMPLIED distribution equals the exact counts^0.75 target — the realized
    stream may change (different pairing), the distribution may not."""
    from glint_word2vec_tpu.ops.sampler import (
        build_alias_table, sampled_probabilities)

    v = small_vocab()
    delta = compute_vocab_delta(v, {"dog": 9, "cat": 5, "fox": 3},
                                min_count=2)
    v2 = extended_vocabulary(v, delta)
    table = build_alias_table(v2.counts)
    prob = np.asarray(table.prob, np.float64)
    alias = np.asarray(table.alias)
    V = v2.size
    # implied p[i] = (kept mass of bucket i + inbound alias mass) / V
    implied = prob.copy()
    np.add.at(implied, alias, 1.0 - prob)
    implied /= V
    np.testing.assert_allclose(
        implied, sampled_probabilities(v2.counts), rtol=0, atol=1e-7)


# -- vocab fingerprint stability (satellite) -----------------------------------------


def test_vocab_fingerprint_stable_across_round_trips():
    v = small_vocab()
    fp = vocab_fingerprint(v)
    v2 = Vocabulary.from_words_and_counts(v.words, v.counts)
    v3 = Vocabulary.from_words_and_counts(list(v2.words),
                                          [int(c) for c in v2.counts])
    assert vocab_fingerprint(v2) == fp
    assert vocab_fingerprint(v3) == fp
    # and it is sensitive to what it must be sensitive to
    assert vocab_fingerprint(Vocabulary.from_words_and_counts(
        v.words, v.counts + 1)) != fp


# -- resume: cache reuse + the migration error ---------------------------------------


def _fit_corpus(n=120, words=14, seed=0):
    rng = np.random.default_rng(seed)
    return [[f"w{i}" for i in rng.integers(0, words, 10)]
            for _ in range(n)]


_RESUME_CFG = dict(vector_size=8, window=2, min_count=1, num_iterations=1,
                   pairs_per_batch=64, subsample_ratio=0.0, seed=1,
                   prefetch_chunks=0)


def test_resume_reuses_encode_cache_without_reencoding(tmp_path, monkeypatch):
    """The common continual/resume case: a cache under the checkpoint's own
    vocabulary must be reused AS-IS — any call to encode_corpus would be a
    full re-encode of the history."""
    from glint_word2vec_tpu.models.estimator import Word2Vec

    sents = _fit_corpus()
    cache = str(tmp_path / "cache")
    ck = str(tmp_path / "ck")
    Word2Vec(**_RESUME_CFG).fit(sents, checkpoint_path=ck,
                                checkpoint_every_steps=4,
                                encode_cache_dir=cache)
    import glint_word2vec_tpu.data.corpus as corpus_mod

    def boom(*a, **k):
        raise AssertionError("resume re-encoded a valid cache")

    monkeypatch.setattr(corpus_mod, "encode_corpus", boom)
    model = Word2Vec.resume(ck, sents, encode_cache_dir=cache)
    assert model.num_words == 14


def test_resume_accepts_ancestor_cache_after_extension(tmp_path):
    """After continual.extend grew the checkpoint, a cache encoded under the
    PRE-extension vocabulary is an ancestor in the lineage chain — resume
    must accept it (identity-prefix ids are still valid), not re-encode."""
    from glint_word2vec_tpu.models.estimator import Word2Vec

    sents = _fit_corpus()
    cache = str(tmp_path / "cache")
    ck = str(tmp_path / "ck")
    Word2Vec(**_RESUME_CFG).fit(sents, checkpoint_path=ck,
                                checkpoint_every_steps=4,
                                encode_cache_dir=cache)
    extend_checkpoint(ck, {"brandnew": 6}, min_count=1)
    header = load_model_header(ck)
    assert header["vocab_size"] == 15
    model = Word2Vec.resume(ck, sents, encode_cache_dir=cache)
    # finished checkpoint: resume just rebuilds the model, at the GROWN size
    assert model.num_words == 15


def test_resume_fingerprint_mismatch_names_migration_path(tmp_path):
    """Direct coverage of the mismatch branch (estimator.py): a cache from a
    genuinely different vocabulary still refuses — and the error now names
    continual.extend as the migration instead of dead-ending."""
    from glint_word2vec_tpu.data.corpus import encode_corpus
    from glint_word2vec_tpu.models.estimator import Word2Vec

    sents = _fit_corpus()
    ck = str(tmp_path / "ck")
    Word2Vec(**_RESUME_CFG).fit(sents, checkpoint_path=ck,
                                checkpoint_every_steps=4)
    other_vocab = Vocabulary.from_words_and_counts(
        ["x", "y", "z"], [3, 2, 1])
    cache = str(tmp_path / "stale-cache")
    encode_corpus([["x", "y", "z"]], other_vocab, cache)
    with pytest.raises(ValueError) as ei:
        Word2Vec.resume(ck, sents, encode_cache_dir=cache)
    msg = str(ei.value)
    assert "continual.extend" in msg and "lineage" in msg


# -- streaming corpus ----------------------------------------------------------------


def _write_segment(path, sentences):
    with open(path, "w", encoding="utf-8") as f:
        for s in sentences:
            f.write(" ".join(s) + "\n")


def test_stream_cursor_stages_and_append_only_audit(tmp_path):
    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "a.txt"), [["x", "y"]] * 5)
    stream = CorpusStream(d)
    cur = StreamCursor(str(tmp_path / "work"))
    assert cur.new_segments(stream) == ["a.txt"]
    assert cur.uncounted(["a.txt"]) == ["a.txt"]
    from glint_word2vec_tpu.continual.stream import segment_fingerprint
    fp = segment_fingerprint(stream.path("a.txt"))
    cur.mark_counted("a.txt", fp)
    assert cur.uncounted(["a.txt"]) == []
    cur.mark_consumed("a.txt", fp, "vfp", {"n_sentences": 5,
                                           "total_tokens": 10})
    assert "a.txt" not in cur.counted                  # consumed implies counted
    cur.save()
    cur2 = StreamCursor(str(tmp_path / "work"))       # round-trips
    assert cur2.consumed["a.txt"]["fingerprint"] == fp
    assert cur2.new_segments(stream) == []
    # append-only violations are errors, not refreshes
    _write_segment(os.path.join(d, "a.txt"), [["CHANGED"]] * 9)
    with pytest.raises(ValueError, match="append-only"):
        cur2.new_segments(stream)


def test_concat_corpus_indexing():
    a = [np.array([1, 2]), np.array([3])]
    b = [np.array([4, 5, 6])]
    c = ConcatCorpus([a, b, []])
    assert len(c) == 3
    np.testing.assert_array_equal(c[1], [3])
    np.testing.assert_array_equal(c[2], [4, 5, 6])
    np.testing.assert_array_equal(c[-1], [4, 5, 6])
    with pytest.raises(IndexError):
        c[3]


def test_encode_delta_reuses_consumed_encodes(tmp_path, monkeypatch):
    """Delta encode must touch only the tail: the consumed segment's cache
    is reused byte-identically (its encode dir untouched), the new segment
    is encoded under the current vocab."""
    from glint_word2vec_tpu.continual.stream import (
        encode_delta, encode_segment, segment_fingerprint)

    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "a.txt"), [["x", "y", "x"]] * 4)
    _write_segment(os.path.join(d, "b.txt"), [["y", "z"]] * 4)
    stream = CorpusStream(d)
    cache = str(tmp_path / "cache")
    vocab = Vocabulary.from_words_and_counts(["x", "y"], [8, 8])
    cur = StreamCursor(str(tmp_path / "work"))
    enc_a = encode_segment(stream, "a.txt", vocab, cache, 1000)
    cur.mark_consumed("a.txt", segment_fingerprint(stream.path("a.txt")),
                      vocab_fingerprint(vocab), enc_a.meta)
    # grown vocab (identity prefix): z appended; a.txt's cache was written
    # under the ancestor fingerprint and must be reused as-is
    vocab2 = Vocabulary.from_words_and_counts(["x", "y", "z"], [8, 12, 4])
    mtime_before = os.path.getmtime(
        os.path.join(cache, "a.txt.enc", "tokens.bin"))
    res = encode_delta(stream, cur, vocab2, cache,
                       lineage=[vocab_fingerprint(vocab)],
                       replay_segments=1)
    assert res["new"] == ["b.txt"] and res["replayed"] == ["a.txt"]
    assert os.path.getmtime(
        os.path.join(cache, "a.txt.enc", "tokens.bin")) == mtime_before
    # the replayed part still decodes under OLD ids (z never appears there)
    assert len(res["corpus"]) == 8


# -- serve-side guards ---------------------------------------------------------------


def test_attach_ann_refuses_stale_index():
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.serve.ann import build_ivf

    rng = np.random.default_rng(0)
    mat = rng.normal(size=(20, 8)).astype(np.float32)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(22)], np.arange(22, 0, -1))
    model = Word2VecModel(
        vocab=vocab, syn0=np.vstack([mat, rng.normal(size=(2, 8))
                                     .astype(np.float32)]))
    stale = build_ivf(mat, num_centroids=4, seed=0)    # built at old V=20
    with pytest.raises(ValueError, match="stale index"):
        model.attach_ann(stale)


def test_service_counts_vocab_change_reloads(tmp_path):
    from glint_word2vec_tpu.serve import EmbeddingService

    v = small_vocab()
    ck = str(tmp_path / "ck")
    save_toy_checkpoint(ck, v)
    service = EmbeddingService(checkpoint=ck, ann=True, max_delay_ms=0.0)
    try:
        assert service.stats()["vocab_change_reloads"] == 0
        extend_checkpoint(ck, {"dog": 9, "fox": 4}, min_count=2)
        service.reload_now()
        stats = service.stats()
        assert stats["vocab_change_reloads"] == 1
        assert service.info()["num_words"] == v.size + 2
        res = service.synonyms("dog", 2)
        assert len(res) == 2 and all(np.isfinite(s) for _, s in res)
    finally:
        service.close()


# -- the driver loop -----------------------------------------------------------------


_RUNNER_CFG = dict(vector_size=8, min_count=1, window=2, pairs_per_batch=64,
                   num_iterations=1, subsample_ratio=0.0, seed=1,
                   prefetch_chunks=0)


def test_runner_end_to_end_grows_and_publishes(tmp_path):
    d = str(tmp_path / "stream")
    os.makedirs(d)
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(12)]
    _write_segment(os.path.join(d, "seg-000.txt"),
                   [[words[i] for i in rng.integers(0, 12, 10)]
                    for _ in range(100)])
    ck = str(tmp_path / "publish" / "ck")
    tele = str(tmp_path / "continual.jsonl")
    with ContinualRunner(ck, d, str(tmp_path / "work"),
                         config_overrides=_RUNNER_CFG,
                         telemetry_path=tele) as runner:
        base = runner.ensure_base()
        assert base["action"] == "base" and base["vocab_size"] == 12
        assert runner.ensure_base()["action"] == "none"   # idempotent
        assert runner.run_once()["action"] == "idle"
        _write_segment(os.path.join(d, "seg-001.txt"),
                       [[w for w in ("w0", "fresh1", "fresh2")]
                        for _ in range(60)])
        rep = runner.run_once()
    assert rep["action"] == "increment" and rep["grew"]
    assert rep["new_words"] == 2 and rep["vocab_size"] == 14
    header = load_model_header(ck)
    assert header["vocab_size"] == 14
    assert header["train_state"].finished
    assert len(header["vocab_lineage"]) == 1
    # the published model answers for the new word
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    model = Word2VecModel.load(ck)
    assert model.find_synonyms("fresh1", 3)
    # telemetry records validate against the catalogue
    from glint_word2vec_tpu.obs.schema import validate_file
    summary = validate_file(tele)
    assert summary["ok"], summary["errors"]
    assert summary["kinds"].get("continual_extend") == 1
    assert summary["kinds"].get("continual_increment") == 2  # base + inc


def test_runner_retry_does_not_double_merge_counts(tmp_path):
    """A crash between the extension publish and the consume mark must not
    double-weight the tail's counts on retry (the cursor's counted stage)."""
    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "seg-000.txt"), [["a", "b"]] * 60)
    ck = str(tmp_path / "publish" / "ck")
    runner = ContinualRunner(ck, d, str(tmp_path / "work"),
                             config_overrides=_RUNNER_CFG)
    runner.ensure_base()
    _write_segment(os.path.join(d, "seg-001.txt"), [["a", "c"]] * 40)
    # simulate the crash: run the count+extend stage, then die before fit —
    # by crashing the fit via a broken params loader
    orig = runner._load_params

    def boom(*a, **k):
        raise RuntimeError("injected mid-increment crash")

    runner._load_params = boom
    with pytest.raises(RuntimeError):
        runner.run_once()
    counts_after_crash = load_model_header(ck)["counts"]
    runner._load_params = orig
    rep = runner.run_once()                            # the retry
    assert rep["action"] == "increment"
    np.testing.assert_array_equal(
        load_model_header(ck)["counts"], counts_after_crash)
    cur = StreamCursor(str(tmp_path / "work"))
    assert "seg-001.txt" in cur.consumed and not cur.counted


def test_trainer_extra_checkpoint_meta_rides_periodic_saves(tmp_path):
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    sents = _fit_corpus(60)
    vocab = build_vocab(sents, 1)
    cfg = Word2VecConfig(**_RESUME_CFG)
    trainer = Trainer(cfg, vocab)
    trainer.extra_checkpoint_meta = {"vocab_lineage": [{"remap": "x"}]}
    ck = str(tmp_path / "ck")
    trainer.fit(encode_sentences(sents, vocab, 1000),
                checkpoint_path=ck, checkpoint_every_steps=2)
    with open(os.path.join(ck, "metadata.json")) as f:
        meta = json.load(f)
    assert meta["vocab_lineage"] == [{"remap": "x"}]


def test_extra_metadata_refuses_reserved_keys(tmp_path):
    v = small_vocab()
    with pytest.raises(ValueError, match="writer-owned"):
        save_model(str(tmp_path / "ck"), v.words, v.counts,
                   np.zeros((4, 8), np.float32), None,
                   Word2VecConfig(vector_size=8),
                   extra_metadata={"digests": {}})


# -- the end-to-end drill (tier-1 acceptance) ----------------------------------------


def test_continual_run_smoke_drill(tmp_path):
    """The closed-loop drill: base fit → corpus append with unseen words →
    incremental fit grows V → publish → a live EmbeddingService hot-reloads
    and answers a query for a new-vocab word with zero failed queries."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "continual_run.py"),
         "--smoke", "--workdir", str(tmp_path / "drill")],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    assert report["vocab_grown"] > report["vocab_base"]
    assert report["failed_queries"] == 0 and report["refused"] == 0
    assert report["vocab_change_reloads"] >= 1
    assert report["lineage_depth"] == 1


# -- review-fix regressions ----------------------------------------------------------


def test_increment_does_not_compound_lr_or_rewrite_base_config(tmp_path):
    """The rewarm rides the dispatch-time lr scale: the PUBLISHED config
    must keep the deployment's base learning_rate after an increment (a
    config rewrite would compound to rewarm^k across k increments)."""
    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "seg-000.txt"), [["a", "b", "c"]] * 80)
    ck = str(tmp_path / "publish" / "ck")
    overrides = dict(_RUNNER_CFG, learning_rate=0.04,
                     continual_lr_rewarm=0.5)
    runner = ContinualRunner(ck, d, str(tmp_path / "work"),
                             config_overrides=overrides)
    runner.ensure_base()
    for i in (1, 2):
        _write_segment(os.path.join(d, f"seg-00{i}.txt"),
                       [["a", f"fresh{i}"]] * 50)
        assert runner.run_once()["action"] == "increment"
    cfg = load_model_header(ck)["config"]
    assert cfg.learning_rate == 0.04          # base lr, NOT 0.04 * 0.5^2
    assert cfg.continual_lr_rewarm == 0.5


def test_crash_between_extend_publish_and_cursor_save_idempotent(tmp_path):
    """The narrower crash window the counted-stage alone cannot close: die
    AFTER the extension publish but BEFORE the cursor records it. The
    lineage link's tail_fingerprint must make the retry recognize the
    already-applied merge — counts not double-weighted, no spurious second
    lineage link."""
    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "seg-000.txt"), [["a", "b"]] * 60)
    ck = str(tmp_path / "publish" / "ck")
    runner = ContinualRunner(ck, d, str(tmp_path / "work"),
                             config_overrides=_RUNNER_CFG)
    runner.ensure_base()
    _write_segment(os.path.join(d, "seg-001.txt"), [["a", "c"]] * 40)
    orig_save = runner.cursor.save
    calls = []

    def crash_once():
        calls.append(1)
        raise RuntimeError("injected crash before the cursor save")

    runner.cursor.save = crash_once
    with pytest.raises(RuntimeError):
        runner.run_once()
    assert calls                               # died in the window
    counts_after_crash = load_model_header(ck)["counts"]
    # fresh runner = fresh cursor state, exactly like a restarted process
    runner2 = ContinualRunner(ck, d, str(tmp_path / "work"),
                              config_overrides=_RUNNER_CFG)
    rep = runner2.run_once()
    assert rep["action"] == "increment"
    header = load_model_header(ck)
    np.testing.assert_array_equal(header["counts"], counts_after_crash)
    assert len(header["vocab_lineage"]) == 1   # no spurious second link
    del orig_save


def test_run_forever_reads_poll_s_from_checkpoint(tmp_path):
    """The knobs travel with the checkpoint: run_forever's default cadence
    is the checkpoint's continual_poll_s, not the dataclass default."""
    import time as _time

    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "seg-000.txt"), [["a", "b"]] * 60)
    ck = str(tmp_path / "publish" / "ck")
    runner = ContinualRunner(
        ck, d, str(tmp_path / "work"),
        config_overrides=dict(_RUNNER_CFG, continual_poll_s=0.05))
    runner.ensure_base()
    t0 = _time.monotonic()
    out = runner.run_forever(max_idle_polls=3)
    elapsed = _time.monotonic() - t0
    assert out["stopped"] == "idle"
    assert elapsed < 1.5, (
        f"idle polls took {elapsed:.1f}s — the checkpoint's "
        f"continual_poll_s=0.05 was ignored (dataclass default 2.0 used)")


def test_consumed_segment_audit_is_memoized(tmp_path, monkeypatch):
    """Idle polls must not re-CRC the whole consumed history every time —
    an unchanged stat signature skips the content re-read."""
    import glint_word2vec_tpu.continual.stream as stream_mod

    d = str(tmp_path / "stream")
    os.makedirs(d)
    _write_segment(os.path.join(d, "a.txt"), [["x", "y"]] * 5)
    stream = CorpusStream(d)
    cur = StreamCursor(str(tmp_path / "work"))
    fp = stream_mod.segment_fingerprint(stream.path("a.txt"))
    cur.mark_consumed("a.txt", fp, "vfp", {})
    calls = []
    real = stream_mod.segment_fingerprint
    monkeypatch.setattr(stream_mod, "segment_fingerprint",
                        lambda p: calls.append(p) or real(p))
    cur.new_segments(stream)
    cur.new_segments(stream)
    cur.new_segments(stream)
    assert len(calls) == 1                     # verified once, then memoized
    # a content change under the same name still fails (stat changes)
    _write_segment(os.path.join(d, "a.txt"), [["MUTATED"]] * 9)
    with pytest.raises(ValueError, match="append-only"):
        cur.new_segments(stream)


def test_fit_corpus_words_anneals_over_the_fed_corpus(tmp_path):
    """The increment decay clock: with vocab counts carrying a history far
    larger than the fed corpus, corpus_words= must anneal alpha over the
    fed tail (alpha ends low) where the default barely decays it."""
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.train.trainer import Trainer

    sents = _fit_corpus(n=80, words=6)
    tokens = sum(len(s) for s in sents)
    # a vocab whose counts claim 100x the fed corpus (the merged-history
    # shape of a continual increment)
    from glint_word2vec_tpu.data.vocab import build_vocab
    base = build_vocab(sents, 1)
    vocab = Vocabulary.from_words_and_counts(
        base.words, base.counts * 100)
    cfg = Word2VecConfig(**dict(_RESUME_CFG, heartbeat_every_steps=2,
                                steps_per_dispatch=2))
    enc = encode_sentences(sents, vocab, 1000)

    def final_alpha(**kw):
        tr = Trainer(cfg, vocab)
        tr.fit(enc, **kw)
        return tr.heartbeats[-1].alpha

    a_default = final_alpha()
    a_clocked = final_alpha(corpus_words=tokens)
    assert a_clocked < a_default * 0.5, (
        f"corpus_words did not re-arm the decay clock "
        f"(default {a_default:.5f}, clocked {a_clocked:.5f})")
