"""Full-step A/B: current 3-scatter shared-pool SGNS step vs merged-scatter variants.

HLO analysis (tools/scatter_model.py + compiled-HLO dump) shows each scatter-add pays
a fixed cost — index sort + a [B,D] update permute + a serial sorted-scatter emitter
(~27 ns/row) — and the production step pays it three times (syn0[centers],
syn1[contexts], syn1[pool]). Variants measured here, all mathematically identical to
sgns_step_shared_core (scatter-add is order-independent up to FP associativity):

    current     — sgns_step_shared_core as shipped (3 scatters)
    merged-syn1 — contexts+pool in one scatter (2 scatters)
    merged-all  — one [2V,D] array, centers/contexts/pool in ONE scatter
    merged-all + dense head H — rows < H updated via one-hot matmul (MXU) and a
                  dense slab add; only tail rows scattered. Exact (one-hot of a
                  head row is zero for tail ids), no compaction needed for A/B —
                  scatter still processes B rows but the cost model says rows are
                  what matters, so this row only shows matmul overhead vs scatter
                  savings potential with compaction.

Run: python tools/step_ab.py [--dtype f32|bf16] [--b 65536] [--pool 256]

--cbow mode: interleaved A/B of the two CBOW step formulations on the SAME
synthetic Zipf sentence stream (PERF.md §9's measurement harness):

    scatter — cbow_step_shared_core as shipped: grouped [B, 2w] context
              batches, B·C-row syn0 gather+scatter (the BENCH cbow row)
    banded  — cbow_step_banded_core: sentence-contiguous halo token blocks,
              windows derived on device from the same hash lattice, context
              traffic via prefix sums (ops/cbow_banded.py)

Both run metrics-elided with a params-carry fetch (the production regime) and
report examples/s over the REAL examples each step trains (the scatter batch
packs B live examples; a banded block trains its ~(w−1)/w·B live core slots).

Run: python tools/step_ab.py --cbow [--dtype bf16] [--b 65536] [--pool 512]
     [--window 5] [--v 200000] [--d 384]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V, D, NEG, K = 200_000, 384, 5, 16


def run_cbow_ab(args) -> None:
    """Interleaved banded-vs-scatter CBOW A/B on one shared sentence stream."""
    import jax
    import jax.numpy as jnp
    from cbow_feed import make_banded_chunk, pack_banded_feeds
    from microbench import time_chunked

    from glint_word2vec_tpu.data.hashrng import (
        STREAM_WINDOW, hash_mod_at, stream_base)
    from glint_word2vec_tpu.ops.sampler import (
        build_alias_table, sample_negatives_hash)
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, cbow_step_shared_core, init_embeddings)

    Vv, Dd = args.v, args.d
    B, P, W = args.b, args.pool, args.window
    C = 2 * W
    H = W
    T = B + 2 * H                      # banded: B core slots per step
    n_sets = 4                         # rotating chunk sets (cache variety)
    seed = 1234
    dt = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    print(f"device: {jax.devices()[0]}  CBOW A/B  dtype={args.dtype} "
          f"B={B} pool={P} window={W} V={Vv} D={Dd}", file=sys.stderr)

    rng = np.random.default_rng(0)
    counts = np.maximum(1e9 / (np.arange(Vv) + 10.0) ** 1.07, 5.0)
    p = counts / counts.sum()
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    syn0_0 = init_embeddings(Vv, Dd, jax.random.key(0)).syn0.astype(dt)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (Vv, Dd)), dt)

    # ---- one shared kept-token stream: Zipf tokens, 40-token sentences ------
    # sized so BOTH feeds draw fresh examples: banded consumes B stream tokens
    # per step, scatter B LIVE examples (~(w-1)/w of tokens are live)
    stream_len = int(n_sets * K * B * W / (W - 1) * 1.05) + 2 * H
    toks = rng.choice(Vv, size=stream_len, p=p).astype(np.int32)
    starts = np.zeros(stream_len, bool)
    starts[::40] = True
    win_base = stream_base(seed, STREAM_WINDOW, 1, 0)

    # host mirror of the device window derivation (sentence-clamped extents),
    # for the scatter batches and the real-example accounting
    ordinals = np.arange(stream_len, dtype=np.uint64)
    bdraw = hash_mod_at(win_base, ordinals, W).astype(np.int64)
    sent_id = np.cumsum(starts) - 1
    sstarts = np.flatnonzero(starts)                       # [n_sentences]
    pos = np.arange(stream_len) - sstarts[sent_id]
    nxt = np.concatenate([sstarts[1:], [stream_len]])
    avail = nxt[sent_id] - 1 - np.arange(stream_len)
    left = np.minimum(bdraw, pos)
    right = np.clip(np.minimum(bdraw - 1, avail), 0, None)
    total = left + right
    live = np.flatnonzero(total > 0)

    # ---- banded feed: K halo blocks per set (shared harness: cbow_feed.py) --
    banded_sets = pack_banded_feeds(toks, starts, T, H, n_sets, K)
    banded_live = float(len(live[live < n_sets * K * B])) / (n_sets * K)

    # ---- scatter feed: K dense [B, C] grouped batches per set ---------------
    scatter_sets = []
    li = 0
    for _ in range(n_sets):
        cb, xb, nb = [], [], []
        for _ in range(K):
            sel = live[li:li + B]
            li += B
            lv, rv = left[sel], right[sel]
            j = np.arange(C, dtype=np.int64)[None, :]
            cpos = np.where(j < lv[:, None], sel[:, None] - lv[:, None] + j,
                            sel[:, None] + j - lv[:, None] + 1)
            valid = j < (lv + rv)[:, None]
            cb.append(toks[sel])
            xb.append(np.where(valid, toks[np.clip(cpos, 0, stream_len - 1)],
                               0).astype(np.int32))
            nb.append((lv + rv).astype(np.int32))
        scatter_sets.append({
            "centers": jnp.asarray(np.stack(cb), jnp.int32),
            "contexts": jnp.asarray(np.stack(xb), jnp.int32),
            "nctx": jnp.asarray(np.stack(nb), jnp.int32),
        })

    ldt = dt
    banded_chunk = make_banded_chunk(W, P, NEG, dt, ldt, win_base, K,
                                     seed=seed)

    def scatter_chunk(params, feed, base_step, prob, alias):
        negs = sample_negatives_hash(prob, alias, seed, base_step, (K, P))

        def body(pr, inp):
            c, x, nc, ng = inp
            cmask = (jnp.arange(C)[None, :] < nc[:, None]).astype(jnp.float32)
            new_p, m = cbow_step_shared_core(
                pr, c, x, cmask, jnp.ones(B, jnp.float32), ng,
                jnp.float32(0.025), NEG, "exact", dt, ldt,
                with_metrics=False)
            return new_p, m.loss

        return jax.lax.scan(body, params, (
            feed["centers"], feed["contexts"], feed["nctx"], negs))

    runners = {}
    for name, fn, sets in (("scatter (B*C rows)", scatter_chunk, scatter_sets),
                           ("banded (prefix sums)", banded_chunk, banded_sets)):
        f = jax.jit(fn, donate_argnums=(0,))

        def run(f=f, sets=sets):
            return time_chunked(
                f,
                lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (sets[i % n_sets], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8,
                # losses are elided — the fetch must depend on the params carry
                fetch=lambda c, out: c.syn0[0, 0].astype(jnp.float32))
        runners[name] = run

    times = {k: [] for k in runners}
    for _ in range(args.repeats):
        for name, run in runners.items():
            spc = run()
            times[name].append(spc / K * 1e3)
    ex_per_step = {"scatter (B*C rows)": float(B),
                   "banded (prefix sums)": banded_live}
    print(f"\nCBOW step A/B (B={B}, pool={P}, window={W}, {args.dtype}, "
          f"median of {args.repeats} interleaved repeats):", file=sys.stderr)
    meds = {}
    for name, ts in times.items():
        med = float(np.median(ts))
        meds[name] = med
        ex = ex_per_step[name]
        print(f"  {name:24s} median {med:8.3f} ms/step  "
              f"[{min(ts):8.3f} .. {max(ts):8.3f}]  "
              f"{ex / (med / 1e3):13,.0f} examples/s "
              f"({ex:,.0f} real ex/step)", file=sys.stderr)
    sc = ex_per_step["scatter (B*C rows)"] / meds["scatter (B*C rows)"]
    bd = ex_per_step["banded (prefix sums)"] / meds["banded (prefix sums)"]
    print(f"  banded/scatter examples/s ratio: {bd / sc:.2f}x", file=sys.stderr)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="f32", choices=["f32", "bf16"])
    ap.add_argument("--b", type=int, default=65536)
    ap.add_argument("--pool", type=int, default=256)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cbow", action="store_true",
                    help="A/B the banded vs scatter CBOW step instead")
    ap.add_argument("--window", type=int, default=5)
    ap.add_argument("--v", type=int, default=V)
    ap.add_argument("--d", type=int, default=D)
    args = ap.parse_args()
    if args.cbow:
        if args.window < 2:
            ap.error("--cbow needs --window >= 2: the reference's legacy "
                     "asymmetric window draws b = nextInt(1) = 0 at window=1, "
                     "which emits no contexts at all (the config path refuses "
                     "cbow_update='banded' there for the same reason)")
        run_cbow_ab(args)
        return
    B, P = args.b, args.pool

    import jax
    import jax.numpy as jnp
    from microbench import time_chunked

    from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, _log_sigmoid, _sigmoid, init_embeddings,
        sgns_step_shared_core)

    dt = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    print(f"device: {jax.devices()[0]}  dtype={args.dtype} B={B} pool={P}",
          file=sys.stderr)

    rng = np.random.default_rng(0)
    counts = np.maximum(1e9 / (np.arange(V) + 10.0) ** 1.07, 5.0)
    p = counts / counts.sum()
    table = build_alias_table(counts)
    prob, alias = table.prob, table.alias
    syn0_0 = init_embeddings(V, D, jax.random.key(0)).syn0.astype(dt)
    syn1_0 = jnp.asarray(rng.normal(0, 0.05, (V, D)), dt)

    batches = []
    for i in range(12):
        r = np.random.default_rng(1000 + i)
        batches.append({
            "centers": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "contexts": jnp.asarray(r.choice(V, size=(K, B), p=p), jnp.int32),
            "mask": jnp.ones((K, B), jnp.float32),
        })

    def core_merged(syn, centers, contexts, mask, negatives, alpha, dense_head=0):
        """One-scatter variant on merged [2V, D] (rows V..2V-1 are syn1)."""
        cdt = jnp.float32
        e_in = syn[centers].astype(cdt)
        e_pos = syn[V + contexts].astype(cdt)
        Z = syn[V + negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        idx = jnp.concatenate([centers, V + contexts, V + negatives])
        upd = jnp.concatenate([d_in, d_pos, d_Z]).astype(syn.dtype)
        if dense_head:
            H = dense_head
            # head rows (idx % V < H) ride the MXU: one-hot matmul -> dense add
            local = jnp.where(idx >= V, idx - V, idx)
            half = (idx >= V).astype(jnp.int32)
            is_head = local < H
            oh = ((local[:, None] == jnp.arange(H)[None, :]) &
                  (half[:, None] == 0)).astype(upd.dtype)
            oh1 = ((local[:, None] == jnp.arange(H)[None, :]) &
                   (half[:, None] == 1)).astype(upd.dtype)
            head0 = oh.T @ upd
            head1 = oh1.T @ upd
            syn = syn.at[:H].add(head0)
            syn = syn.at[V:V + H].add(head1)
            idx = jnp.where(is_head, 2 * V, idx)  # dropped
            syn = syn.at[idx].add(upd, mode="drop")
        else:
            syn = syn.at[idx].add(upd)
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return syn, loss

    def core_merged_syn1(params, centers, contexts, mask, negatives, alpha):
        """contexts+pool in one scatter; syn0/syn1 stay separate (2 scatters)."""
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)
        e_pos = syn1[contexts].astype(cdt)
        Z = syn1[negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype))
        idx1 = jnp.concatenate([contexts, negatives])
        upd1 = jnp.concatenate([d_pos, d_Z]).astype(syn1.dtype)
        new_syn1 = syn1.at[idx1].add(upd1)
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_runner(kind, dense_head=0):
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                if kind == "current":
                    new_p, m = sgns_step_shared_core(
                        s, b["centers"], b["contexts"], b["mask"], ng,
                        jnp.float32(0.025), NEG, "exact", jnp.float32)
                    return new_p, m.loss
                if kind == "merged_syn1":
                    return core_merged_syn1(
                        s, b["centers"], b["contexts"], b["mask"], ng,
                        jnp.float32(0.025))
                return core_merged(
                    s, b["centers"], b["contexts"], b["mask"], ng,
                    jnp.float32(0.025), dense_head)
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        if kind == "merged":
            def mk():
                return jnp.concatenate([syn0_0, syn1_0])
        else:
            def mk():
                return EmbeddingPair(syn0_0 + 0, syn1_0 + 0)

        def run():
            return time_chunked(
                f, mk, lambda i: (batches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    # ---- center-grouped variant: the reference's wOutput shape (mllib:419) ----
    # skip-gram emits ~2*window pairs per center; grouping contexts per center
    # cuts syn0 gather+scatter rows and the pool matmul by the group width.
    W = 10                      # 2*window slots
    FILL = 0.655                # mean window fill under the reference's shrink rule
    Bc = max(1, int(B * 1.0 / (W * FILL)))  # groups per batch ~ same real pairs

    gbatches = []
    for i in range(12):
        r = np.random.default_rng(2000 + i)
        centers = np.sort(r.choice(V, size=(K, Bc), p=p), axis=-1)  # host-sorted
        ctx = r.choice(V, size=(K, Bc, W), p=p)
        n_ctx = r.integers(1, W + 1, size=(K, Bc))
        cmask = (np.arange(W)[None, None, :] < n_ctx[..., None])
        gbatches.append({
            "centers": jnp.asarray(centers, jnp.int32),
            "ctx": jnp.asarray(ctx, jnp.int32),
            "cmask": jnp.asarray(cmask, jnp.float32),
        })
    real_pairs = float(np.mean([np.asarray(g["cmask"]).sum(axis=(1, 2)).mean()
                                for g in gbatches]))

    def core_grouped(params, centers, ctx, cmask, negatives, alpha):
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)                 # [Bc, D]
        e_pos = syn1[ctx].astype(cdt)                    # [Bc, W, D]
        Z = syn1[negatives].astype(cdt)                  # [P, D]
        f_pos = jnp.einsum("bd,bwd->bw", e_in, e_pos)
        f_neg = e_in @ Z.T                               # [Bc, P] — per center!
        neg_valid = (negatives[None, :] != centers[:, None]).astype(cdt)
        n_ctx = cmask.sum(axis=-1)                       # [Bc]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * cmask
        # per-pair negative term depends only on the center -> weight by n_ctx
        g_neg = ((0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid
                 * (NEG / P)) * n_ctx[:, None]
        d_in = jnp.einsum("bw,bwd->bd", g_pos, e_pos) + g_neg @ Z
        d_pos = g_pos[..., None] * e_in[:, None, :]      # [Bc, W, D]
        d_Z = g_neg.T @ e_in                             # [P, D]
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype),
                                        indices_are_sorted=True)
        new_syn1 = syn1.at[ctx.reshape(-1)].add(
            d_pos.reshape(-1, D).astype(syn1.dtype))
        new_syn1 = new_syn1.at[negatives].add(d_Z.astype(syn1.dtype))
        loss = (f_pos * cmask).sum() / jnp.maximum(cmask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_grouped_runner():
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                return core_grouped(s, b["centers"], b["ctx"], b["cmask"], ng,
                                    jnp.float32(0.025))
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (gbatches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    # ---- host-sorted batch + indices_are_sorted on the syn0 scatter ----------
    sbatches = []
    for i in range(12):
        b = batches[i]
        c = np.asarray(b["centers"])
        x = np.asarray(b["contexts"])
        order = np.argsort(c, axis=-1)
        sbatches.append({
            "centers": jnp.asarray(np.take_along_axis(c, order, -1), jnp.int32),
            "contexts": jnp.asarray(np.take_along_axis(x, order, -1), jnp.int32),
            "mask": b["mask"],
        })

    def core_sorted(params, centers, contexts, mask, negatives, alpha):
        syn0, syn1 = params
        cdt = jnp.float32
        e_in = syn0[centers].astype(cdt)
        e_pos = syn1[contexts].astype(cdt)
        Z = syn1[negatives].astype(cdt)
        f_pos = jnp.sum(e_in * e_pos, axis=-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).astype(cdt) \
            * mask[:, None]
        g_pos = (1.0 - _sigmoid(f_pos, "exact")) * alpha * mask
        g_neg = (0.0 - _sigmoid(f_neg, "exact")) * alpha * neg_valid * (NEG / P)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        d_pos = g_pos[:, None] * e_in
        d_Z = g_neg.T @ e_in
        new_syn0 = syn0.at[centers].add(d_in.astype(syn0.dtype),
                                        indices_are_sorted=True)
        new_syn1 = syn1.at[contexts].add(d_pos.astype(syn1.dtype))
        new_syn1 = new_syn1.at[negatives].add(d_Z.astype(syn1.dtype))
        loss = (-_log_sigmoid(f_pos) * mask
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
                * (NEG / P)).sum() / jnp.maximum(mask.sum(), 1.0)
        return EmbeddingPair(new_syn0, new_syn1), loss

    def make_sorted_runner():
        def chunk(state, batch, base_step, prob, alias):
            negs = sample_negatives_hash(prob, alias, 1234, base_step, (K, P))

            def body(s, inp):
                b, ng = inp
                return core_sorted(s, b["centers"], b["contexts"], b["mask"], ng,
                                   jnp.float32(0.025))
            return jax.lax.scan(body, state, (batch, negs))

        f = jax.jit(chunk, donate_argnums=(0,))

        def run():
            return time_chunked(
                f, lambda: EmbeddingPair(syn0_0 + 0, syn1_0 + 0),
                lambda i: (sbatches[i % 12], np.int32(100 + i), prob, alias),
                n_lo=2, n_hi=8, fetch=lambda c, out: out[-1])
        return run

    runners = {
        "current (3 scatters)": make_runner("current"),
        "sorted-centers + flag": make_sorted_runner(),
        "merged-syn1 (2 scatters)": make_runner("merged_syn1"),
        "grouped-centers": make_grouped_runner(),
    }
    times = {k: [] for k in runners}
    for r in range(args.repeats):
        for name, run in runners.items():
            spc = run()
            times[name].append(spc / K * 1e3)
    print(f"\nSGNS step A/B (B={B}, pool={P}, {args.dtype}, median of "
          f"{args.repeats} interleaved repeats):", file=sys.stderr)
    for name, ts in times.items():
        med = float(np.median(ts))
        pairs = real_pairs if name == "grouped-centers" else B
        print(f"  {name:28s} median {med:7.3f} ms/step  "
              f"[{min(ts):7.3f} .. {max(ts):7.3f}]  "
              f"{pairs / (med / 1e3):13,.0f} pairs/s", file=sys.stderr)
    print(f"  (grouped: Bc={Bc} groups x W={W} slots, "
          f"{real_pairs:,.0f} real pairs/step)", file=sys.stderr)


if __name__ == "__main__":
    main()
