"""R3 transitive-closure good twin: the helper the jitted probe calls stays
device-pure; host-side flattening happens OUTSIDE the jit boundary on the
fetched result (the obs/probe.py stats_to_channels split)."""

import jax
import jax.numpy as jnp


def _stats_helper(m):
    norms = jnp.sqrt(jnp.sum(m.astype(jnp.float32) ** 2, axis=1))
    return jnp.max(norms)


def make_probe():
    def probe(params):
        return _stats_helper(params)

    return jax.jit(probe)


def fetched_to_channels(stats):
    # host-side: runs on the FETCHED result, outside any jit — allowed
    return {"max_norm": float(stats)}
