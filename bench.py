"""Benchmark: fused SGNS step throughput (word-pairs/sec) on the available accelerator.

Measures the framework's hot path — the jitted gather → batched-dot → sigmoid →
scatter-add SGNS update (glint_word2vec_tpu/ops/sgns.py) with on-device negative
sampling — on a realistic single-chip config:

    vocab 200k (Zipf counts), d=300, 8192 pairs/step, 5 negatives  (BASELINE configs 2-3
    territory; the reference's per-minibatch RPC budget capped it at ~65 pairs per
    round-trip, mllib:83-85)

The reference publishes no numbers (BASELINE.md: "none"), so ``vs_baseline`` is measured,
not quoted: the identical step math implemented with torch on the host CPU (gather +
einsum + index_add_), i.e. "what this machine could do without the accelerator". Values
> 1 mean the TPU path wins.

Prints exactly one JSON line on stdout:
    {"metric": "sgns_word_pairs_per_sec_per_chip", "value": N, "unit": "pairs/s",
     "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np

V, D, B, NEG = 200_000, 300, 8192, 5
POOL = 64          # shared negative pool (sgns_step_shared); reweighted to NEG semantics
PAD_D = 384        # lane-padded physical dim (config.pad_vector_to_lanes)
WARMUP, STEPS, SCAN_LEN = 2, 10, 20
CPU_STEPS = 10


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def zipf_counts(v: int) -> np.ndarray:
    return np.maximum(1e9 / (np.arange(v) + 10.0) ** 1.07, 5.0)


def bench_tpu(counts: np.ndarray) -> float:
    import jax
    import jax.numpy as jnp

    from glint_word2vec_tpu.ops.sampler import build_alias_table
    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, init_embeddings, sgns_step_shared)

    dev = jax.devices()[0]
    log(f"device: {dev} ({dev.platform})")
    table = build_alias_table(counts)
    params = init_embeddings(V, D, jax.random.key(0))
    # lane-pad the minor dim exactly as the Trainer does (config.pad_vector_to_lanes)
    params = EmbeddingPair(
        jnp.pad(params.syn0, ((0, 0), (0, PAD_D - D))),
        jnp.pad(params.syn1, ((0, 0), (0, PAD_D - D))))

    rng = np.random.default_rng(0)
    centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
    contexts = jnp.asarray(rng.integers(0, V, B), jnp.int32)
    mask = jnp.ones(B, jnp.float32)
    alpha = jnp.float32(0.025)

    # SCAN_LEN steps per dispatch: amortizes host->device dispatch latency (significant
    # through the remote-TPU tunnel) the same way the production trainer amortizes it by
    # keeping batches large. Params are donated — updates are in-place in HBM.
    from functools import partial

    @partial(jax.jit, donate_argnums=(0,))
    def run_chunk(params, base_key):
        def body(p, i):
            new_p, m = sgns_step_shared(
                p, centers, contexts, mask, jax.random.fold_in(base_key, i),
                alpha, table, NEG, POOL)
            return new_p, m.loss
        return jax.lax.scan(body, params, jnp.arange(SCAN_LEN))

    t0 = time.perf_counter()
    for i in range(WARMUP):
        params, losses = run_chunk(params, jax.random.key(i))
    jax.block_until_ready(params)
    log(f"compile+warmup: {time.perf_counter() - t0:.1f}s, "
        f"loss {float(losses[-1]):.4f}")

    t0 = time.perf_counter()
    for i in range(STEPS):
        params, losses = run_chunk(params, jax.random.key(WARMUP + i))
    jax.block_until_ready(params)
    dt = time.perf_counter() - t0
    pps = STEPS * SCAN_LEN * B / dt
    log(f"accelerator: {STEPS}x{SCAN_LEN} steps in {dt:.3f}s -> {pps:,.0f} pairs/s "
        f"({dt / (STEPS * SCAN_LEN) * 1e3:.2f} ms/step)")
    return pps


def bench_cpu_torch(counts: np.ndarray) -> float:
    """Same step math on host CPU with torch (gather/einsum/index_add_)."""
    import torch

    torch.manual_seed(0)
    g = torch.Generator().manual_seed(0)
    syn0 = (torch.rand(V, D, generator=g) - 0.5) / D
    syn1 = torch.zeros(V, D)
    probs = torch.tensor(counts ** 0.75, dtype=torch.float64)
    probs /= probs.sum()
    alpha = 0.025
    rng = np.random.default_rng(0)
    centers = torch.tensor(rng.integers(0, V, B), dtype=torch.long)
    contexts = torch.tensor(rng.integers(0, V, B), dtype=torch.long)

    def step():
        # identical shared-negative-pool algorithm as the accelerator side
        negatives = torch.multinomial(probs.float(), POOL, replacement=True)
        e_in = syn0[centers]
        e_pos = syn1[contexts]
        Z = syn1[negatives]
        f_pos = (e_in * e_pos).sum(-1)
        f_neg = e_in @ Z.T
        neg_valid = (negatives[None, :] != contexts[:, None]).float()
        g_pos = (1 - torch.sigmoid(f_pos)) * alpha
        g_neg = (0 - torch.sigmoid(f_neg)) * alpha * neg_valid * (NEG / POOL)
        d_in = g_pos[:, None] * e_pos + g_neg @ Z
        syn0.index_add_(0, centers, d_in)
        syn1.index_add_(0, contexts, g_pos[:, None] * e_in)
        syn1.index_add_(0, negatives, g_neg.T @ e_in)

    step()  # warmup
    t0 = time.perf_counter()
    for _ in range(CPU_STEPS):
        step()
    dt = time.perf_counter() - t0
    pps = CPU_STEPS * B / dt
    log(f"cpu-torch baseline: {CPU_STEPS} steps in {dt:.3f}s -> {pps:,.0f} pairs/s")
    return pps


def main() -> None:
    counts = zipf_counts(V)
    tpu_pps = bench_tpu(counts)
    try:
        cpu_pps = bench_cpu_torch(counts)
    except Exception as e:  # torch missing or OOM: report absolute number only
        log(f"cpu baseline failed: {e}")
        cpu_pps = None
    result = {
        "metric": "sgns_word_pairs_per_sec_per_chip",
        "value": round(tpu_pps),
        "unit": "pairs/s",
        "vs_baseline": round(tpu_pps / cpu_pps, 2) if cpu_pps else 1.0,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
