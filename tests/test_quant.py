"""Quantized ANN serving tests (ISSUE 18 — serve/quant.py + serve/ann.py):

- int8 scalar quantization: encode round-trip error bound, build
  determinism, full-probe + re-rank parity with the exact oracle;
- PQ: AUTO-floor recall on clustered geometry, exact re-ranked scores,
  footprint byte-math identities for both quantized arms;
- the recall gate: per-arm AUTO floor resolution, RecallFloorError
  refusal on an adversarial (random, unclusterable) matrix;
- search semantics preserved across ALL three storage arms: tiny-cell
  starvation under best-first probing (the PR-10 chaos-found bug),
  sub-k ``(-inf, -1)`` fill, zero-norm row exclusion, OOV KeyError;
- the shard-native build: bit-identical codes vs the in-memory build,
  structural proof that no dense [V, D] f32 copy is ever materialized
  (monkeypatched reader), f32 refusal;
- EmbeddingService integration: quant knobs from checkpoint config and
  ctor, V-grew hot reload rebuilding at the SAME arm with recall
  re-measured, the in-memory densify guard naming the shard-native
  migration;
- statusd: glint_serve_index_bytes / bytes_per_vector rendering and the
  fleet-wide footprint aggregation.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.obs.statusd import (
    fleet_prometheus_text,
    serve_prometheus_text,
)
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.serve import (
    EmbeddingService,
    Int8Storage,
    PQStorage,
    RecallFloorError,
    build_ivf,
    build_ivf_from_shards,
)
from glint_word2vec_tpu.serve.ann import (
    RECALL_FLOORS,
    _normalize_rows,
    resolve_recall_floor,
)
from glint_word2vec_tpu.serve.quant import auto_pq_m
from glint_word2vec_tpu.train.checkpoint import (
    ShardedMatrixReader,
    save_model_sharded,
)


def clustered_matrix(v=3000, d=32, clusters=40, seed=0, noise=0.35):
    """Same synthetic geometry as test_serve.py: tight unit-centroid
    cells, the shape trained embeddings actually take."""
    rng = np.random.default_rng(seed)
    cents = rng.standard_normal((clusters, d)).astype(np.float32)
    cents /= np.linalg.norm(cents, axis=1, keepdims=True)
    return (cents[rng.integers(0, clusters, v)]
            + noise * rng.standard_normal((v, d)).astype(np.float32)
            / np.sqrt(d))


def make_model(v=3000, d=32, seed=0):
    m = clustered_matrix(v, d, seed=seed)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    return Word2VecModel(vocab, jnp.asarray(m))


def _save_shards(tmp_path, matrix, name="ck"):
    """A row-shards checkpoint around a raw matrix (syn1 omitted — the
    serving tier never reads it)."""
    v, d = matrix.shape
    ck = str(tmp_path / name)
    cfg = Word2VecConfig(vector_size=d, min_count=1)
    save_model_sharded(ck, [f"w{i}" for i in range(v)],
                       np.ones(v, np.int64), jnp.asarray(matrix), None,
                       cfg)
    return ck


# -- quantized storage encodings --------------------------------------------------------


def test_int8_encode_roundtrip_and_zero_rows():
    rows = _normalize_rows(clustered_matrix(v=64, d=32, seed=3))[0]
    rows[5] = 0.0  # a zero row must stay silent, not divide-by-zero
    codes, scales = Int8Storage.encode(rows)
    assert codes.dtype == np.int8 and scales.dtype == np.float32
    deq = codes.astype(np.float32) * scales[:, None]
    # per-row max quantization error is bounded by scale/2 = maxabs/254
    assert np.max(np.abs(deq - rows)) <= np.max(np.abs(rows)) / 254 + 1e-7
    assert not codes[5].any() and scales[5] == 1.0


def test_quant_builds_are_deterministic():
    m = clustered_matrix(v=400, d=24, seed=7)
    for quant in ("int8", "pq"):
        a = build_ivf(m, seed=4, quant=quant, measure_recall=False,
                      recall_floor=0.0)
        b = build_ivf(m, seed=4, quant=quant, measure_recall=False,
                      recall_floor=0.0)
        np.testing.assert_array_equal(a._centroids, b._centroids)
        np.testing.assert_array_equal(a._ids, b._ids)
        np.testing.assert_array_equal(a._storage._codes, b._storage._codes)


def test_int8_full_probe_with_rerank_matches_exact_oracle():
    m = clustered_matrix(v=600, d=32, seed=1)
    ix = build_ivf(m, seed=0, quant="int8", measure_recall=False,
                   recall_floor=0.0)
    normed = _normalize_rows(m)[0]
    q = normed[:8]
    s, i = ix.search(q, 5, nprobe=ix.num_centroids)  # full probe
    exact = q @ normed.T
    for r in range(q.shape[0]):
        want = np.argsort(-exact[r], kind="stable")[:5]
        # the AUTO re-rank stage scores the shortlist with exact cosines,
        # so full-probe results match the oracle EXACTLY, scores included
        np.testing.assert_array_equal(i[r], want)
        np.testing.assert_allclose(s[r], exact[r][want], rtol=1e-5)


def test_pq_recall_floor_passes_on_clustered_geometry():
    m = clustered_matrix(v=3000, d=32, seed=2)
    ix = build_ivf(m, seed=0, quant="pq")  # AUTO floor 0.95 gates this
    assert ix.quant == "pq"
    assert ix.stats["recall_at_10"] >= RECALL_FLOORS["pq"]
    assert ix.stats["recall_floor"] == RECALL_FLOORS["pq"]
    assert ix.stats["pq_m"] == auto_pq_m(32)
    assert ix.stats["rerank"] >= 100  # the AUTO shortlist width


def test_footprint_byte_math_and_stats():
    v, d = 2000, 32
    m = clustered_matrix(v=v, d=d, seed=5)
    f32 = build_ivf(m, seed=0, measure_recall=False)
    i8 = build_ivf(m, seed=0, quant="int8", measure_recall=False,
                   recall_floor=0.0)
    pq = build_ivf(m, seed=0, quant="pq", measure_recall=False,
                   recall_floor=0.0)
    # exact storage identities: the quantized arms own codes, not floats
    assert i8._storage.nbytes == v * d + v * 4          # int8 + scales
    mm = pq._storage.m
    assert pq._storage.nbytes == (v * mm * 2             # uint16 codes
                                  + mm * 256 * pq._storage.dsub * 4)
    assert i8._storage.nbytes < 0.30 * f32._storage.nbytes
    for ix in (f32, i8, pq):
        assert ix.stats["index_bytes"] == ix.index_bytes
        assert (ix.stats["bytes_per_vector"]
                == round(ix.index_bytes / v, 2))
    assert i8.index_bytes < f32.index_bytes
    assert pq.index_bytes < i8.index_bytes


def test_quant_vector_is_exact_and_keep_rows_false_drops_source():
    m = clustered_matrix(v=500, d=16, seed=6)
    normed = _normalize_rows(m)[0]
    ix = build_ivf(m, seed=0, quant="pq", measure_recall=False,
                   recall_floor=0.0)
    np.testing.assert_allclose(ix.vector(17), normed[17], rtol=1e-5)
    codes_only = build_ivf(m, seed=0, quant="pq", recall_floor=0.0,
                           keep_rows=False)
    assert codes_only._row_fetch is None
    # build-time recall was still measured and travels with the index...
    assert isinstance(codes_only.stats["recall_at_10"], float)
    # ...but a post-hoc oracle needs the row source
    with pytest.raises(RuntimeError, match="keep_rows"):
        codes_only.measure_recall(np.arange(8))
    # vector() degrades to dequantized codes: right direction, not exact
    rec = codes_only.vector(17)
    assert rec.shape == normed[17].shape


# -- recall gating ----------------------------------------------------------------------


def test_resolve_recall_floor_auto_and_explicit():
    assert resolve_recall_floor(-1.0, "int8") == RECALL_FLOORS["int8"]
    assert resolve_recall_floor(None, "pq") == RECALL_FLOORS["pq"]
    assert resolve_recall_floor(-1.0, "f32") == 0.0
    assert resolve_recall_floor(0.5, "pq") == 0.5
    assert resolve_recall_floor(0.0, "int8") == 0.0  # explicit disable


def test_recall_floor_refuses_adversarial_matrix():
    # isotropic random rows are the IVF worst case: no cluster structure,
    # so probing a few cells misses most true neighbors. With re-rank
    # explicitly off, PQ's ADC ordering cannot reach a 0.95 floor here.
    rng = np.random.default_rng(0)
    m = rng.standard_normal((2500, 48)).astype(np.float32)
    with pytest.raises(RecallFloorError) as ei:
        build_ivf(m, seed=0, quant="pq", rerank=-1)
    err = ei.value
    assert err.quant == "pq"
    assert err.measured < err.floor == RECALL_FLOORS["pq"]
    assert "explicit recall_floor to override" in str(err)
    # the documented override: an explicit floor of 0 publishes anyway
    ix = build_ivf(m, seed=0, quant="pq", rerank=-1, recall_floor=0.0)
    assert ix.stats["recall_at_10"] == err.measured


# -- search semantics across all three arms ---------------------------------------------


@pytest.mark.parametrize("quant", ["f32", "int8", "pq"])
def test_tiny_cell_probing_covers_k_all_arms(quant):
    # the PR-10 chaos-found starvation bug: nprobe=1 on tiny uneven cells
    # must keep probing best-first until the pool covers k
    m = clustered_matrix(v=30, d=8, clusters=5, seed=0)
    ix = build_ivf(m, seed=0, quant=quant, measure_recall=False,
                   recall_floor=0.0)
    s, i = ix.search(m[:4], 6, nprobe=1)
    assert (i >= 0).all() and np.isfinite(s).all()
    # no duplicates inside one result row
    for r in range(4):
        assert len(set(i[r].tolist())) == 6


@pytest.mark.parametrize("quant", ["f32", "int8", "pq"])
def test_sub_k_fill_semantics_all_arms(quant):
    # fewer candidates than k: identical (-inf, -1) tail fill on every arm
    m = clustered_matrix(v=6, d=8, clusters=2, seed=1)
    ix = build_ivf(m, seed=0, quant=quant, measure_recall=False,
                   recall_floor=0.0)
    s, i = ix.search(m[:2], 10, nprobe=ix.num_centroids)
    assert (i[:, :6] >= 0).all()
    assert (i[:, 6:] == -1).all()
    assert np.isneginf(s[:, 6:]).all()


@pytest.mark.parametrize("quant", ["int8", "pq"])
def test_zero_norm_rows_never_surface_quant(quant):
    m = clustered_matrix(v=200, d=16, seed=8)
    dead = [3, 77, 150]
    m[dead] = 0.0
    ix = build_ivf(m, seed=0, quant=quant, measure_recall=False,
                   recall_floor=0.0)
    _, i = ix.search(m[:5], 8, nprobe=ix.num_centroids)
    assert not (np.isin(i, dead)).any()


def test_oov_raises_keyerror_through_quant_service():
    model = make_model(v=300, d=16)
    ix = build_ivf(np.asarray(model.syn0), seed=0, quant="int8",
                   measure_recall=False, recall_floor=0.0)
    svc = EmbeddingService(model=model, ann_index=ix)
    try:
        assert len(svc.synonyms("w0", 5)) == 5
        with pytest.raises(KeyError, match="not in vocabulary"):
            svc.synonyms("nope", 5)
    finally:
        svc.close()


# -- shard-native build -----------------------------------------------------------------


def test_shard_native_build_matches_in_memory(tmp_path):
    m = clustered_matrix(v=500, d=24, seed=9)
    ck = _save_shards(tmp_path, m)
    for quant in ("int8", "pq"):
        mem = build_ivf(m, seed=0, quant=quant, recall_floor=0.0)
        shd = build_ivf_from_shards(ck, quant=quant, seed=0,
                                    recall_floor=0.0, block_rows=64)
        assert shd.stats["build"] == "shard-native"
        np.testing.assert_array_equal(mem._centroids, shd._centroids)
        np.testing.assert_array_equal(mem._ids, shd._ids)
        np.testing.assert_array_equal(mem._storage._codes,
                                      shd._storage._codes)
        if quant == "int8":
            np.testing.assert_array_equal(mem._storage._scales,
                                          shd._storage._scales)
        # same geometry + same codes -> same measured recall
        assert shd.stats["recall_at_10"] == mem.stats["recall_at_10"]
        # word-query vectors come back exact through the shard fetch
        np.testing.assert_allclose(shd.vector(11),
                                   _normalize_rows(m)[0][11], rtol=1e-5)


def test_shard_native_build_is_structurally_dense_free(tmp_path,
                                                       monkeypatch):
    # the ISSUE-18 acceptance proof: every reader touch during the build
    # is bounded by block_rows, and the whole-matrix entry points are
    # unreachable — a dense [V, D] f32 materialization cannot happen.
    m = clustered_matrix(v=420, d=16, seed=10)
    ck = _save_shards(tmp_path, m)
    block_rows = 50
    real_read = ShardedMatrixReader.read

    def bounded_read(self, start, stop):
        assert stop - start <= block_rows, \
            f"unbounded read [{start}, {stop})"
        return real_read(self, start, stop)

    def forbidden(self, *a, **kw):
        raise AssertionError("dense read_all() inside shard-native build")

    monkeypatch.setattr(ShardedMatrixReader, "read_all", forbidden)
    monkeypatch.setattr(ShardedMatrixReader, "read", bounded_read)
    ix = build_ivf_from_shards(ck, quant="int8", seed=0, recall_floor=0.0,
                               block_rows=block_rows, train_sample=64,
                               measure_recall=False)
    assert ix.num_rows == 420
    # the recall oracle streams through the same reader in bounded blocks
    # (_ORACLE_BLOCK_BYTES, wider than block_rows at toy scale) — relax
    # the per-read bound but keep the whole-matrix entry point unreachable
    monkeypatch.setattr(ShardedMatrixReader, "read", real_read)
    ix2 = build_ivf_from_shards(ck, quant="int8", seed=0, recall_floor=0.0,
                                block_rows=block_rows, train_sample=64,
                                recall_queries=32)
    assert ix2.stats["recall_at_10"] > 0


def test_shard_native_refuses_f32(tmp_path):
    ck = _save_shards(tmp_path, clustered_matrix(v=50, d=8, seed=11))
    with pytest.raises(ValueError, match="dense \\[V, D\\] float32"):
        build_ivf_from_shards(ck, quant="f32")


def test_shard_native_recall_gate_fires(tmp_path):
    rng = np.random.default_rng(1)
    ck = _save_shards(tmp_path,
                      rng.standard_normal((800, 16)).astype(np.float32))
    with pytest.raises(RecallFloorError):
        build_ivf_from_shards(ck, quant="pq", seed=0, rerank=-1)


# -- EmbeddingService integration -------------------------------------------------------


def test_service_quant_knob_from_checkpoint_config(tmp_path):
    # the knob travels WITH the checkpoint (config -> service), ctor None
    m = clustered_matrix(v=300, d=16, seed=12)
    ck = str(tmp_path / "ck")
    cfg = Word2VecConfig(vector_size=16, min_count=1,
                         serve_ann_quant="int8",
                         serve_ann_recall_floor=0.0)
    save_model_sharded(ck, [f"w{i}" for i in range(300)],
                       np.ones(300, np.int64), jnp.asarray(m), None, cfg)
    svc = EmbeddingService(checkpoint=ck, ann=True)
    try:
        ann = svc.info()["ann"]
        assert ann["quant"] == "int8"
        assert "index_bytes" in ann and "bytes_per_vector" in ann
        assert len(svc.synonyms("w0", 5)) == 5
    finally:
        svc.close()


def test_service_shard_native_build_and_ctor_override(tmp_path):
    ck = _save_shards(tmp_path, clustered_matrix(v=300, d=16, seed=13))
    svc = EmbeddingService(checkpoint=ck, ann=True, ann_from_shards=True,
                           ann_quant="pq", ann_recall_floor=0.0)
    try:
        ann = svc.info()["ann"]
        assert ann["quant"] == "pq" and ann["build"] == "shard-native"
        assert len(svc.synonyms("w3", 5)) == 5
    finally:
        svc.close()
    with pytest.raises(ValueError, match="shard"):
        EmbeddingService(model=make_model(50, 16), ann=True,
                         ann_from_shards=True)


def test_densify_guard_names_shard_native_migration(tmp_path):
    ck = _save_shards(tmp_path, clustered_matrix(v=300, d=16, seed=14))
    with pytest.raises(RuntimeError) as ei:
        EmbeddingService(checkpoint=ck, ann=True, ann_quant="int8",
                         ann_recall_floor=0.0, ann_max_densify_bytes=1)
    msg = str(ei.value)
    assert "shard-native" in msg and "ann_from_shards" in msg
    # the shard-native path itself sails under the same guard
    svc = EmbeddingService(checkpoint=ck, ann=True, ann_from_shards=True,
                           ann_quant="int8", ann_recall_floor=0.0,
                           ann_max_densify_bytes=1)
    try:
        assert svc.info()["ann"]["quant"] == "int8"
    finally:
        svc.close()


def test_service_vgrew_reload_keeps_quant_arm_and_remeasures(tmp_path):
    # the continual-serving interplay (ISSUE 18 satellite): a vocabulary-
    # grown publish hot-reloads into a rebuild at the SAME quant arm with
    # recall re-measured on the grown matrix
    from glint_word2vec_tpu.continual.extend import extend_checkpoint
    m = clustered_matrix(v=300, d=16, seed=15)
    ck = _save_shards(tmp_path, m)
    svc = EmbeddingService(checkpoint=ck, ann=True, ann_quant="int8",
                           ann_recall_floor=0.0)
    try:
        before = svc.info()["ann"]
        assert before["quant"] == "int8" and before["rows"] == 300
        rep = extend_checkpoint(ck, {"brandnew0": 20, "brandnew1": 20},
                                min_count=1)
        svc.reload_now()
        after = svc.info()["ann"]
        assert after["quant"] == "int8"
        assert after["rows"] == rep["new_vocab_size"] == 302
        assert isinstance(after["recall_at_10"], float)
        s = svc.synonyms("brandnew0", 3)
        assert len(s) == 3 and all(np.isfinite(x) for _, x in s)
    finally:
        svc.close()


# -- observability ----------------------------------------------------------------------


def test_statusd_renders_index_footprint_gauges():
    snap = {"status": "serving", "submitted": 1, "completed": 1,
            "ann": {"recall_at_10": 0.97, "nprobe": 4, "centroids": 32,
                    "index_bytes": 123456, "bytes_per_vector": 36.5}}
    text = serve_prometheus_text(snap)
    assert "glint_serve_index_bytes 123456" in text
    assert "glint_serve_ann_bytes_per_vector 36.5" in text


def test_fleet_aggregates_index_bytes_across_replicas():
    rep = lambda b: {"state": "closed", "alive": 1, "degraded": 0,
                     "in_flight": 0, "restarts": 0, "reloads": 0,
                     "stats": {"ann": {"index_bytes": b,
                                       "bytes_per_vector": 36.0}}}
    snap = {"status": "serving", "replicas": {"r0": rep(1000),
                                              "r1": rep(2500)}}
    text = fleet_prometheus_text(snap)
    assert 'glint_serve_index_bytes{replica="r0"} 1000' in text
    assert 'glint_serve_index_bytes{replica="r1"} 2500' in text
    assert "glint_serve_fleet_index_bytes 3500" in text
