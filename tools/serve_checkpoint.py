"""Mode-B deployment surface: serve model ops from a checkpoint in a separate process.

The reference's mode B runs a standalone Glint PS cluster that training apps and query
clients both attach to (README.md:45-57, it spec:108-135). The TPU-native analog
(documented design call, models/compat.py): training owns the pod; QUERY serving reads
checkpoints — any number of serving processes can load the same checkpoint directory
(dense or row-shards; row-shards stream onto this process's mesh without a dense host
copy) and answer transform/find_synonyms while training continues writing newer
checkpoints alongside.

Protocol: JSON-lines over stdin/stdout — one request object per line, one response
object per line (the process-boundary analog of the reference's Akka query RPCs, with
the same ops the PS served: pull / multiply+top-k, mllib:514,598):

    {"op": "synonyms", "word": "berlin", "num": 10}
    {"op": "synonyms_batch", "words": ["berlin", "wien"], "num": 10}
    {"op": "synonyms_vec", "vector": [...], "num": 10}
    {"op": "vector", "word": "berlin"}
    {"op": "reload"}                      # pick up a newer checkpoint at the same path
    {"op": "info"}

Usage:
    python tools/serve_checkpoint.py /path/to/checkpoint [--mesh DATAxMODEL]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    # honor JAX_PLATFORMS even on images whose sitecustomize pins the platform
    # programmatically (env alone is not enough there — see tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint")
    ap.add_argument("--mesh", default=None,
                    help="DATAxMODEL, e.g. 1x8: load row-shards straight onto this "
                         "mesh (no dense host copy)")
    args = ap.parse_args()

    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    plan = None
    if args.mesh:
        d, m = (int(x) for x in args.mesh.lower().split("x"))
        plan = make_mesh(d, m)

    def load_with_retry(attempts=8, delay=0.25):
        """The trainer's atomic swap has a sub-second window where the checkpoint
        path is mid-rename / the old dir is being removed; a reload landing inside
        it sees FileNotFoundError or a half-listed directory. Retry over the window
        instead of bouncing the error to the client."""
        import time
        for i in range(attempts):
            try:
                return Word2VecModel.load(args.checkpoint, plan=plan)
            # only the transient swap-window failures: a missing path, half-written
            # JSON, or a metadata/words pair read across the two renames of the
            # swap (surfaces as the loader's vocab_size-mismatch ValueError).
            # Permanent problems (bad --mesh for the shard layout, corrupt arrays)
            # surface immediately instead of retrying.
            except (FileNotFoundError, json.JSONDecodeError) as e:
                last = e
            except ValueError as e:
                if "vocab_size" not in str(e) and "words" not in str(e):
                    raise
                last = e
            if i == attempts - 1:
                raise last
            time.sleep(delay)

    model = load_with_retry()

    def out(obj):
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    out({"ready": True, "num_words": model.num_words,
         "vector_size": model.vector_size})
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
            op = req["op"]
            if op == "synonyms":
                res = model.find_synonyms(req["word"], int(req.get("num", 10)))
                out({"synonyms": [[w, s] for w, s in res]})
            elif op == "synonyms_vec":
                import numpy as np
                vec = np.asarray(req["vector"], np.float32)
                res = model.find_synonyms(vec, int(req.get("num", 10)))
                out({"synonyms": [[w, s] for w, s in res]})
            elif op == "synonyms_batch":
                # many queries, one device dispatch per chunk — through a thin
                # link per-query round trips dominate (PERF.md §6)
                res = model.find_synonyms_batch(
                    list(req["words"]), int(req.get("num", 10)))
                out({"synonyms": [[[w, s] for w, s in row] for row in res]})
            elif op == "vector":
                out({"vector": model.transform(req["word"]).tolist()})
            elif op == "reload":
                old = model
                model = load_with_retry()
                old.stop()
                out({"reloaded": True, "num_words": model.num_words})
            elif op == "info":
                out({"num_words": model.num_words,
                     "vector_size": model.vector_size,
                     "iteration": (model.train_state.iteration
                                   if model.train_state else None),
                     "finished": (model.train_state.finished
                                  if model.train_state else None)})
            elif op == "quit":
                out({"bye": True})
                break
            else:
                out({"error": f"unknown op {op!r}"})
        except Exception as e:  # noqa: BLE001 — protocol errors go to the client
            out({"error": f"{type(e).__name__}: {e}"})


if __name__ == "__main__":
    main()
