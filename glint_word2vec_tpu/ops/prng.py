"""Counter-based stateless PRNG for the training hot path.

Why not ``jax.random``: on TPU, threefry (and rbg) ops inside the training program
measurably destroy step time — the scan-chunked SGNS step runs at ~2.2 ms/step with a
single in-program ``jax.random.randint`` and at ~0.04 ms/step without it (55x, measured
on v5e; see bench.py). The negative sampler only needs statistically-good, reproducible
draws, not crypto-strength ones, so the hot path uses a murmur3-finalizer hash over a
(seed, stream, counter, lane) lattice — pure vectorizable integer ops, identical results
on every backend and every device (the reference's shared-seed trick, G3 mllib:419-421,
survives as: all shards derive the same negatives from the same step counter for free).

``jax.random`` remains in use for one-time work outside the step (embedding init).
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp

_GOLDEN = 0x9E3779B9  # 2^32 / phi — Weyl-sequence increment


def mix32(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer: full avalanche on uint32."""
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def hash_bits(
    seed: Union[int, jax.Array],
    stream: int,
    counter: jax.Array,
    shape: Tuple[int, ...],
) -> jax.Array:
    """uint32 grid of pseudo-random bits, a pure function of
    (seed, stream, counter, flat index).

    ``stream`` separates independent uses at the same counter (e.g. bucket draw vs
    keep/alias draw); ``counter`` is typically the global step.
    """
    n = 1
    for d in shape:
        n *= d
    i = jax.lax.iota(jnp.uint32, n)
    s = jnp.asarray(seed).astype(jnp.uint32) * jnp.uint32(_GOLDEN)
    c = jnp.asarray(counter).astype(jnp.uint32)
    base = mix32(c ^ mix32(s ^ jnp.uint32(stream * 0x7FEB352D + 0x68E31DA4)))
    return mix32(i ^ base).reshape(shape)


def uniform01(
    seed: Union[int, jax.Array],
    stream: int,
    counter: jax.Array,
    shape: Tuple[int, ...],
) -> jax.Array:
    """float32 uniforms in [0, 1) with 24 bits of mantissa entropy."""
    bits = hash_bits(seed, stream, counter, shape)
    return (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def randint_mod(
    seed: Union[int, jax.Array],
    stream: int,
    counter: jax.Array,
    shape: Tuple[int, ...],
    bound: int,
) -> jax.Array:
    """int32 draws in [0, bound) via modulo. Bias is ≤ bound/2^32 relative
    (2e-3 ppm at bound = 10M) — negligible for negative sampling."""
    bits = hash_bits(seed, stream, counter, shape)
    return (bits % jnp.uint32(bound)).astype(jnp.int32)
