"""Fixture lock registry (mirrors the real lockcheck module's contract:
LOCK_TABLE is a pure literal the drift gate can ast.literal_eval)."""
import threading

LOCK_TABLE = {
    "outer": {"rank": 10, "kind": "lock",
              "site": "glint_word2vec_tpu/pipe.py:Pipe.__init__",
              "owner": "fixture pipe"},
    "inner": {"rank": 20, "kind": "lock",
              "site": "glint_word2vec_tpu/pipe.py:Pipe.__init__",
              "owner": "fixture pipe"},
    "ghost": {"rank": 30, "kind": "lock",
              "site": "glint_word2vec_tpu/gone.py:Gone.__init__",
              "owner": "never constructed — the stale-entry drift case"},
}


def make_lock(name):
    return threading.Lock()
