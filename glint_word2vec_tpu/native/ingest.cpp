// Native corpus ingestion: the tokenize+count and tokenize+encode passes of the
// streaming data loader (data/corpus.py / data/vocab.py), ~4-5x the pure-Python
// throughput. Replaces the hot inner loops only — vocabulary filter/sort rules
// and metadata stay in Python (data/ingest_native.py) so the ordering contract
// (count desc, stable on first occurrence — the reference's sortWith,
// mllib:266) lives in exactly one place.
//
// Tokenization contract: BIT-IDENTICAL to the Python path
// (TokenFileCorpus: text-mode line iteration + line.split()) or REFUSE.
// Each buffer is scanned first; if it contains anything whose semantics differ
// between this ASCII tokenizer and Python — unicode whitespace (U+00A0,
// U+2000-200A, ...), C0 separators 0x1C-0x1F, a lone \r (a Python universal-
// newline line break), or invalid UTF-8 (Python substitutes U+FFFD) — the call
// returns NEEDS_PYTHON and the wrapper silently falls back. Valid multi-byte
// UTF-8 (accents etc.) is fine: byte-level tokens match Python's str tokens.
//
// Memory contract: same as the Python pass — O(wave) not O(file). The file is
// processed in line-aligned ~64 MB ranges, n_threads at a time; each wave's
// buffers and outputs are written and freed before the next starts (the count
// pass's vocabulary map is the only thing that grows with corpus size, exactly
// like Python's Counter).
//
// Plain C ABI over files (no Python headers): the count pass writes words in
// FIRST-SEEN order (+ int64 counts), the encode pass writes the exact
// tokens.bin/offsets.bin layout EncodedCorpus mmaps.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kChunk = 64 << 20;  // per-range text budget (wave = T ranges)
constexpr int64_t kNeedsPython = -2;  // tokenization semantics differ: fall back

struct WordStat {
  int64_t count = 0;
  int64_t first_pos = 0;  // byte offset of first occurrence (global order key)
};

// transparent hashing: the hot loops look words up by string_view (no per-token
// allocation); std::string keys are built only on first insertion
struct SvHash {
  using is_transparent = void;
  size_t operator()(std::string_view sv) const {
    return std::hash<std::string_view>{}(sv);
  }
};
struct SvEq {
  using is_transparent = void;
  bool operator()(std::string_view a, std::string_view b) const {
    return a == b;
  }
};
template <typename V>
using SvMap = std::unordered_map<std::string, V, SvHash, SvEq>;

inline bool is_space(unsigned char c) {
  return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

// True iff Python's text-mode + line.split() would tokenize [p, end) exactly
// like the ASCII tokenizer below. Checks: C0 separators 0x1C-0x1F (Python
// str.split whitespace), lone \r (universal-newline line break), invalid
// UTF-8 (errors="replace" merges distinct byte strings), and every unicode
// whitespace code point Python splits on (0x85, 0xA0, 0x1680, 0x2000-0x200A,
// 0x2028, 0x2029, 0x202F, 0x205F, 0x3000 — the full set str.isspace() accepts
// beyond ASCII, cross-checked against CPython).
bool python_semantics_match(const unsigned char* p, const unsigned char* end) {
  while (p < end) {
    unsigned char c = *p;
    if (c < 0x80) {
      if (c >= 0x1C && c <= 0x1F) return false;
      if (c == '\r' && (p + 1 == end || p[1] != '\n')) return false;
      ++p;
      continue;
    }
    // decode one UTF-8 sequence (strict: no overlong, no surrogates)
    uint32_t cp;
    int n;
    if ((c & 0xE0) == 0xC0) { cp = c & 0x1F; n = 1; }
    else if ((c & 0xF0) == 0xE0) { cp = c & 0x0F; n = 2; }
    else if ((c & 0xF8) == 0xF0) { cp = c & 0x07; n = 3; }
    else return false;                       // stray continuation / invalid
    if (end - p <= n) return false;          // truncated sequence
    for (int i = 1; i <= n; ++i) {
      if ((p[i] & 0xC0) != 0x80) return false;
      cp = (cp << 6) | (p[i] & 0x3F);
    }
    if (n == 1 && cp < 0x80) return false;             // overlong
    if (n == 2 && cp < 0x800) return false;            // overlong
    if (n == 3 && cp < 0x10000) return false;          // overlong
    if (cp >= 0xD800 && cp <= 0xDFFF) return false;    // surrogate
    if (cp > 0x10FFFF) return false;
    if (cp == 0x85 || cp == 0xA0 || cp == 0x1680 ||
        (cp >= 0x2000 && cp <= 0x200A) || cp == 0x2028 || cp == 0x2029 ||
        cp == 0x202F || cp == 0x205F || cp == 0x3000)
      return false;                                    // unicode whitespace
    p += n + 1;
  }
  return true;
}

// 64-bit seek/tell everywhere: plain fseek takes a long, which is 32-bit on
// Windows/ILP32 and would truncate offsets past 2 GiB in exactly the multi-GB
// corpora this loader targets.
int seek64(std::FILE* f, int64_t pos, int whence) {
#ifdef _WIN32
  return _fseeki64(f, pos, whence);
#else
  return fseeko(f, static_cast<off_t>(pos), whence);
#endif
}

int64_t tell64(std::FILE* f) {
#ifdef _WIN32
  return _ftelli64(f);
#else
  return static_cast<int64_t>(ftello(f));
#endif
}

// Read [lo, hi) of the file, already line-aligned by the caller.
std::vector<char> read_range(std::FILE* f, int64_t lo, int64_t hi) {
  std::vector<char> buf(static_cast<size_t>(hi - lo));
  if (!buf.empty()) {
    seek64(f, lo, SEEK_SET);
    size_t got = std::fread(buf.data(), 1, buf.size(), f);
    buf.resize(got);
  }
  return buf;
}

// Split [0, size) into ~(size/kChunk) line-aligned ranges (each ends just
// after a '\n'), so waves of n_threads ranges bound peak memory.
std::vector<int64_t> line_aligned_cuts(std::FILE* f, int64_t size) {
  int n = static_cast<int>(std::max<int64_t>(1, (size + kChunk - 1) / kChunk));
  std::vector<int64_t> cuts{0};
  for (int i = 1; i < n; ++i) {
    int64_t target = size * i / n;
    if (target <= cuts.back()) continue;
    seek64(f, target, SEEK_SET);
    int c;
    int64_t pos = target;
    while ((c = std::fgetc(f)) != EOF) {
      ++pos;
      if (c == '\n') break;
    }
    if (pos < size && pos > cuts.back()) cuts.push_back(pos);
  }
  cuts.push_back(size);
  return cuts;
}

int64_t file_size(std::FILE* f) {
  seek64(f, 0, SEEK_END);
  int64_t n = tell64(f);
  seek64(f, 0, SEEK_SET);
  return n;
}

}  // namespace

extern "C" {

int32_t glint_ingest_abi_version() { return 2; }

// Pass 1: count words. Writes out_words (newline-separated, FIRST-SEEN file
// order) and out_counts (int64[n], same order). Returns the number of distinct
// words, -1 on I/O error, or -2 when the corpus needs Python tokenization
// semantics (caller falls back).
int64_t glint_ingest_count(const char* corpus_path, const char* out_words,
                           const char* out_counts, int32_t n_threads) {
  std::FILE* f = std::fopen(corpus_path, "rb");
  if (!f) return -1;
  int64_t size = file_size(f);
  auto cuts = line_aligned_cuts(f, size);
  std::fclose(f);
  int R = static_cast<int>(cuts.size()) - 1;
  int T = std::max(1, static_cast<int>(n_threads));

  SvMap<WordStat> all;
  std::atomic<bool> io_error{false};
  std::atomic<bool> mismatch{false};
  for (int w0 = 0; w0 < R && !io_error && !mismatch; w0 += T) {
    int nw = std::min(T, R - w0);
    std::vector<SvMap<WordStat>> maps(nw);
    std::vector<std::thread> threads;
    for (int i = 0; i < nw; ++i) {
      threads.emplace_back([&, i]() {
        int r = w0 + i;
        std::FILE* fr = std::fopen(corpus_path, "rb");
        if (!fr) { io_error = true; return; }
        auto buf = read_range(fr, cuts[r], cuts[r + 1]);
        std::fclose(fr);
        const unsigned char* ub =
            reinterpret_cast<const unsigned char*>(buf.data());
        if (!python_semantics_match(ub, ub + buf.size())) {
          mismatch = true;
          return;
        }
        auto& m = maps[i];
        m.reserve(1 << 16);
        const char* p = buf.data();
        const char* end = p + buf.size();
        const char* base = buf.data();
        while (p < end) {
          while (p < end &&
                 (is_space(static_cast<unsigned char>(*p)) || *p == '\n'))
            ++p;
          const char* w = p;
          while (p < end && !is_space(static_cast<unsigned char>(*p)) &&
                 *p != '\n')
            ++p;
          if (p > w) {
            std::string_view sv(w, static_cast<size_t>(p - w));
            auto it = m.find(sv);
            if (it == m.end()) {
              it = m.emplace(std::string(sv),
                             WordStat{0, cuts[r] + (w - base)}).first;
            }
            ++it->second.count;
          }
        }
      });
    }
    for (auto& t : threads) t.join();
    if (io_error || mismatch) break;
    // merge this wave in range order; keep the globally-first position
    for (auto& m : maps) {
      for (auto& kv : m) {
        auto ins = all.emplace(kv.first, kv.second);
        if (!ins.second) {
          ins.first->second.count += kv.second.count;
          ins.first->second.first_pos = std::min(ins.first->second.first_pos,
                                                 kv.second.first_pos);
        }
      }
    }
  }
  if (io_error) return -1;
  if (mismatch) return kNeedsPython;

  // first-seen file order == ascending first_pos
  std::vector<std::pair<const std::string*, const WordStat*>> order;
  order.reserve(all.size());
  for (auto& kv : all) order.emplace_back(&kv.first, &kv.second);
  std::sort(order.begin(), order.end(),
            [](const auto& a, const auto& b) {
              return a.second->first_pos < b.second->first_pos;
            });

  std::FILE* fw = std::fopen(out_words, "wb");
  std::FILE* fc = std::fopen(out_counts, "wb");
  if (!fw || !fc) {
    if (fw) std::fclose(fw);
    if (fc) std::fclose(fc);
    return -1;
  }
  for (auto& e : order) {
    std::fwrite(e.first->data(), 1, e.first->size(), fw);
    std::fputc('\n', fw);
    std::fwrite(&e.second->count, sizeof(int64_t), 1, fc);
  }
  std::fclose(fw);
  std::fclose(fc);
  return static_cast<int64_t>(order.size());
}

// Pass 2: encode. vocab_words is the FINAL vocabulary (newline-separated, line
// index == id). Writes tokens.bin (int32) and offsets.bin (int64, leading 0,
// one entry per emitted sentence chunk) exactly as data/corpus.py's
// encode_corpus does: OOV dropped, empty sentences skipped, chunked to
// max_sentence_length. Returns total tokens written (>= 0), -1 on error, -2
// when the corpus needs Python tokenization semantics. out_n_sents receives
// the number of sentence chunks.
int64_t glint_ingest_encode(const char* corpus_path, const char* vocab_words,
                            int32_t max_sentence_length,
                            const char* out_tokens, const char* out_offsets,
                            int32_t n_threads, int64_t* out_n_sents) {
  // vocabulary: word -> id
  SvMap<int32_t> index;
  {
    std::FILE* fv = std::fopen(vocab_words, "rb");
    if (!fv) return -1;
    auto buf = read_range(fv, 0, file_size(fv));
    std::fclose(fv);
    const char* p = buf.data();
    const char* end = p + buf.size();
    int32_t id = 0;
    index.reserve(1 << 16);
    while (p < end) {
      const char* w = p;
      while (p < end && *p != '\n') ++p;
      if (p > w) index.emplace(std::string(w, p - w), id++);
      if (p < end) ++p;
    }
  }

  std::FILE* f = std::fopen(corpus_path, "rb");
  if (!f) return -1;
  int64_t size = file_size(f);
  auto cuts = line_aligned_cuts(f, size);
  std::fclose(f);
  int R = static_cast<int>(cuts.size()) - 1;
  int T = std::max(1, static_cast<int>(n_threads));
  const int32_t msl = std::max(1, max_sentence_length);

  std::FILE* ft = std::fopen(out_tokens, "wb");
  std::FILE* fo = std::fopen(out_offsets, "wb");
  if (!ft || !fo) {
    if (ft) std::fclose(ft);
    if (fo) std::fclose(fo);
    return -1;
  }
  int64_t total = 0, nsents = 0;
  std::fwrite(&total, sizeof(int64_t), 1, fo);  // leading 0

  struct RangeOut {
    std::vector<int32_t> tokens;
    std::vector<int32_t> sent_lens;  // per emitted chunk
  };
  std::atomic<bool> io_error{false};
  std::atomic<bool> mismatch{false};
  for (int w0 = 0; w0 < R && !io_error && !mismatch; w0 += T) {
    int nw = std::min(T, R - w0);
    std::vector<RangeOut> outs(nw);
    std::vector<std::thread> threads;
    for (int i = 0; i < nw; ++i) {
      threads.emplace_back([&, i]() {
        int r = w0 + i;
        std::FILE* fr = std::fopen(corpus_path, "rb");
        if (!fr) { io_error = true; return; }
        auto buf = read_range(fr, cuts[r], cuts[r + 1]);
        std::fclose(fr);
        const unsigned char* ub =
            reinterpret_cast<const unsigned char*>(buf.data());
        if (!python_semantics_match(ub, ub + buf.size())) {
          mismatch = true;
          return;
        }
        auto& out = outs[i];
        std::vector<int32_t> ids;
        const char* p = buf.data();
        const char* end = p + buf.size();
        while (p <= end) {
          bool line_end = (p == end) || (*p == '\n');
          if (line_end) {
            for (size_t s = 0; s < ids.size(); s += msl) {
              size_t n = std::min(ids.size() - s, static_cast<size_t>(msl));
              out.tokens.insert(out.tokens.end(), ids.begin() + s,
                                ids.begin() + s + n);
              out.sent_lens.push_back(static_cast<int32_t>(n));
            }
            ids.clear();
            if (p == end) break;
            ++p;
            continue;
          }
          while (p < end && is_space(static_cast<unsigned char>(*p))) ++p;
          const char* w = p;
          while (p < end && !is_space(static_cast<unsigned char>(*p)) &&
                 *p != '\n')
            ++p;
          if (p > w) {
            auto it = index.find(
                std::string_view(w, static_cast<size_t>(p - w)));
            if (it != index.end()) ids.push_back(it->second);
          }
        }
      });
    }
    for (auto& t : threads) t.join();
    if (io_error || mismatch) break;
    for (auto& out : outs) {  // write this wave in range order, then free it
      if (!out.tokens.empty())
        std::fwrite(out.tokens.data(), sizeof(int32_t), out.tokens.size(), ft);
      for (int32_t len : out.sent_lens) {
        total += len;
        ++nsents;
        std::fwrite(&total, sizeof(int64_t), 1, fo);
      }
    }
  }
  std::fclose(ft);
  std::fclose(fo);
  if (io_error) return -1;
  if (mismatch) return kNeedsPython;
  if (out_n_sents) *out_n_sents = nsents;
  return total;
}

}  // extern "C"
