"""The declarative knob registry graftcheck enumerates from.

One entry per ``Word2VecConfig`` field — the checker FAILS (``registry_drift``)
when the dataclass and this table disagree in either direction, so a new knob
cannot ship without declaring its sampled domain here (and, via the docs gate,
without a row in docs/configuration.md). Maintenance rule, enforced:

- ``domain``  — valid sample values, boundary-biased; MUST contain the field's
  dataclass default (the shrinker resets knobs to defaults, and a default
  outside its own domain would make minimal counterexamples unreachable).
- ``auto``    — the AUTO-marker value, when the knob has resolve-later
  semantics (pool ``-1``, subsample ``-1.0``). Always also in ``domain`` so
  every tier samples the marker path.
- ``invalid`` — one out-of-range sample the construction-time validation must
  refuse (the range tier executes these). ``None`` = the knob has no invalid
  value (bools, fully-enumerated strings).
- ``dispatch_inert`` — construction/dispatch refusal logic provably never
  reads the knob; the dispatch-probe cache projects it away. Marking a
  refusal-relevant knob inert blinds property (a) to it — when a new refusal
  reads a knob, FLIP THIS OFF in the same PR.
- ``pinned``  — non-empty reason string when the domain is deliberately a
  single value (side-effectful at construction, e.g. telemetry_path opens the
  sink file).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    domain: Tuple[Any, ...]
    invalid: Optional[Any] = None
    auto: Optional[Any] = None
    dispatch_inert: bool = False
    pinned: str = ""


_K = Knob

# NB: domains are chosen so the DISPATCH PROBE stays cheap and hermetic —
# small vector sizes, a 1k-word uniform probe vocabulary (so the corpus-
# dependent duplicate-overload refusal can never fire), a single-device plan
# passed explicitly (so device-count refusals can never fire). Refusals the
# sweep observes are therefore config-driven, which is exactly the surface
# properties (a)-(d) model-check.
KNOBS = {k.name: k for k in [
    _K("vector_size", (8, 100), invalid=0),
    _K("learning_rate", (0.01875, 0.5), invalid=0.0, dispatch_inert=True),
    _K("num_partitions", (1, 4), invalid=0, dispatch_inert=True),
    _K("num_iterations", (0, 1, 2), invalid=-1, dispatch_inert=True),
    _K("min_count", (0, 5), invalid=-1, dispatch_inert=True),
    _K("max_sentence_length", (10, 1000), invalid=0, dispatch_inert=True),
    _K("window", (1, 2, 5, 127), invalid=0),
    _K("batch_size", (1, 50), invalid=0, dispatch_inert=True),
    _K("negatives", (1, 5, 25), invalid=0),
    _K("subsample_ratio", (-1.0, 0.0, 1e-4, 1e-3, 1.0), invalid=-0.5,
       auto=-1.0),
    _K("seed", (0, 1, 2 ** 31), dispatch_inert=True),
    _K("num_model_shards", (1, 2), invalid=0),
    _K("num_data_shards", (1, 2), invalid=0),
    _K("embedding_partition", ("rows", "cols"), invalid="diag"),
    _K("mesh_shape", (None, (1, 1))),
    _K("step_lowering", ("gspmd", "shard_map"), invalid="magic"),
    _K("unigram_table_size", (1, 100_000_000), invalid=0,
       dispatch_inert=True),
    _K("sample_power", (0.75, 1.0), dispatch_inert=True),
    _K("pairs_per_batch", (64, 4096, 8192), invalid=0),
    _K("sigmoid_mode", ("exact", "clipped"), invalid="lut"),
    _K("allow_unstable", (False, True)),
    _K("duplicate_scaling", (False, True)),
    _K("negative_pool", (-1, 0, 64, 2048), invalid=-2, auto=-1),
    _K("pad_vector_to_lanes", (True, False)),
    _K("param_dtype", ("float32", "bfloat16"), invalid="float8"),
    _K("compute_dtype", ("float32", "bfloat16"), invalid="float8"),
    _K("logits_dtype", ("float32", "bfloat16"), invalid="float64"),
    # --- ISSUE-14 step restructurings (PERF.md §11): all three gate
    # dispatch-path selection and carry multi-knob refusals, so none is
    # dispatch-inert ---
    _K("fused_logits", (False, True)),
    _K("bf16_chain", (False, True)),
    _K("hot_rows", (0, 8), invalid=-1),
    _K("hot_flush_every", (0, 2), invalid=-1),
    _K("use_pallas", (False, True)),
    _K("sharded_checkpoint", (False, True)),
    _K("cbow", (False, True)),
    _K("cbow_update", ("scatter", "banded"), invalid="fused"),
    _K("shuffle", (True, False), dispatch_inert=True),
    _K("min_alpha_factor", (1e-4, 1.0), dispatch_inert=True),
    _K("decay_interval_words", (1, 10_000), dispatch_inert=True),
    _K("steps_per_dispatch", (1, 16), invalid=0),
    # local-SGD merge cadence (ISSUE 17): 2 exercises the window dispatch
    # path (shard_map-only, must divide steps_per_dispatch — both refusal
    # twins live in config __post_init__ beside the dispatch guards)
    _K("sync_every", (1, 2), invalid=0),
    _K("heartbeat_every_steps", (2, 100), invalid=0, dispatch_inert=True),
    _K("prefetch_chunks", (0, 8), invalid=-1, dispatch_inert=True),
    _K("profile_dir", ("",), dispatch_inert=True,
       pinned="fit-only effect; a non-empty dir would arm the profiler on "
              "any candidate a later tool fits"),
    _K("feed_consistency_check", (False, True), dispatch_inert=True),
    _K("shard_input", (True, False)),
    _K("device_pairgen", (False, True)),
    _K("tokens_per_step", (0, 64, 200_000), invalid=-1),
    _K("producer_workers", (1, 4), invalid=0, dispatch_inert=True),
    _K("io_workers", (1, 2), invalid=0, dispatch_inert=True),
    _K("sharded_prefetch", (True, False), dispatch_inert=True),
    _K("nonfinite_policy", ("halt", "rollback", "none"), invalid="retry",
       dispatch_inert=True),
    _K("rollback_history", (1, 2), invalid=0, dispatch_inert=True),
    _K("max_rollbacks", (0, 8), invalid=-1, dispatch_inert=True),
    _K("telemetry_path", ("",), dispatch_inert=True,
       pinned="side-effectful at Trainer construction (opens the JSONL "
              "sink); the sink contract is tested in tests/test_obs.py"),
    _K("telemetry_rotate_bytes", (1, 64 << 20), invalid=0,
       dispatch_inert=True),
    _K("heartbeat_ring", (1, 512), invalid=0, dispatch_inert=True),
    _K("norm_watch", ("off", "warn", "recover", "halt"), invalid="auto"),
    _K("norm_watch_threshold", (1.0, 100.0), invalid=0.0,
       dispatch_inert=True),
    _K("norm_watch_frac", (0.01, 1.0), invalid=0.0, dispatch_inert=True),
    _K("norm_watch_max", (1.0, 1000.0), invalid=0.0, dispatch_inert=True),
    _K("max_row_norm", (0.0, 50.0), invalid=-1.0),
    _K("update_clip", (0.0, 0.5), invalid=-1.0),
    _K("row_l2", (0.0, 1e-4, 0.99), invalid=1.0),
    _K("recover_lr_backoff", (0.5, 1.0), invalid=0.0, dispatch_inert=True),
    _K("max_recoveries", (0, 4), invalid=-1, dispatch_inert=True),
    _K("profile_steps", (0, 10), invalid=-1, dispatch_inert=True),
    _K("status_port", (0,), invalid=-1, dispatch_inert=True,
       pinned="side-effectful at fit start (binds a localhost socket + "
              "serving thread); the statusd contract incl. zero-cost-when-"
              "off is tested in tests/test_statusd.py"),
    _K("blackbox_ring", (1, 256), invalid=0, dispatch_inert=True),
    # --- preemption + training-supervisor knobs (train/supervisor.py,
    # docs/robustness.md §supervisor): checkpoint_on_preempt/
    # preempt_deadline_s/peer_beacon_s are read only by the trainer's
    # signal + round-bookkeeping paths (host-side, after dispatch is
    # staged); the supervisor_* knobs only by the supervisor process —
    # dispatch-inert by construction ---
    _K("checkpoint_on_preempt", (False, True), dispatch_inert=True),
    _K("preempt_deadline_s", (1.0, 30.0), invalid=0.0, dispatch_inert=True),
    _K("peer_beacon_s", (0.0, 0.5, 5.0), invalid=-1.0, dispatch_inert=True),
    _K("supervisor_stall_s", (5.0, 300.0), invalid=0.0, dispatch_inert=True),
    _K("supervisor_max_restarts", (0, 2, 8), invalid=-1,
       dispatch_inert=True),
    _K("supervisor_loop_window", (2, 3), invalid=1, dispatch_inert=True),
    # --- serving-tier knobs (serve/, docs/serving.md): read only by the
    # serving process (EmbeddingService), never by trainer construction or
    # dispatch — dispatch-inert by construction ---
    _K("serve_max_batch", (1, 16, 64), invalid=0, dispatch_inert=True),
    _K("serve_max_delay_ms", (0.0, 2.0), invalid=-1.0, dispatch_inert=True),
    _K("serve_queue_depth", (1, 256), invalid=0, dispatch_inert=True),
    _K("serve_ann_centroids", (0, 8, 4096), invalid=-1, auto=0,
       dispatch_inert=True),
    _K("serve_ann_nprobe", (0, 1, 64), invalid=-1, auto=0,
       dispatch_inert=True),
    _K("serve_ann_quant", ("f32", "int8", "pq"), invalid="int4",
       dispatch_inert=True),
    _K("serve_ann_pq_m", (0, 8, 16), invalid=-1, auto=0,
       dispatch_inert=True),
    _K("serve_ann_rerank", (-1, 0, 64), invalid=-2, auto=0,
       dispatch_inert=True),
    _K("serve_ann_recall_floor", (-1.0, 0.0, 0.95), invalid=1.5, auto=-1.0,
       dispatch_inert=True),
    _K("serve_ann_max_densify_bytes", (0, 8 << 30), invalid=-1,
       dispatch_inert=True),
    _K("serve_reload_poll_s", (0.05, 0.5), invalid=0.0, dispatch_inert=True),
    # --- serving-fleet knobs (serve/fleet.py, docs/serving.md §5): read
    # only by the fleet router process (FleetRouter / tools/fleet_run.py),
    # never by trainer construction or dispatch — dispatch-inert by
    # construction, like the serve_* tier ---
    _K("serve_fleet_replicas", (1, 3, 8), invalid=0, dispatch_inert=True),
    _K("serve_fleet_probe_s", (0.05, 0.5), invalid=0.0, dispatch_inert=True),
    _K("serve_fleet_breaker_failures", (1, 3), invalid=0,
       dispatch_inert=True),
    _K("serve_fleet_breaker_reset_s", (0.25, 2.0), invalid=0.0,
       dispatch_inert=True),
    _K("serve_fleet_hedge_ms", (-1.0, 0.0, 5.0), invalid=-2.0, auto=-1.0,
       dispatch_inert=True),
    _K("serve_fleet_retry_deadline_s", (1.0, 10.0), invalid=0.0,
       dispatch_inert=True),
    # --- continual-training knobs (continual/, docs/continual.md): read
    # only by the continual driver (ContinualRunner), never by trainer
    # construction or dispatch — dispatch-inert by construction, like the
    # serve_* tier ---
    _K("continual_min_new_words", (1, 100), invalid=0, dispatch_inert=True),
    _K("continual_lr_rewarm", (0.5, 1.0), invalid=0.0, dispatch_inert=True),
    _K("continual_iterations", (1, 3), invalid=0, dispatch_inert=True),
    _K("continual_replay_segments", (0, 2), invalid=-1,
       dispatch_inert=True),
    _K("continual_poll_s", (0.05, 2.0), invalid=0.0, dispatch_inert=True),
]}


def config_defaults() -> dict:
    """Field -> dataclass default (the lattice's origin point)."""
    from glint_word2vec_tpu.config import Word2VecConfig
    return {f.name: f.default for f in dataclasses.fields(Word2VecConfig)}


def registry_drift() -> list:
    """Both-direction diff of the registry vs the live dataclass, plus the
    domain-contains-default invariant the shrinker depends on. Non-empty =
    the checker fails (the maintenance rule is a gate, not advice)."""
    defaults = config_defaults()
    drift = []
    for name in sorted(set(defaults) - set(KNOBS)):
        drift.append(f"config field {name!r} missing from the graftcheck "
                     f"knob registry — declare its sampled domain "
                     f"(tools/graftcheck/registry.py)")
    for name in sorted(set(KNOBS) - set(defaults)):
        drift.append(f"registry knob {name!r} no longer exists on "
                     f"Word2VecConfig — drop the stale entry")
    for name, knob in sorted(KNOBS.items()):
        if name in defaults and defaults[name] not in knob.domain:
            drift.append(f"registry domain for {name!r} does not contain "
                         f"the dataclass default {defaults[name]!r} — the "
                         f"shrinker resets knobs to defaults")
        if len(knob.domain) < 2 and not knob.pinned:
            drift.append(f"registry domain for {name!r} is a single value "
                         f"with no pinned reason — widen it or document why")
    return drift
