"""Property tests for the fused SGNS/CBOW step.

The key property: the manual scatter-update step equals SGD-via-autodiff on the SGNS loss
(with the same pre-drawn negatives) — the reference could never test this (async Hogwild
races, SURVEY §4); synchronous training makes it exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    alpha_schedule,
    cbow_step,
    init_embeddings,
    sgns_loss,
    sgns_step,
)

V, D, B, N = 50, 16, 32, 5


@pytest.fixture(scope="module")
def setup():
    key = jax.random.key(0)
    params = init_embeddings(V, D, key)
    # make syn1 nonzero so gradients flow everywhere
    params = EmbeddingPair(
        syn0=params.syn0,
        syn1=jax.random.normal(jax.random.key(1), (V, D)) * 0.1,
    )
    counts = np.arange(V, 0, -1) ** 2
    table = build_alias_table(counts)
    rng = np.random.default_rng(0)
    centers = jnp.asarray(rng.integers(0, V, B), jnp.int32)
    contexts = jnp.asarray(rng.integers(0, V, B), jnp.int32)
    mask = jnp.ones(B, jnp.float32)
    return params, table, centers, contexts, mask


def test_manual_step_matches_autodiff_sgd(setup):
    params, table, centers, contexts, mask = setup
    alpha = 0.05
    step_key = jax.random.key(42)
    new_params, metrics = sgns_step(
        params, centers, contexts, mask, step_key, alpha, table, N,
        duplicate_scaling=False)

    negatives = sample_negatives(table, step_key, (B, N))
    denom = jnp.maximum(mask.sum(), 1.0)
    grads = jax.grad(
        lambda p: sgns_loss(p, centers, contexts, negatives, mask) * denom)(params)
    exp_syn0 = params.syn0 - alpha * grads.syn0
    exp_syn1 = params.syn1 - alpha * grads.syn1
    np.testing.assert_allclose(np.asarray(new_params.syn0), np.asarray(exp_syn0),
                               atol=1e-6, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(new_params.syn1), np.asarray(exp_syn1),
                               atol=1e-6, rtol=1e-5)
    assert float(metrics.pairs) == B


def test_masked_pairs_do_not_update(setup):
    params, table, centers, contexts, _ = setup
    mask = jnp.zeros(B, jnp.float32)
    new_params, metrics = sgns_step(
        params, centers, contexts, mask, jax.random.key(0), 0.1, table, N)
    np.testing.assert_array_equal(np.asarray(new_params.syn0), np.asarray(params.syn0))
    np.testing.assert_array_equal(np.asarray(new_params.syn1), np.asarray(params.syn1))
    assert float(metrics.pairs) == 0.0


def test_partial_mask_matches_smaller_batch(setup):
    params, table, centers, contexts, _ = setup
    # Batch with the last half masked == batch of just the first half, with the caveat that
    # negatives are drawn per-slot; use the same key and compare only syn0 rows untouched by
    # negatives' e_in scatter — simplest exact check: masked-slot contributions are zero, so
    # rows appearing ONLY in masked slots are unchanged.
    mask = jnp.concatenate([jnp.ones(B // 2), jnp.zeros(B // 2)]).astype(jnp.float32)
    new_params, _ = sgns_step(
        params, centers, contexts, mask, jax.random.key(3), 0.1, table, N)
    live = set(np.asarray(centers[: B // 2]).tolist())
    dead = set(np.asarray(centers[B // 2:]).tolist()) - live
    for row in dead:
        np.testing.assert_array_equal(
            np.asarray(new_params.syn0[row]), np.asarray(params.syn0[row]))


def test_duplicate_indices_accumulate(setup):
    params, table, *_ = setup
    centers = jnp.zeros(B, jnp.int32)  # every pair hits row 0
    contexts = jnp.ones(B, jnp.int32)
    mask = jnp.ones(B, jnp.float32)
    new_params, _ = sgns_step(
        params, centers, contexts, mask, jax.random.key(5), 0.05, table, N,
        duplicate_scaling=False)
    # update to row 0 must equal B times the single-pair update (same context, same e values
    # pre-update, negatives differ per slot — so compare against per-slot sum via autodiff)
    negatives = sample_negatives(table, jax.random.key(5), (B, N))
    grads = jax.grad(
        lambda p: sgns_loss(p, centers, contexts, negatives, mask) * B)(params)
    np.testing.assert_allclose(
        np.asarray(new_params.syn0[0]),
        np.asarray(params.syn0[0] - 0.05 * grads.syn0[0]), atol=1e-6, rtol=1e-5)


def test_clipped_sigmoid_saturates(setup):
    _, table, centers, contexts, mask = setup
    # Huge positive dots → σ=1 → zero positive gradient under "clipped" mode (reference LUT
    # behavior, mllib:292-302).
    big = EmbeddingPair(
        syn0=jnp.ones((V, D)) * 10.0,
        syn1=jnp.ones((V, D)) * 10.0,
    )
    new_params, _ = sgns_step(
        big, centers, contexts, mask, jax.random.key(0), 0.1, table, N,
        sigmoid_mode="clipped")
    # positive grad is exactly 0; negative grad is exactly -1·α (σ clipped to 1 for f>6)
    # so syn1[context] rows get only the positive-side update = 0 + possible negative hits.
    # Check f_pos path: rows used only as centers changed solely via negative coefficients;
    # with all-equal embeddings every update direction is identical — simply assert finite
    # and that clipped mode differs from exact mode.
    exact_params, _ = sgns_step(
        big, centers, contexts, mask, jax.random.key(0), 0.1, table, N,
        sigmoid_mode="exact")
    assert np.all(np.isfinite(np.asarray(new_params.syn0)))
    # σ_exact(200) ≈ 1 to float precision too, so exact vs clipped agree at saturation
    np.testing.assert_allclose(np.asarray(new_params.syn0),
                               np.asarray(exact_params.syn0), atol=1e-4)


def test_negatives_colliding_with_positive_are_skipped():
    # Vocab of 1: every negative == the context word → all negative grads masked out.
    params = EmbeddingPair(syn0=jnp.ones((1, 4)) * 0.1, syn1=jnp.ones((1, 4)) * 0.1)
    table = build_alias_table(np.array([10]))
    centers = jnp.zeros(8, jnp.int32)
    contexts = jnp.zeros(8, jnp.int32)
    mask = jnp.ones(8, jnp.float32)
    new_params, metrics = sgns_step(
        params, centers, contexts, mask, jax.random.key(0), 0.1, table, 5)
    # only the positive-pair gradient applied; loss = -log σ(f_pos) only
    f = float(jnp.sum(params.syn0[0] * params.syn1[0]))
    expected_loss = -np.log(1.0 / (1.0 + np.exp(-f)))
    np.testing.assert_allclose(float(metrics.loss), expected_loss, rtol=1e-5)


def test_training_reduces_loss(setup):
    params, table, *_ = setup
    rng = np.random.default_rng(1)
    # deterministic corpus: word i co-occurs with i+1 mod 10 within first 10 words
    c = jnp.asarray(rng.integers(0, 10, 256), jnp.int32)
    x = (c + 1) % 10
    mask = jnp.ones(256, jnp.float32)
    step = jax.jit(lambda p, k: sgns_step(p, c, x, mask, k, 0.02, table, N))
    losses = []
    for i in range(60):
        params, m = step(params, jax.random.key(i))
        losses.append(float(m.loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_cbow_step_basics(setup):
    params, table, *_ = setup
    rng = np.random.default_rng(2)
    Bc, C = 64, 6
    centers = jnp.asarray(rng.integers(0, V, Bc), jnp.int32)
    contexts = jnp.asarray(rng.integers(0, V, (Bc, C)), jnp.int32)
    ctx_mask = jnp.asarray(rng.integers(0, 2, (Bc, C)), jnp.float32)
    mask = jnp.ones(Bc, jnp.float32)
    first = last = None
    for i in range(30):
        params, m = cbow_step(
            params, centers, contexts, ctx_mask, mask, jax.random.key(i), 0.1, table, N)
        if first is None:
            first = float(m.loss)
        last = float(m.loss)
    assert np.isfinite(last) and last < first


def test_cbow_masked_batch_no_update(setup):
    params, table, *_ = setup
    centers = jnp.zeros(8, jnp.int32)
    contexts = jnp.zeros((8, 4), jnp.int32)
    ctx_mask = jnp.ones((8, 4), jnp.float32)
    mask = jnp.zeros(8, jnp.float32)
    new_params, _ = cbow_step(
        params, centers, contexts, ctx_mask, mask, jax.random.key(0), 0.1, table, N)
    np.testing.assert_array_equal(np.asarray(new_params.syn0), np.asarray(params.syn0))


def test_cbow_empty_context_no_update(setup):
    params, table, *_ = setup
    centers = jnp.arange(8, dtype=jnp.int32)
    contexts = jnp.zeros((8, 4), jnp.int32)
    ctx_mask = jnp.zeros((8, 4), jnp.float32)  # no context at all
    mask = jnp.ones(8, jnp.float32)
    new_params, m = cbow_step(
        params, centers, contexts, ctx_mask, mask, jax.random.key(0), 0.1, table, N)
    np.testing.assert_array_equal(np.asarray(new_params.syn0), np.asarray(params.syn0))
    np.testing.assert_array_equal(np.asarray(new_params.syn1), np.asarray(params.syn1))
    # loss telemetry must also ignore empty-context rows entirely
    assert float(m.loss) == 0.0


def test_alpha_schedule_reference_semantics():
    # alpha = lr·(1−progress), floor lr·1e-4 (mllib:405-413)
    lr = 0.025
    assert alpha_schedule(0, 1000, lr) == pytest.approx(lr)
    assert alpha_schedule(500, 1000, lr) == pytest.approx(lr * 0.5)
    assert alpha_schedule(2000, 1000, lr) == pytest.approx(lr * 1e-4)
    # jnp path
    a = alpha_schedule(jnp.asarray(500.0), 1000.0, lr)
    np.testing.assert_allclose(float(a), lr * 0.5)


def test_init_embeddings_ranges():
    p = init_embeddings(V, D, jax.random.key(0))
    s0 = np.asarray(p.syn0)
    assert s0.max() <= 0.5 / D and s0.min() >= -0.5 / D
    assert np.all(np.asarray(p.syn1) == 0)


def test_duplicate_scaling_stabilizes_large_batches(setup):
    # Pathological density: vocab 6, batch 512, lr 0.05 — accumulate-semantics diverges,
    # scaled semantics must stay finite and learn (the sync-large-batch design point).
    _, _, *_ = setup
    counts = np.array([100, 90, 80, 70, 60, 50])
    table6 = build_alias_table(counts)
    params = init_embeddings(6, 16, jax.random.key(0))
    params = EmbeddingPair(params.syn0,
                           jax.random.normal(jax.random.key(1), (6, 16)) * 0.05)
    rng = np.random.default_rng(0)
    c = jnp.asarray(rng.integers(0, 6, 512), jnp.int32)
    x = (c + 1) % 6
    mask = jnp.ones(512, jnp.float32)
    for i in range(50):
        params, m = sgns_step(
            params, c, x, mask, jax.random.key(i), 0.05, table6, N,
            duplicate_scaling=True)
    assert np.isfinite(float(m.loss))
    assert np.all(np.isfinite(np.asarray(params.syn0)))


def test_shared_negative_step_basics(setup):
    from glint_word2vec_tpu.ops.sgns import sgns_step_shared
    params, table, centers, contexts, mask = setup
    P = 16
    new_params, m = sgns_step_shared(
        params, centers, contexts, mask, jax.random.key(0), 0.05, table, N, P)
    assert np.all(np.isfinite(np.asarray(new_params.syn0)))
    assert float(m.pairs) == B
    # masked batch -> no update, zero loss
    zp, zm = sgns_step_shared(
        params, centers, contexts, jnp.zeros(B, jnp.float32),
        jax.random.key(0), 0.05, table, N, P)
    np.testing.assert_array_equal(np.asarray(zp.syn0), np.asarray(params.syn0))
    np.testing.assert_array_equal(np.asarray(zp.syn1), np.asarray(params.syn1))
    assert float(zm.loss) == 0.0


def test_shared_negative_step_learns(setup):
    from glint_word2vec_tpu.ops.sgns import sgns_step_shared
    params, table, *_ = setup
    rng = np.random.default_rng(3)
    c = jnp.asarray(rng.integers(0, 10, 256), jnp.int32)
    x = (c + 1) % 10
    mask = jnp.ones(256, jnp.float32)
    step = jax.jit(lambda p, k: sgns_step_shared(p, c, x, mask, k, 0.02, table, N, 16))
    losses = []
    for i in range(60):
        params, m = step(params, jax.random.key(i))
        losses.append(float(m.loss))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


def test_shared_negative_pool_collision_masked():
    # Vocab of 1: the whole pool == every context word -> zero negative gradient.
    from glint_word2vec_tpu.ops.sgns import sgns_step_shared
    params = EmbeddingPair(syn0=jnp.ones((1, 4)) * 0.1, syn1=jnp.ones((1, 4)) * 0.1)
    table = build_alias_table(np.array([10]))
    centers = contexts = jnp.zeros(8, jnp.int32)
    mask = jnp.ones(8, jnp.float32)
    _, m = sgns_step_shared(
        params, centers, contexts, mask, jax.random.key(0), 0.1, table, 5, 4)
    f = float(jnp.sum(params.syn0[0] * params.syn1[0]))
    expected_loss = -np.log(1.0 / (1.0 + np.exp(-f)))
    np.testing.assert_allclose(float(m.loss), expected_loss, rtol=1e-5)


def test_shared_pool_bf16_logits_tracks_f32(setup):
    """logits_dtype="bfloat16" (PERF.md §4: halves the [B, P] chain's bandwidth) must
    produce the same update direction with only half-precision rounding noise: the
    per-row deltas stay within bf16 relative tolerance of the f32-logit step, and the
    CBOW shared path mirrors it."""
    from glint_word2vec_tpu.ops.sgns import (
        cbow_step_shared_core, sgns_step_shared_core)
    params, table, centers, contexts, mask = setup
    negs = jnp.asarray(np.random.default_rng(7).integers(0, V, 16), jnp.int32)
    ref, m_ref = sgns_step_shared_core(
        params, centers, contexts, mask, negs, jnp.float32(0.05), N)
    lo, m_lo = sgns_step_shared_core(
        params, centers, contexts, mask, negs, jnp.float32(0.05), N,
        logits_dtype=jnp.bfloat16)
    d_ref = np.asarray(ref.syn0) - np.asarray(params.syn0)
    d_lo = np.asarray(lo.syn0) - np.asarray(params.syn0)
    # bf16 has ~3 significant digits; deltas are tiny so compare against scale
    np.testing.assert_allclose(d_lo, d_ref, atol=2e-2 * np.abs(d_ref).max())
    np.testing.assert_allclose(float(m_lo.loss), float(m_ref.loss), rtol=2e-2)

    C = 4
    ctx = jnp.asarray(np.random.default_rng(8).integers(0, V, (B, C)), jnp.int32)
    cmask = jnp.ones((B, C), jnp.float32)
    ref_c, mc_ref = cbow_step_shared_core(
        params, centers, ctx, cmask, mask, negs, jnp.float32(0.05), N)
    lo_c, mc_lo = cbow_step_shared_core(
        params, centers, ctx, cmask, mask, negs, jnp.float32(0.05), N,
        logits_dtype=jnp.bfloat16)
    d_ref = np.asarray(ref_c.syn1) - np.asarray(params.syn1)
    d_lo = np.asarray(lo_c.syn1) - np.asarray(params.syn1)
    np.testing.assert_allclose(d_lo, d_ref, atol=2e-2 * np.abs(d_ref).max())
    np.testing.assert_allclose(float(mc_lo.loss), float(mc_ref.loss), rtol=2e-2)


def test_shared_pool_metrics_elision_bit_identical(setup):
    """with_metrics=False (the trainer's fast twin for chunks no heartbeat
    samples, PERF.md §4) must change ONLY the metric side-channel: parameters
    bit-identical, pairs exact, loss/mean_f_pos zeroed."""
    from glint_word2vec_tpu.ops.sgns import sgns_step_shared_core
    params, table, centers, contexts, mask = setup
    negs = jnp.asarray(np.random.default_rng(9).integers(0, V, 16), jnp.int32)
    full, m_full = sgns_step_shared_core(
        params, centers, contexts, mask, negs, jnp.float32(0.05), N)
    fast, m_fast = sgns_step_shared_core(
        params, centers, contexts, mask, negs, jnp.float32(0.05), N,
        with_metrics=False)
    np.testing.assert_array_equal(np.asarray(full.syn0), np.asarray(fast.syn0))
    np.testing.assert_array_equal(np.asarray(full.syn1), np.asarray(fast.syn1))
    assert float(m_fast.pairs) == float(m_full.pairs) == B
    assert float(m_fast.loss) == 0.0 and float(m_full.loss) > 0.0

    # the CBOW shared-pool path has the same twin contract
    from glint_word2vec_tpu.ops.sgns import cbow_step_shared_core
    C = 4
    ctx = jnp.asarray(np.random.default_rng(10).integers(0, V, (B, C)), jnp.int32)
    cmask = jnp.ones((B, C), jnp.float32)
    cf, mcf = cbow_step_shared_core(
        params, centers, ctx, cmask, mask, negs, jnp.float32(0.05), N)
    cq, mcq = cbow_step_shared_core(
        params, centers, ctx, cmask, mask, negs, jnp.float32(0.05), N,
        with_metrics=False)
    np.testing.assert_array_equal(np.asarray(cf.syn0), np.asarray(cq.syn0))
    np.testing.assert_array_equal(np.asarray(cf.syn1), np.asarray(cq.syn1))
    assert float(mcq.pairs) == float(mcf.pairs)
    assert float(mcq.loss) == 0.0 and float(mcf.loss) > 0.0


def test_shared_pool_duplicate_scaling_mean_semantics():
    """With duplicate_scaling=True on the shared-pool path, R identical pairs move
    each row exactly as far as ONE pair does (mean of identical updates), bounding the
    per-row step at any batch size; without it the movement is R-fold (sum)."""
    import jax.numpy as jnp

    from glint_word2vec_tpu.ops.sgns import EmbeddingPair, sgns_step_shared_core

    V, D, R = 12, 8, 16
    rng = np.random.default_rng(0)
    syn0 = jnp.asarray(rng.normal(0, 0.1, (V, D)), jnp.float32)
    syn1 = jnp.asarray(rng.normal(0, 0.1, (V, D)), jnp.float32)
    pool = jnp.asarray([7, 8, 9, 7], jnp.int32)  # word 7 twice: multiplicity covered
    alpha = jnp.float32(0.1)

    def run(B, scaled):
        centers = jnp.full((B,), 2, jnp.int32)
        contexts = jnp.full((B,), 5, jnp.int32)
        mask = jnp.ones((B,), jnp.float32)
        (s0, s1), _ = sgns_step_shared_core(
            EmbeddingPair(syn0, syn1), centers, contexts, mask, pool, alpha,
            num_negatives=2, duplicate_scaling=scaled)
        return np.asarray(s0), np.asarray(s1)

    one0, one1 = run(1, True)
    many0, many1 = run(R, True)
    np.testing.assert_allclose(many0, one0, rtol=2e-5, atol=1e-7)
    np.testing.assert_allclose(many1, one1, rtol=2e-5, atol=1e-7)

    # sum semantics (default) moves the center row ~R times as far
    sum0, _ = run(R, False)
    d_scaled = np.abs(many0[2] - np.asarray(syn0)[2]).sum()
    d_sum = np.abs(sum0[2] - np.asarray(syn0)[2]).sum()
    assert d_sum > 5 * d_scaled


def test_cbow_shared_pool_learns_and_masks():
    """CBOW shared-pool path (the CBOW TPU fast tier): learns a predictive toy task,
    zero-masked batches are no-ops, and pool==center collisions contribute nothing."""
    import jax

    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, cbow_step_shared_core, init_embeddings)

    V, D, B, C, P = 20, 16, 128, 4, 8
    rng = np.random.default_rng(0)
    params = init_embeddings(V, D, jax.random.key(1))
    params = EmbeddingPair(params.syn0, params.syn0[::-1] * 0.5)
    # predictable structure: center = (first context + 1) % 10
    contexts = jnp.asarray(rng.integers(0, 10, (B, C)), jnp.int32)
    centers = (contexts[:, 0] + 1) % 10
    ctx_mask = jnp.ones((B, C), jnp.float32)
    mask = jnp.ones(B, jnp.float32)

    def step(p, i):
        pool = jnp.asarray(rng.integers(10, V, P), jnp.int32)  # disjoint negatives
        return cbow_step_shared_core(
            p, centers, contexts, ctx_mask, mask, pool, jnp.float32(0.05), 3)

    losses = []
    for i in range(40):
        params, m = jax.jit(step, static_argnums=1)(params, i)
        losses.append(float(m.loss))
    assert np.all(np.isfinite(np.asarray(params.syn0)))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])

    # fully masked batch: params unchanged, zero loss
    zp, zm = cbow_step_shared_core(
        params, centers, contexts, ctx_mask, jnp.zeros(B, jnp.float32),
        jnp.asarray(rng.integers(10, V, P), jnp.int32), jnp.float32(0.05), 3)
    np.testing.assert_array_equal(np.asarray(zp.syn0), np.asarray(params.syn0))
    assert float(zm.loss) == 0.0

    # pool made entirely of the centers themselves -> negative term fully masked:
    # identical update to a pool of valid negatives with zero gradient coefficient
    all_self = jnp.full((P,), int(centers[0]), jnp.int32)
    sp, sm = cbow_step_shared_core(
        params, centers[:1], contexts[:1], ctx_mask[:1], mask[:1],
        all_self, jnp.float32(0.05), 3)
    f = float(sm.mean_f_pos)
    assert np.isfinite(f)
    # loss reduces to the positive term only
    expected = float(np.log1p(np.exp(-f)))
    np.testing.assert_allclose(float(sm.loss), expected, rtol=1e-5)
