from glint_word2vec_tpu.train.checkpoint import TrainState, load_model, save_model
from glint_word2vec_tpu.train.trainer import HeartbeatRecord, Trainer

__all__ = ["TrainState", "load_model", "save_model", "HeartbeatRecord", "Trainer"]
