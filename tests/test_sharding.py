"""Multi-chip sharding tests on the 8-device virtual CPU mesh (conftest).

The TPU analog of the reference's Docker "multi-node" integration mechanism (SURVEY §4):
validate that training and model ops compile and execute with the embeddings row-sharded
over the 'model' axis and batches split over 'data' — the layout that replaces the Glint
parameter-server sharding (G2, README.md:69).
"""

import jax
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.parallel.mesh import make_mesh, pad_vocab_for_sharding
from glint_word2vec_tpu.train.trainer import Trainer


def test_make_mesh_shapes():
    plan = make_mesh(2, 4)
    assert plan.num_data == 2 and plan.num_model == 4
    plan = make_mesh(1)  # auto model axis = all devices
    assert plan.num_model == 8
    with pytest.raises(ValueError, match="devices"):
        make_mesh(3, 4)  # 12 > 8


def test_pad_vocab_for_sharding():
    assert pad_vocab_for_sharding(3611, 1) == 3616   # lane multiple 8
    assert pad_vocab_for_sharding(3611, 4) == 3616
    assert pad_vocab_for_sharding(3611, 5) == 3640   # lcm(5,8)=40
    assert pad_vocab_for_sharding(40, 5) == 40       # already aligned


def test_sharded_training_runs_and_layout():
    rng = np.random.default_rng(0)
    sents = [[f"w{i}" for i in rng.integers(0, 50, 12)] for _ in range(60)]
    vocab = build_vocab(sents, 1)
    enc = encode_sentences(sents, vocab)
    plan = make_mesh(2, 4)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=64,
                         num_iterations=2, window=3)
    trainer = Trainer(cfg, vocab, plan=plan)
    assert trainer.padded_vocab % 4 == 0
    trainer.fit(enc)
    # params stayed row-sharded across donated updates
    assert trainer.params.syn0.sharding.is_equivalent_to(plan.embedding, 2)
    assert trainer.params.syn1.sharding.is_equivalent_to(plan.embedding, 2)
    p = trainer.unpadded_params()
    assert np.all(np.isfinite(np.asarray(p.syn0)))


def test_sharded_model_ops_match_unsharded():
    rng = np.random.default_rng(1)
    V, D = 37, 12  # deliberately not divisible by the model axis
    words = [f"w{i}" for i in range(V)]
    vocab = Vocabulary.from_words_and_counts(words, np.arange(V, 0, -1))
    syn0 = rng.normal(size=(V, D)).astype(np.float32)

    base = Word2VecModel(vocab, syn0.copy())
    plan = make_mesh(1, 8)
    sharded = Word2VecModel(vocab, syn0.copy(), plan=plan)
    assert sharded._full0.shape[0] == pad_vocab_for_sharding(V, 8)

    # every model op agrees with the unsharded computation
    np.testing.assert_allclose(sharded.transform("w3"), base.transform("w3"), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(sharded.norms), np.asarray(base.norms), rtol=1e-5)
    q = rng.normal(size=D).astype(np.float32)
    np.testing.assert_allclose(sharded.multiply(q), base.multiply(q),
                               rtol=1e-4, atol=1e-5)
    s_sharded = sharded.find_synonyms("w0", 5)
    s_base = base.find_synonyms("w0", 5)
    assert [w for w, _ in s_sharded] == [w for w, _ in s_base]
    np.testing.assert_allclose([s for _, s in s_sharded], [s for _, s in s_base],
                               rtol=1e-4)
    # padded zero rows never leak into results, even for num >= vocab
    all_syns = sharded.find_synonyms("w0", 50)
    assert len(all_syns) == V - 1


def test_data_parallel_batch_sharding():
    plan = make_mesh(4, 2)
    arr = np.arange(64, dtype=np.int32)
    out = jax.device_put(arr, plan.batch)
    assert out.sharding.is_equivalent_to(plan.batch, 1)
    np.testing.assert_array_equal(np.asarray(out), arr)


def test_graft_entry_single_chip():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    loss = jax.jit(fn)(*args)
    assert np.isfinite(float(loss))


def test_graft_entry_dryrun_multichip():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)
    ge.dryrun_multichip(2)


def test_row_and_column_sharding_train_identically():
    """GSPMD layout-independence: the same training run under row-sharded
    (north-star) and column-sharded (CIKM'16 / reference-PS, G2) embeddings must
    produce numerically identical params — the layouts differ only in which
    collectives XLA inserts (SURVEY §7.4's open question; per-chip timing needs
    real multi-chip hardware, correctness does not)."""
    import numpy as np

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(40)]
    sents = [[words[j] for j in rng.integers(0, 40, 10)] for _ in range(120)]
    vocab = build_vocab(sents, min_count=1)

    def run(partition):
        cfg = Word2VecConfig(vector_size=128, min_count=1, pairs_per_batch=256,
                             num_iterations=1, window=2, negatives=3,
                             negative_pool=8, steps_per_dispatch=2, seed=5,
                             embedding_partition=partition)
        plan = make_mesh(1, 8)
        tr = Trainer(cfg, vocab, plan=plan)
        tr.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
        return tr

    t_rows = run("rows")
    t_cols = run("cols")
    assert t_rows.params.syn0.sharding.is_equivalent_to(
        t_rows.plan.embedding, 2)
    assert t_cols.params.syn0.sharding.is_equivalent_to(
        t_cols.plan.embedding_cols, 2)
    np.testing.assert_allclose(
        np.asarray(t_rows.params.syn0), np.asarray(t_cols.params.syn0),
        rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(t_rows.params.syn1), np.asarray(t_cols.params.syn1),
        rtol=1e-5, atol=1e-7)


def test_column_sharding_rejects_sharded_checkpoint():
    import pytest

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    sents = [["a", "b", "c"]] * 10
    vocab = build_vocab(sents, min_count=1)
    # refused at CONSTRUCTION since the graftcheck parity sweep (the refusal
    # used to live only in Trainer.__init__, so the config could be
    # serialized before any Trainer rejected it)
    with pytest.raises(ValueError, match="cols"):
        Word2VecConfig(vector_size=128, min_count=1,
                       embedding_partition="cols", sharded_checkpoint=True)
    # and the dispatch-side twin still refuses a config smuggled past
    # validation (the R8 parity discipline keeps both)
    cfg = Word2VecConfig(vector_size=128, min_count=1,
                         embedding_partition="cols")
    object.__setattr__(cfg, "sharded_checkpoint", True)
    with pytest.raises(ValueError, match="cols"):
        Trainer(cfg, vocab, plan=make_mesh(1, 8))
