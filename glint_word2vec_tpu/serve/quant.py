"""Quantized IVF storage arms + the shard-native index build (ISSUE 18).

serve/ann.py owns the index structure (coarse centroids, CSR inverted
lists, best-first probing, recall gating); this module owns what a cell's
rows are STORED as when ``quant != "f32"``, and the build path that never
materializes a dense [V, D] float32 matrix:

- :class:`Int8Storage` — per-row scalar quantization: ``scale =
  maxabs/127`` per unit-normalized row, codes int8. A probed cell is one
  contiguous int8 block, scanned as ``codes.astype(f32) @ q`` then
  rescaled — the astype scratch is one cell (~mean_list_len × D × 4 B,
  L2-resident), while the DRAM read the scan is actually bound by drops
  from 4 B to 1 B per element. ~4x footprint cut, ≤ ~1e-2 relative score
  error.
- :class:`PQStorage` — product quantization (Jégou, Douze, Schmid,
  PAMI 2011): D is split into ``m`` subspaces, each coded against 256
  seeded-k-means centroids (EUCLIDEAN subspace k-means — unlike the
  coarse stage, subvectors are not unit vectors). Scanning is asymmetric
  distance computation: per query, one [m, 256] lookup table of exact
  subspace dots; a cell scan is then a pure table gather + row sum.
  Codes are stored uint16 with the ``+ 256*j`` subspace offset PRE-BAKED
  so the gather indexes one flat LUT with no per-scan arithmetic.
  ~16-32x footprint cut; exact re-rank (ann.py) restores recall.
- :class:`ShardRowFetch` — lazy exact-row source over a mmap'd
  row-shards checkpoint (train/checkpoint.py), powering PQ re-rank,
  word-query vectors, and the recall oracle without a dense copy.
- :func:`build_ivf_from_shards` — ROADMAP 1(b): builds a quantized index
  straight from ``<ckpt>/syn0.shards``, streaming bounded row blocks
  through normalize → assign → quantize. Peak extra memory is O(V) 1-D
  bookkeeping plus one [block_rows, D] f32 scratch — never [V, D] f32.

docs/serving.md §6 documents arm selection and the measured footprints.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

import numpy as np

from glint_word2vec_tpu.serve.ann import (
    IvfIndex,
    _argmax_rows,
    _gate_recall,
    _finish_stats,
    _kmeans_unit,
    _normalize_rows,
    auto_centroids,
    auto_nprobe,
    resolve_recall_floor,
)

logger = logging.getLogger("glint_word2vec_tpu")

_PQ_K = 256          # centroids per subspace — one uint8 worth, the PAMI
                     # 2011 operating point; codes carry uint16 only to
                     # pre-bake the flat-LUT subspace offset
_PQ_ITERS = 6        # subspace Lloyd iterations (cheap: dsub-dim points)


def auto_pq_m(dim: int) -> int:
    """AUTO subspace count: ~8 dims per subspace (the PAMI 2011 sweet
    spot for ADC), clamped to [1, 64]. D=128 → m=16 → 32 B/row codes."""
    return max(1, min(64, dim // 8 if dim >= 8 else 1))


class Int8Storage:
    """Per-row-scaled int8 codes in the packed-cell layout."""

    kind = "int8"

    def __init__(self, codes: np.ndarray, scales: np.ndarray):
        self._codes = codes              # [V, D] int8, list order
        self._scales = scales            # [V] f32: dequant = codes*scale

    @property
    def nbytes(self) -> int:
        return int(self._codes.nbytes + self._scales.nbytes)

    @staticmethod
    def encode(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(int8 codes, per-row scales) for unit-normalized f32 rows.
        Zero rows get scale 1 and all-zero codes (score 0 everywhere —
        same exclusion behavior as the f32 arm's zero-norm rows)."""
        maxabs = np.max(np.abs(rows), axis=1)
        scales = np.where(maxabs > 0, maxabs / 127.0, 1.0).astype(np.float32)
        codes = np.clip(np.rint(rows / scales[:, None]),
                        -127, 127).astype(np.int8)
        return codes, scales

    def scanner(self, q: np.ndarray) -> Callable[[int, int], np.ndarray]:
        codes, scales = self._codes, self._scales

        def scan(lo: int, hi: int) -> np.ndarray:
            # contiguous int8 block -> in-cache f32 -> one BLAS matvec;
            # DRAM traffic is the int8 read (1 B/elem vs f32's 4)
            return (codes[lo:hi].astype(np.float32) @ q) * scales[lo:hi]

        return scan

    def reconstruct(self, pos) -> np.ndarray:
        return (self._codes[pos].astype(np.float32)
                * np.asarray(self._scales[pos])[..., None]
                if np.ndim(pos) else
                self._codes[pos].astype(np.float32) * self._scales[pos])


class PQStorage:
    """Product-quantized codes + per-subspace codebooks, ADC scan."""

    kind = "pq"

    def __init__(self, codes: np.ndarray, codebooks: np.ndarray, dim: int):
        self.m = int(codebooks.shape[0])
        self.dsub = int(codebooks.shape[2])
        self._dim = int(dim)             # original D (≤ m*dsub zero-pad)
        self._codes = codes              # [V, m] uint16, +256*j baked in
        self._codebooks = codebooks      # [m, 256, dsub] f32
        self._flat_cb = np.ascontiguousarray(
            codebooks.reshape(self.m * _PQ_K, self.dsub))

    @property
    def nbytes(self) -> int:
        return int(self._codes.nbytes + self._codebooks.nbytes)

    @staticmethod
    def train(train_rows: np.ndarray, m: int, dsub: int,
              seed: int) -> np.ndarray:
        """Seeded EUCLIDEAN k-means per subspace over the (zero-padded)
        training sample → [m, 256, dsub] codebooks. Subvectors are not
        unit vectors, so nearest-centroid uses ``x@c.T - ||c||²/2``, and
        means are NOT re-normalized — both unlike the coarse stage."""
        rng = np.random.default_rng(seed + 1)   # decorrelate from coarse
        cb = np.zeros((m, _PQ_K, dsub), np.float32)
        n = train_rows.shape[0]
        if n == 0:
            return cb
        X = _pad_cols(train_rows, m * dsub)
        k = min(_PQ_K, n)
        for j in range(m):
            xj = np.ascontiguousarray(X[:, j * dsub:(j + 1) * dsub])
            cents = xj[rng.choice(n, size=k, replace=False)].copy()
            for _ in range(_PQ_ITERS):
                assign = np.argmax(
                    xj @ cents.T - 0.5 * np.sum(cents * cents, axis=1),
                    axis=1)
                sums = np.zeros_like(cents)
                np.add.at(sums, assign, xj)
                counts = np.bincount(assign, minlength=k)
                live = counts > 0
                sums[live] /= counts[live, None]
                dead = np.flatnonzero(~live)
                if dead.size:
                    sums[dead] = xj[rng.choice(n, size=dead.size)]
                cents = sums
            cb[j, :k] = cents
        return cb

    def encode(self, rows: np.ndarray) -> np.ndarray:
        """Nearest-codeword per subspace → [n, m] uint16 offset codes."""
        X = _pad_cols(np.asarray(rows, np.float32), self.m * self.dsub)
        out = np.empty((X.shape[0], self.m), np.uint16)
        for j in range(self.m):
            xj = X[:, j * self.dsub:(j + 1) * self.dsub]
            cj = self._codebooks[j]
            idx = np.argmax(xj @ cj.T - 0.5 * np.sum(cj * cj, axis=1),
                            axis=1)
            out[:, j] = idx + _PQ_K * j
        return out

    def scanner(self, q: np.ndarray) -> Callable[[int, int], np.ndarray]:
        # ADC: one exact [m, 256] table of subspace dots per query, then
        # every cell scan is a flat gather + row-sum — no float math on
        # the codes at all
        qr = _pad_cols(q[None, :], self.m * self.dsub).reshape(
            self.m, self.dsub)
        flat_lut = np.ascontiguousarray(np.einsum(
            "mcd,md->mc", self._codebooks, qr).ravel().astype(np.float32))
        codes = self._codes

        def scan(lo: int, hi: int) -> np.ndarray:
            return flat_lut[codes[lo:hi]].sum(axis=1)

        return scan

    def reconstruct(self, pos) -> np.ndarray:
        rec = self._flat_cb[self._codes[pos]]
        return rec.reshape(rec.shape[:-2] + (-1,))[..., :self._dim]


def _pad_cols(x: np.ndarray, width: int) -> np.ndarray:
    if x.shape[1] == width:
        return x
    out = np.zeros((x.shape[0], width), np.float32)
    out[:, :x.shape[1]] = x
    return out


def make_quant_storage(quant: str, train_rows: np.ndarray, seed: int,
                       pq_m: int, encode_blocks: Iterable[
                           Tuple[np.ndarray, np.ndarray]],
                       num_rows: int, dim: int):
    """Build a quantized storage by streaming ``encode_blocks`` — an
    iterator of ``(unit-normalized f32 rows, their PACKED positions)`` —
    into preallocated code arrays. Both the in-memory build (blocks in
    list order) and the shard-native build (blocks in row order,
    scattered via ``row_pos``) feed the same factory, so the resulting
    codes are bit-identical for the same matrix + seed either way."""
    if quant == "int8":
        codes = np.empty((num_rows, dim), np.int8)
        scales = np.empty(num_rows, np.float32)
        for rows, pos in encode_blocks:
            c, s = Int8Storage.encode(rows)
            codes[pos] = c
            scales[pos] = s
        return Int8Storage(codes, scales)
    if quant == "pq":
        m = int(pq_m) if pq_m else auto_pq_m(dim)
        dsub = -(-dim // m)
        cb = PQStorage.train(train_rows, m, dsub, seed)
        storage = PQStorage(np.empty((num_rows, m), np.uint16), cb, dim)
        for rows, pos in encode_blocks:
            storage._codes[pos] = storage.encode(rows)
        return storage
    raise ValueError(f"unknown quant arm {quant!r}")


class ShardRowFetch:
    """Lazy exact-row source over a mmap'd row-shards checkpoint: fetched
    rows are truncated to the real (unpadded) extents recorded in
    checkpoint metadata and unit-normalized. Contiguous id runs become
    single reader reads (the recall oracle streams ``arange`` blocks;
    re-rank shortlists are scattered but small), and a scattered set
    whose span is modest is served by one span read + gather."""

    kind = "row-shards"

    def __init__(self, reader, vocab_size: int, vector_size: int):
        self._reader = reader
        self._rows = int(vocab_size)
        self._cols = int(vector_size)

    def __call__(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids)
        if ids.size == 0:
            return np.zeros((0, self._cols), np.float32)
        lo, hi = int(ids.min()), int(ids.max()) + 1
        if hi - lo == ids.size and np.array_equal(ids, np.arange(lo, hi)):
            rows = self._reader.read(lo, hi)      # oracle/streaming blocks
        else:
            rows = self._reader.gather(ids)       # re-rank shortlists
        return _normalize_rows(
            np.asarray(rows[:, :self._cols], np.float32))[0]


def build_ivf_from_shards(
    checkpoint_path: str,
    quant: str = "int8",
    num_centroids: int = 0,
    nprobe: int = 0,
    seed: int = 0,
    kmeans_iters: int = 4,
    train_sample: int = 65536,
    recall_queries: int = 256,
    recall_k: int = 10,
    measure_recall: bool = True,
    pq_m: int = 0,
    rerank: int = 0,
    recall_floor: float = -1.0,
    block_rows: int = 65536,
    keep_rows: bool = True,
) -> IvfIndex:
    """Build a quantized :class:`~glint_word2vec_tpu.serve.ann.IvfIndex`
    straight from a row-shards checkpoint (ROADMAP 1(b)) without ever
    materializing a dense [V, D] float32 matrix.

    Three bounded streaming passes over the mmap'd shard files:

    1. **sample + norms** — one pass computing per-row norms (zero-norm
       exclusion, exactly as the in-memory build) plus a seeded row
       sample for k-means training;
    2. **assign** — normalize each [block_rows, D] block and assign to
       the trained coarse centroids → ``assign[V]``;
    3. **quantize + scatter** — re-stream blocks, quantize (int8 / PQ
       codes) and scatter each row's codes to its packed position.

    Peak extra memory: O(V) 1-D bookkeeping (norms, assign, ids,
    row_pos) + one [block_rows, D] f32 scratch + the sample — the codes
    array itself is the index being built. ``quant="f32"`` is refused:
    its packed copy IS a dense [V, D] f32 allocation, defeating the
    point; use :func:`~glint_word2vec_tpu.serve.ann.build_ivf`.

    Recall is measured at EVERY build against the exact oracle (streamed
    through the shard reader in bounded blocks) and gated by
    ``recall_floor`` exactly as the in-memory build; the resulting index
    keeps a :class:`ShardRowFetch` as its lazy exact-row source (re-rank
    + word-query vectors) unless ``keep_rows=False``. bf16 shards are
    handled by the reader and upcast per block."""
    from glint_word2vec_tpu.train.checkpoint import ShardedMatrixReader

    t0 = time.perf_counter()
    if quant not in ("int8", "pq"):
        raise ValueError(
            f"build_ivf_from_shards is the dense-free path: quant must be "
            f"'int8' or 'pq', got {quant!r} — an f32 packed index IS a "
            f"dense [V, D] float32 copy; use serve.ann.build_ivf for that")
    meta_path = os.path.join(checkpoint_path, "metadata.json")
    with open(meta_path, "r", encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("layout") != "row-shards":
        raise ValueError(
            f"{checkpoint_path!r} is not a row-shards checkpoint "
            f"(layout={meta.get('layout')!r})")
    V = int(meta["vocab_size"])
    D = int(meta["vector_size"])
    reader = ShardedMatrixReader(os.path.join(checkpoint_path,
                                              "syn0.shards"))
    fetch = ShardRowFetch(reader, V, D)
    rng = np.random.default_rng(seed)
    block_rows = max(int(block_rows), 1)

    # pass 1: norms (padding rows beyond V never enter; zero-norm rows
    # inside V are excluded from training/queries like the dense build)
    norms = np.empty(V, np.float32)
    for lo in range(0, V, block_rows):
        hi = min(lo + block_rows, V)
        norms[lo:hi] = np.linalg.norm(
            np.asarray(reader.read(lo, hi)[:, :D], np.float32), axis=1)
    nonzero = np.flatnonzero(norms > 0)

    # seeded training sample, fetched as sorted contiguous runs
    if nonzero.size > train_sample:
        sample_ids = np.sort(rng.choice(nonzero, size=train_sample,
                                        replace=False))
    else:
        sample_ids = nonzero
    X = fetch(sample_ids) if sample_ids.size else np.zeros((0, D),
                                                           np.float32)

    C = int(num_centroids) if num_centroids else auto_centroids(V)
    C = max(1, min(C, max(nonzero.size, 1)))
    if X.shape[0]:
        centroids = _kmeans_unit(X, C, rng, kmeans_iters)
    else:
        centroids = np.zeros((1, D), np.float32)
        C = 1

    # pass 2: assignment
    assign_all = np.empty(V, np.int32)
    for lo in range(0, V, block_rows):
        hi = min(lo + block_rows, V)
        block = _normalize_rows(
            np.asarray(reader.read(lo, hi)[:, :D], np.float32))[0]
        assign_all[lo:hi] = _argmax_rows(block, centroids)

    counts = np.bincount(assign_all, minlength=C)
    offsets = np.zeros(C + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    ids = np.argsort(assign_all, kind="stable").astype(np.int32)
    row_pos = np.empty(V, np.int64)
    row_pos[ids] = np.arange(V)

    # pass 3: quantize + scatter codes to packed positions
    def blocks():
        for lo in range(0, V, block_rows):
            hi = min(lo + block_rows, V)
            block = _normalize_rows(
                np.asarray(reader.read(lo, hi)[:, :D], np.float32))[0]
            yield block, row_pos[lo:hi]

    storage = make_quant_storage(quant, train_rows=X, seed=seed,
                                 pq_m=pq_m, encode_blocks=blocks(),
                                 num_rows=V, dim=D)

    npr = int(nprobe) if nprobe else auto_nprobe(C)
    floor = resolve_recall_floor(recall_floor, quant)
    stats: Dict = {
        "quant": quant,
        "build": "shard-native",
        "centroids": C,
        "nprobe": min(npr, C),
        "rows": V,
        "mean_list_len": round(float(counts.mean()), 2) if C else 0.0,
        "max_list_len": int(counts.max()) if C else 0,
        "recall_floor": floor,
    }
    index = IvfIndex(centroids, offsets, storage, ids, row_pos,
                     min(npr, C), stats, rerank=rerank, row_fetch=fetch)
    _finish_stats(index, t0)
    if measure_recall and nonzero.size > recall_k:
        _gate_recall(index, rng, nonzero, recall_queries, recall_k, floor)
    stats["build_seconds"] = round(time.perf_counter() - t0, 3)
    if not keep_rows:
        index._row_fetch = None
    logger.info(
        "shard-native IVF build: %s V=%d C=%d nprobe=%d quant=%s "
        "recall@%d=%s bytes/vec=%s in %.2fs",
        checkpoint_path, V, C, stats["nprobe"], quant, recall_k,
        stats.get(f"recall_at_{recall_k}"), stats["bytes_per_vector"],
        stats["build_seconds"])
    return index
