"""Deterministic fault injection + bounded-retry primitives for the runtime.

The reference system survives worker loss because Spark re-executes partitions
against Hogwild parameter servers (SURVEY §5); this single-program port has no
scheduler above it, so its fault tolerance (checkpoint integrity, non-finite
guardrails, retrying ingest — train/checkpoint.py, train/trainer.py, data/) must
be testable without flaky kill-timing. This module is the single switchboard the
runtime consults at each fault point, so a test or the chaos runner
(tools/chaos_run.py) can script "crash during the second checkpoint swap" or
"fail the first two ingest reads" deterministically.

Fault points (env-driven for subprocess tests, :func:`configure` for in-process
tests; all off by default and zero-cost when off):

- ``GLINT_FAULT_CRASH_AT_STEP=N`` — SIGKILL this process at the end of the
  dispatch round that reaches global step >= N (trainer._finish_round).
  ``GLINT_FAULT_CRASH_SIGNAL=TERM|INT|KILL`` (default KILL) picks the
  signal: TERM is the catchable graceful-kill first warning a preemption
  sends, the path the flight recorder's dump-on-SIGTERM hook rides
  (obs/blackbox.py; chaos phase ``blackbox``).
- ``GLINT_FAULT_CRASH_POINT=name[@k]`` — SIGKILL at the k-th (default first)
  pass through the named crash point. Checkpoint saves expose
  ``save:arrays-written`` (data files staged, no metadata yet),
  ``save:staged`` (staging dir complete, swap not started) and ``save:swap``
  (previous checkpoint renamed aside, replacement not yet in place — the torn
  window the SIGKILL recovery test exercises).
- ``GLINT_FAULT_CORRUPT_CKPT_BYTES=N`` — after every completed save, flip N
  bytes of one array file (deterministic offsets derived from the file bytes),
  simulating bit rot / torn writes that the digest verification must catch.
- ``GLINT_FAULT_FAIL_INGEST_FIRST_N=N`` — the first N guarded ingest I/O
  attempts raise :class:`InjectedFault` (an ``OSError``), exercising the
  bounded-backoff retry wrappers in ``data/``.
- ``GLINT_FAULT_NAN_AT_STEP=N`` — the trainer poisons one param entry with NaN
  at the first round whose global step reaches N (once), exercising the
  non-finite guardrail's halt/rollback policies.
- ``GLINT_FAULT_STALL_AT_STEP=N`` (with optional ``GLINT_FAULT_STALL_S``,
  default 30) — the trainer sleeps ``stall_s`` seconds INSIDE the round that
  reaches global step >= N (once): a deterministic in-step hang with no step
  advance and no heartbeat, the signature the supervisor's stall watchdog
  (train/supervisor.py, ``config.supervisor_stall_s``) must detect and kill.
  The sleep is sliced so an intervening signal handler (the SIGTERM blackbox
  dump) still runs promptly; the stalled round itself never finishes early —
  exactly a wedged collective/IO from the watchdog's point of view.
- ``GLINT_FAULT_SCALE_PARAMS_AT_STEP=N`` (with optional
  ``GLINT_FAULT_SCALE_PARAMS_FACTOR``, default 1e6, and
  ``GLINT_FAULT_SCALE_PARAMS_TIMES``, default 1) — the trainer multiplies
  the whole params carry at the first round reaching step N (and, with
  TIMES > 1, each qualifying round after until the count is spent): a FINITE
  norm blowup, the measured large-vocab collapse signature the non-finite
  guardrail cannot see — exercising the norm watchdog and its recovery
  ladder (``config.norm_watch``, obs/watch.py, trainer._watchdog_check).

SIGKILL (not ``sys.exit``) is deliberate: no ``finally`` blocks, no atexit, no
flushes — the same failure surface as an OOM-kill or preemption.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

logger = logging.getLogger("glint_word2vec_tpu")

T = TypeVar("T")


class InjectedFault(OSError):
    """A scripted fault from this module — an OSError so the production retry
    paths treat it exactly like a real transient I/O failure."""


class NonFiniteParamsError(RuntimeError):
    """Raised by the trainer's non-finite guardrail under ``policy="halt"`` (or
    when ``rollback`` has no snapshot left / exhausted its retry budget)."""


class NormBlowupError(RuntimeError):
    """Raised by the norm watchdog (``config.norm_watch="halt"``,
    obs/watch.py) on a FINITE norm blowup — the measured large-vocab collapse
    channel the non-finite guardrail cannot see (EVAL.md round-5 ladder)."""


@dataclasses.dataclass
class FaultPlan:
    """One scripted fault schedule. All zeros/empties = no faults."""

    crash_at_step: int = 0
    crash_signal: str = "KILL"     # which signal the crash points send to
                                   # self. "KILL" (default): the OOM/
                                   # preemption-hard surface — no finally,
                                   # no handlers, nothing flushes. "TERM":
                                   # the graceful-kill FIRST WARNING a k8s
                                   # eviction/preemption sends — catchable,
                                   # so the flight-recorder SIGTERM hook
                                   # (obs/blackbox.py) can be chaos-tested
                                   # end-to-end. "INT": delivered as
                                   # KeyboardInterrupt through the abort
                                   # path
    crash_point: str = ""          # e.g. "save:swap" or "save:swap@2"
    corrupt_checkpoint_bytes: int = 0
    fail_ingest_first_n: int = 0
    nan_at_step: int = 0
    scale_params_at_step: int = 0  # multiply the params carry by
                                   # scale_params_factor (once) — a FINITE
                                   # blowup: the norm watchdog's channel, a
                                   # state the nan_at_step injection cannot
                                   # produce (isfinite stays True throughout)
    stall_at_step: int = 0         # sleep stall_s inside the round reaching
                                   # this global step (once) — the
                                   # no-progress hang the supervisor's
                                   # stall watchdog detects; 0 = off
    stall_s: float = 30.0
    scale_params_factor: float = 1e6
    scale_params_times: int = 1    # how many rounds the scale injection
                                   # fires (each subsequent qualifying round
                                   # re-fires until the count is spent) — a
                                   # repeatedly-reblowing run, the schedule
                                   # the norm_watch="recover" budget-
                                   # exhaustion chaos phase needs: every
                                   # recovery restores a good snapshot and
                                   # the next firing blows it up again


_override: Optional[FaultPlan] = None
_counters: dict = {}


def configure(**kwargs) -> FaultPlan:
    """Install an in-process fault plan (tests); overrides the env until
    :func:`reset`. Resets all hit counters."""
    global _override
    _override = FaultPlan(**kwargs)
    _counters.clear()
    return _override


def reset() -> None:
    """Clear any in-process plan and all hit counters (env vars still apply)."""
    global _override
    _override = None
    _counters.clear()


def _env_int(name: str) -> int:
    v = os.environ.get(name, "")
    try:
        return int(v) if v else 0
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", name, v)
        return 0


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name, "")
    try:
        return float(v) if v else default
    except ValueError:
        logger.warning("ignoring non-float %s=%r", name, v)
        return default


def active_plan() -> FaultPlan:
    """The effective plan: the in-process override if set, else the env (read
    fresh each call — fault consults sit on cold paths, and tests flip env
    vars mid-process)."""
    if _override is not None:
        return _override
    return FaultPlan(
        crash_at_step=_env_int("GLINT_FAULT_CRASH_AT_STEP"),
        crash_signal=os.environ.get("GLINT_FAULT_CRASH_SIGNAL", "KILL"),
        crash_point=os.environ.get("GLINT_FAULT_CRASH_POINT", ""),
        corrupt_checkpoint_bytes=_env_int("GLINT_FAULT_CORRUPT_CKPT_BYTES"),
        fail_ingest_first_n=_env_int("GLINT_FAULT_FAIL_INGEST_FIRST_N"),
        nan_at_step=_env_int("GLINT_FAULT_NAN_AT_STEP"),
        stall_at_step=_env_int("GLINT_FAULT_STALL_AT_STEP"),
        stall_s=_env_float("GLINT_FAULT_STALL_S", 30.0),
        scale_params_at_step=_env_int("GLINT_FAULT_SCALE_PARAMS_AT_STEP"),
        scale_params_factor=_env_float(
            "GLINT_FAULT_SCALE_PARAMS_FACTOR", 1e6),
        scale_params_times=max(
            _env_int("GLINT_FAULT_SCALE_PARAMS_TIMES"), 1),
    )


def _crash_now(reason: str) -> None:
    # stderr directly (not logging): handlers may buffer, and under the
    # default SIGKILL nothing after this line runs. A scripted crash_signal
    # of TERM/INT instead exercises the CATCHABLE-death surface (the
    # graceful first warning a preemption sends) — the flight recorder's
    # dump-on-SIGTERM hook is chaos-tested through exactly this path.
    sig = {"KILL": signal.SIGKILL, "TERM": signal.SIGTERM,
           "INT": signal.SIGINT}.get(
        active_plan().crash_signal.upper(), signal.SIGKILL)
    os.write(2, f"[glint-fault] SIG{signal.Signals(sig).name[3:]}: "
                f"{reason}\n".encode())
    os.kill(os.getpid(), sig)


def crash_at_step(global_step: int) -> None:
    """Trainer hook: die when the run reaches the scripted global step."""
    p = active_plan()
    if p.crash_at_step and global_step >= p.crash_at_step:
        _crash_now(f"crash_at_step {p.crash_at_step} (global_step {global_step})")


def _parse_point(spec: str) -> Tuple[str, int]:
    if "@" in spec:
        name, _, nth = spec.rpartition("@")
        try:
            return name, max(1, int(nth))
        except ValueError:
            return spec, 1
    return spec, 1


def crash_point(name: str) -> None:
    """Named crash point (e.g. inside checkpoint save). Dies on the k-th pass
    when the plan scripts ``name@k`` (default k=1)."""
    p = active_plan()
    if not p.crash_point:
        return
    want, nth = _parse_point(p.crash_point)
    if want != name:
        return
    hits = _counters.get(("point", name), 0) + 1
    _counters[("point", name)] = hits
    if hits >= nth:
        _crash_now(f"crash_point {name} (hit {hits})")


def take_nan_injection(global_step: int) -> bool:
    """Trainer hook: True exactly once, at the first round whose global step
    reaches the scripted ``nan_at_step``."""
    p = active_plan()
    if not p.nan_at_step or global_step < p.nan_at_step:
        return False
    if _counters.get("nan_done"):
        return False
    _counters["nan_done"] = True
    logger.warning("injecting NaN into params at global step %d (scripted "
                   "nan_at_step=%d)", global_step, p.nan_at_step)
    return True


def maybe_stall(global_step: int) -> float:
    """Trainer hook: sleep ``stall_s`` seconds at the first round whose
    global step reaches the scripted ``stall_at_step`` (once per process).
    Returns the scripted stall duration (0.0 = did not fire). The sleep is
    sliced into sub-second waits so a signal handler interrupting it (the
    SIGTERM blackbox-dump hook) returns to the stall, not past it — the
    round stays wedged for the full duration, like a real hung collective,
    and only SIGKILL (the supervisor's escalation) ends it early."""
    p = active_plan()
    if not p.stall_at_step or global_step < p.stall_at_step:
        return 0.0
    if _counters.get("stall_done"):
        return 0.0
    _counters["stall_done"] = True
    logger.warning("injecting %.1fs in-step stall at global step %d "
                   "(scripted stall_at_step=%d)", p.stall_s, global_step,
                   p.stall_at_step)
    end = time.monotonic() + p.stall_s
    while True:
        left = end - time.monotonic()
        if left <= 0:
            break
        time.sleep(min(left, 0.25))
    return float(p.stall_s)


def take_scale_injection(global_step: int) -> float:
    """Trainer hook: the scripted scale factor at the first round whose
    global step reaches ``scale_params_at_step`` — and, with
    ``scale_params_times > 1``, at each subsequent qualifying round until the
    count is spent (the repeated-reblowup schedule the recovery-budget chaos
    phase drives); 0.0 otherwise. The deterministic FINITE-blowup twin of
    :func:`take_nan_injection` — scaled params stay finite, so the non-finite
    guardrail must stay silent while the norm watchdog (obs/watch.py)
    fires."""
    p = active_plan()
    if not p.scale_params_at_step or global_step < p.scale_params_at_step:
        return 0.0
    done = _counters.get("scale_done", 0)
    if done >= max(p.scale_params_times, 1):
        return 0.0
    _counters["scale_done"] = done + 1
    logger.warning(
        "injecting finite param blowup (x%g) at global step %d (scripted "
        "scale_params_at_step=%d, firing %d/%d)", p.scale_params_factor,
        global_step, p.scale_params_at_step, done + 1,
        max(p.scale_params_times, 1))
    return float(p.scale_params_factor)


def maybe_fail_ingest(what: str) -> None:
    """Ingest-I/O hook: raise :class:`InjectedFault` for the first
    ``fail_ingest_first_n`` guarded attempts."""
    p = active_plan()
    if not p.fail_ingest_first_n:
        return
    n = _counters.get("ingest", 0)
    if n >= p.fail_ingest_first_n:
        return
    _counters["ingest"] = n + 1
    raise InjectedFault(
        f"injected ingest fault {n + 1}/{p.fail_ingest_first_n}: {what}")


def corrupt_checkpoint(path: str) -> None:
    """Post-save hook: flip ``corrupt_checkpoint_bytes`` bytes of one array
    file under the completed checkpoint at ``path`` — deterministic offsets (a
    function of the file size), so a scripted corruption is reproducible."""
    p = active_plan()
    n = p.corrupt_checkpoint_bytes
    if not n:
        return
    target = None
    for cand in ("syn0.npy", "syn1.npy", "counts.npy"):
        if os.path.exists(os.path.join(path, cand)):
            target = os.path.join(path, cand)
            break
    if target is None:
        shards = os.path.join(path, "syn0.shards")
        if os.path.isdir(shards):
            names = sorted(f for f in os.listdir(shards) if f.endswith(".npy"))
            if names:
                target = os.path.join(shards, names[0])
    if target is None:
        logger.warning("corrupt_checkpoint: no array file under %r", path)
        return
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        for i in range(n):
            # land inside the array payload (skip the ~128-byte .npy header)
            off = 128 + (size // 3 + i * 7919) % max(size - 129, 1)
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ 0xFF]))
    logger.warning("corrupt_checkpoint: flipped %d byte(s) of %s", n, target)


def retry_io(
    fn: Callable[[], T],
    what: str,
    attempts: int = 5,
    base_delay: float = 0.05,
    max_delay: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
) -> T:
    """Run ``fn`` with bounded exponential backoff — the retry contract for
    every flaky-I/O surface in ``data/`` (corpus opens, encoded-corpus mmaps,
    native ingest passes). Delays are deterministic (no jitter): the producers
    these wrap are single-caller, so thundering-herd spreading buys nothing and
    determinism keeps the fault tests exact. Permanent errors (missing path,
    permissions, disk full, read-only fs) fail fast — retrying those burns the
    whole backoff budget, and for restart-from-scratch encode attempts re-runs
    a potentially multi-GB pass, with no chance of success. Re-raises the last
    error once the attempt budget is spent."""
    import errno
    permanent_types = (FileNotFoundError, PermissionError, IsADirectoryError,
                       NotADirectoryError)
    permanent_errnos = (errno.ENOENT, errno.EACCES, errno.EISDIR,
                        errno.ENOSPC, errno.EROFS)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — cold path
            last = e
            if (isinstance(e, permanent_types)
                    or getattr(e, "errno", None) in permanent_errnos
                    or i == attempts - 1):
                break
            delay = min(base_delay * (2.0 ** i), max_delay)
            logger.warning("%s failed (%s); retry %d/%d in %.2fs",
                           what, e, i + 1, attempts - 1, delay)
            time.sleep(delay)
    assert last is not None
    raise last
