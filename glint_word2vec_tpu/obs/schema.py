"""The telemetry JSONL schema: one versioned catalogue + the validator.

Every record the sink writes carries ``schema`` (this module's
:data:`SCHEMA_VERSION`), ``kind`` (one of :data:`KINDS`), and ``t`` (unix
seconds). The validator is the drift gate: tests and CI validate every
emitted file against the catalogue here, so a field rename/removal fails the
build instead of silently orphaning downstream consumers of old run logs.
Additive fields are fine (consumers must ignore unknown keys); renaming or
removing a required field — or changing a type — requires a version bump and
a catalogue entry, reviewed like any contract change.

Run as a CLI (the CI schema-validation step)::

    python -m glint_word2vec_tpu.obs.schema run.jsonl [more.jsonl ...]

Prints one JSON summary line on stdout; exit code 0 iff every record of
every file validates.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

SCHEMA_VERSION = 1

# null is legal wherever a number is: the sink writes non-finite measured
# values (NaN loss in a diverging run) as null to keep every line strict
# RFC-8259 JSON (obs/sink.py _sanitize)
_NUM = (int, float, type(None))

# kind -> {field: allowed python types}. These are the REQUIRED fields; extra
# keys are always allowed (additive evolution).
KINDS: Dict[str, Dict[str, tuple]] = {
    "run_start": {
        "run_id": (str,),
        "vocab_size": (int,),
        "mesh": (list,),
        "config": (dict,),       # the stability-relevant knob subset
    },
    "heartbeat": {
        "step": (int,),
        "words": (int,),
        "alpha": _NUM,
        "loss": _NUM,
        "mean_f_pos": _NUM,
        "pairs_per_sec": _NUM,
        "host_wait_s": _NUM,     # host-side wait since the previous heartbeat
        "dispatch_s": _NUM,      # dispatch time since the previous heartbeat
        # optional: "norms" (the probe channel dict) when the probe ran
    },
    "watchdog": {
        "step": (int,),
        "policy": (str,),        # "warn" | "recover" | "halt"
        "reason": (str,),
        "channels": (dict,),     # the probe channels the decision was made on
    },
    # one per norm_watch="recover" ladder action (ADDITIVE under the schema
    # evolution rule: a brand-new kind; no existing field moved). Emitted
    # BEFORE the rollback mutates any state, so even a crash mid-recovery
    # leaves the evidence in the run log — and the budget-exhaustion record
    # (action="halt") lands before the NormBlowupError raise, the same
    # record-before-raise contract as the watchdog-halt path.
    "recovery": {
        "step": (int,),          # global step the firing probe observed
        "action": (str,),        # "rollback" | "halt" (budget exhausted)
        "reason": (str,),        # the watchdog firing reason
        "snapshot_step": (int,), # restore point (-1 when action="halt")
        "recoveries_performed": (int,),  # AFTER this action
        "max_recoveries": (int,),
        "lr_scale": _NUM,        # effective lr multiplier AFTER this action
        "max_row_norm": _NUM,    # engaged clamp AFTER this action (0 = off)
        "channels": (dict,),
    },
    "run_end": {
        "run_id": (str,),
        "status": (str,),        # "ok" | "error"
        "steps": (int,),
        "pairs_trained": _NUM,
        "host_wait_s_total": _NUM,
        "dispatch_s_total": _NUM,
        "watchdog_fires": (int,),
    },
}

_COMMON = {"schema": (int,), "kind": (str,), "t": _NUM}


def validate_record(rec: Any) -> List[str]:
    """Errors for one parsed record; empty list = valid."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    errs: List[str] = []
    for field, types in _COMMON.items():
        if field not in rec:
            errs.append(f"missing common field {field!r}")
        elif not isinstance(rec[field], types) or isinstance(rec[field], bool):
            errs.append(f"{field!r} has type {type(rec[field]).__name__}")
    if errs:
        return errs
    if rec["schema"] != SCHEMA_VERSION:
        return [f"schema version {rec['schema']} != {SCHEMA_VERSION} "
                f"(drift: bump the catalogue, not just the writer)"]
    kind = rec["kind"]
    if kind not in KINDS:
        return [f"unknown kind {kind!r}"]
    for field, types in KINDS[kind].items():
        if field not in rec:
            errs.append(f"{kind}: missing field {field!r}")
        elif not isinstance(rec[field], types) or (
                isinstance(rec[field], bool) and bool not in types):
            errs.append(f"{kind}.{field} has type {type(rec[field]).__name__}, "
                        f"expected {'/'.join(t.__name__ for t in types)}")
    return errs


def validate_file(path: str, max_errors: int = 20) -> Dict[str, Any]:
    """Validate every line of a telemetry JSONL file (rotated segments are
    just more files — pass each). Returns a summary dict with per-kind counts
    and the first ``max_errors`` error strings."""
    counts: Dict[str, int] = {}
    errors: List[str] = []
    n = 0
    with open(path, "r", encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            n += 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"{path}:{lineno}: not JSON ({e})")
                continue
            errs = validate_record(rec)
            if errs:
                errors.extend(f"{path}:{lineno}: {e}" for e in errs)
            else:
                counts[rec["kind"]] = counts.get(rec["kind"], 0) + 1
    return {"path": path, "records": n, "kinds": counts,
            "ok": not errors, "errors": errors[:max_errors]}


def main(argv: List[str]) -> int:
    if not argv:
        print(json.dumps({"ok": False,
                          "errors": ["usage: python -m "
                                     "glint_word2vec_tpu.obs.schema "
                                     "FILE.jsonl [...]"]}))
        return 2
    results = [validate_file(p) for p in argv]
    ok = all(r["ok"] for r in results) and all(
        r["records"] > 0 for r in results)
    print(json.dumps({"ok": ok, "schema": SCHEMA_VERSION, "files": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
