"""R3 bad: host-sync ops inside a jit-wrapped function."""
import time

import jax
import numpy as np


def step(params, batch):
    scale = float(batch.mean())          # concretizes a tracer
    t0 = time.perf_counter()             # trace-time constant
    host = np.asarray(params)            # device->host copy
    return params * scale + host.sum() + t0


step_fn = jax.jit(step)
