"""Fleet timeline collector: N per-process sinks → one causal story.

PRs 10-12 made the system a fleet — a router, N replica subprocesses, a
trainer/ContinualRunner publishing checkpoints — and each process writes its
own telemetry JSONL (plus, on death, a ``.blackbox.json`` flight-recorder
dump). A hedged query's journey, a publish rippling through rolling
reloads, or a SIGKILL's blast radius therefore lands scattered across N
uncorrelated files. This module is the merge:

- **clock alignment** — every ``run_start``/``serve_start``/``fleet_start``
  a tracing-era writer emits carries a clock ANCHOR (one simultaneous
  ``wall_ns``/``mono_ns`` reading, obs/trace.clock_anchor). Spans record
  process-local monotonic time (immune to NTP steps mid-run); the collector
  places one on the fleet's wall timeline as ``anchor.wall_ns +
  (span.mono_ns - anchor.mono_ns)``. Records without a monotonic stamp
  (breaker transitions, publishes, reloads) use their wall-clock ``t``.
  Files may be arbitrarily out of order internally and skewed against each
  other in monotonic base — the merge sorts on aligned wall time.
- **trace reassembly** — ``trace_span`` records group by ``trace_id`` into
  one causal tree per client query: the router's ``fleet_query`` root, its
  per-replica ``attempt`` children (outcome-labeled: a hedge loser is
  ``abandoned``, never ``failed``), and the replica-side
  ``queue_wait``/``batch_service``/``ann_probe`` children that crossed the
  wire under the attempt's span id.
- **publish chains** — ``publish`` records (trainer/ContinualRunner) join
  ``serve_start``/``serve_reload``/``fleet_reload`` records by the shared
  ``publish_sig`` string (serve/reload.publish_signature_str): save →
  watcher detect → per-replica drain+reload reads as one chain.
- **SLO recompute** — the availability/latency objectives (obs/slo.py) are
  recomputed OFFLINE over the merged ``fleet_query`` roots with the same
  :func:`~glint_word2vec_tpu.obs.slo.burn_rates_from_samples` math the live
  router uses — one math, two surfaces; ``tools/obs_collect.py --gate``
  fails CI when any burn window exceeds 1.0.
- **exports** — a multi-track Perfetto/Chrome trace (one pid per process,
  one row per span kind, instant markers for breaker flips / publishes /
  reloads / blackbox causes) and a one-line summary JSON with slowest-K
  per-query exemplars carrying their full span breakdown.

Everything here is offline and stdlib-only — the collector reads artifacts
a dead fleet left behind; it must not import the serving stack it
diagnoses.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from glint_word2vec_tpu.obs.slo import (
    SloObjectives,
    burn_rates_from_samples,
    slowest_k,
)

# span outcomes that mean "the CALLER got no answer" for the offline
# availability SLI (obs/slo.py: shed and deadline-exhaustion are BAD;
# abandoned hedge losers and per-attempt failures are attempt-level churn,
# visible on the trace but invisible to the caller-facing SLO)
_BAD_ROOT_OUTCOMES = ("failed", "shed")


def scan_artifacts(paths: Iterable[str]) -> List[str]:
    """Expand directories into the artifact files the fleet leaves behind:
    ``*.jsonl`` sinks, their rotated ``*.jsonl.N`` segments, and
    ``*.blackbox.json`` dumps. Files pass through untouched; order is
    deterministic (sorted per directory)."""
    out: List[str] = []
    for p in paths:
        if not os.path.isdir(p):
            out.append(p)
            continue
        for name in sorted(os.listdir(p)):
            full = os.path.join(p, name)
            if not os.path.isfile(full):
                continue
            stem, ext = os.path.splitext(name)
            if ext == ".jsonl" or name.endswith(".blackbox.json") or (
                    ext.lstrip(".").isdigit() and stem.endswith(".jsonl")):
                out.append(full)
    return out


def _read_jsonl(path: str) -> Tuple[List[dict], int]:
    """Parsed records + count of unparseable lines (a truncated tail —
    exactly what a SIGKILL leaves — must not sink the merge)."""
    recs: List[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad += 1
                continue
            if isinstance(rec, dict):
                recs.append(rec)
            else:
                bad += 1
    return recs, bad


def _group_files(files: List[str]) -> Dict[str, dict]:
    """Group artifact files per PROCESS log: rotated segments
    (``x.jsonl.1``...) and the blackbox dump (``x.jsonl.blackbox.json``)
    attach to their base ``x.jsonl``. Returns base-path → {"segments":
    [oldest..newest], "blackbox": path|None}."""
    groups: Dict[str, dict] = {}

    def grp(base: str) -> dict:
        return groups.setdefault(base, {"segments": [], "blackbox": None})

    rotated: List[Tuple[str, int]] = []
    for f in files:
        if f.endswith(".blackbox.json"):
            grp(f[: -len(".blackbox.json")])["blackbox"] = f
        elif f.endswith(".jsonl"):
            grp(f)  # ensure the group exists even for an empty sink
        else:
            stem, ext = os.path.splitext(f)
            if ext.lstrip(".").isdigit() and stem.endswith(".jsonl"):
                rotated.append((stem, int(ext.lstrip("."))))
                # a process killed between rotate and the lazy reopen leaves
                # ONLY .jsonl.N segments — the group must still exist
                grp(stem)
            else:
                grp(f)  # unknown extension: treat as a standalone JSONL
    for base in groups:
        segs = sorted((n for s, n in rotated if s == base), reverse=True)
        # oldest rotated segment first (.3, .2, .1), the live file last
        groups[base]["segments"] = [f"{base}.{n}" for n in segs] + (
            [base] if os.path.exists(base) or not segs else [])
    return groups


class ProcessLog:
    """One process's telemetry: its records (rotated segments folded in,
    oldest first) each stamped with its fleet-wall-timeline position, its
    track label, and its blackbox dump when the process died with one.

    Anchoring is EPOCHED, not per-file: a restarted replica appends to the
    same sink path with a fresh monotonic base, announcing itself with a
    new ``serve_start`` anchor — so each record's monotonic stamp is
    aligned through the most recent anchor ABOVE it in file order (records
    within one file are append-ordered by the process that wrote them,
    even when their monotonic values jump backwards across a restart). A
    span with a monotonic stamp but no anchor yet gets None (unanchored
    monotonic time is process-relative garbage); anchorless records fall
    back to their wall-clock ``t``."""

    def __init__(self, base: str, segments: List[str],
                 blackbox_path: Optional[str]):
        self.path = base
        self.records: List[dict] = []
        self.walls: List[Optional[int]] = []
        self.bad_lines = 0
        anchor: Optional[Tuple[int, int]] = None
        for seg in segments:
            try:
                recs, bad = _read_jsonl(seg)
            except OSError:
                continue
            self.bad_lines += bad
            for rec in recs:
                if isinstance(rec.get("wall_ns"), int) and isinstance(
                        rec.get("mono_ns"), int):
                    anchor = (rec["wall_ns"], rec["mono_ns"])
                self.records.append(rec)
                self.walls.append(_wall_ns(rec, anchor))
        self.blackbox: Optional[dict] = None
        if blackbox_path is not None:
            try:
                with open(blackbox_path, "r", encoding="utf-8") as f:
                    self.blackbox = json.load(f)
            except (OSError, json.JSONDecodeError):
                self.bad_lines += 1
        # track label: the first record naming its process, else file stem
        self.process = next(
            (r["process"] for r in self.records
             if isinstance(r.get("process"), str)),
            os.path.splitext(os.path.basename(base))[0])


def _wall_ns(rec: dict, anchor: Optional[Tuple[int, int]]) -> Optional[int]:
    mono = rec.get("mono_ns")
    if isinstance(mono, int):
        if anchor is None:
            return None
        aw, am = anchor
        return aw + (mono - am)
    t = rec.get("t")
    return int(t * 1e9) if isinstance(t, (int, float)) else None


def load_process_logs(paths: Iterable[str]) -> List[ProcessLog]:
    groups = _group_files(scan_artifacts(paths))
    logs = [ProcessLog(base, g["segments"], g["blackbox"])
            for base, g in sorted(groups.items())]
    return [pl for pl in logs if pl.records or pl.blackbox]


# ---------------------------------------------------------------------------
# the merge
# ---------------------------------------------------------------------------


def build_timeline(logs: List[ProcessLog]) -> dict:
    """Merge N process logs into the fleet timeline dict every consumer
    (summary, Perfetto export, gate, drill assertions) reads:

    - ``events``: every non-span record, wall-ordered, each stamped with
      ``_process`` and ``_wall_ns``;
    - ``traces``: trace_id → {"root": span|None, "spans": [all spans,
      wall-ordered], "dur_ns", "outcome", "op"};
    - ``publish_chains``: publish_sig → wall-ordered correlated records;
    - ``blackboxes``: per dead process, the dump's cause + counts.
    """
    events: List[dict] = []
    spans_by_trace: Dict[str, List[dict]] = {}
    for pl in logs:
        for rec, w in zip(pl.records, pl.walls):
            entry = dict(rec, _process=pl.process, _wall_ns=w)
            if rec.get("kind") == "trace_span":
                if w is not None:
                    spans_by_trace.setdefault(
                        rec.get("trace_id", "?"), []).append(entry)
            elif w is not None:
                events.append(entry)
    events.sort(key=lambda r: r["_wall_ns"])

    traces: Dict[str, dict] = {}
    for tid, spans in spans_by_trace.items():
        spans.sort(key=lambda s: s["_wall_ns"])
        root = next((s for s in spans if s.get("name") == "fleet_query"),
                    None)
        traces[tid] = {
            "root": root,
            "spans": spans,
            "dur_ns": (root or {}).get("dur_ns"),
            "outcome": (root or {}).get("outcome"),
            "op": (root or {}).get("op"),
        }

    chains: Dict[str, List[dict]] = {}
    for ev in events:
        sig = ev.get("publish_sig")
        if isinstance(sig, str) and ev.get("kind") in (
                "publish", "serve_start", "serve_reload", "fleet_reload"):
            chains.setdefault(sig, []).append(ev)

    blackboxes = [
        {"process": pl.process, "path": f"{pl.path}.blackbox.json",
         "cause": (pl.blackbox.get("cause") or {}),
         "events": len(pl.blackbox.get("events") or []),
         "dispatches": len(pl.blackbox.get("dispatches") or [])}
        for pl in logs if pl.blackbox is not None]

    return {"events": events, "traces": traces, "publish_chains": chains,
            "blackboxes": blackboxes,
            "processes": sorted({pl.process for pl in logs}),
            "bad_lines": sum(pl.bad_lines for pl in logs)}


# ---------------------------------------------------------------------------
# offline SLO recompute (one math with the live tracker: obs/slo.py)
# ---------------------------------------------------------------------------


def recompute_slo(timeline: dict,
                  objectives: Optional[SloObjectives] = None) -> dict:
    """The availability + latency SLO over the merged ``fleet_query`` roots
    — the same burn math the live router computes, re-derived from the
    artifacts alone so an incident review needs no surviving process.
    ``now`` is the last root's wall time: burn windows are anchored to the
    END of the storm, which is what "was the budget intact when it ended"
    means."""
    obj = objectives or SloObjectives()
    roots = [t for t in timeline["traces"].values()
             if t["root"] is not None]
    samples = sorted(
        (t["root"]["_wall_ns"] / 1e9,
         t["outcome"] not in _BAD_ROOT_OUTCOMES,
         t["outcome"] not in _BAD_ROOT_OUTCOMES
         and t["dur_ns"] is not None
         and t["dur_ns"] / 1e6 <= obj.latency_ms)
        for t in roots)
    if not samples:
        return {"samples": 0, "availability": None, "within_budget": True,
                "objective_availability": obj.availability}
    now = samples[-1][0]
    windows = (("short", obj.short_window_s), ("long", obj.long_window_s))
    avail = burn_rates_from_samples(
        [(t, ok) for t, ok, _ in samples], now, obj.availability, windows)
    lat = burn_rates_from_samples(
        [(t, within) for t, ok, within in samples if ok], now,
        obj.latency_target, windows)
    bad = sum(1 for _, ok, _ in samples if not ok)
    burns = [w["burn_rate"] for b in (avail, lat) for w in b.values()
             if w["burn_rate"] is not None]
    return {
        "samples": len(samples),
        "bad": bad,
        "availability": round(1.0 - bad / len(samples), 6),
        "objective_availability": obj.availability,
        "objective_latency_ms": obj.latency_ms,
        "availability_burn": avail,
        "latency_burn": lat,
        "within_budget": all(b <= 1.0 for b in burns),
    }


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------

# non-span record kinds worth an instant marker on the Perfetto timeline
_MARKER_KINDS = ("fleet_breaker", "publish", "serve_reload", "fleet_reload",
                 "serve_start", "fleet_start", "run_start", "watchdog",
                 "recovery", "fleet_slo")


def _marker_name(ev: dict) -> str:
    k = ev["kind"]
    if k == "fleet_breaker":
        return (f"breaker {ev.get('replica', '?')} "
                f"{ev.get('from_state', '?')}->{ev.get('to_state', '?')}")
    if k == "publish":
        return f"publish sig={ev.get('publish_sig', '?')[:16]}"
    if k in ("serve_reload", "fleet_reload"):
        return f"{k} sig={str(ev.get('publish_sig', '?'))[:16]}"
    return k


def export_perfetto(timeline: dict, path: str) -> int:
    """Write the merged timeline as a Chrome-trace/Perfetto JSON: one pid
    per PROCESS (named tracks), one tid row per span kind, ``X`` duration
    events for spans (args carry trace_id/outcome/replica so Perfetto's
    search finds a query end-to-end), instant events for state transitions,
    and one instant per blackbox cause. Returns the event count. Timestamps
    are microseconds relative to the earliest record (Chrome-trace
    convention; absolute ns wall time rides in args)."""
    all_ns = [s["_wall_ns"] for t in timeline["traces"].values()
              for s in t["spans"]]
    all_ns += [e["_wall_ns"] for e in timeline["events"]]
    if not all_ns:
        t0 = 0
    else:
        t0 = min(all_ns)
    pid_of = {p: i for i, p in enumerate(timeline["processes"])}
    events: List[dict] = [
        {"ph": "M", "name": "process_name", "pid": pid,
         "args": {"name": proc}} for proc, pid in pid_of.items()]
    tid_of: Dict[Tuple[str, str], int] = {}
    tids_used: Dict[int, Dict[int, str]] = {}

    def tid(proc: str, row: str) -> int:
        key = (proc, row)
        if key not in tid_of:
            per = tids_used.setdefault(pid_of.get(proc, 0), {})
            tid_of[key] = len(per)
            per[len(per)] = row
        return tid_of[key]

    for t in timeline["traces"].values():
        for s in t["spans"]:
            proc = s["_process"]
            args = {k: s[k] for k in ("trace_id", "span", "parent",
                                      "replica", "outcome", "op")
                    if k in s}
            args["wall_ns"] = s["_wall_ns"]
            events.append({
                "ph": "X", "name": s.get("name", "span"),
                "pid": pid_of.get(proc, 0),
                "tid": tid(proc, s.get("name", "span")),
                "ts": round((s["_wall_ns"] - t0) / 1e3, 3),
                "dur": round(s.get("dur_ns", 0) / 1e3, 3),
                "args": args})
    for ev in timeline["events"]:
        if ev["kind"] not in _MARKER_KINDS:
            continue
        proc = ev["_process"]
        events.append({
            "ph": "i", "s": "p", "name": _marker_name(ev),
            "pid": pid_of.get(proc, 0), "tid": tid(proc, "events"),
            "ts": round((ev["_wall_ns"] - t0) / 1e3, 3),
            "args": {k: v for k, v in ev.items()
                     if not k.startswith("_") and k not in ("schema",)}})
    for bb in timeline["blackboxes"]:
        events.append({
            "ph": "i", "s": "g",
            "name": f"blackbox {bb['process']}: "
                    f"{bb['cause'].get('kind', '?')}",
            "pid": pid_of.get(bb["process"], 0),
            "tid": tid(bb["process"], "events"),
            # the dump has no aligned stamp of its own; park it at the end
            "ts": round((max(all_ns) - t0) / 1e3, 3) if all_ns else 0,
            "args": bb["cause"]})
    events += [{"ph": "M", "name": "thread_name", "pid": pid,
                "tid": small, "args": {"name": row}}
               for pid, rows in tids_used.items()
               for small, row in rows.items()]
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"processes": timeline["processes"],
                         "t0_wall_ns": t0}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return len(events)


def _span_brief(s: dict, root_ns: Optional[int]) -> dict:
    return {
        "name": s.get("name"), "process": s["_process"],
        "offset_ms": (round((s["_wall_ns"] - root_ns) / 1e6, 3)
                      if root_ns is not None else None),
        "dur_ms": round(s.get("dur_ns", 0) / 1e6, 3),
        **{k: s[k] for k in ("replica", "outcome", "op") if k in s},
    }


def summarize(timeline: dict, slo: dict, k: int = 5) -> dict:
    """The collector's one-line report: counts, attempt-outcome census,
    breaker transitions, publish chains, the slowest-K exemplar traces
    with their full cross-process span breakdown, and the offline SLO."""
    outcomes: Dict[str, int] = {}
    n_spans = 0
    for t in timeline["traces"].values():
        for s in t["spans"]:
            n_spans += 1
            if s.get("name") == "attempt":
                oc = s.get("outcome", "?")
                outcomes[oc] = outcomes.get(oc, 0) + 1
    slowest = slowest_k(
        [(t["dur_ns"], t) for t in timeline["traces"].values()
         if t["dur_ns"] is not None and t["root"] is not None], k)
    exemplars = [{
        "trace_id": t["root"].get("trace_id"),
        "op": t["op"], "outcome": t["outcome"],
        "dur_ms": round(t["dur_ns"] / 1e6, 3),
        "spans": [_span_brief(s, t["root"]["_wall_ns"])
                  for s in t["spans"]],
    } for t in slowest]
    breakers = [
        {"t_ms": round((ev["_wall_ns"]) / 1e6, 1),
         "process": ev["_process"], "replica": ev.get("replica"),
         "transition": f"{ev.get('from_state')}->{ev.get('to_state')}"}
        for ev in timeline["events"] if ev["kind"] == "fleet_breaker"]
    chains = {
        sig: [{"kind": ev["kind"], "process": ev["_process"],
               "t_ms": round(ev["_wall_ns"] / 1e6, 1)} for ev in evs]
        for sig, evs in timeline["publish_chains"].items()}
    return {
        "processes": timeline["processes"],
        "records": len(timeline["events"]) + n_spans,
        "bad_lines": timeline["bad_lines"],
        "traces": len(timeline["traces"]),
        "spans": n_spans,
        "attempt_outcomes": outcomes,
        "breaker_transitions": breakers[:64],
        "publish_chains": chains,
        "slowest": exemplars,
        "blackboxes": [{"process": b["process"],
                        "cause": b["cause"].get("kind", "?")}
                       for b in timeline["blackboxes"]],
        "slo": slo,
    }


def collect(paths: Iterable[str],
            objectives: Optional[SloObjectives] = None,
            slowest: int = 5) -> Tuple[dict, dict]:
    """The whole pipeline: artifacts → (timeline, summary). The timeline is
    the rich in-memory form (drill assertions read it); the summary is the
    JSON-safe report."""
    timeline = build_timeline(load_process_logs(paths))
    slo = recompute_slo(timeline, objectives)
    return timeline, summarize(timeline, slo, k=slowest)
