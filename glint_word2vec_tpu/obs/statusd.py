"""Live run inspection: a read-only HTTP status endpoint for one trainer.

``config.status_port > 0`` starts this server for the duration of a fit
(trainer._start_run_bookkeeping → trainer._end_run), so an operator can ask
a live remote trainer what it is doing — global step, pairs/s, effective
lr, norm channels, recoveries, per-phase time histograms — without
attaching a debugger or waiting for the run log to flush. Off by default
and ZERO-cost when off: no thread is created and no socket is bound
(tested in tests/test_statusd.py).

Routes (GET only — the server mutates nothing):

- ``/`` or ``/status.json`` — the full gauge snapshot as JSON (the same
  dict ``Trainer.status_snapshot()`` returns);
- ``/metrics`` — the scalar gauges in Prometheus text exposition format
  (``glint_*`` names, docs/observability.md has the table);
- ``/healthz`` — ``200 ok`` (liveness for scrapers).

Design constraints:

- read-only and single-threaded: one ``HTTPServer`` served from one daemon
  thread (graftlint R1 documented owner — it only READS trainer state, so
  the worker-count determinism contract is untouched); requests are
  answered serially, which is exactly right for a human + one scraper;
- snapshots are built by the serving thread from a callable the trainer
  provides; the callable reads plain Python attributes and bounded rings
  (GIL-consistent) — it never touches device state, so a scrape can never
  interleave a collective into the dispatch pipeline;
- binds ``127.0.0.1`` only: the endpoint is an operator tool, not a
  service — remote scraping goes through a tunnel or a real exporter.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Callable, Optional

logger = logging.getLogger("glint_word2vec_tpu")


def _gauge(lines: list, name: str, value, labels: str = "",
           seen: Optional[set] = None) -> None:
    """Append one gauge sample (``# TYPE`` + sample line) to ``lines`` —
    the shared rendering rule of every exposition surface (trainer
    ``glint_*``, serving ``glint_serve_*``, fleet); None skips, bools
    render as 0/1. ``seen``: emit the ``# TYPE`` header only on a metric
    name's FIRST sample — the text format forbids a second TYPE line for
    the same name, and label-fanned surfaces (the fleet's per-replica
    loop) emit many samples per metric."""
    if value is None or isinstance(value, bool):
        value = float(bool(value)) if isinstance(value, bool) else None
    if value is None:
        return
    if seen is None or name not in seen:
        lines.append(f"# TYPE {name} gauge")
        if seen is not None:
            seen.add(name)
    lines.append(f"{name}{labels} {float(value):g}")


def prometheus_text(snap: dict) -> str:
    """Render a status snapshot's scalar gauges in Prometheus text format.

    Names/labels (stable contract, docs/observability.md): scalar fields
    become ``glint_<field>``; per-matrix norm channels become
    ``glint_norm_<channel>{matrix="syn0"|"syn1"}``; per-phase rollups become
    ``glint_phase_seconds_total{phase=...}`` / ``glint_phase_count{phase=...}``.
    """
    lines: list = []

    def gauge(name: str, value, labels: str = "") -> None:
        _gauge(lines, name, value, labels)

    for field in ("global_step", "words", "pairs_trained", "pairs_per_sec",
                  "alpha", "lr_scale", "recoveries", "rollbacks",
                  "watchdog_fires", "heartbeats", "host_wait_s_total",
                  "dispatch_s_total"):
        gauge(f"glint_{field}", snap.get(field))
    gauge("glint_running", 1.0 if snap.get("status") == "running" else 0.0)
    norms = snap.get("norms") or {}
    for matrix in ("syn0", "syn1"):
        ch = norms.get(matrix) or {}
        for channel in ("max_norm", "mean_norm", "p99_norm", "frac_over"):
            if channel in ch:
                gauge(f"glint_norm_{channel}", ch[channel],
                      f'{{matrix="{matrix}"}}')
    for phase, ph in (snap.get("phases") or {}).items():
        gauge("glint_phase_seconds_total", ph.get("total_s"),
              f'{{phase="{phase}"}}')
        gauge("glint_phase_count", ph.get("count"), f'{{phase="{phase}"}}')
        gauge("glint_phase_p99_seconds", ph.get("p99_s"),
              f'{{phase="{phase}"}}')
    return "\n".join(lines) + "\n"


def serve_prometheus_text(snap: dict) -> str:
    """Render a SERVING snapshot (serve.EmbeddingService.status_snapshot) in
    Prometheus text format — the ``glint_serve_*`` names (stable contract,
    docs/serving.md): batcher counters/gauges, latency quantiles over the
    recent ring, hot-reload counts, and the live index's measured recall."""
    lines: list = []

    def gauge(name: str, value, labels: str = "") -> None:
        _gauge(lines, name, value, labels)

    gauge("glint_serve_up", 1.0 if snap.get("status") == "serving" else 0.0)
    for field in ("submitted", "refused", "completed", "errors", "batches",
                  "reloads", "models_released"):
        gauge(f"glint_serve_{field}_total", snap.get(field))
    for field in ("queue_depth", "occupancy_mean", "vocab_size",
                  "load_seconds"):
        gauge(f"glint_serve_{field}", snap.get(field))
    lat = snap.get("latency_ms") or {}
    for q in ("p50", "p95", "p99"):
        if q in lat:
            gauge("glint_serve_latency_ms", lat[q], f'{{quantile="{q}"}}')
    ann = snap.get("ann") or {}
    for field in ("recall_at_10", "nprobe", "centroids", "build_seconds",
                  "bytes_per_vector"):
        if field in ann:
            gauge(f"glint_serve_ann_{field}", ann[field])
    # index footprint (ISSUE 18): bytes the live index OWNS — the capacity-
    # planning gauge for the quantized arms (docs/serving.md §6)
    if "index_bytes" in ann:
        gauge("glint_serve_index_bytes", ann["index_bytes"])
    return "\n".join(lines) + "\n"


# breaker state as an ordered gauge: closed is healthy, open is worst —
# dashboards alert on max() over replicas
_BREAKER_GAUGE = {"closed": 0, "half-open": 1, "open": 2}


def fleet_prometheus_text(snap: dict) -> str:
    """Render a FLEET snapshot (serve.fleet.FleetRouter.status_snapshot) in
    Prometheus text format: the fleet-level ``glint_serve_fleet_*`` gauges
    plus each replica's own ``glint_serve_*`` gauges AGGREGATED fleet-wide
    under a ``replica`` label (stable contract, docs/serving.md §5) — one
    scrape of the router sees the whole fleet."""
    lines: list = []
    seen: set = set()

    def gauge(name: str, value, labels: str = "") -> None:
        _gauge(lines, name, value, labels, seen=seen)

    gauge("glint_serve_fleet_up",
          1.0 if snap.get("status") == "serving" else 0.0)
    for field in ("queries", "failures", "retries", "hedges", "hedge_wins",
                  "shed_single", "shed_bulk", "reload_rounds"):
        gauge(f"glint_serve_fleet_{field}_total", snap.get(field))
    for field in ("healthy", "degraded", "min_serving_during_reloads"):
        gauge(f"glint_serve_fleet_{field}", snap.get(field))
    lat = snap.get("latency_ms") or {}
    for q in ("p50", "p95", "p99"):
        if q in lat:
            gauge("glint_serve_fleet_latency_ms", lat[q],
                  f'{{quantile="{q}"}}')
    # the SLO gauge block (obs/slo.py owns the names — one renderer, two
    # surfaces: live scrape here, offline recompute in tools/obs_collect.py)
    from glint_word2vec_tpu.obs.slo import slo_gauge_lines
    slo_gauge_lines(gauge, snap.get("slo") or {})
    fleet_index_bytes = 0
    fleet_index_replicas = 0
    for name, rep in (snap.get("replicas") or {}).items():
        lab = f'{{replica="{name}"}}'
        gauge("glint_serve_fleet_breaker_state",
              _BREAKER_GAUGE.get(rep.get("state")), lab)
        gauge("glint_serve_up", rep.get("alive"), lab)
        gauge("glint_serve_fleet_degraded_replica", rep.get("degraded"), lab)
        gauge("glint_serve_fleet_in_flight", rep.get("in_flight"), lab)
        gauge("glint_serve_fleet_restarts_total", rep.get("restarts"), lab)
        gauge("glint_serve_fleet_reloads_total", rep.get("reloads"), lab)
        # the replica's own service gauges, relabeled fleet-wide (from the
        # prober's cached stats op — absent while a replica is down)
        stats = rep.get("stats") or {}
        for field in ("submitted", "refused", "completed", "errors",
                      "batches", "reloads", "models_released"):
            gauge(f"glint_serve_{field}_total", stats.get(field), lab)
        for field in ("queue_depth", "occupancy_mean", "vocab_size",
                      "load_seconds"):
            gauge(f"glint_serve_{field}", stats.get(field), lab)
        slat = stats.get("latency_ms") or {}
        for q in ("p50", "p95", "p99"):
            if q in slat:
                gauge("glint_serve_latency_ms", slat[q],
                      f'{{replica="{name}",quantile="{q}"}}')
        ann = stats.get("ann") or {}
        for field in ("recall_at_10", "nprobe", "centroids",
                      "bytes_per_vector"):
            if field in ann:
                gauge(f"glint_serve_ann_{field}", ann[field], lab)
        if "index_bytes" in ann:
            gauge("glint_serve_index_bytes", ann["index_bytes"], lab)
            fleet_index_bytes += ann["index_bytes"]
            fleet_index_replicas += 1
    # fleet-wide index footprint: the sum over replicas that reported one
    # (every replica holds its own copy — the number capacity planning
    # actually pays; docs/serving.md §6)
    if fleet_index_replicas:
        gauge("glint_serve_fleet_index_bytes", fleet_index_bytes)
    return "\n".join(lines) + "\n"


def supervisor_prometheus_text(snap: dict) -> str:
    """Render a SUPERVISOR snapshot (train.supervisor.TrainingSupervisor
    .status_snapshot) in Prometheus text format — the
    ``glint_supervisor_*`` names (stable contract, docs/robustness.md
    §supervisor): restart/stall/preempt counters, the escalation-ladder
    stage, the quarantine latch, and the child gang's last observed step."""
    lines: list = []

    def gauge(name: str, value, labels: str = "") -> None:
        _gauge(lines, name, value, labels)

    gauge("glint_supervisor_up", snap.get("up"))
    for field in ("attempts", "restarts", "stalls", "preempts"):
        gauge(f"glint_supervisor_{field}_total", snap.get(field))
    for field in ("ladder_stage", "quarantined", "last_step", "child_up"):
        gauge(f"glint_supervisor_{field}", snap.get(field))
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # set per-server via the factory in StatusServer.start
    snapshot_fn: Callable[[], dict]
    metrics_fn: Callable[[dict], str]

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 — http.server contract
        path = self.path.split("?", 1)[0]
        try:
            if path in ("/", "/status.json"):
                body = json.dumps(self.snapshot_fn()).encode()
                self._send(200, body, "application/json")
            elif path == "/metrics":
                body = self.metrics_fn(self.snapshot_fn()).encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._send(200, b"ok\n", "text/plain")
            else:
                self._send(404, b"not found\n", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-response — nothing to do

    def log_message(self, fmt: str, *args) -> None:
        logger.debug("statusd: %s", fmt % args)


class StatusServer:
    """One localhost HTTP server serving a snapshot callable, read-only."""

    def __init__(self, port: int, snapshot_fn: Callable[[], dict],
                 metrics_fn: Optional[Callable[[dict], str]] = None):
        self._requested_port = int(port)
        self._snapshot_fn = snapshot_fn
        # /metrics renderer: the trainer gauges by default; the serving
        # tier passes serve_prometheus_text (glint_serve_* names)
        self._metrics_fn = metrics_fn or prometheus_text
        self._server: Optional[HTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        """The actually bound port (== requested unless requested was 0 —
        tests use 0 for an ephemeral port; config refuses 0 as 'on')."""
        return self._server.server_address[1] if self._server else 0

    def start(self) -> "StatusServer":
        handler = type("_BoundHandler", (_Handler,),
                       {"snapshot_fn": staticmethod(self._snapshot_fn),
                        "metrics_fn": staticmethod(self._metrics_fn)})
        self._server = HTTPServer(("127.0.0.1", self._requested_port), handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="glint-statusd",
            daemon=True)
        self._thread.start()
        logger.info("statusd listening on 127.0.0.1:%d "
                    "(/status.json, /metrics, /healthz)", self.port)
        return self

    def stop(self) -> int:
        """Returns the number of leaked threads (0/1)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        leaked = 0
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
            if t.is_alive():
                leaked = 1
                logger.warning("statusd server thread leaked (join timeout)")
        return leaked
