"""Fixture lock registry — good twin: every entry has a live construction
site whose path:qualname matches, and ranks strictly increase along the one
nesting in the tree."""
import threading

LOCK_TABLE = {
    "outer": {"rank": 10, "kind": "lock",
              "site": "glint_word2vec_tpu/pipe.py:Pipe.__init__",
              "owner": "fixture pipe"},
    "inner": {"rank": 20, "kind": "lock",
              "site": "glint_word2vec_tpu/pipe.py:Pipe.__init__",
              "owner": "fixture pipe"},
}


def make_lock(name):
    return threading.Lock()
