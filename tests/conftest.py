"""Test configuration: force an 8-device virtual CPU mesh before JAX backends initialize.

Tests exercise the multi-chip sharding path the same way the reference exercises
"multi-node" behavior inside a single Docker container (build.sbt:48-77): by faking the
topology — here with XLA's host-platform device-count flag instead of Docker.

Note: the session image registers a remote-TPU PJRT plugin in sitecustomize and pins
``jax_platforms`` programmatically, so setting JAX_PLATFORMS alone is not enough — we also
update the jax config after import (backends are still uninitialized at conftest time).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

REFERENCE_CORPUS = "/root/reference/de_wikipedia_articles_country_capitals.txt"


@pytest.fixture(scope="session")
def toy_corpus_path():
    if not os.path.exists(REFERENCE_CORPUS):
        pytest.skip("reference toy corpus not available")
    return REFERENCE_CORPUS
