"""Local-SGD data parallelism (config.sync_every — ISSUE 17,
docs/sharding.md §Local-SGD).

Five layers, each pinned where it can actually break:

1. DEFAULT IDENTITY — ``sync_every=1`` is byte-for-byte the pre-knob
   shard_map step at every mesh shape: the knob's existence cannot perturb
   the synchronous path.
2. ORACLE — the k-step owner-local window + delta merge against a NumPy
   float64 oracle that replays k steps PER DATA SHARD on that shard's
   disjoint batch/pool slices and then merges the per-shard deltas
   (merged = start + mean(local − start)), stabilizers off and on. The
   mean is exact at the power-of-2 shard counts this repo ships, so the
   bound is ~1e-11, not "close".
3. DEGENERATION — at nd=1 (no data axis) the window is bit-identical to
   running the synchronous step k times: the merge degenerates to identity
   and the owner-local schedule IS the synchronous schedule.
4. DETERMINISM — merged training runs are bit-identical per
   (seed, mesh, sync_every): the disjoint per-shard sample lattices + the
   replica-consistent merge leave nothing order-dependent.
5. REFUSALS — the config selection matrix refuses every combination the
   window has no form for (GSPMD lowering, device_pairgen, a sync_every
   that does not divide steps_per_dispatch), with messages naming the knob.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair, Stabilizers, sgns_step_shared_core)
from glint_word2vec_tpu.ops.sgns_shard import make_shard_map_sgns_step
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train.trainer import Trainer

# the stabilized shared-pool NumPy oracle both repos' step tests pin against
from test_stabilizers import _np_shared_step

MESHES = [(1, 8), (2, 4), (4, 2), (8, 1)]
NEG = 3


def _inputs(dtype, v=64, d=16, b=32, pool_per_shard=4, k=2, nd=1, seed=0):
    """Window-shaped inputs: batch leaves [k, b], negatives [k, nd·P]
    (each data shard consumes its own disjoint [k, P] slice), alphas [k]."""
    rng = np.random.default_rng(seed)
    params = EmbeddingPair(
        jnp.asarray(rng.standard_normal((v, d)), dtype),
        jnp.asarray(rng.standard_normal((v, d)) * 0.1, dtype))
    batch = {
        "centers": jnp.asarray(rng.integers(0, v, (k, b)), jnp.int32),
        "contexts": jnp.asarray(rng.integers(0, v, (k, b)), jnp.int32),
        "mask": jnp.asarray(rng.random((k, b)) < 0.9, jnp.float32),
    }
    negs = jnp.asarray(
        rng.integers(0, v, (k, nd * pool_per_shard)), jnp.int32)
    alphas = jnp.asarray(np.full(k, 0.025), dtype)
    return params, batch, negs, alphas


@pytest.mark.parametrize("shape", MESHES)
def test_sync_every_one_bit_identity(shape):
    """sync_every=1 returns the existing synchronous step — outputs are
    bit-identical to a factory call that never heard of the knob."""
    plan = make_mesh(*shape)
    params, batch, negs, alphas = _inputs(jnp.float32, k=1)
    sharded = EmbeddingPair(
        jax.device_put(params.syn0, plan.embedding),
        jax.device_put(params.syn1, plan.embedding))
    flat_batch = {kk: vv[0] for kk, vv in batch.items()}
    base = make_shard_map_sgns_step(
        plan.mesh, NEG, "exact", jnp.float32, jnp.float32, True)
    knob = make_shard_map_sgns_step(
        plan.mesh, NEG, "exact", jnp.float32, jnp.float32, True,
        sync_every=1)
    b_out, b_m = jax.jit(base)(sharded, flat_batch, negs[0], alphas[0])
    k_out, k_m = jax.jit(knob)(sharded, flat_batch, negs[0], alphas[0])
    assert np.array_equal(np.asarray(b_out.syn0), np.asarray(k_out.syn0))
    assert np.array_equal(np.asarray(b_out.syn1), np.asarray(k_out.syn1))
    assert float(b_m.loss) == float(k_m.loss)


def _np_window_oracle(params, batch, negs, alphas, nd, k, stab):
    """Replay the window in NumPy float64: each data shard runs k steps on
    its contiguous batch-column slice and disjoint pool slice against its own
    full-view replica, then merged = start + mean over shards of the deltas
    (exact: nd is a power of 2)."""
    syn0 = np.asarray(params.syn0, np.float64)
    syn1 = np.asarray(params.syn1, np.float64)
    b = batch["centers"].shape[1]
    bl = b // nd
    p = negs.shape[1] // nd
    locals_ = []
    for j in range(nd):
        s0, s1 = syn0.copy(), syn1.copy()
        for i in range(k):
            cols = slice(j * bl, (j + 1) * bl)
            s0, s1 = _np_shared_step(
                s0, s1,
                np.asarray(batch["centers"][i, cols]),
                np.asarray(batch["contexts"][i, cols]),
                np.asarray(batch["mask"][i, cols], np.float64),
                np.asarray(negs[i, j * p:(j + 1) * p]),
                float(alphas[i]), NEG, stab)
        locals_.append((s0, s1))
    m0 = syn0 + sum(s0 - syn0 for s0, _ in locals_) / nd
    m1 = syn1 + sum(s1 - syn1 for _, s1 in locals_) / nd
    return m0, m1


@pytest.mark.parametrize("shape", MESHES)
@pytest.mark.parametrize("stab", [
    None,
    Stabilizers(max_row_norm=5.0, update_clip=0.05),
])
def test_window_matches_numpy_oracle_f64(shape, stab):
    """The k-step merged result ≡ the NumPy per-shard replay at f64 ~1e-11,
    every mesh shape, stabilizers off and on (the owner-local clamp pass runs
    on the LOCAL touched set, which is exactly what the per-shard oracle
    replays; the merge preserves the clamp ball by convexity)."""
    from jax.experimental import enable_x64

    nd, nm = shape
    k = 2
    with enable_x64():
        params, batch, negs, alphas = _inputs(
            jnp.float64, k=k, nd=nd, seed=5)
        plan = make_mesh(*shape)
        sharded = EmbeddingPair(
            jax.device_put(params.syn0, plan.embedding),
            jax.device_put(params.syn1, plan.embedding))
        window = make_shard_map_sgns_step(
            plan.mesh, NEG, "exact", jnp.float64, jnp.float64, True,
            stabilizers=stab, sync_every=k)
        got, m = jax.jit(window)(sharded, batch, negs, alphas)
        ref0, ref1 = _np_window_oracle(
            params, batch, negs, alphas, nd, k, stab or Stabilizers())
        # atol 5e-9 for the INDEPENDENT NumPy oracle: XLA's exp differs from
        # libm's in the last ulps (the test_stabilizers oracle documents the
        # same gap at 3e-8 with deliberately blown rows); chaining k steps
        # feeds step 1's ulp drift through step 2's gathers and the merge
        # averages it across shards, landing ~2e-9 here. Any real semantic
        # error — a shard reading another shard's pool slice, a missed merge
        # scale, a stabilizer pass on the wrong touched set — is orders of
        # magnitude larger, and the same-transcendentals replay below pins
        # those at 1e-12.
        np.testing.assert_allclose(
            np.asarray(got.syn0), ref0, rtol=0, atol=5e-9,
            err_msg=f"merged syn0 @ {shape}")
        np.testing.assert_allclose(
            np.asarray(got.syn1), ref1, rtol=0, atol=5e-9,
            err_msg=f"merged syn1 @ {shape}")
        # metrics come back per-step: [k] vectors
        assert np.asarray(m.loss).shape == (k,)
        assert np.asarray(m.pairs).shape == (k,)

        # the ~1e-11-class semantic pin: replay k owner-local steps per
        # shard with the single-device JAX core (same transcendentals, so
        # only SCHEDULE errors can show) and merge in f64 on the host
        if stab is not None:
            return  # the stabilized replay is the NumPy oracle's job above
        b = batch["centers"].shape[1]
        bl, p = b // nd, negs.shape[1] // nd
        start0, start1 = np.asarray(params.syn0), np.asarray(params.syn1)
        d0 = np.zeros_like(start0)
        d1 = np.zeros_like(start1)
        for j in range(nd):
            rp = EmbeddingPair(params.syn0, params.syn1)
            for i in range(k):
                cols = slice(j * bl, (j + 1) * bl)
                rp, _ = sgns_step_shared_core(
                    rp, batch["centers"][i, cols], batch["contexts"][i, cols],
                    batch["mask"][i, cols], negs[i, j * p:(j + 1) * p],
                    alphas[i], NEG, "exact", jnp.float64, False, jnp.float64,
                    True)
            d0 += np.asarray(rp.syn0) - start0
            d1 += np.asarray(rp.syn1) - start1
        np.testing.assert_allclose(
            np.asarray(got.syn0), start0 + d0 / nd, rtol=0, atol=1e-12,
            err_msg=f"replay syn0 @ {shape}")
        np.testing.assert_allclose(
            np.asarray(got.syn1), start1 + d1 / nd, rtol=0, atol=1e-12,
            err_msg=f"replay syn1 @ {shape}")


def test_window_nd1_bit_identical_to_sync_chain():
    """No data axis → the merge is identity and the owner-local schedule IS
    the synchronous schedule: the window equals k chained synchronous steps
    bit-for-bit (f32 — same ops in the same order, not just close)."""
    shape = (1, 8)
    k = 2
    plan = make_mesh(*shape)
    params, batch, negs, alphas = _inputs(jnp.float32, k=k, nd=1, seed=7)
    sharded = EmbeddingPair(
        jax.device_put(params.syn0, plan.embedding),
        jax.device_put(params.syn1, plan.embedding))
    window = make_shard_map_sgns_step(
        plan.mesh, NEG, "exact", jnp.float32, jnp.float32, True,
        sync_every=k)
    w_out, _ = jax.jit(window)(sharded, batch, negs, alphas)
    step = make_shard_map_sgns_step(
        plan.mesh, NEG, "exact", jnp.float32, jnp.float32, True)
    p = sharded
    for i in range(k):
        p, _ = jax.jit(step)(
            p, {kk: vv[i] for kk, vv in batch.items()}, negs[i], alphas[i])
    assert np.array_equal(np.asarray(w_out.syn0), np.asarray(p.syn0))
    assert np.array_equal(np.asarray(w_out.syn1), np.asarray(p.syn1))


def _fit_localsgd(shape, sync_every, seed=11):
    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(40)]
    sents = [[words[j] for j in rng.integers(0, 40, 10)] for _ in range(80)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=64,
                         num_iterations=1, window=2, negatives=NEG,
                         negative_pool=16, steps_per_dispatch=2, seed=seed,
                         step_lowering="shard_map", sync_every=sync_every)
    tr = Trainer(cfg, vocab, plan=make_mesh(*shape))
    tr.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    return np.asarray(tr.params.syn0), np.asarray(tr.params.syn1)


def test_trainer_localsgd_deterministic_and_finite():
    """Merged runs are bit-identical per (seed, mesh, sync_every) — the
    determinism contract docs/sharding.md §Local-SGD documents — and train
    to finite params on a mesh with a real data axis."""
    a0, a1 = _fit_localsgd((2, 4), 2)
    b0, b1 = _fit_localsgd((2, 4), 2)
    assert np.array_equal(a0, b0) and np.array_equal(a1, b1), (
        "local-SGD run is not deterministic per (seed, mesh, k)")
    assert np.all(np.isfinite(a0)) and np.all(np.isfinite(a1))


def test_config_refusals_sync_every():
    base = dict(negative_pool=16, steps_per_dispatch=4)
    with pytest.raises(ValueError, match="sync_every.*shard_map"):
        Word2VecConfig(sync_every=2, **base)          # GSPMD has no window
    with pytest.raises(ValueError, match="sync_every.*positive"):
        Word2VecConfig(sync_every=0, **base)
    with pytest.raises(ValueError, match="sync_every.*packed-pair"):
        Word2VecConfig(sync_every=2, step_lowering="shard_map",
                       device_pairgen=True, **base)
    with pytest.raises(ValueError, match="sync_every.*divide"):
        Word2VecConfig(sync_every=3, step_lowering="shard_map", **base)
    # the valid combination constructs
    cfg = Word2VecConfig(sync_every=2, step_lowering="shard_map", **base)
    assert cfg.sync_every == 2
