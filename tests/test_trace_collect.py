"""Fleet observability plane (ISSUE 13, docs/observability.md §9):

- schema round-trip for the new record kinds (trace_span / publish /
  fleet_slo) and the clock-anchor optional fields;
- the SLO burn math (obs/slo.py): tracker sampling, multi-window burn
  rates, the within-budget gate predicate;
- trace propagation through an in-process ``ReplicaSet.adopt`` fleet:
  router-born context crossing into the replica's batcher and scan spans,
  byte-identical requests when tracing is off, ``trace_sample`` thinning;
- hedge semantics on the timeline: the losing replica of a hedge race is
  ``abandoned``, never ``failed``;
- the collector (obs/collect.py): merge over out-of-order, clock-skewed,
  restart-epoch fixtures; publish chains; offline SLO recompute; Perfetto
  export; slowest-K exemplars;
- run_report's fleet mode (N ``--log`` sinks → per-process + merged).
"""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.obs.collect import (
    build_timeline,
    collect,
    export_perfetto,
    load_process_logs,
    recompute_slo,
)
from glint_word2vec_tpu.obs.schema import (
    SCHEMA_VERSION,
    validate_file,
    validate_record,
)
from glint_word2vec_tpu.obs.sink import TelemetrySink
from glint_word2vec_tpu.obs.slo import (
    SloObjectives,
    SloTracker,
    burn_rates_from_samples,
    flatten_burn,
)
from glint_word2vec_tpu.obs.trace import (
    SpanEmitter,
    clock_anchor,
    new_span_id,
    new_trace_id,
    wire_context,
)
from glint_word2vec_tpu.serve.fleet import FleetRouter, FleetTicket, ReplicaSet
from glint_word2vec_tpu.serve.service import EmbeddingService


def make_model(v=60, d=8, seed=0):
    rng = np.random.default_rng(seed)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    return Word2VecModel(vocab, jnp.asarray(
        rng.standard_normal((v, d)).astype(np.float32)))


# -- schema round-trip ------------------------------------------------------------------


def test_new_kinds_roundtrip_schema_valid(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with TelemetrySink(path) as sink:
        em = SpanEmitter(sink, "router-test")
        tid = new_trace_id()
        root = em.emit(tid, "fleet_query", 1_000, 2_000_000,
                       outcome="ok", op="synonyms")
        em.emit(tid, "attempt", 1_100, 1_500_000, parent=root,
                replica="r0", outcome="failed")
        sink.emit("publish", publish_sig="1-2-3", checkpoint="/ck",
                  step=7, publisher="trainer")
        sink.emit("fleet_slo", objective=0.999, availability=1.0,
                  samples=10, burn_short=0.0, burn_long=None,
                  latency_good_fraction=0.99)
        sink.emit("fleet_start", replicas=3, checkpoint="/ck",
                  process="router-test", **clock_anchor())
    v = validate_file(path)
    assert v["ok"], v["errors"]
    assert v["kinds"] == {"trace_span": 2, "publish": 1, "fleet_slo": 1,
                          "fleet_start": 1}


def test_schema_rejects_bad_span_and_anchor_types():
    base = {"schema": SCHEMA_VERSION, "t": 1.0}
    good = {**base, "kind": "trace_span", "trace_id": "t1", "span": "s1",
            "name": "attempt", "mono_ns": 5, "dur_ns": 2}
    assert validate_record(good) == []
    assert validate_record({**good, "mono_ns": "5"})  # required, wrong type
    assert validate_record({**good, "outcome": 3})    # optional, wrong type
    anchor_bad = {**base, "kind": "run_start", "run_id": "r",
                  "vocab_size": 1, "mesh": [1, 1], "config": {},
                  "wall_ns": 1.5}  # anchor fields are ints, not floats
    assert any("wall_ns" in e for e in validate_record(anchor_bad))


def test_clock_anchor_and_ids():
    a = clock_anchor()
    assert isinstance(a["wall_ns"], int) and isinstance(a["mono_ns"], int)
    assert new_trace_id() != new_trace_id()
    assert new_span_id().startswith("s")
    assert wire_context("t", "s") == {"tid": "t", "ps": "s"}


# -- SLO math ---------------------------------------------------------------------------


def test_slo_objectives_validation():
    with pytest.raises(ValueError, match="availability"):
        SloObjectives(availability=1.0)
    with pytest.raises(ValueError, match="latency_ms"):
        SloObjectives(latency_ms=0)
    with pytest.raises(ValueError, match="windows"):
        SloObjectives(short_window_s=100, long_window_s=10)


def test_burn_rates_window_math():
    # 10 samples over the last 100 s, 2 bad inside the 50 s window, none
    # inside 10 s; objective 0.9 -> budget 0.1
    now = 1000.0
    samples = [(now - 95 + 10 * i, True) for i in range(10)]
    samples[7] = (samples[7][0], False)  # t = 975 -> inside 50 s
    samples[8] = (samples[8][0], False)  # t = 985 -> inside 50 s
    out = burn_rates_from_samples(samples, now, 0.9,
                                  [("w10", 10.0), ("w50", 50.0)])
    assert out["w10"]["samples"] == 1 and out["w10"]["bad"] == 0
    assert out["w10"]["burn_rate"] == 0.0
    assert out["w50"]["samples"] == 5 and out["w50"]["bad"] == 2
    assert out["w50"]["burn_rate"] == pytest.approx(4.0)  # 0.4 / 0.1
    # empty window: burn 0.0 with samples 0 (silence != health, but burns
    # no budget)
    empty = burn_rates_from_samples([], now, 0.9, [("w", 10.0)])
    assert empty["w"] == {"window_s": 10.0, "samples": 0, "bad": 0,
                          "bad_fraction": 0.0, "burn_rate": 0.0}


def test_slo_tracker_within_budget_flip():
    tr = SloTracker(SloObjectives(availability=0.9, latency_ms=100.0,
                                  short_window_s=60, long_window_s=600))
    for _ in range(50):
        tr.note(True, latency_s=0.01)
    snap = tr.snapshot()
    assert snap["availability"] == 1.0
    assert snap["latency_good_fraction"] == 1.0
    assert tr.within_budget(snap)
    for _ in range(20):
        tr.note(False)  # 20/70 bad >> the 10% budget
    snap = tr.snapshot()
    assert not tr.within_budget(snap)
    assert snap["budget_remaining"] < 0  # blown, not just spent
    flat = flatten_burn(snap)
    assert flat["samples"] == 70 and flat["burn_short"] > 1.0


def test_slo_latency_conditioned_on_answered():
    tr = SloTracker(SloObjectives(availability=0.5, latency_ms=100.0,
                                  latency_target=0.5))
    tr.note(True, latency_s=0.01)   # answered fast
    tr.note(True, latency_s=5.0)    # answered slow
    tr.note(False)                  # unanswered: not a latency sample
    snap = tr.snapshot()
    assert snap["latency_good_fraction"] == 0.5  # of the 2 ANSWERED
    assert snap["availability"] == pytest.approx(2 / 3)


# -- fake-replica router tests (trace wire + hedging) -----------------------------------


class FakeReplica:
    """Scripted replica on the fleet client surface (test_fleet.py's
    shape): behavior maps request -> response dict; delay_s resolves late
    so hedges fire."""

    def __init__(self, name, behavior, delay_s=0.0):
        self.name = name
        self.behavior = behavior
        self.delay_s = delay_s
        self.calls = []
        self.restarts = 0
        self._alive = True

    def start(self):
        return self

    def alive(self):
        return self._alive

    @property
    def pid(self):
        return None

    def submit(self, req):
        import threading
        self.calls.append(req)
        t = FleetTicket(len(self.calls))
        resp = self.behavior(req)
        if self.delay_s:
            threading.Timer(self.delay_s, t.resolve, args=(resp,)).start()
        else:
            t.resolve(resp)
        return t

    def wait(self, ticket, timeout):
        if not ticket.done.wait(timeout):
            raise TimeoutError(f"{self.name}: no response")
        return ticket.response

    def abandon(self, ticket):
        pass

    def kill(self):
        self._alive = False

    def close(self):
        self._alive = False


def ok_syn(req):
    if req.get("op") == "stats":
        return {"publish_sig": "sig-1"}
    n = int(req.get("num", 10))
    return {"synonyms": [[f"s{i}", 0.5] for i in range(n)]}


def _spans(path):
    with open(path) as f:
        return [json.loads(line) for line in f
                if json.loads(line).get("kind") == "trace_span"]


def test_untraced_requests_cross_the_wire_byte_identical():
    reps = [FakeReplica("r0", ok_syn), FakeReplica("r1", ok_syn)]
    router = FleetRouter(ReplicaSet(reps, can_respawn=False), probe_s=30.0,
                         hedge_ms=0.0, retry_deadline_s=5.0)
    try:
        router.synonyms("w0", 5)
        syn = [r for r in reps[0].calls + reps[1].calls
               if r.get("op") == "synonyms"]
        assert syn and all("trace" not in r for r in syn), \
            "tracing-off requests must carry no trace context"
    finally:
        router.close(close_replicas=False)


def test_traced_requests_carry_wire_context(tmp_path):
    path = str(tmp_path / "router.jsonl")
    reps = [FakeReplica("r0", ok_syn), FakeReplica("r1", ok_syn)]
    router = FleetRouter(ReplicaSet(reps, can_respawn=False), probe_s=30.0,
                         hedge_ms=0.0, retry_deadline_s=5.0,
                         telemetry_path=path)
    try:
        router.synonyms("w0", 5)
        syn = [r for r in reps[0].calls + reps[1].calls
               if r.get("op") == "synonyms"]
        assert syn and all(
            set(r["trace"]) == {"tid", "ps"} for r in syn)
    finally:
        router.close(close_replicas=False)
    spans = _spans(path)
    root = [s for s in spans if s["name"] == "fleet_query"]
    att = [s for s in spans if s["name"] == "attempt"]
    assert len(root) == 1 and root[0]["outcome"] == "ok"
    assert len(att) == 1 and att[0]["parent"] == root[0]["span"]
    # the wire context's parent span IS the attempt span id
    assert syn[0]["trace"]["ps"] == att[0]["span"]
    assert syn[0]["trace"]["tid"] == root[0]["trace_id"]


def test_trace_sample_thins_traces(tmp_path):
    path = str(tmp_path / "router.jsonl")
    reps = [FakeReplica("r0", ok_syn)]
    router = FleetRouter(ReplicaSet(reps, can_respawn=False), probe_s=30.0,
                         hedge_ms=0.0, retry_deadline_s=5.0,
                         telemetry_path=path, trace_sample=4)
    try:
        for _ in range(8):
            router.synonyms("w0", 5)
    finally:
        router.close(close_replicas=False)
    spans = _spans(path)
    assert len([s for s in spans if s["name"] == "fleet_query"]) == 2
    with pytest.raises(ValueError, match="trace_sample"):
        FleetRouter(ReplicaSet([FakeReplica("r0", ok_syn)],
                               can_respawn=False), trace_sample=0)


def test_hedge_loser_is_abandoned_not_failed(tmp_path):
    path = str(tmp_path / "router.jsonl")
    slow = FakeReplica("r0", ok_syn, delay_s=0.4)
    fast = FakeReplica("r1", ok_syn)
    router = FleetRouter(ReplicaSet([slow, fast], can_respawn=False),
                         probe_s=30.0, hedge_ms=20.0, retry_deadline_s=5.0,
                         telemetry_path=path)
    try:
        router._replicas[1].degraded = True  # force the slow primary
        assert len(router.synonyms("w0", 5)) == 5
        assert router.stats()["hedge_wins"] == 1
    finally:
        router.close(close_replicas=False)
    att = {s["replica"]: s["outcome"] for s in _spans(path)
           if s["name"] == "attempt"}
    # the slow-but-healthy primary lost the race: ABANDONED on the
    # timeline — "failed" would read as a sick replica in every review
    assert att == {"r0": "abandoned", "r1": "win"}


def test_hedge_target_dead_at_submit_gets_failed_span(tmp_path):
    path = str(tmp_path / "router.jsonl")

    def dead_at_submit(req):
        from glint_word2vec_tpu.serve.fleet import ReplicaError
        if req.get("op") == "synonyms":
            raise ReplicaError("dead at submit")
        return {"publish_sig": "sig-1"}

    slow = FakeReplica("r0", ok_syn, delay_s=0.4)
    dead = FakeReplica("r1", dead_at_submit)
    router = FleetRouter(ReplicaSet([slow, dead], can_respawn=False),
                         probe_s=30.0, hedge_ms=20.0, retry_deadline_s=5.0,
                         telemetry_path=path)
    try:
        router._replicas[1].degraded = True  # force the slow primary
        assert len(router.synonyms("w0", 5)) == 5
        assert router.stats()["failures"] == 0
    finally:
        router.close(close_replicas=False)
    att = {s["replica"]: s["outcome"] for s in _spans(path)
           if s["name"] == "attempt"}
    # the hedge touched the dead replica: the timeline must show it (the
    # mirror of the primary's dead-at-submit failed span)
    assert att == {"r0": "ok", "r1": "failed"}


def test_failed_attempt_and_retry_share_one_trace(tmp_path):
    path = str(tmp_path / "router.jsonl")

    def dying(req):
        from glint_word2vec_tpu.serve.fleet import ReplicaError
        if req.get("op") == "synonyms":
            raise ReplicaError("scripted death")
        return {"publish_sig": "sig-1"}

    reps = [FakeReplica("r0", dying), FakeReplica("r1", ok_syn)]
    router = FleetRouter(ReplicaSet(reps, can_respawn=False), probe_s=30.0,
                         hedge_ms=0.0, retry_deadline_s=5.0,
                         breaker_failures=5, telemetry_path=path)
    try:
        router._replicas[1].degraded = True  # force r0 first
        assert len(router.synonyms("w0", 5)) == 5
        assert router.stats()["failures"] == 0
    finally:
        router.close(close_replicas=False)
    spans = _spans(path)
    tids = {s["trace_id"] for s in spans}
    assert len(tids) == 1, "retry must stay inside the SAME trace"
    att = sorted((s["replica"], s["outcome"]) for s in spans
                 if s["name"] == "attempt")
    assert att == [("r0", "failed"), ("r1", "ok")]


# -- propagation through an in-process adopted fleet ------------------------------------


def test_adopted_fleet_cross_process_span_propagation(tmp_path):
    model = make_model()
    svcs = [EmbeddingService(model=model, ann=False,
                             telemetry_path=str(tmp_path / f"r{i}.jsonl"),
                             process_name=f"r{i}") for i in range(2)]
    # container-tolerant latency objective: the FIRST query pays the jit
    # compile (hundreds of ms) — at 7 samples one slow query would blow a
    # 250 ms p99 budget, which is the SLO working, not the test's subject
    lax = SloObjectives(availability=0.999, latency_ms=60_000.0)
    router = FleetRouter(ReplicaSet.adopt(svcs), probe_s=30.0, hedge_ms=0.0,
                         retry_deadline_s=10.0, slo=lax,
                         telemetry_path=str(tmp_path / "router.jsonl"))
    try:
        for i in range(6):
            assert len(router.synonyms(f"w{i}", 5)) == 5
        assert len(router.synonyms_batch(["w1", "w2"], 3)) == 2
        assert router.slo_within_budget()
    finally:
        router.close()  # closes the adopted services too
    timeline, summary = collect([str(tmp_path)], objectives=lax)
    # every router trace reassembles with replica-side children: the
    # context crossed the in-process "wire" exactly like the subprocess one
    cross = [t for t in timeline["traces"].values()
             if len({s["_process"] for s in t["spans"]}) >= 2]
    assert len(cross) == len(timeline["traces"]) >= 7
    for t in timeline["traces"].values():
        names = [s["name"] for s in t["spans"]]
        assert "fleet_query" in names and "attempt" in names
        assert "queue_wait" in names and "batch_service" in names
        assert "exact_scan" in names
        att = next(s for s in t["spans"] if s["name"] == "attempt")
        # replica-side children parent to the attempt span id that rode
        # the request
        for s in t["spans"]:
            if s["name"] in ("queue_wait", "batch_service", "exact_scan"):
                assert s["parent"] == att["span"]
                assert s["_process"] in ("r0", "r1")
    assert summary["slo"]["within_budget"]
    assert summary["attempt_outcomes"] == {
        "ok": len(timeline["traces"])}


# -- the collector on crafted fixtures --------------------------------------------------


def _rec(kind, t=0.0, **fields):
    return json.dumps({"schema": SCHEMA_VERSION, "kind": kind, "t": t,
                       **fields})


def _write(path, lines):
    with open(path, "w", encoding="utf-8") as f:
        f.write("\n".join(lines) + "\n")


WALL0 = 1_700_000_000_000_000_000  # ns


def test_collector_aligns_skewed_clocks_and_out_of_order_files(tmp_path):
    # router: anchor at WALL0 with mono base 500 s
    rm = 500_000_000_000
    # replica: same wall instant, WILDLY different monotonic base (9e15),
    # i.e. a host booted much earlier — alignment must come from the
    # anchor, never from comparing raw monotonic values
    pm = 9_000_000_000_000_000
    router_lines = [
        _rec("fleet_start", t=WALL0 / 1e9, replicas=1, checkpoint="/ck",
             process="router", wall_ns=WALL0, mono_ns=rm),
        # spans written OUT OF ORDER (thread interleaving): attempt line
        # lands before its root
        _rec("trace_span", t=0, trace_id="t1", span="a1", name="attempt",
             parent="q1", mono_ns=rm + 10_000_000, dur_ns=80_000_000,
             replica="r0", outcome="ok", process="router"),
        _rec("trace_span", t=0, trace_id="t1", span="q1",
             name="fleet_query", mono_ns=rm + 5_000_000,
             dur_ns=90_000_000, outcome="ok", op="synonyms",
             process="router"),
    ]
    replica_lines = [
        _rec("serve_start", t=WALL0 / 1e9, checkpoint="/ck", vocab_size=9,
             vector_size=4, process="r0", wall_ns=WALL0, mono_ns=pm),
        _rec("trace_span", t=0, trace_id="t1", span="b1",
             name="batch_service", parent="a1",
             mono_ns=pm + 30_000_000, dur_ns=40_000_000, process="r0"),
        _rec("trace_span", t=0, trace_id="t1", span="w1",
             name="queue_wait", parent="a1", mono_ns=pm + 12_000_000,
             dur_ns=18_000_000, process="r0"),
    ]
    _write(str(tmp_path / "router.jsonl"), router_lines)
    _write(str(tmp_path / "r0.jsonl"), replica_lines)
    timeline = build_timeline(load_process_logs([str(tmp_path)]))
    t1 = timeline["traces"]["t1"]
    order = [(s["name"], s["_wall_ns"] - WALL0) for s in t1["spans"]]
    # merged causal order across BOTH processes, on the fleet wall clock
    assert order == [("fleet_query", 5_000_000), ("attempt", 10_000_000),
                     ("queue_wait", 12_000_000),
                     ("batch_service", 30_000_000)]
    assert t1["root"]["span"] == "q1" and t1["dur_ns"] == 90_000_000


def test_collector_reanchors_across_process_restart(tmp_path):
    # one sink file, TWO anchor epochs: the respawned replica appends with
    # a fresh (smaller!) monotonic base — each span must align through the
    # most recent anchor above it in file order
    m1, m2 = 7_000_000_000_000, 3_000_000_000
    lines = [
        _rec("serve_start", t=WALL0 / 1e9, checkpoint="/ck", vocab_size=9,
             vector_size=4, process="r0", wall_ns=WALL0, mono_ns=m1),
        _rec("trace_span", t=0, trace_id="t1", span="s1", name="queue_wait",
             mono_ns=m1 + 1_000_000, dur_ns=500, process="r0"),
        _rec("serve_start", t=(WALL0 + 60_000_000_000) / 1e9,
             checkpoint="/ck", vocab_size=9, vector_size=4, process="r0",
             wall_ns=WALL0 + 60_000_000_000, mono_ns=m2),
        _rec("trace_span", t=0, trace_id="t2", span="s2", name="queue_wait",
             mono_ns=m2 + 2_000_000, dur_ns=500, process="r0"),
    ]
    _write(str(tmp_path / "r0.jsonl"), lines)
    timeline = build_timeline(load_process_logs([str(tmp_path)]))
    w1 = timeline["traces"]["t1"]["spans"][0]["_wall_ns"]
    w2 = timeline["traces"]["t2"]["spans"][0]["_wall_ns"]
    assert w1 == WALL0 + 1_000_000
    assert w2 == WALL0 + 60_002_000_000  # the SECOND epoch's anchor


def test_collector_publish_chain_joins_by_sig(tmp_path):
    sig = "111-22-333"
    _write(str(tmp_path / "trainer.jsonl"), [
        _rec("run_start", t=100.0, run_id="r", vocab_size=9, mesh=[1, 1],
             config={}),
        _rec("publish", t=101.0, publish_sig=sig, checkpoint="/ck", step=5,
             publisher="trainer"),
    ])
    _write(str(tmp_path / "r0.jsonl"), [
        _rec("serve_start", t=100.5, checkpoint="/ck", vocab_size=9,
             vector_size=4, process="r0"),
        _rec("serve_reload", t=102.0, vocab_size=9, reloads=1,
             load_seconds=0.1, publish_sig=sig),
    ])
    _write(str(tmp_path / "router.jsonl"), [
        _rec("fleet_start", t=100.2, replicas=1, checkpoint="/ck",
             process="router"),
        _rec("fleet_reload", t=103.0, publishes=1, min_serving=1,
             replicas=1, seconds=0.5, publish_sig=sig),
    ])
    timeline = build_timeline(load_process_logs([str(tmp_path)]))
    chain = timeline["publish_chains"][sig]
    assert [(e["kind"], e["_process"]) for e in chain] == [
        ("publish", "trainer"), ("serve_reload", "r0"),
        ("fleet_reload", "router")]


def test_collector_offline_slo_flags_blown_budget(tmp_path):
    lines = [_rec("fleet_start", t=100.0, replicas=1, checkpoint="/ck",
                  process="router", wall_ns=100_000_000_000,
                  mono_ns=1_000)]
    for i in range(10):
        lines.append(_rec(
            "trace_span", t=0, trace_id=f"t{i}", span=f"q{i}",
            name="fleet_query", mono_ns=1_000 + i * 1_000_000_000,
            dur_ns=2_000_000, op="synonyms",
            outcome="failed" if i < 2 else "ok", process="router"))
    _write(str(tmp_path / "router.jsonl"), lines)
    timeline = build_timeline(load_process_logs([str(tmp_path)]))
    slo = recompute_slo(timeline, SloObjectives(
        availability=0.999, short_window_s=60, long_window_s=600))
    assert slo["samples"] == 10 and slo["bad"] == 2
    assert slo["availability"] == 0.8
    assert not slo["within_budget"]
    # the same artifacts pass a lax objective: the gate is the objective's
    tolerant = recompute_slo(timeline, SloObjectives(
        availability=0.5, short_window_s=60, long_window_s=600))
    assert tolerant["within_budget"]


def test_collector_exports_perfetto_and_exemplars(tmp_path):
    model = make_model()
    svc = EmbeddingService(model=model, ann=False,
                           telemetry_path=str(tmp_path / "r0.jsonl"),
                           process_name="r0")
    router = FleetRouter(ReplicaSet.adopt([svc]), probe_s=30.0,
                         hedge_ms=0.0, retry_deadline_s=10.0,
                         telemetry_path=str(tmp_path / "router.jsonl"))
    try:
        for i in range(5):
            router.synonyms(f"w{i}", 5)
    finally:
        router.close()
    timeline, summary = collect([str(tmp_path)], slowest=3)
    assert len(summary["slowest"]) == 3
    # exemplars are sorted slowest-first and carry the full breakdown
    durs = [e["dur_ms"] for e in summary["slowest"]]
    assert durs == sorted(durs, reverse=True)
    assert all(len(e["spans"]) >= 4 for e in summary["slowest"])
    out = str(tmp_path / "timeline.json")
    n = export_perfetto(timeline, out)
    with open(out) as f:
        doc = json.load(f)
    assert n == len(doc["traceEvents"])
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs == {"r0", *{p for p in timeline["processes"]
                             if p.startswith("router")}}
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert xs and all(e["dur"] >= 0 and "trace_id" in e["args"]
                      for e in xs)


# -- statusd SLO gauges -----------------------------------------------------------------


def test_fleet_prometheus_renders_slo_gauges():
    from glint_word2vec_tpu.obs.statusd import fleet_prometheus_text
    tr = SloTracker(SloObjectives(availability=0.9, latency_ms=100.0,
                                  short_window_s=60, long_window_s=600))
    for i in range(10):
        tr.note(i != 0, latency_s=0.01)  # one unanswered of ten
    snap = {"status": "serving", "queries": 10, "failures": 1,
            "replicas": {}, "slo": tr.snapshot()}
    text = fleet_prometheus_text(snap)
    for needle in (
            "glint_serve_fleet_slo_availability_objective 0.9",
            "glint_serve_fleet_slo_availability 0.9",
            "glint_serve_fleet_slo_samples_total 10",
            'glint_serve_fleet_slo_burn_rate{sli="availability",'
            'window="short"}',
            'glint_serve_fleet_slo_burn_rate{sli="latency",'
            'window="long"}',
            "glint_serve_fleet_slo_budget_remaining"):
        assert needle in text, f"{needle!r} missing from:\n{text}"
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(type_lines) == len(set(type_lines))


# -- run_report fleet mode --------------------------------------------------------------


def test_run_report_fleet_mode(tmp_path):
    from tools.run_report import summarize_fleet
    ok_log = str(tmp_path / "r0.jsonl")
    _write(ok_log, [
        _rec("serve_start", t=1.0, checkpoint="/ck", vocab_size=9,
             vector_size=4),
        _rec("serve_end", t=2.0, submitted=5, refused=0, reloads=0),
    ])
    dead_log = str(tmp_path / "r1.jsonl")
    _write(dead_log, [
        _rec("serve_start", t=1.0, checkpoint="/ck", vocab_size=9,
             vector_size=4),
    ])
    # the dead replica left a flight-recorder dump (the SIGTERM shape)
    from glint_word2vec_tpu.obs.blackbox import FlightRecorder
    fr = FlightRecorder(dead_log + ".blackbox.json")
    fr.begin_run("r1")
    fr.dump(cause=FlightRecorder.signal_cause(15))
    rep = summarize_fleet([ok_log, dead_log])
    assert rep["ok"] and rep["mode"] == "fleet"
    assert rep["processes"]["r0"]["status"] == "ok"
    assert rep["processes"]["r1"]["status"] == "truncated"
    assert rep["processes"]["r1"]["dumped"]
    assert rep["processes"]["r1"]["cause"] == "signal"
    assert rep["merged"]["logs"] == 2 and rep["merged"]["dumps"] == 1
    assert rep["merged"]["schema_valid"]


def test_run_report_fleet_mode_gates_on_error_status(tmp_path):
    # a trainer whose run ENDED "error" must redden the fleet verdict —
    # "truncated" (SIGKILL teardown) is tolerated, an explicit error is not
    from tools.run_report import summarize_fleet
    bad = str(tmp_path / "trainer.jsonl")
    _write(bad, [
        _rec("run_start", t=1.0, run_id="r", vocab_size=9, mesh=[1, 1],
             config={}),
        _rec("run_end", t=2.0, run_id="r", status="error", steps=3,
             pairs_trained=10, wall_seconds=1.0),
    ])
    rep = summarize_fleet([bad])
    assert not rep["ok"]
    assert not rep["processes"]["trainer"]["ok"]
    assert rep["processes"]["trainer"]["status"] == "error"


def test_validate_file_tolerates_torn_tail_only(tmp_path):
    good = _rec("serve_start", t=1.0, checkpoint="/ck", vocab_size=9,
                vector_size=4)
    # SIGKILL mid-flush: a half-written FINAL line
    torn = str(tmp_path / "torn.jsonl")
    with open(torn, "w", encoding="utf-8") as f:
        f.write(good + "\n" + good[: len(good) // 2])
    assert not validate_file(torn)["ok"]  # strict: still an error
    v = validate_file(torn, tolerate_torn_tail=True)
    assert v["ok"] and v["torn_tail"] and not v["errors"]
    # mid-file garbage is CORRUPTION, not a torn tail — fails either way
    midbad = str(tmp_path / "midbad.jsonl")
    with open(midbad, "w", encoding="utf-8") as f:
        f.write(good[: len(good) // 2] + "\n" + good + "\n")
    assert not validate_file(midbad, tolerate_torn_tail=True)["ok"]
    # fleet-mode run_report rides the same tolerance: a torn-tail replica
    # sink must not redden the verdict (the drill kills replicas mid-write)
    from tools.run_report import summarize_fleet
    rep = summarize_fleet([torn])
    assert rep["ok"] and rep["processes"]["torn"]["schema_valid"]


def test_collector_keeps_rotated_only_logs(tmp_path):
    # killed between rotate and the lazy reopen: ONLY x.jsonl.1 remains —
    # the process must still appear on the merged timeline
    _write(str(tmp_path / "r0.jsonl.1"), [
        _rec("serve_start", t=WALL0 / 1e9, checkpoint="/ck", vocab_size=9,
             vector_size=4, process="r0", wall_ns=WALL0, mono_ns=1_000),
        _rec("trace_span", t=0, trace_id="t1", span="s1", name="queue_wait",
             mono_ns=1_000 + 2_000_000, dur_ns=500, process="r0"),
    ])
    timeline = build_timeline(load_process_logs([str(tmp_path)]))
    assert "r0" in timeline["processes"]
    span = timeline["traces"]["t1"]["spans"][0]
    assert span["_wall_ns"] == WALL0 + 2_000_000
