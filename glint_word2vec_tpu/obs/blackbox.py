"""Flight recorder: a bounded in-memory ring that dumps on fit death.

An hour-3 death on a remote host today leaves a truncated JSONL and a
traceback in a terminated terminal — nothing that says what the run was
doing when it died. The recorder mirrors the tail of the telemetry stream
(recent heartbeats, watchdog/recovery records, per-dispatch metadata) in
memory and, when the run dies, writes one self-contained JSON document —
``<telemetry_path>.blackbox.json`` — atomically (tmp + ``os.replace``, the
checkpoint swap discipline), stamped with the terminal cause. Dump
triggers, all riding paths that already exist (docs/observability.md):

- any fit-aborting exception — the trainer's ``except BaseException:
  _abort_run(); raise`` arms the dump with the in-flight exception (this
  covers ``NormBlowupError``, ``NonFiniteParamsError``, feed errors, and
  ``KeyboardInterrupt``/SIGINT, which Python delivers as an exception);
- SIGTERM — the first signal a preemption/k8s eviction sends; the trainer
  installs a handler for the duration of fit() that dumps, restores the
  previous disposition, and re-raises the signal so exit semantics are
  untouched (trainer._install_run_signals).

The ring is bounded (``config.blackbox_ring`` dispatch records; heartbeats
and watchdog/recovery events keep smaller fixed fractions) so a weeks-long
run holds kilobytes, and the DUMP is what costs — feeding the ring is a
lock + deque append per dispatch round, nothing on the step path. The
recorder exists only when telemetry is on (the dump path derives from
``telemetry_path``); a telemetry-off trainer has none.

Dump document format (validated by ``obs.schema.validate_blackbox``): one
JSON object with ``schema``/``kind="blackbox"``/``t``, the ``run_id``, a
``cause`` record (exception | signal | none), the ring contents
(``heartbeats``/``events``/``dispatches`` — heartbeats and events are the
SAME schema records the sink wrote, so one validator covers both files),
and the at-death ``phases``/``spans``/``status`` snapshots.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, Optional

from glint_word2vec_tpu.obs.schema import SCHEMA_VERSION
from glint_word2vec_tpu.lockcheck import make_rlock

logger = logging.getLogger("glint_word2vec_tpu")

# sink-record kinds mirrored into the event ring (everything that is not a
# heartbeat or a dispatch: watchdog firings, recovery-ladder actions, the
# run_start/run_end bracketing records)
_EVENT_KINDS = ("run_start", "run_end", "watchdog", "recovery")


class FlightRecorder:
    """Bounded rings of recent telemetry + per-dispatch metadata, dumped
    atomically to ``path`` on fit death."""

    def __init__(self, path: str, ring: int = 256):
        if ring <= 0:
            raise ValueError(f"blackbox ring must be positive but got {ring}")
        self.path = path
        # RLock, not Lock: the SIGTERM dump runs ON the main thread at a
        # bytecode boundary — possibly while that same thread is inside
        # note_dispatch()/observe() holding this lock. A non-reentrant lock
        # would deadlock the handler through the kill grace period and the
        # process would die dumpless — the exact failure this class exists
        # to prevent. (Same rule in phases/spans/sink: every lock the
        # handler's dump path can touch is reentrant.)
        self._lock = make_rlock("obs.blackbox")
        # dispatches dominate volume (one per round); heartbeats arrive at
        # 1/heartbeat_every_steps of that and events are rarer still — the
        # smaller rings keep the dump proportioned without more knobs
        self._dispatches: deque = deque(maxlen=ring)
        self._heartbeats: deque = deque(maxlen=max(ring // 4, 16))
        self._events: deque = deque(maxlen=max(ring // 4, 16))
        self._run_id = ""
        self._dumped = False

    # -- feeding ----------------------------------------------------------------

    def begin_run(self, run_id: str) -> None:
        with self._lock:
            self._dispatches.clear()
            self._heartbeats.clear()
            self._events.clear()
            self._run_id = run_id
            self._dumped = False

    def observe(self, kind: str, rec: Dict[str, Any]) -> None:
        """Mirror one sink record (already schema-stamped fields) into the
        matching ring. Unknown kinds ride the event ring — a future record
        kind must not silently vanish from the forensics artifact."""
        entry = {"schema": SCHEMA_VERSION, "kind": kind,
                 "t": round(time.time(), 3), **rec}
        with self._lock:
            if kind == "heartbeat":
                self._heartbeats.append(entry)
            else:
                self._events.append(entry)

    def note_dispatch(self, global_step: int, real: int,
                      dispatch_s: float, wait_s: float) -> None:
        """One tiny record per dispatch round — the finest-grained trace of
        what the run was doing right before death (heartbeats are 1-in-N)."""
        with self._lock:
            self._dispatches.append({
                "t": round(time.time(), 3), "step": int(global_step),
                "real": int(real), "dispatch_s": round(dispatch_s, 6),
                "wait_s": round(wait_s, 6)})

    # -- dumping ----------------------------------------------------------------

    @staticmethod
    def exception_cause(exc: BaseException) -> dict:
        return {
            "kind": "exception",
            "type": type(exc).__name__,
            "message": str(exc)[:2000],
            # last 20 frames: enough to place the death, bounded on purpose
            "traceback": traceback.format_exception(
                type(exc), exc, exc.__traceback__)[-20:],
        }

    @staticmethod
    def signal_cause(signum: int) -> dict:
        import signal as _signal
        try:
            name = _signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        return {"kind": "signal", "signal": name, "signum": int(signum)}

    def dump(self, cause: Optional[dict] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the dump document atomically; returns the path, or None on
        failure (best-effort like the sink — forensics must never mask the
        original failure). Idempotent per run: the first cause wins (a
        SIGTERM dump must not be overwritten by the KeyboardInterrupt-style
        unwind that may follow it)."""
        with self._lock:
            if self._dumped:
                return self.path
            self._dumped = True
            doc = {
                "schema": SCHEMA_VERSION,
                "kind": "blackbox",
                "t": round(time.time(), 3),
                "run_id": self._run_id,
                "cause": cause or {"kind": "none"},
                "heartbeats": list(self._heartbeats),
                "events": list(self._events),
                "dispatches": list(self._dispatches),
            }
        if extra:
            doc.update(extra)
        tmp = f"{self.path}.tmp-{os.getpid()}"
        try:
            from glint_word2vec_tpu.obs.sink import TelemetrySink
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(TelemetrySink._sanitize(doc), f, allow_nan=False)
            os.replace(tmp, self.path)
        except (OSError, TypeError, ValueError) as e:
            logger.warning("blackbox dump failed: %s (the run's original "
                           "failure is unaffected)", e)
            try:
                os.remove(tmp)
            except OSError:
                pass
            return None
        logger.warning("blackbox dump written: %s (%d heartbeats, %d events, "
                       "%d dispatch records)", self.path,
                       len(doc["heartbeats"]), len(doc["events"]),
                       len(doc["dispatches"]))
        return self.path
