"""R8 bad config half: no construction-time refusal for the combinations the
trainer fixture refuses at dispatch. The single-knob negative_pool RANGE
check must NOT count as coverage for the {cbow, negative_pool} dispatch
combo — its condition says nothing about the combination. The max_row_norm
range check likewise must not cover the {use_pallas, max_row_norm}
stabilizer-knob dispatch refusal (the ISSUE-7 regression class: a NEW knob
lands with a dispatch-only refusal), and the sync_every POSITIVITY check
must not cover the {sync_every, step_lowering} dispatch refusal (the
ISSUE-17 class: a cadence knob whose window exists for one lowering only)."""
import dataclasses


@dataclasses.dataclass
class Word2VecConfig:
    cbow: bool = False
    device_pairgen: bool = False
    use_pallas: bool = False
    negative_pool: int = -1
    max_row_norm: float = 0.0
    vector_size: int = 100
    step_lowering: str = "gspmd"
    sync_every: int = 1

    def __post_init__(self) -> None:
        if self.vector_size <= 0:
            raise ValueError("vector_size must be positive")
        if self.negative_pool < -1:
            raise ValueError("negative_pool must be >= -1")
        if self.max_row_norm < 0:
            raise ValueError("max_row_norm must be nonnegative")
        if self.sync_every <= 0:
            raise ValueError("sync_every must be positive")
