"""Streaming corpus — an append-only segment directory with a persisted
consumed-offset cursor and a delta encode pass.

The production corpus never stops growing: new token files land in a
directory (``seg-000.txt``, ``seg-001.txt``, …), each IMMUTABLE once written
(the append-only contract — a segment whose bytes change under the cursor is
an error, not a refresh). The continual driver (continual/loop.py) consumes
the directory incrementally:

- :class:`CorpusStream` — lists the segments in sorted-name order and
  fingerprints their content (size + head/tail CRC, cheap at any size).
- :class:`StreamCursor` — the persisted consumed-offset: which segments have
  been trained through, each with the content fingerprint it had and the
  vocabulary fingerprint it was encoded under. Written atomically
  (tmp + ``os.replace``) so a SIGTERM between increments never tears it.
- :func:`encode_delta` — encodes ONLY the new tail under the current
  (possibly just-extended) vocabulary; already-consumed segments reuse their
  cached encode as-is when their recorded vocab fingerprint is the current
  one OR any ancestor in the checkpoint's lineage chain — the
  identity-prefix extension contract (continual/extend.py) keeps ancestor
  ids valid, so the common continual case re-encodes nothing old.
- :class:`ConcatCorpus` — a zero-copy ``Sequence`` view over several
  :class:`~glint_word2vec_tpu.data.corpus.EncodedCorpus` segments, so the
  trainer consumes (replay + tail) as one corpus without concatenating
  files.
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from glint_word2vec_tpu.data.corpus import (
    EncodedCorpus,
    TokenFileCorpus,
    encode_corpus,
    vocab_fingerprint,
)
from glint_word2vec_tpu.data.vocab import Vocabulary

logger = logging.getLogger("glint_word2vec_tpu")

_CURSOR = "cursor.json"
_FP_BYTES = 1 << 20  # head/tail window hashed per segment


def segment_fingerprint(path: str) -> str:
    """Cheap content identity of one segment file: size plus CRC32 of the
    first and last MiB — enough to catch truncation, in-place edits, and
    the classic rewrite-with-same-name violation of the append-only
    contract, without re-reading multi-GB segments every poll."""
    size = os.path.getsize(path)
    h = 0
    with open(path, "rb") as f:
        h = zlib.crc32(f.read(_FP_BYTES), h)
        if size > _FP_BYTES:
            f.seek(max(size - _FP_BYTES, 0))
            h = zlib.crc32(f.read(_FP_BYTES), h)
    return f"{size}-{h:08x}"


class CorpusStream:
    """The append-only corpus: a directory of immutable token segment files
    (one sentence per line, whitespace-tokenized — the TokenFileCorpus
    format), consumed in sorted-name order."""

    def __init__(self, directory: str, suffix: str = ".txt"):
        self.directory = directory
        self.suffix = suffix

    def segments(self) -> List[str]:
        """Sorted segment file names currently present."""
        try:
            names = os.listdir(self.directory)
        except OSError as e:
            raise FileNotFoundError(
                f"cannot list corpus stream directory "
                f"{self.directory!r}: {e}") from e
        return sorted(n for n in names
                      if n.endswith(self.suffix)
                      and not n.startswith("."))

    def path(self, name: str) -> str:
        return os.path.join(self.directory, name)

    def corpus(self, name: str) -> TokenFileCorpus:
        return TokenFileCorpus(self.path(name))


class StreamCursor:
    """Persisted consumed-offset over a :class:`CorpusStream`.

    ``consumed`` maps segment name → record::

        {"fingerprint": <content fp at consume time>,
         "vocab_fingerprint": <vocab fp the cached encode was written under>,
         "n_sentences": int, "total_tokens": int}

    Saves are atomic (tmp + ``os.replace``); a crash between increments
    leaves either the old or the new cursor, never a torn one — and because
    the driver marks segments consumed only AFTER a successful increment,
    re-running after a crash retries the whole increment (idempotent: the
    extension is a no-op the second time, the fit re-trains the same tail
    from the last published params).
    """

    def __init__(self, directory: str):
        self.directory = directory
        self.consumed: Dict[str, Dict[str, Any]] = {}
        # the count-merge stage marker: segments whose word counts have
        # already been merged into the checkpoint (the extension publish)
        # but whose increment has NOT finished training. A crashed increment
        # retries the FIT without re-merging the counts. The remaining
        # window — a crash BETWEEN the extension publish and this marker's
        # save — is closed by the lineage link's tail_fingerprint
        # (extend.py): the retry recognizes the already-applied merge. The
        # two together make the increment exactly idempotent (chaos phase
        # continual-drift + tests drive both windows).
        self.counted: Dict[str, Dict[str, Any]] = {}
        # per-process audit memo: consumed segments whose (size, mtime_ns)
        # matched when their content fingerprint last verified. Re-CRCing
        # every consumed segment on EVERY poll is O(total history) in disk
        # reads — a year-old deployment would re-read GBs per idle poll; a
        # stat compare catches the same in-place-edit violations for free,
        # and any stat change re-verifies the content.
        self._audit_memo: Dict[str, tuple] = {}
        os.makedirs(directory, exist_ok=True)
        p = os.path.join(directory, _CURSOR)
        if os.path.exists(p):
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
            self.consumed = doc.get("consumed", {})
            self.counted = doc.get("counted", {})

    def save(self) -> None:
        p = os.path.join(self.directory, _CURSOR)
        tmp = p + f".tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"consumed": self.consumed,
                       "counted": self.counted}, f, indent=1)
        os.replace(tmp, p)

    def new_segments(self, stream: CorpusStream) -> List[str]:
        """Names present in the stream but not yet consumed, sorted. Also
        audits the append-only contract on the CONSUMED set: a consumed
        segment that vanished or changed content is an error — silently
        training on a mutated history would corrupt the count/lineage
        bookkeeping."""
        names = stream.segments()
        present = set(names)
        for name, rec in self.consumed.items():
            if name not in present:
                raise ValueError(
                    f"consumed segment {name!r} vanished from "
                    f"{stream.directory!r} — the corpus stream is "
                    f"append-only; restore the segment or rebuild the "
                    f"cursor")
            st = os.stat(stream.path(name))
            sig = (st.st_size, st.st_mtime_ns)
            if self._audit_memo.get(name) == sig:
                continue  # verified under this exact stat already
            fp = segment_fingerprint(stream.path(name))
            if fp != rec.get("fingerprint"):
                raise ValueError(
                    f"consumed segment {name!r} changed content "
                    f"({rec.get('fingerprint')} -> {fp}) — the corpus "
                    f"stream is append-only; write drift as a NEW segment")
            self._audit_memo[name] = sig
        return [n for n in names if n not in self.consumed]

    def uncounted(self, names: Iterable[str]) -> List[str]:
        """The subset of ``names`` whose counts have not been merged yet."""
        return [n for n in names if n not in self.counted]

    def mark_counted(self, name: str, fingerprint: str) -> None:
        self.counted[name] = {"fingerprint": fingerprint}

    def mark_consumed(self, name: str, fingerprint: str,
                      vocab_fp: str, meta: Dict[str, Any]) -> None:
        self.consumed[name] = {
            "fingerprint": fingerprint,
            "vocab_fingerprint": vocab_fp,
            "n_sentences": int(meta.get("n_sentences", 0)),
            "total_tokens": int(meta.get("total_tokens", 0)),
        }
        self.counted.pop(name, None)  # consumed implies counted


class ConcatCorpus(Sequence):
    """Read-only concatenation of several encoded segments — satisfies the
    ``Sequence[np.ndarray]`` feed contract like one EncodedCorpus."""

    def __init__(self, parts: Iterable[Sequence]):
        self._parts = [p for p in parts if len(p)]
        self._offsets = np.cumsum([0] + [len(p) for p in self._parts])

    def __len__(self) -> int:
        return int(self._offsets[-1])

    def __getitem__(self, i: int) -> np.ndarray:
        if isinstance(i, slice):
            raise TypeError("ConcatCorpus supports integer indexing only")
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        part = int(np.searchsorted(self._offsets, i, side="right")) - 1
        return self._parts[part][i - int(self._offsets[part])]

    @property
    def total_tokens(self) -> int:
        return sum(int(getattr(p, "total_tokens", 0)) for p in self._parts)


def _segment_cache_dir(cache_dir: str, name: str) -> str:
    return os.path.join(cache_dir, f"{name}.enc")


def encode_segment(
    stream: CorpusStream,
    name: str,
    vocab: Vocabulary,
    cache_dir: str,
    max_sentence_length: int,
    allowed_fingerprints: Optional[Sequence[str]] = None,
) -> EncodedCorpus:
    """Encode one segment under ``vocab``, reusing the cached encode when it
    was written under the current vocabulary or any allowed ancestor
    (``allowed_fingerprints`` — the checkpoint's lineage chain). A cache
    under a NON-ancestor vocabulary is stale (ids would map to the wrong
    words) and is re-encoded in place."""
    enc_dir = _segment_cache_dir(cache_dir, name)
    want = vocab_fingerprint(vocab)
    allowed = set(allowed_fingerprints or ()) | {want}
    if os.path.exists(os.path.join(enc_dir, "meta.json")):
        enc = EncodedCorpus(enc_dir)
        got = enc.meta.get("vocab_fingerprint")
        if got in allowed:
            return enc  # the common continual case: NOT re-encoded
        logger.warning(
            "segment %s encode cache was written under a non-ancestor "
            "vocabulary (%s); re-encoding under the current one", name, got)
    return encode_corpus(stream.corpus(name), vocab, enc_dir,
                         max_sentence_length)


def encode_delta(
    stream: CorpusStream,
    cursor: StreamCursor,
    vocab: Vocabulary,
    cache_dir: str,
    max_sentence_length: int = 1000,
    lineage: Optional[Sequence[str]] = None,
    replay_segments: int = 0,
) -> Dict[str, Any]:
    """The delta encode pass: encode only the unconsumed tail under
    ``vocab``; assemble the increment's training corpus as (optional replay
    of the most recent consumed segments, from their caches) + (the new
    tail). Returns::

        {"corpus": ConcatCorpus, "new": [names], "replayed": [names],
         "encoded": {name: EncodedCorpus for the new tail}}
    """
    os.makedirs(cache_dir, exist_ok=True)
    new_names = cursor.new_segments(stream)
    encoded: Dict[str, EncodedCorpus] = {}
    parts: List[EncodedCorpus] = []
    replayed: List[str] = []
    if replay_segments > 0:
        for name in sorted(cursor.consumed)[-replay_segments:]:
            parts.append(encode_segment(
                stream, name, vocab, cache_dir, max_sentence_length,
                allowed_fingerprints=lineage))
            replayed.append(name)
    for name in new_names:
        enc = encode_segment(stream, name, vocab, cache_dir,
                             max_sentence_length,
                             allowed_fingerprints=lineage)
        encoded[name] = enc
        parts.append(enc)
    return {"corpus": ConcatCorpus(parts), "new": new_names,
            "replayed": replayed, "encoded": encoded}
