"""Trainer integration for config.device_pairgen (on-device pair generation).

Stream-level bit-equivalence is covered by tests/test_device_pairgen.py; these tests
drive the Trainer end-to-end: learning on a topical corpus, exact pair accounting,
data-parallel segments on the virtual mesh, and config validation.
"""

import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.train.trainer import Trainer


def _topic_corpus(n=400, rng=None):
    rng = rng or np.random.default_rng(0)
    topics = [["a", "b", "c", "d"], ["x", "y", "z", "w"]]
    return [list(rng.choice(topics[i % 2], size=12)) for i in range(n)]


def _cos(a, b):
    return float(np.dot(a, b) / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


def _fit(cfg, sentences):
    vocab = build_vocab(sentences, min_count=1)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encoded)
    return trainer, vocab


def test_device_feed_learns_topics():
    cfg = Word2VecConfig(
        vector_size=32, min_count=1, pairs_per_batch=256, num_iterations=5,
        learning_rate=0.025, seed=3, negative_pool=16, device_pairgen=True,
        steps_per_dispatch=4, window=3, subsample_ratio=0.0)
    trainer, vocab = _fit(cfg, _topic_corpus())
    syn0 = np.asarray(trainer.unpadded_params().syn0)
    wv = {w: syn0[vocab.index[w]] for w in "abxy"}
    assert _cos(wv["a"], wv["b"]) > 0.8
    assert _cos(wv["a"], wv["x"]) < 0.5
    # exact device-side accounting replaced the host estimate
    assert trainer.pairs_trained > 0
    assert np.isfinite(trainer.pairs_trained)


def _packer_reference_pairs(encoded, vocab, seed, iteration, shard, num_shards,
                            T, window, ratio):
    """Host replay of the device-feed packer's stream contract for one
    (iteration, shard): hashrng subsample on raw ordinals, shuffled shard order,
    kept stream cut at T boundaries, windows keyed by kept ordinals
    (host _block_pairs with keep ≡ 1 per cut block). Returns total pair count."""
    from glint_word2vec_tpu.data.hashrng import (
        STREAM_SUBSAMPLE, hash_u01_at, stream_base)
    from glint_word2vec_tpu.data.pipeline import (
        _block_pairs, keep_probabilities, stream_rng)
    keep = keep_probabilities(
        vocab.counts, vocab.train_words_count, ratio).astype(np.float32)
    rng = stream_rng(seed, iteration, shard)
    order = np.arange(shard, len(encoded), num_shards)
    rng.shuffle(order)
    sub = stream_base(seed, STREAM_SUBSAMPLE, iteration, shard)
    kept_sents, raw_ord = [], 0
    for si in order:
        arr = encoded[si]
        if ratio > 0:
            u = hash_u01_at(sub, np.arange(raw_ord, raw_ord + arr.shape[0],
                                           dtype=np.uint64))
            ks = arr[u <= keep[arr]]
        else:
            ks = arr
        raw_ord += arr.shape[0]
        if ks.shape[0]:
            kept_sents.append(ks)
    if not kept_sents:
        return 0
    tokens = np.concatenate(kept_sents)
    is_start = np.zeros(tokens.shape[0], bool)
    is_start[np.cumsum([s.shape[0] for s in kept_sents])[:-1]] = True
    is_start[0] = True
    total = 0
    for i in range(0, tokens.shape[0], T):
        tk = tokens[i:i + T]
        st = is_start[i:i + T].copy()
        st[0] = True
        idx = np.flatnonzero(st)
        lens = np.diff(np.append(idx, tk.shape[0])).astype(np.int64)
        hc, _, _, _ = _block_pairs(tk, lens, np.ones(vocab.size), window,
                                   seed, iteration, shard, i, True)
        total += hc.shape[0]
    return total


def test_device_feed_pair_totals_match_host_stream():
    """The device must train exactly the pairs the packer's stream contract emits
    (host-side subsampling + kept-ordinal-keyed windows + T-boundary cuts)."""
    sentences = _topic_corpus(200)
    cfg = Word2VecConfig(
        vector_size=16, min_count=1, pairs_per_batch=512, num_iterations=1,
        seed=11, negative_pool=8, device_pairgen=True, steps_per_dispatch=2,
        window=3, subsample_ratio=1e-3, shuffle=True)
    vocab = build_vocab(sentences, min_count=1)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    total = _packer_reference_pairs(
        encoded, vocab, 11, 1, 0, 1, trainer._tokens_per_step, 3, 1e-3)
    trainer.fit(encoded)
    assert trainer.pairs_trained == pytest.approx(total, abs=0.5)


def test_device_feed_data_parallel_segments():
    """num_data > 1 on the virtual mesh: per-segment generation matches the host
    pipeline's shard semantics (round-robin sentences, per-shard hash streams)."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    sentences = _topic_corpus(300)
    cfg = Word2VecConfig(
        vector_size=16, min_count=1, pairs_per_batch=512, num_iterations=2,
        seed=5, negative_pool=8, device_pairgen=True, steps_per_dispatch=2,
        window=3, num_data_shards=2, subsample_ratio=0.0)
    vocab = build_vocab(sentences, min_count=1)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)
    trainer = Trainer(cfg, vocab)
    host_pairs = sum(
        _packer_reference_pairs(encoded, vocab, 5, it, s, 2,
                                trainer._tokens_per_step, 3, 0.0)
        for it in (1, 2) for s in (0, 1))
    trainer.fit(encoded)
    assert trainer.pairs_trained == pytest.approx(host_pairs, abs=0.5)
    syn0 = np.asarray(trainer.unpadded_params().syn0)
    wv = {w: syn0[vocab.index[w]] for w in "abxy"}
    assert _cos(wv["a"], wv["b"]) > 0.6
    assert _cos(wv["a"], wv["x"]) < 0.6


def test_device_feed_overflow_drops_counted(caplog):
    """A deliberately tiny tokens_per_step forces overflow; the trainer reports it
    and still trains the first-B prefix of each block's pairs."""
    sentences = _topic_corpus(100)
    cfg = Word2VecConfig(
        vector_size=16, min_count=1, pairs_per_batch=64, num_iterations=1,
        seed=2, negative_pool=8, device_pairgen=True, steps_per_dispatch=2,
        window=5, tokens_per_step=128, max_sentence_length=64)
    import logging
    with caplog.at_level(logging.INFO, logger="glint_word2vec_tpu"):
        trainer, _ = _fit(cfg, sentences)
    assert trainer.pairs_trained > 0


def test_device_feed_config_validation():
    sentences = _topic_corpus(20)
    vocab = build_vocab(sentences, min_count=1)
    with pytest.raises(ValueError, match="skip-gram only"):
        Trainer(Word2VecConfig(min_count=1, device_pairgen=True, cbow=True,
                               negative_pool=8), vocab)
    with pytest.raises(ValueError, match="use_pallas"):
        Trainer(Word2VecConfig(min_count=1, device_pairgen=True, use_pallas=True,
                               negative_pool=8), vocab)


def test_device_feed_resume_is_deterministic(tmp_path):
    """Interrupt + resume lands on the same params as an uninterrupted run
    (the packer stream is a pure function of (seed, iteration, shard), and
    batches_done skips whole steps)."""
    sentences = _topic_corpus(200)
    vocab = build_vocab(sentences, min_count=1)
    encoded = encode_sentences(sentences, vocab, 1000)

    def mk():
        return Word2VecConfig(
            vector_size=16, min_count=1, pairs_per_batch=256, num_iterations=2,
            learning_rate=0.02, seed=9, negative_pool=8, device_pairgen=True,
            steps_per_dispatch=2, window=3, prefetch_chunks=0,
            subsample_ratio=0.0)

    full = Trainer(mk(), vocab)
    full.fit(encoded)
    ref = np.asarray(full.unpadded_params().syn0)

    ckpt = str(tmp_path / "ck")
    part = Trainer(mk().replace(heartbeat_every_steps=6), vocab)
    # interrupt on the SECOND heartbeat — the first _finish_round's periodic
    # checkpoint (which runs after the heartbeat) has been written by then
    calls = {"n": 0}

    def boom(_rec):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise KeyboardInterrupt

    try:
        part.fit(encoded, checkpoint_path=ckpt, checkpoint_every_steps=6,
                 on_heartbeat=boom)
    except KeyboardInterrupt:
        pass
    assert calls["n"] >= 2

    from glint_word2vec_tpu.models.estimator import Word2Vec
    resumed = Word2Vec.resume(ckpt, sentences)
    got = np.asarray(resumed.syn0)[:ref.shape[0], :ref.shape[1]]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
