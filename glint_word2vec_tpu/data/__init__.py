from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab, read_corpus
from glint_word2vec_tpu.data.pipeline import (
    encode_sentences,
    subsample_sentence,
    dynamic_window_pairs,
    dynamic_window_cbow,
    PairBatcher,
    epoch_batches,
    epoch_batches_cbow,
)

__all__ = [
    "Vocabulary",
    "build_vocab",
    "read_corpus",
    "encode_sentences",
    "subsample_sentence",
    "dynamic_window_pairs",
    "dynamic_window_cbow",
    "PairBatcher",
    "epoch_batches",
    "epoch_batches_cbow",
]
