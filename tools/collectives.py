"""HLO collective audit: GSPMD vs shard_map sharded-step collective bytes.

Every multi-chip number in PERF.md §7 was, until round 9, an ESTIMATE from
byte formulas — the GSPMD lowering's actual collective profile had never been
inspected. This tool closes that: it AOT-compiles BOTH step lowerings
(``config.step_lowering="gspmd"`` — jit + sharding constraints, the compiler
chooses the schedule; and ``"shard_map"`` — the explicit schedule of
ops/sgns_shard.py) at a given geometry and mesh shape, walks the compiled
HLO, and tabulates every ``all-gather`` / ``all-reduce`` / ``reduce-scatter``
/ ``all-to-all`` / ``collective-permute`` with its shape, bytes, and which
mesh axis its replica groups span (parallel/mesh.classify_replica_groups).

No hardware or execution is involved — compiled HLO is a static artifact, so
the collective *structure and bytes* are measurable on the forced-device CPU
mesh (``--xla_force_host_platform_device_count``). The SPMD partitioner is
the same platform-independent pass that runs for TPU; backend-specific
rewrites (e.g. async pairs, ICI-topology-aware algorithms) can change HOW the
bytes move, not how many a collective op names. Numbers from this tool are
labeled "HLO-measured collective bytes" in PERF.md §7, distinct from both the
old formula estimates and a future on-hardware traffic profile.

Bytes metric, stated precisely: for each collective instruction,
``max(sum of operand bytes, result bytes)`` — the payload the op names, a
lower bound on link traffic (ring/tree algorithms move a small multiple).

The step audited is the metrics-elided twin (``with_metrics=False`` — the
production steady state; the full twin adds three f32 scalars over `data`).

Run:  python tools/collectives.py [--smoke] [--mesh 2x4|all] [--json-out F]
      (defaults to the headline geometry: V=1M rows padded, B=64k, D=384,
       bf16 params, pool=512)
Prints per-collective tables on stderr and exactly ONE JSON line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

# self-provision the virtual multi-device CPU mesh BEFORE jax initializes
if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NEG = 5

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "all-reduce-start", "all-gather-start",
    "reduce-scatter-start", "collective-permute-start",
)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def shape_bytes(shape_str: str) -> int:
    """Total bytes of an HLO shape string — handles tuples and layouts:
    ``bf16[65536,384]{1,0}``, ``(f32[8], f32[8])``, ``f32[]`` (scalar)."""
    total = 0
    for dtype, dims in re.findall(r"([a-z]\d+|pred|bf16)\[([0-9,]*)\]",
                                  shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _parse_replica_groups(text: str):
    """Parse the two HLO replica-group syntaxes into a list of id lists:
    explicit ``{{0,1},{2,3}}`` and iota ``[2,4]<=[8]`` /
    ``[4,2]<=[2,2,2]T(2,1,0)`` (reshape iota to the bound dims, transpose by
    the perm, flatten, regroup to the group shape)."""
    m = re.search(r"replica_groups=\{(\{[^=]*?\})\}", text)
    if m:
        return [[int(x) for x in g.split(",") if x.strip() != ""]
                for g in re.findall(r"\{([^{}]*)\}", m.group(1))]
    m = re.search(
        r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?",
        text)
    if m:
        ngroups, gsize = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(x) for x in m.group(4).split(",")])
        return ids.reshape(-1).reshape(ngroups, gsize).tolist()
    return None


def parse_collectives(hlo_text: str, num_data: int, num_model: int) -> list:
    """Walk HLO text; return one row per collective instruction:
    {op, shape, bytes, axis, replica_groups}."""
    from glint_word2vec_tpu.parallel.mesh import classify_replica_groups

    # name -> result shape, for operand-bytes lookup
    shapes = {}
    defline = re.compile(
        r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
        r"(\([^)]*\)|[a-z]\d*[a-z0-9]*\[[^\]]*\](?:\{[^}]*\})?)\s")
    for line in hlo_text.splitlines():
        m = defline.match(line)
        if m:
            shapes[m.group(1)] = m.group(2)

    opline = re.compile(
        r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"
        r"(\([^)]*\)|[a-z]\d*[a-z0-9]*\[[^\]]*\](?:\{[^}]*\})?)\s+"
        r"(" + "|".join(re.escape(o) for o in _COLLECTIVE_OPS) + r")\(([^)]*)\)")
    rows = []
    for line in hlo_text.splitlines():
        m = opline.match(line)
        if not m:
            continue
        _, out_shape, op, operands = m.groups()
        in_bytes = 0
        for name in re.findall(r"%?([\w.\-]+)", operands):
            in_bytes += shape_bytes(shapes.get(name, ""))
        groups = _parse_replica_groups(line)
        if groups is None or not any(groups):
            # empty replica_groups={} = one group over every participant
            axis = "all"
        else:
            axis = classify_replica_groups(num_data, num_model, groups)
        # a size-1 axis makes "all devices" and "the other axis" the same set
        if axis == "all" and num_data == 1 and num_model > 1:
            axis = "model"
        elif axis == "all" and num_model == 1 and num_data > 1:
            axis = "data"
        rows.append({
            "op": op.replace("-start", ""),
            "shape": out_shape,
            "bytes": max(shape_bytes(out_shape), in_bytes),
            "axis": axis,
        })
    return rows


def summarize(rows: list, assembly_rows: int = None,
              assembly_count: int = 1) -> dict:
    by_axis = {}
    for r in rows:
        by_axis[r["axis"]] = by_axis.get(r["axis"], 0) + r["bytes"]
    out = {
        "collectives": rows,
        "count": len(rows),
        "total_bytes": sum(r["bytes"] for r in rows),
        "bytes_by_axis": by_axis,
    }
    if assembly_rows is not None:
        # shard_map schedule claim: the ONLY model-axis collectives are the
        # forward row-assembly psums -> model-axis UPDATE bytes are zero.
        # Computed, not asserted: subtract every model-axis all-reduce whose
        # leading dim is the assembly row count (2·Bl + P; matched on ROWS,
        # not bytes — CPU float normalization can rewrite a bf16 collective
        # to f32, see run()); anything left over is flagged.
        # ``assembly_count``: how many assembly psums the program legitimately
        # carries — 1 for the synchronous step, k for a sync_every=k local-SGD
        # window (its k-step loop is PYTHON-UNROLLED precisely so each
        # in-window step's psum appears in the HLO text and is counted here;
        # a lax.scan body would show its collectives once regardless of trip
        # count and the tabulated bytes would be a lie).
        residual = 0
        matched = 0
        matched_n = 0
        for r in [r for r in rows if r["axis"] == "model"]:
            dims = re.search(r"\[(\d+)", r["shape"])
            if (r["op"] == "all-reduce" and matched_n < assembly_count
                    and dims and int(dims.group(1)) == assembly_rows):
                matched += r["bytes"]
                matched_n += 1
            else:
                residual += r["bytes"]
        out["forward_assembly_bytes"] = matched
        out["forward_assembly_count"] = matched_n
        out["model_axis_update_bytes"] = residual
    return out


def build_geometry(args) -> dict:
    if args.smoke:
        return dict(v=4096, d=64, b=512, pool=128, param_dtype="float32")
    return dict(v=1_000_000, d=384, b=65536, pool=512, param_dtype="bfloat16")


def audit_mesh(geom: dict, shape: tuple, sync_every: int = 1) -> dict:
    """Compile both lowerings at one mesh shape; return their summaries.
    ``sync_every=k > 1`` additionally compiles the local-SGD WINDOW program
    (k owner-local steps + one delta-merge — config.sync_every) and prices
    its per-window data-axis bytes against both k=1 schedules."""
    import jax
    import jax.numpy as jnp

    from glint_word2vec_tpu.ops.sgns import (
        EmbeddingPair, sgns_step_shared_core)
    from glint_word2vec_tpu.ops.sgns_shard import make_shard_map_sgns_step
    from glint_word2vec_tpu.parallel.mesh import (
        make_mesh, pad_vocab_for_sharding)

    nd, nm = shape
    plan = make_mesh(nd, nm)
    v = pad_vocab_for_sharding(geom["v"], nm)
    d, b, pool = geom["d"], geom["b"], geom["pool"]
    pdt = jnp.dtype(geom["param_dtype"])
    cdt = ldt = pdt
    alpha_sds = jax.ShapeDtypeStruct((), jnp.float32, sharding=plan.replicated)
    batch_sds = {
        "centers": jax.ShapeDtypeStruct((b,), jnp.int32, sharding=plan.batch),
        "contexts": jax.ShapeDtypeStruct((b,), jnp.int32, sharding=plan.batch),
        "mask": jax.ShapeDtypeStruct((b,), jnp.float32, sharding=plan.batch),
    }
    negs_sds = jax.ShapeDtypeStruct((pool,), jnp.int32,
                                    sharding=plan.replicated)

    def make_gspmd_step(emb_sharding):
        # the production GSPMD path: core step + the same sharding constraint
        # trainer._build_step applies to the scan carry, metrics elided
        def gspmd_step(params, batch, negatives, alpha):
            new_p, m = sgns_step_shared_core(
                params, batch["centers"], batch["contexts"], batch["mask"],
                negatives, alpha, NEG, "exact", cdt, False, ldt,
                with_metrics=False)
            new_p = jax.lax.with_sharding_constraint(
                new_p, EmbeddingPair(emb_sharding, emb_sharding))
            return new_p, m.pairs
        return gspmd_step

    sm_inner = make_shard_map_sgns_step(
        plan.mesh, NEG, "exact", cdt, ldt, with_metrics=False)

    def shard_map_step(params, batch, negatives, alpha):
        new_p, m = sm_inner(params, batch, negatives, alpha)
        return new_p, m.pairs

    variants = [("gspmd", make_gspmd_step(plan.embedding), plan.embedding),
                ("shard_map", shard_map_step, plan.embedding)]
    if d % nm == 0:
        # the CIKM'16 column layout (embedding_partition='cols'), GSPMD-
        # lowered — audited so PERF.md §7's rows-vs-cols verdict rests on
        # measured bytes for BOTH layouts, not formulas
        variants.append(("gspmd_cols", make_gspmd_step(plan.embedding_cols),
                         plan.embedding_cols))

    out = {}
    for name, fn, emb in variants:
        p_sds = EmbeddingPair(
            jax.ShapeDtypeStruct((v, d), pdt, sharding=emb),
            jax.ShapeDtypeStruct((v, d), pdt, sharding=emb))
        compiled = jax.jit(fn, donate_argnums=(0,)).lower(
            p_sds, batch_sds, negs_sds, alpha_sds).compile()
        rows = parse_collectives(compiled.as_text(), nd, nm)
        fwd = None
        if name == "shard_map":
            fwd = 2 * (b // nd) + pool   # assembly psum row count
        out[name] = summarize(rows, assembly_rows=fwd)
    out["mesh"] = list(shape)
    out["padded_vocab"] = v
    g, s = out["gspmd"]["total_bytes"], out["shard_map"]["total_bytes"]
    out["bytes_ratio_shard_map_over_gspmd"] = (s / g) if g else None

    if sync_every > 1:
        # --- the local-SGD window (config.sync_every=k): ONE program = k
        # owner-local steps + the delta-merge. Its whole point is priced per
        # WINDOW: the window's data-axis bytes replace what a k-step
        # synchronous schedule pays k times ---
        k = sync_every
        ls_inner = make_shard_map_sgns_step(
            plan.mesh, NEG, "exact", cdt, ldt, with_metrics=False,
            sync_every=k)

        def localsgd_window(params, batch, negatives, alphas):
            new_p, m = ls_inner(params, batch, negatives, alphas)
            return new_p, m.pairs

        win_batch_sds = {
            name: jax.ShapeDtypeStruct((k, b), dt,
                                       sharding=plan.batch_stacked)
            for name, dt in (("centers", jnp.int32), ("contexts", jnp.int32),
                             ("mask", jnp.float32))}
        # disjoint per-shard lattices: [k, nd·pool], pool per shard unchanged
        win_negs_sds = jax.ShapeDtypeStruct(
            (k, nd * pool), jnp.int32, sharding=plan.batch_stacked)
        win_alpha_sds = jax.ShapeDtypeStruct(
            (k,), jnp.float32, sharding=plan.replicated)
        p_sds = EmbeddingPair(
            jax.ShapeDtypeStruct((v, d), pdt, sharding=plan.embedding),
            jax.ShapeDtypeStruct((v, d), pdt, sharding=plan.embedding))
        compiled = jax.jit(localsgd_window, donate_argnums=(0,)).lower(
            p_sds, win_batch_sds, win_negs_sds, win_alpha_sds).compile()
        rows = parse_collectives(compiled.as_text(), nd, nm)
        ls = summarize(rows, assembly_rows=2 * (b // nd) + pool,
                       assembly_count=k)
        ls["sync_every"] = k
        # per-WINDOW data-axis bytes vs what each k=1 schedule pays over the
        # same k steps. The acceptance ratio is against the DEFAULT (gspmd)
        # synchronous schedule — "the k=1 schedule" a data-parallel run pays
        # today; the shard_map-baseline ratio is reported beside it (that
        # schedule's per-step payload all_gather is batch-sized, so the dense
        # [Vs, D] merge amortizes against it more slowly).
        win_data = ls["bytes_by_axis"].get("data", 0)
        g_data = out["gspmd"]["bytes_by_axis"].get("data", 0)
        s_data = out["shard_map"]["bytes_by_axis"].get("data", 0)
        ls["window_data_bytes"] = win_data
        ls["window_data_over_gspmd_k1_schedule"] = (
            win_data / (k * g_data) if g_data else None)
        ls["window_data_over_shard_map_k1_schedule"] = (
            win_data / (k * s_data) if s_data else None)
        out["localsgd"] = ls
    return out


def run(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="tiny geometry (the tier-1 wiring)")
    ap.add_argument("--mesh", default="all",
                    help="'NDxNM' (e.g. 2x4) or 'all' (1x8,2x4,4x2,8x1)")
    ap.add_argument("--sync-every", type=int, default=8,
                    help="local-SGD window length k for the 'localsgd' "
                         "variant (config.sync_every; 0/1 = skip the "
                         "variant). The window program is audited per "
                         "WINDOW — k steps + one delta-merge")
    ap.add_argument("--json-out", default="",
                    help="also write the JSON result to this path")
    args = ap.parse_args(argv)

    import jax
    n = len(jax.devices())
    if n < 8:
        raise SystemExit(
            f"need 8 devices (have {n}); run as a script so the CPU mesh "
            "self-provisions, or set --xla_force_host_platform_device_count=8")

    geom = build_geometry(args)
    shapes = ([(1, 8), (2, 4), (4, 2), (8, 1)] if args.mesh == "all"
              else [tuple(int(x) for x in args.mesh.split("x"))])
    result = {"geometry": geom, "meshes": []}
    if geom["param_dtype"] == "bfloat16":
        # the CPU backend's float-normalization pass rewrites bf16 compute
        # (collectives included) to f32, so the audited payloads appear at
        # 4 bytes/element: absolute bytes here are 2x the TPU bf16 wire
        # payloads, UNIFORMLY for both lowerings — the per-axis structure,
        # op counts, and every ratio are dtype-independent
        result["note"] = ("bf16 collectives observed as f32 (CPU float "
                          "normalization); absolute bytes are 2x the TPU "
                          "bf16 payloads, ratios unaffected")
    for shape in shapes:
        log(f"compiling both lowerings at mesh {shape[0]}x{shape[1]} "
            f"(V={geom['v']:,}, B={geom['b']}, D={geom['d']}, "
            f"pool={geom['pool']}, {geom['param_dtype']}) ...")
        res = audit_mesh(geom, shape, sync_every=max(args.sync_every, 1))
        result["meshes"].append(res)
        for name in ("gspmd", "shard_map", "gspmd_cols", "localsgd"):
            if name not in res:
                continue
            s = res[name]
            log(f"  {name:9s} total {s['total_bytes'] / 1e6:10.2f} MB over "
                f"{s['count']} collectives  by-axis: "
                + ", ".join(f"{a}={v / 1e6:.2f} MB"
                            for a, v in sorted(s["bytes_by_axis"].items())))
            for r in s["collectives"]:
                log(f"      {r['op']:20s} {r['axis']:6s} "
                    f"{r['bytes'] / 1e6:10.3f} MB  {r['shape'][:60]}")
        sm = res["shard_map"]
        log(f"  shard_map model-axis UPDATE bytes: "
            f"{sm['model_axis_update_bytes']} "
            f"(forward assembly matched: "
            f"{sm['forward_assembly_bytes'] / 1e6:.2f} MB); "
            f"bytes ratio shard_map/gspmd: "
            f"{res['bytes_ratio_shard_map_over_gspmd']:.3f}"
            if res["bytes_ratio_shard_map_over_gspmd"] is not None else
            "  gspmd emitted no collectives at this mesh")
        if "localsgd" in res:
            ls = res["localsgd"]
            rg = ls["window_data_over_gspmd_k1_schedule"]
            rs = ls["window_data_over_shard_map_k1_schedule"]
            log(f"  localsgd (k={ls['sync_every']}) per-WINDOW data bytes "
                f"{ls['window_data_bytes'] / 1e6:.2f} MB; model-axis UPDATE "
                f"bytes {ls['model_axis_update_bytes']} (assembly psums "
                f"matched: {ls['forward_assembly_count']}); window/k-step "
                f"ratios: vs gspmd k=1 "
                + (f"{rg:.4f}" if rg is not None else "n/a (no data axis)")
                + ", vs shard_map k=1 "
                + (f"{rs:.4f}" if rs is not None else "n/a"))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main(argv=None) -> None:
    result = run(argv)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
