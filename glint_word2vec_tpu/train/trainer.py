"""Synchronous training executor — the TPU-native replacement for the reference's
per-partition async loop (C7, mllib:392-433).

What the reference does with Spark partitions racing Hogwild-style against parameter
servers (2 RPC round-trips per 50-pair minibatch, 1-deep future pipelining, mllib:417-429),
this trainer does as one jitted, donated, sharded step over large fixed-shape batches:

- lr decay keeps the exact reference schedule: ``alpha = lr·(1 − words/total)`` floored at
  ``lr·1e-4``, recomputed from the subsampled-word clock (mllib:405-413), where
  ``total = num_iterations · train_words_count + 1`` (mllib:363).
- the training heartbeat mirrors the reference's every-10k-words log line
  (wordCount/alpha/fPlus, mllib:411-412) and adds loss + throughput.
- mid-training checkpointing (the reference has none — a numIterations run is
  all-or-nothing, SURVEY §5) via ``checkpoint_every_steps``.
- determinism: per-step keys are ``fold_in(root_key, global_step)`` — replacing the
  reference's XORShift-seeded async chaos, which made its results untestable numerically
  (SURVEY §4).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, replace as dc_replace
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import epoch_batches, epoch_batches_cbow
from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.ops.sampler import build_alias_table, sample_negatives_hash
from glint_word2vec_tpu.ops.sgns import (
    EmbeddingPair,
    Stabilizers,
    StepMetrics,
    alpha_schedule,
    cbow_step_core,
    cbow_step_shared_core,
    hot_flush,
    init_embeddings,
    sgns_step_core,
    sgns_step_shared_core,
)
from glint_word2vec_tpu.parallel.distributed import put_global
from glint_word2vec_tpu.parallel.mesh import (
    MeshPlan, make_mesh, pad_dim_to_lanes, pad_vocab_for_sharding)
from glint_word2vec_tpu.train import faults
from glint_word2vec_tpu.train.checkpoint import TrainState, save_model
from glint_word2vec_tpu.train.faults import NonFiniteParamsError

logger = logging.getLogger("glint_word2vec_tpu")


def _pairs_per_kept_token(window: int) -> float:
    """Analytic E[pairs emitted per kept token] under the reference's legacy
    asymmetric window (mllib:381-390): span b = nextInt(window) to the left and
    max(b − 1, 0) to the right. Ignores sentence-boundary clipping, so it
    OVERESTIMATES slightly — every caller (tokens-per-step sizing, heartbeat
    pair estimates, the duplicate-load stability bound) wants the conservative
    direction. Floored at 1e-3 so window=1 (zero expected pairs) never divides
    by zero."""
    b = np.arange(window, dtype=np.float64)
    return max(float(b.mean() + np.clip(b - 1, 0, None).mean()), 1e-3)


def _cbow_examples_per_kept_token(window: int) -> float:
    """Analytic P[a kept token trains a CBOW example] under the legacy
    asymmetric window: the b = nextInt(window) = 0 draw yields zero context
    (and so no example), hence (window−1)/window. Sentence-boundary clipping
    is ignored (slight overestimate — heartbeat display only; the banded feed
    settles exact totals from the scanned metrics at end of run). Floored like
    :func:`_pairs_per_kept_token`."""
    return max((window - 1) / window, 1e-3)


@dataclass
class HeartbeatRecord:
    words: int
    alpha: float
    loss: float
    mean_f_pos: float
    pairs_per_sec: float
    # --- extended telemetry (round 11, docs/observability.md). Defaults keep
    # pre-round-11 constructors valid; every field lands in the JSONL sink ---
    global_step: int = -1
    host_wait_s: float = 0.0       # host-side wait since the previous heartbeat
    dispatch_s: float = 0.0        # dispatch time since the previous heartbeat
    norms: Optional[dict] = None   # fused health-probe channels (obs/probe.py)
                                   # when the probe ran this round: per-matrix
                                   # max/mean/p99 row norm + frac_over, plus
                                   # update_mag (delta of mean_norm between
                                   # consecutive probes — a cheap update-
                                   # magnitude proxy needing no extra pass)
    # --- mid-run recovery state (round 13): before this, only run_start/
    # run_end carried them — telemetry_tail and the blackbox had to replay
    # the whole sink file to know whether a live run had already recovered
    recoveries: int = 0            # recoveries performed so far this fit
    lr_scale: float = 1.0          # effective lr multiplier this heartbeat's
                                   # chunk actually DISPATCHED under
    phases: Optional[dict] = None  # per-phase log2 duration histograms over
                                   # this heartbeat window (obs/phases.py)
                                   # when time attribution is armed
    # --- local-SGD window metadata (config.sync_every, docs/sharding.md
    # §Local-SGD): which merge cadence this run dispatched under and how many
    # delta-merge rounds have completed — a consumer replaying telemetry can
    # tell a merged carry from a mid-window one would-be state (there is
    # none: dispatch boundaries ARE merge boundaries, which is exactly what
    # these fields let it verify)
    sync_every: int = 1            # merge cadence (1 = fully synchronous)
    merge_round: int = -1          # completed delta-merge rounds at this
                                   # heartbeat (global_step // sync_every);
                                   # -1 when sync_every == 1 (no windows)


class _threaded_iter:
    """Run a generator on a background thread with a bounded buffer.

    Exceptions raised by the generator re-raise at the consumer's ``next()``.
    ``close()`` (also called on garbage collection) stops the producer promptly even
    if it is blocked on a full buffer.
    """

    _DONE = object()

    def __init__(self, gen, maxsize: int):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=maxsize)
        self._stop = threading.Event()
        self._queue_mod = queue

        def put_checked(item) -> bool:
            """Bounded put that gives up once the consumer signals stop — every put
            (including the terminal DONE/exception) must be preemptible or an
            abandoned iterator leaks a blocked producer thread."""
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def run():
            try:
                for item in gen:
                    if not put_checked(item):
                        return
                put_checked(self._DONE)
            except BaseException as e:  # noqa: BLE001 — relayed to the consumer
                put_checked(e)

        self._thread = threading.Thread(
            target=run, daemon=True, name="glint-batch-producer")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is self._DONE:
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        try:  # unblock a producer waiting on a full queue
            while True:
                self._q.get_nowait()
        except self._queue_mod.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _one_ahead_iter:
    """Run a generator on a background thread exactly ONE item ahead of the
    consumer, under an explicit ``ack()`` ticket: after delivering item r the
    producer does not start producing item r+1 until the consumer acks r.

    This is the multi-process staging primitive (PERF.md §10). Producing a
    round launches device programs (the next round's allgather, the staging
    touch) and consuming one launches more (the step dispatch, heartbeat
    fetches, checkpoint collectives). Cross-host deadlock-freedom requires
    every process to enqueue collective programs in the same order, so the
    ticket serializes the two threads into ONE deterministic per-process
    launch order — [stage_r, dispatch_r + bookkeeping_r, stage_{r+1}, ...] —
    identical on every process because both sides are pure functions of
    allgathered values. The overlap win survives: stage_{r+1}'s HOST work
    (allgather result decode, feed assembly, device-put DMA) runs while chunk
    r executes on device.

    Generator exceptions re-raise at the consumer's ``next()``; ``close()``
    unblocks and joins the producer."""

    _DONE = object()

    def __init__(self, gen):
        import queue
        import threading

        self._out: "queue.Queue" = queue.Queue(maxsize=1)
        self._ack: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._queue_mod = queue

        def put_checked(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._out.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def wait_ack() -> bool:
            while not self._stop.is_set():
                try:
                    self._ack.get(timeout=0.1)
                    return True
                except queue.Empty:
                    continue
            return False

        def run():
            it = iter(gen)
            try:
                first = True
                while True:
                    # the ack gate sits BEFORE producing item r+1 (before
                    # re-entering the generator), so stage r+1's program
                    # launches come after the consumer's round-r launches
                    # everywhere
                    if not first and not wait_ack():
                        return
                    first = False
                    try:
                        item = next(it)
                    except StopIteration:
                        put_checked(self._DONE)
                        return
                    if not put_checked(item):
                        return
            except BaseException as e:  # noqa: BLE001 — relayed to the consumer
                put_checked(e)

        self._thread = threading.Thread(
            target=run, daemon=True, name="glint-round-stager")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._out.get()
        if item is self._DONE:
            self._stop.set()
            raise StopIteration
        if isinstance(item, BaseException):
            self._stop.set()
            raise item
        return item

    def ack(self) -> None:
        self._ack.put(None)

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._out.get_nowait()
        except self._queue_mod.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class Trainer:
    """Owns the sharded embedding pair and runs the synchronous SGNS/CBOW loop."""

    def __init__(
        self,
        config: Word2VecConfig,
        vocab: Vocabulary,
        plan: Optional[MeshPlan] = None,
        params: Optional[EmbeddingPair] = None,
        train_state: Optional[TrainState] = None,
    ):
        self.config = config
        self.vocab = vocab
        # vocab-scaled AUTO pool (EVAL.md round-5): config resolved the pool
        # without seeing the vocabulary; at > 500k words the measured safe
        # load band tightens 600 -> 160, so a still-AUTO pool re-resolves
        # upward here. Must run before anything reads config.negative_pool.
        self._resolve_vocab_scaled_pool()
        config = self.config
        if plan is None:
            shape = config.mesh_shape or (config.num_data_shards, config.num_model_shards)
            n_avail = len(jax.devices())
            if shape[0] * shape[1] > n_avail:
                if config.mesh_shape is not None:
                    raise ValueError(
                        f"mesh_shape {config.mesh_shape} needs "
                        f"{shape[0] * shape[1]} devices but only {n_avail} are available")
                logger.warning(
                    "requested %dx%d shards exceed %d available devices; "
                    "falling back to a single-device mesh", shape[0], shape[1], n_avail)
                shape = (1, 1)
            plan = make_mesh(*shape)
        self.plan = plan
        # design verdict, not a TODO (PERF.md §7): rows is the production
        # layout — it divides the per-update-row scatter bound by the mesh
        # size and owns whole rows for shard checkpoints; cols stays an
        # experimental single-host option for the per-pair-sampling regime.
        # Two guards: the pure-config half (whose construction twin lives in
        # config.__post_init__ — refusal parity, graftlint R8/graftcheck)
        # and the runtime half (process count, which config cannot see).
        if config.embedding_partition == "cols" and config.sharded_checkpoint:
            raise ValueError(
                "embedding_partition='cols' is experimental and single-host only: "
                "row-shards checkpoints need each process to own whole rows "
                "(design rationale: PERF.md §7); use 'rows'")
        if config.embedding_partition == "cols" and jax.process_count() > 1:
            raise ValueError(
                "embedding_partition='cols' is experimental and single-host only: "
                "multi-process runs need each process to own whole rows "
                "(design rationale: PERF.md §7); use 'rows'")
        if (config.step_lowering == "shard_map"
                and config.pairs_per_batch % plan.num_data):
            raise ValueError(
                f"step_lowering='shard_map' splits the batch over the data "
                f"axis with static shapes: pairs_per_batch="
                f"{config.pairs_per_batch} must be divisible by num_data="
                f"{plan.num_data}")
        self.padded_vocab = pad_vocab_for_sharding(vocab.size, plan.num_model)
        # Pad the minor dim to the TPU lane width: D=300 rows are misaligned and row
        # gathers/scatters measurably slower than at 384. Padded columns are zero-init and
        # receive zero gradient (all products with the zero columns vanish), so they stay
        # zero and are sliced off on export.
        self.padded_dim = pad_dim_to_lanes(
            config.vector_size, config.pad_vector_to_lanes)
        self._emb_sharding = (plan.embedding_cols
                              if config.embedding_partition == "cols"
                              else plan.embedding)
        if config.embedding_partition == "cols" and self.padded_dim % plan.num_model:
            raise ValueError(
                f"embedding_partition='cols' needs the padded vector dim "
                f"{self.padded_dim} divisible by num_model={plan.num_model}")
        self.table = build_alias_table(vocab.counts, config.sample_power,
                                       workers=config.io_workers)
        # replicated device copies, passed into the jitted chunk as ARGUMENTS every
        # dispatch — closure-captured constants take a catastrophically slow gather
        # path on TPU (see ops/prng.py)
        tabs = put_global(plan.replicated,
                          {"prob": np.asarray(self.table.prob),
                           "alias": np.asarray(self.table.alias)})
        self._table_prob = tabs["prob"]
        self._table_alias = tabs["alias"]
        self._root_key = jax.random.key(config.seed)
        if params is None:
            params = init_embeddings(
                self.padded_vocab, config.vector_size,
                jax.random.fold_in(self._root_key, 0),
                dtype=jnp.dtype(config.param_dtype))
        if (isinstance(params.syn0, jax.Array)
                and params.syn0.shape == (self.padded_vocab, self.padded_dim)
                and params.syn0.dtype == jnp.dtype(config.param_dtype)
                and params.syn0.sharding.is_equivalent_to(self._emb_sharding, 2)):
            # already padded and placed (e.g. streamed in by load_params_into_plan)
            self.params = params
        else:
            params = self._pad_params(params)
            placed = put_global(
                self._emb_sharding,
                # every process computes the same deterministic init (same key), so
                # the callback assembly is consistent across hosts
                {"syn0": np.asarray(params.syn0), "syn1": np.asarray(params.syn1)})
            self.params = EmbeddingPair(placed["syn0"], placed["syn1"])
        self.state = train_state or TrainState()
        # additive checkpoint-metadata keys (train/checkpoint.py
        # extra_metadata) merged into EVERY save this trainer performs —
        # periodic and final alike. Owned by drivers above the trainer (the
        # continual loop records its vocab_lineage chain here); empty = the
        # pre-continual metadata, byte-identical.
        self.extra_checkpoint_meta: dict = {}
        # Chunk transfer layout (see chunk_stream in fit): pairs ride in ONE packed
        # array per dispatch — through a narrow host→device link the per-transfer
        # overhead dominates, so fewer/larger puts win. Indices ship as uint16 when the
        # vocab allows (halves feed bytes; upcast on device is free).
        self._pair_dtype = np.uint16 if self.padded_vocab <= 65536 else np.int32
        if config.cbow:
            self._chunk_shardings = {"centers": plan.batch_stacked,
                                     "contexts": plan.ctx_stacked,
                                     "nctx": plan.batch_stacked}
        else:
            self._chunk_shardings = {"pairs": plan.pairs_stacked}
        # Sharded input feed (the repartition analog, mllib:345): each process
        # generates only its 1/N of the sentence stream; the global batch is assembled
        # from per-process segments by a per-round allgather (see _fit_sharded). The
        # batch's B axis is composed of N per-process segments, each prefix-masked.
        self._feed_segments = 1
        if config.shard_input and jax.process_count() > 1:
            n = jax.process_count()
            if config.pairs_per_batch % n:
                raise ValueError(
                    f"shard_input=True needs pairs_per_batch divisible by the "
                    f"process count ({config.pairs_per_batch} % {n} != 0)")
            self._feed_segments = n
        # On-device pair generation (ops/pairgen.py): host ships raw token blocks,
        # the jitted step subsamples + windows them itself — same hash lattice, so
        # the pair stream is bit-identical to the host pipeline's.
        if config.device_pairgen:
            if config.cbow:
                raise ValueError("device_pairgen is skip-gram only (CBOW batches "
                                 "are grouped windows the device generator does "
                                 "not produce)")
            if config.use_pallas:
                raise ValueError("device_pairgen is not supported with use_pallas")
            if config.window == 1:
                raise ValueError(
                    "device_pairgen with window=1 emits no pairs at all under the "
                    "reference's legacy asymmetric window (b = nextInt(1) = 0 "
                    "always, and the right bound is exclusive) — use window >= 2")
            self._init_token_block_feed(
                "device_pairgen",
                config.tokens_per_step or self._auto_tokens_per_step())
            # ops/pairgen._cumsum_i32 is exact only while prefix sums stay below
            # 2^24 (f32 mantissa); the largest sum is T * (2*window - 1) pair counts
            if self._tokens_per_step * (2 * config.window - 1) >= 1 << 24:
                raise ValueError(
                    f"tokens_per_step={self._tokens_per_step} with window="
                    f"{config.window} overflows the device generator's exact-f32 "
                    f"prefix-sum bound (T * (2*window - 1) must stay below 2^24); "
                    "lower tokens_per_step or split the batch")
        # Banded CBOW (config.cbow_update="banded", ops/cbow_banded.py): rides
        # the same token-block feed plumbing as device_pairgen — the host packs
        # kept-token blocks, the jitted step derives window draws from the hash
        # lattice — but with a ±window halo overlap at block cuts
        # (pipeline.pack_halo_token_blocks) so chunk-edge windows are exact.
        # The config-level selection matrix already refused unsupported
        # combinations (duplicate_scaling/pool=0/pallas/window=1).
        self._banded_cbow = bool(config.cbow and config.cbow_update == "banded")
        self._block_halo = 0
        if self._banded_cbow:
            self._block_halo = config.window
            # core slots per segment block = examples per segment per step
            self._init_token_block_feed(
                "cbow_update='banded'",
                config.pairs_per_batch // self.plan.num_data
                + 2 * self._block_halo)
        # bound the duplicate-overload divergence channel (EVAL.md measured
        # boundary): auto-lower an AUTO subsample_ratio or refuse an explicit
        # unstable one. Idempotent — the device-feed path already resolved it
        # before deriving its keep probabilities above.
        self._resolve_duplicate_channel()
        # resume continues the (seed, counter) PRNG lattice where the checkpoint left
        # off — restarting at 0 would redraw the run's opening negative-sample stream
        self.global_step = self.state.global_step
        self.pairs_trained = 0.0  # real (unmasked) pairs dispatched over this run
        from collections import deque
        # bounded ring (config.heartbeat_ring): pre-round-11 this was an
        # unbounded list — weeks-long runs leaked one record per heartbeat.
        # The full history persists in the telemetry sink file instead.
        self.heartbeats: "deque" = deque(maxlen=config.heartbeat_ring)
        # non-finite guardrail state (config.nonfinite_policy): a ring of the
        # last K good device-resident param snapshots plus small jitted probes,
        # all built lazily — a policy="none" run pays nothing
        self._snapshot_ring: "deque" = deque(maxlen=config.rollback_history)
        self.rollbacks_performed = 0
        # stabilization + auto-recovery state (docs/robustness.md escalation
        # ladder). _stabilizers starts from the config knobs but is TRAINER
        # state: a norm_watch="recover" firing may engage max_row_norm
        # mid-run (the step functions are rebuilt then). _lr_scale multiplies
        # the dispatched alphas (see _stage_dispatch_meta) — recovery backs
        # it off by config.recover_lr_backoff per firing; it persists across
        # fit() calls on this trainer (a recovered run's mitigation should
        # outlive the fit that needed it), while the recovery BUDGET resets
        # per fit like max_rollbacks.
        self._stabilizers = Stabilizers(
            max_row_norm=config.max_row_norm,
            update_clip=config.update_clip,
            row_l2=config.row_l2)
        # cross-step hot-row accumulation (config.hot_rows, ISSUE 14 /
        # PERF.md §11): K clamped to the REAL vocabulary — config cannot see
        # it, and the padding rows past vocab.size are never touched by
        # construction so a slab covering them would waste VMEM. The flush
        # cadence resolves AUTO (0) to once per dispatch chunk; config
        # already refused explicit values that do not divide the chunk.
        self._hot_rows = 0
        self._hot_flush = 0
        if config.hot_rows:
            if len(plan.mesh.devices.flat) > 1:
                # runtime twin of the config-side multi-shard refusal (the
                # plan's device count is state config cannot see — same
                # split as the pallas multi-device guard)
                raise ValueError(
                    "hot_rows is the single-chip step restructuring "
                    "(PERF.md §11) and the mesh plan has "
                    f"{len(plan.mesh.devices.flat)} devices; use a "
                    "single-device plan or hot_rows=0")
            self._hot_rows = int(min(config.hot_rows, vocab.size))
            self._hot_flush = (config.hot_flush_every
                               or config.steps_per_dispatch)
        self._lr_scale = 1.0
        self.recoveries_performed = 0
        self._health_fn: Optional[Callable] = None  # fused probe (obs/probe.py)
        self._copy_params_fn: Optional[Callable] = None
        self._poison_fn: Optional[Callable] = None  # scripted NaN injection
        self._scale_fn: Optional[Callable] = None   # scripted finite blowup
        # run-telemetry layer (docs/observability.md) — all lazy/no-op when
        # config.telemetry_path is empty and norm_watch is "off"
        from glint_word2vec_tpu.obs.spans import default_tracer
        from glint_word2vec_tpu.obs.watch import NormWatchdog
        self._tracer = default_tracer()
        self._telemetry = None
        if config.telemetry_path:
            from glint_word2vec_tpu.obs.sink import TelemetrySink
            self._telemetry = TelemetrySink(
                config.telemetry_path,
                rotate_bytes=config.telemetry_rotate_bytes)
        # flight recorder (obs/blackbox.py): exists only with telemetry on —
        # the dump path derives from telemetry_path. Feeding it is a deque
        # append per dispatch round; the dump itself only runs on fit death.
        self._blackbox = None
        if self._telemetry is not None:
            from glint_word2vec_tpu.obs.blackbox import FlightRecorder
            self._blackbox = FlightRecorder(
                config.telemetry_path + ".blackbox.json",
                config.blackbox_ring)
        # per-phase host time attribution (obs/phases.py): armed whenever
        # anything consumes it — the sink (heartbeat/run_end rollups) or the
        # live status endpoint. Disabled adds cost one attribute check.
        from glint_word2vec_tpu.obs.phases import PhaseAccumulator
        observing = self._telemetry is not None or config.status_port > 0
        self._phases = PhaseAccumulator(enabled=observing)
        self._statusd = None                 # obs/statusd.py, fit-scoped
        self._prev_sigterm = None            # saved handler while fit runs
        self._sigterm_installed = False      # see _install_run_signals
        # arm (or DISARM) the process-wide tracer for this trainer — at
        # construction, not only at fit start: the fit paths build their feed
        # iterators before _start_run_bookkeeping runs, and the producer
        # spans must observe the right state from the start. Disarming
        # matters as much as arming: a telemetry-off trainer after a
        # telemetry-on one in the same process (the overhead A/B's off arm)
        # must not keep recording spans into the shared ring. The phase
        # accumulator attaches under the same rule (spans tee durations into
        # it — obs/spans.py _PHASE_OF).
        self._tracer.configure(enabled=observing)
        self._tracer.attach_phases(self._phases if observing else None)
        self.norm_watchdog = NormWatchdog(
            config.norm_watch, config.norm_watch_threshold,
            config.norm_watch_max, config.norm_watch_frac)
        self._last_probe_channels: Optional[dict] = None
        # At most ONE collective-bearing program may be in flight on a
        # multi-device CPU mesh: XLA:CPU collectives rendezvous across
        # per-device threads of a bounded shared pool, so when a SECOND
        # program reaches its collectives while the first is still at a
        # rendezvous, the two runs' blocked participants can starve each
        # other and everything stops at 0% CPU. Observed live on the forced
        # 8-device mesh (either step lowering, ~200-dispatch fits): the
        # racers were the producer-thread feed-touch program
        # (_stage_to_device — its cross-shard reduction lowers to
        # collectives; now skipped on this backend) and the finiteness probe
        # (now dispatched only after draining the carry). This flag guards
        # both and gates _after_dispatch, which drains the carry after every
        # chunk so the invariant holds for the dispatch pipeline itself.
        # TPU/GPU execute programs in launch order on the device stream — no
        # gate, pipelining untouched.
        self._sync_collectives = (
            jax.default_backend() == "cpu" and plan.mesh.devices.size > 1)
        self._step_fn = self._build_step()
        # fast twin (metrics elided) for the shared-pool paths (skip-gram and
        # CBOW): the paths whose loss side-channel is an extra full [B, pool]
        # pass (PERF.md §4); the CBOW+duplicate_scaling and per-pair paths
        # keep full metrics (their loss chains are not the measured slice)
        self._step_fn_fast = (
            self._build_step(with_metrics=False)
            if (self.config.negative_pool > 0 and not self.config.use_pallas
                and not (self.config.cbow and self.config.duplicate_scaling))
            else self._step_fn)

    # -- setup -------------------------------------------------------------------------

    def _init_token_block_feed(self, feature: str, tokens_per_step: int) -> None:
        """Shared feed setup of the two token-block feeds (device_pairgen and
        banded CBOW): multi-process segment-ownership checks, duplicate-channel
        resolution BEFORE keep-probability derivation (an AUTO subsample may be
        lowered there; feature-specific shape errors fire before this runs),
        the replicated keep table, T, and the chunk shardings. One owner so the
        two feeds cannot drift on these invariants."""
        config = self.config
        plan = self.plan
        if jax.process_count() > 1:
            if not config.shard_input:
                raise ValueError(
                    f"{feature} with multiple processes requires "
                    "shard_input=True (each process packs token blocks for "
                    "its own data segments; a replicated token feed would "
                    "have every process regenerate everything)")
            if plan.num_data % jax.process_count():
                raise ValueError(
                    f"{feature} across {jax.process_count()} processes "
                    f"needs the mesh data degree ({plan.num_data}) "
                    "divisible by the process count — each process produces "
                    "num_data/process_count token segments")
        Sd = plan.num_data
        if config.pairs_per_batch % Sd:
            raise ValueError(
                f"{feature} needs pairs_per_batch divisible by the data-"
                f"parallel degree ({config.pairs_per_batch} % {Sd} != 0)")
        self._resolve_duplicate_channel()
        from glint_word2vec_tpu.data.pipeline import keep_probabilities
        keep = keep_probabilities(
            self.vocab.counts, self.vocab.train_words_count,
            self.config.subsample_ratio).astype(np.float32)
        self._keep_host = keep
        kp = np.zeros(self.padded_vocab, np.float32)
        kp[:self.vocab.size] = keep
        self._keep_prob_dev = put_global(plan.replicated, {"k": kp})["k"]
        self._tokens_per_step = tokens_per_step
        self._chunk_shardings = {"tokens": plan.tokens_stacked,
                                 "starts": plan.tokens_stacked,
                                 "obase": plan.tokens_stacked}

    def _auto_tokens_per_step(self) -> int:
        """Token slots per step for the device pair generator: targets ~93% pair-slot
        fill from the analytic per-kept-token pair rate E[window span] (boundary
        clipping at sentence edges is ignored, which *overestimates* the rate, so the
        realized fill lands safely below target instead of overflowing). A step's
        actual pair count concentrates tightly (std ≈ √T window-draw noise, <1% of B),
        so overflow drops stay rare; the trainer counts and reports them."""
        cfg = self.config
        # the packer subsamples host-side, so shipped tokens are KEPT tokens
        rate = _pairs_per_kept_token(cfg.window)
        T = int(np.ceil(0.93 * cfg.pairs_per_batch / self.plan.num_data / rate))
        return max(T, 64)

    def _pad_params(self, params: EmbeddingPair) -> EmbeddingPair:
        def pad(a):
            a = jnp.asarray(a)
            row_pad = self.padded_vocab - a.shape[0]
            col_pad = self.padded_dim - a.shape[1]
            if row_pad or col_pad:
                a = jnp.pad(a, ((0, row_pad), (0, col_pad)))
            return a

        return EmbeddingPair(syn0=pad(params.syn0), syn1=pad(params.syn1))

    def _stability_warnings(self, check_pool: bool = True) -> None:
        """Large synchronous batches can diverge through two per-step row-overload
        channels the reference's tiny async minibatches never hit (measured, EVAL.md):

        - POOL load ``B·n/P``: every pool row absorbs the negative gradient of all B
          pairs scaled by n/P. B=64k/P=64 (load 5120) trains to NaN at lr 0.025; the
          same run at P=256 (load 1280) is stable with the best quality of the sweep.
          The config default auto-scales the pool to load ≤ 600, so the generic
          warning fires only on explicit pool choices — but the round-5
          LARGE-VOCAB advisory (load > 300 at vocab > 500k, a measured finite-
          blowup region) also covers the auto-scaled default: at large
          vocabularies the default IS inside the measured danger zone.
        - DUPLICATE load ``B·max_word_share``: a frequent word's context occurrences
          scatter-add summed updates. With no subsampling the top Zipf word is ~1% of
          pairs (~650 summed updates at B=64k) and training explodes even at small
          pool loads; frequency subsampling (≈1e-4) or duplicate_scaling bounds it.
          This channel also hits the per-pair (negative_pool=0) paths — they get
          ``check_pool=False``.
        """
        cfg = self.config
        if cfg.duplicate_scaling:
            return  # mean-update semantics bound both channels by construction
        pool = cfg.negative_pool if cfg.negative_pool > 0 else 64  # pallas substitute
        pool_load = (cfg.pairs_per_batch * cfg.negatives / pool if check_pool
                     else 0.0)
        if pool_load > 300 and self.vocab.size > 500_000:
            # large-vocab advisory (EVAL.md round-5 ladder) — takes precedence
            # over the generic >2000 warning, whose "keep the load ~1300"
            # advice sits deep inside the measured large-vocab blowup region.
            # Mechanism: at 1.6M vocab a word serves in the pool only ~2x per
            # run, so each service's load-sized summed update is never
            # re-corrected — measured FINITE norm blowup (purity 0.99 -> 0.14,
            # no NaN) at load 640 over 120M words; load 160 (pool 2048) fixed
            # that collapse at the same lr and tames norm growth ~8x at 240M
            # (it delays the channel rather than eliminating it — EVAL.md).
            # The load <= 600 auto-rule is calibrated at 90k vocab; grow the
            # pool for large-vocabulary long runs.
            logger.warning(
                "negative-pool load %.0f with a %d-word vocabulary: large-vocab "
                "long runs measured a finite norm blowup in this region "
                "(EVAL.md round-5 ladder — purity collapse without NaN at load "
                "640; load 160 fixed that collapse and tames norm growth on "
                "longer runs); consider negative_pool >= %d (an AUTO pool "
                "scales itself to load <= 160 past 500k vocab — this one was "
                "set explicitly), or the stabilizer/watchdog knobs "
                "(max_row_norm, norm_watch='recover' — docs/robustness.md)",
                pool_load, self.vocab.size,
                128 * (-(-cfg.pairs_per_batch * cfg.negatives // (160 * 128))))
        elif pool_load > 2000:
            logger.warning(
                "pairs_per_batch*negatives/negative_pool = %.0f > 2000: pool-row "
                "updates this large can diverge at default learning rates — scale "
                "negative_pool with the batch (e.g. %d) to keep the load ~1300 "
                "(EVAL.md)", pool_load,
                max(64, int(cfg.pairs_per_batch * cfg.negatives / 1300)))
        dup_load = self._duplicate_load(cfg.subsample_ratio)
        if dup_load > 300:
            logger.warning(
                "expected duplicates of the most frequent word per %d-pair batch "
                "= %.0f > 300: summed scatter updates this dense can diverge — "
                "set subsample_ratio (~1e-4, recommended) or "
                "duplicate_scaling=True, or shrink pairs_per_batch (EVAL.md)",
                cfg.pairs_per_batch, dup_load)
        elif pool_load > 1000 and dup_load > 150:
            # the channels COMPOUND on frequent syn1 rows over long runs: B=64k/P=256
            # (pool 1280, dups ~260 — neither alone past its threshold) was stable on
            # a 17M-word corpus but NaN'd at 60M; either channel halved holds (EVAL.md)
            logger.warning(
                "pool load %.0f and top-word duplicate load %.0f are each below "
                "their individual divergence thresholds but compound on frequent "
                "rows over long runs (measured NaN at 60M words, EVAL.md) — for "
                "long runs grow negative_pool (load <= ~600) or shrink "
                "pairs_per_batch", pool_load, dup_load)

    def _duplicate_load(self, subsample_ratio: float) -> float:
        """Expected in-batch duplicates of the most frequent word under the given
        subsample ratio — the divergence channel's driving quantity (EVAL.md)."""
        from glint_word2vec_tpu.data.pipeline import keep_probabilities
        cfg = self.config
        keep = keep_probabilities(
            self.vocab.counts, self.vocab.train_words_count, subsample_ratio)
        eff = np.asarray(self.vocab.counts, np.float64) * keep
        s = float(eff.sum())
        if s <= 0.0:
            return 0.0
        # a batch cannot hold more REAL pairs than one epoch supplies — on
        # corpora smaller than pairs_per_batch the batch is mostly mask padding
        real_pairs = min(float(cfg.pairs_per_batch),
                         s * _pairs_per_kept_token(cfg.window))
        # NB: a max(s, 1.0) floor on the denominator would deflate the SHARE
        # whenever strong subsampling drives the total effective count below 1
        # (the share is scale-free; only s == 0 needs guarding)
        return float(eff.max()) / s * real_pairs

    # the measured NaN boundary is ~300 expected top-word duplicates per batch
    # (EVAL.md round-4 addendum: 336 trains to NaN at 60M words); auto-lowering
    # targets 250 for margin under the run-to-run corpus variation
    _DUP_LOAD_REFUSE = 300.0
    _DUP_LOAD_TARGET = 250.0

    def _resolve_duplicate_channel(self) -> None:
        """Bound the duplicate-overload channel at construction, like the pool
        channel's auto-sizing (config.py): an AUTO subsample_ratio is lowered
        until the expected top-word duplicates per batch fall under the measured
        divergence boundary; an explicit ratio past the boundary is REFUSED
        (config.allow_unstable overrides to the old warn-only behavior). The
        reference never faces this channel — its async 50-pair minibatches
        interleave a frequent word's updates instead of summing them
        (mllib:417-429)."""
        cfg = self.config
        if cfg.duplicate_scaling:
            return  # mean-update semantics bound the channel by construction
        load = self._duplicate_load(cfg.subsample_ratio)
        if load <= self._DUP_LOAD_REFUSE:
            return
        if not getattr(cfg, "_auto_subsample", False):
            if cfg.allow_unstable:
                return  # _stability_warnings still names the danger at fit time
            raise ValueError(
                f"expected duplicates of the most frequent word per "
                f"{cfg.pairs_per_batch}-pair batch = {load:.0f} exceed the "
                f"measured divergence boundary (~{self._DUP_LOAD_REFUSE:.0f}: "
                f"summed scatter updates this dense trained to NaN at 60M words, "
                f"EVAL.md) with subsample_ratio={cfg.subsample_ratio}. Lower "
                f"subsample_ratio (~1e-4), set duplicate_scaling=True, shrink "
                f"pairs_per_batch, or set allow_unstable=True to proceed anyway")
        # AUTO ratio: binary-search the largest ratio meeting the target load
        # (smaller ratio = stronger subsampling = fewer top-word duplicates)
        lo, hi = 1e-12, cfg.subsample_ratio
        if self._duplicate_load(lo) > self._DUP_LOAD_TARGET:
            if cfg.allow_unstable:
                return  # _stability_warnings still names the danger at fit time
            raise ValueError(
                f"the duplicate-overload channel cannot be bounded by subsampling "
                f"alone on this corpus (top-word duplicates per "
                f"{cfg.pairs_per_batch}-pair batch stay > "
                f"{self._DUP_LOAD_TARGET:.0f} at any ratio — tiny vocabulary?); "
                f"set duplicate_scaling=True, shrink pairs_per_batch, or set "
                f"allow_unstable=True for a short toy run")
        for _ in range(60):
            mid = (lo * hi) ** 0.5  # geometric: the scale spans many decades
            if self._duplicate_load(mid) > self._DUP_LOAD_TARGET:
                hi = mid
            else:
                lo = mid
        logger.warning(
            "auto subsample_ratio lowered 1e-3 -> %.3g: at pairs_per_batch=%d "
            "this corpus's most frequent word would otherwise see ~%.0f summed "
            "duplicate updates per batch, past the measured divergence boundary "
            "(~%.0f, EVAL.md); pass subsample_ratio explicitly to pin a value",
            lo, cfg.pairs_per_batch, load, self._DUP_LOAD_REFUSE)
        self.config = cfg.replace(subsample_ratio=lo)
        # replace() re-derives a still-AUTO pool with the CONFIG-level load
        # rule (<= 600 — config cannot see the vocabulary), which would
        # silently revert a vocab-scaled enlargement already applied at
        # __init__; re-apply the large-vocab rule so the auto-lowered-
        # subsample config keeps the safe pool (graftcheck-review finding)
        self._resolve_vocab_scaled_pool()

    # Vocab-scaled AUTO pool rule, provenance EVAL.md round-5 ladder: the
    # config-time load <= 600 auto-rule was calibrated at 90k vocab, where
    # every pool row re-serves (and is re-corrected) thousands of times per
    # run. At 1.6M vocab a word serves in the pool only ~2x per run, so each
    # service's load-sized summed update is never re-corrected — measured
    # FINITE norm blowup (purity 0.99 -> 0.14, NO NaN) at load 640 over 120M
    # words; load 160 (pool 2048) fixed that collapse at the same lr and
    # tamed norm growth ~8x at 240M words. The boundary between the regimes
    # is taken at 500k (the construction-time advisory's threshold since
    # round 5); between 90k and 500k no collapse was ever measured at load
    # <= 600.
    _LARGE_VOCAB_BOUNDARY = 500_000
    _LARGE_VOCAB_SAFE_LOAD = 160.0

    def _resolve_vocab_scaled_pool(self) -> None:
        """Re-resolve a still-AUTO shared pool for the vocabulary the config
        never saw: once vocab.size > 500k, grow the pool until the load
        B·n/P sits inside the measured large-vocab safe band (<= 160,
        provenance above), rounded up to the 128-lane MXU tile. Explicit
        pools are NEVER changed — `_stability_warnings` names the danger
        instead — and auto-ness is preserved on the replaced config, so
        ``replace()``/``from_dict`` re-resolution semantics are intact (a
        later geometry change re-derives the pool from -1 as before)."""
        cfg = self.config
        if not getattr(cfg, "_auto_pool", False) or cfg.negative_pool <= 0:
            return
        if self.vocab.size <= self._LARGE_VOCAB_BOUNDARY:
            return
        load = cfg.pairs_per_batch * cfg.negatives / cfg.negative_pool
        if load <= self._LARGE_VOCAB_SAFE_LOAD:
            return
        p_min = -(-cfg.pairs_per_batch * cfg.negatives
                  // int(self._LARGE_VOCAB_SAFE_LOAD))
        pool = max(128, 128 * (-(-p_min // 128)))
        logger.warning(
            "auto negative_pool %d -> %d: a %d-word vocabulary puts the "
            "resolved pool load %.0f inside the measured large-vocab finite-"
            "blowup region (EVAL.md round-5: collapse at load 640, fixed at "
            "160); pass negative_pool explicitly to pin a value",
            cfg.negative_pool, pool, self.vocab.size, load)
        new_cfg = cfg.replace(negative_pool=pool)
        new_cfg._auto_pool = True  # still AUTO — geometry changes re-derive
        self.config = new_cfg

    def _build_step(self, with_metrics: bool = True) -> Callable:
        """Build the jitted chunk function. ``with_metrics=False`` builds the
        fast twin of the shared-pool paths (skip-gram and CBOW):
        loss/mean_f_pos elided (one fewer full [B, P] pass, ~0.3 ms at the
        headline shape — PERF.md §4), pairs kept exact. The trainer dispatches
        the fast twin for chunks no heartbeat will sample (see
        _dispatch_step_fn); both twins share the same update math, so the
        trained parameters are bit-identical."""
        cfg = self.config
        quiet = not with_metrics  # the full build already warned at __init__
        compute_dtype = jnp.dtype(cfg.compute_dtype)
        logits_dtype = jnp.dtype(cfg.logits_dtype)
        # in-step stabilizers: trainer state, not raw config — a
        # norm_watch="recover" firing may have engaged max_row_norm since
        # construction (the rebuild path through _perform_recovery). None
        # when all off, so the default step compiles bit-identical to the
        # pre-stabilizer step.
        stab = self._stabilizers if self._stabilizers.enabled else None
        # ISSUE-14 step restructurings: dispatch-side twins of the config
        # selection matrix (construction already refused these — graftlint R8
        # refusal parity; kept here so a hand-mutated config can never reach
        # an unsupported lowering), plus the resolved hot-row geometry.
        if cfg.hot_rows and (cfg.use_pallas or cfg.cbow
                             or cfg.step_lowering == "shard_map"
                             or cfg.duplicate_scaling):
            raise ValueError(
                "hot_rows supports the single-device SGNS XLA paths only "
                "(not use_pallas/cbow/shard_map/duplicate_scaling) — config "
                "construction refuses these combinations (docs/sharding.md)")
        if (cfg.fused_logits or cfg.bf16_chain) and (
                cfg.use_pallas or cfg.cbow):
            raise ValueError(
                "fused_logits/bf16_chain support the SGNS XLA chains only "
                "(not use_pallas/cbow) — config construction refuses these "
                "combinations")
        if cfg.sync_every > 1 and cfg.step_lowering != "shard_map":
            raise ValueError(
                "sync_every > 1 (local-SGD) requires the shard_map lowering "
                "— the owner-local k-step window has no GSPMD form; config "
                "construction refuses this combination (docs/sharding.md "
                "§Local-SGD)")
        fused = cfg.fused_logits
        chain = cfg.bf16_chain
        hot_k = self._hot_rows
        inner_hot = None
        if not quiet and logits_dtype != jnp.float32 and not (
                cfg.negative_pool > 0 and not cfg.use_pallas
                and not (cfg.cbow and cfg.duplicate_scaling)):
            logger.warning(
                "logits_dtype=%s only applies to the shared-pool XLA paths "
                "(negative_pool > 0, no pallas, no CBOW+duplicate_scaling); this "
                "configuration keeps the float32 logit chain", cfg.logits_dtype)
        plan = self.plan
        # np.uint32 (not a Python int): any negative or 64-bit seed masked to 32 bits
        # lands in [2^31, 2^32), which jnp.asarray rejects under int32 canonicalization
        seed = np.uint32(cfg.seed & 0xFFFFFFFF)

        def shared_pool_shape(K, B):  # negatives per chunk on the shared-pool paths
            return (K, cfg.negative_pool)

        # CBOW update-path selection matrix (config.__post_init__ holds the
        # validation-side twin — every unsupported combination is refused at
        # construction, never silently downgraded):
        #
        #   cbow_update  duplicate_scaling  pool   → step
        #   ------------ -----------------  -----  ---------------------------
        #   "banded"     False              > 0    cbow_step_banded_core
        #                                          (token-block feed + halo)
        #   "banded"     True               any    REFUSED (config)
        #   "banded"     False              = 0    REFUSED (config; banded is
        #                                          built on the shared pool)
        #   "scatter"    False              > 0    cbow_step_shared_core
        #   "scatter"    True               = 0    cbow_step_core (per-example
        #                                          negatives; explicit pool>0
        #                                          REFUSED, auto resolves to 0)
        #   "scatter"    False              = 0    cbow_step_core
        #   any          any + use_pallas   any    REFUSED (SGNS-only kernel)
        if self._banded_cbow:
            if not quiet:
                self._stability_warnings()
            return self._build_banded_cbow_chunk(
                with_metrics, compute_dtype, logits_dtype, seed)

        if cfg.use_pallas:
            from glint_word2vec_tpu.ops.pallas import sgns_kernel  # deferred import
            if cfg.duplicate_scaling:
                raise ValueError(
                    "duplicate_scaling is not implemented for use_pallas=True — the "
                    "fused kernel applies sum semantics only; use the XLA path or "
                    "bound the row loads via negative_pool/subsample_ratio instead")
            if cfg.max_row_norm or cfg.update_clip or cfg.row_l2:
                raise ValueError(
                    "the in-step stabilizers (max_row_norm/update_clip/row_l2) "
                    "are not implemented for use_pallas=True — the fused "
                    "kernel owns its own update math; use the XLA paths")
            if cfg.norm_watch == "recover":
                raise ValueError(
                    "norm_watch='recover' auto-engages max_row_norm, which "
                    "the fused pallas kernel does not implement — use "
                    "norm_watch='warn'/'halt' or the XLA paths")
            self._stability_warnings()
            if len(plan.mesh.devices.flat) > 1:
                raise ValueError(
                    "use_pallas=True currently supports single-device plans only: the "
                    "fused kernel owns the whole [V, D] matrices in one HBM space and "
                    "cannot be GSPMD-partitioned; use the XLA negative_pool path on "
                    "multi-device meshes")
            if cfg.cbow:
                raise ValueError("use_pallas=True is not implemented for CBOW")
            inner = sgns_kernel.make_pallas_sgns_step(
                cfg.negatives, cfg.negative_pool, cfg.sigmoid_mode, compute_dtype,
                interpret=jax.default_backend() == "cpu")
            if cfg.negative_pool <= 0:
                logger.warning(
                    "use_pallas=True requires a shared negative pool; negative_pool=0 "
                    "(per-pair negatives) is substituted with a 64-negative shared pool "
                    "— a different objective estimator. Set negative_pool explicitly "
                    "to silence this.")
            pool = cfg.negative_pool if cfg.negative_pool > 0 else 64
            neg_shape = lambda K, B: (K, pool)  # noqa: E731
        elif cfg.negative_pool > 0 and not cfg.cbow:
            if not quiet:
                self._stability_warnings()

            if cfg.step_lowering == "shard_map":
                # the explicit schedule (ops/sgns_shard.py, docs/sharding.md):
                # owner-local gathers + ONE model-axis psum forward, owner-local
                # scatters + ONE data-axis payload all_gather backward — zero
                # update bytes over the model axis (HLO-audited,
                # tools/collectives.py). The config selection matrix already
                # refused cbow/pallas/duplicate_scaling/cols beside it.
                from glint_word2vec_tpu.ops.sgns_shard import (
                    make_shard_map_sgns_step)
                inner = make_shard_map_sgns_step(
                    plan.mesh, cfg.negatives, cfg.sigmoid_mode, compute_dtype,
                    logits_dtype, with_metrics, stabilizers=stab,
                    fused=fused, bf16_chain=chain, sync_every=cfg.sync_every)
            else:
                def inner(params, batch, negatives, alpha):
                    return sgns_step_shared_core(
                        params, batch["centers"], batch["contexts"],
                        batch["mask"], negatives, alpha, cfg.negatives,
                        cfg.sigmoid_mode, compute_dtype,
                        cfg.duplicate_scaling, logits_dtype, with_metrics,
                        stabilizers=stab, fused=fused, bf16_chain=chain)

                if hot_k:
                    def inner_hot(params, slabs, batch, negatives, alpha):
                        return sgns_step_shared_core(
                            params, batch["centers"], batch["contexts"],
                            batch["mask"], negatives, alpha, cfg.negatives,
                            cfg.sigmoid_mode, compute_dtype,
                            cfg.duplicate_scaling, logits_dtype,
                            with_metrics, stabilizers=stab, fused=fused,
                            bf16_chain=chain, hot_slabs=slabs)

            neg_shape = shared_pool_shape
            if cfg.step_lowering == "shard_map" and cfg.sync_every > 1:
                # local-SGD window (docs/sharding.md §Local-SGD): `inner`
                # consumes [k, B]-stacked batches and [k, nd·P] negatives —
                # each data shard a DISJOINT [k, P] lattice slice, so the
                # merged run is deterministic per (seed, mesh, k)
                neg_shape = lambda K, B: (  # noqa: E731
                    K, plan.num_data * cfg.negative_pool)
        elif cfg.cbow and cfg.negative_pool > 0 and not cfg.duplicate_scaling:
            if not quiet:
                self._stability_warnings()

            def inner(params, batch, negatives, alpha):
                return cbow_step_shared_core(
                    params, batch["centers"], batch["contexts"], batch["ctx_mask"],
                    batch["mask"], negatives, alpha, cfg.negatives,
                    cfg.sigmoid_mode, compute_dtype, logits_dtype, with_metrics,
                    stabilizers=stab)

            neg_shape = shared_pool_shape
        elif cfg.cbow:
            # per-example CBOW (pool resolved to 0: small batches, or
            # duplicate_scaling — config refuses an explicit pool beside it)
            self._stability_warnings(check_pool=False)

            def inner(params, batch, negatives, alpha):
                return cbow_step_core(
                    params, batch["centers"], batch["contexts"], batch["ctx_mask"],
                    batch["mask"], negatives, alpha,
                    cfg.sigmoid_mode, compute_dtype, cfg.duplicate_scaling,
                    stabilizers=stab)

            neg_shape = lambda K, B: (K, B, cfg.negatives)  # noqa: E731
        else:
            # per-pair path (negative_pool=0): no shared pool, but the duplicate
            # overload channel still applies (summed scatter-adds of a frequent
            # word's updates — the EVAL.md regime)
            self._stability_warnings(check_pool=False)

            def inner(params, batch, negatives, alpha):
                return sgns_step_core(
                    params, batch["centers"], batch["contexts"], batch["mask"],
                    negatives, alpha, cfg.sigmoid_mode, compute_dtype,
                    cfg.duplicate_scaling, stabilizers=stab,
                    fused=fused, bf16_chain=chain)

            if hot_k:
                def inner_hot(params, slabs, batch, negatives, alpha):
                    return sgns_step_core(
                        params, batch["centers"], batch["contexts"],
                        batch["mask"], negatives, alpha, cfg.sigmoid_mode,
                        compute_dtype, cfg.duplicate_scaling,
                        stabilizers=stab, fused=fused, bf16_chain=chain,
                        hot_slabs=slabs)

            neg_shape = lambda K, B: (K, B, cfg.negatives)  # noqa: E731

        is_cbow = cfg.cbow
        S = self._feed_segments
        emb_sharding = self._emb_sharding
        # > 1 only on the shard_map SGNS path (config refuses every other
        # combination) — the chunk below scans windows instead of steps
        sync_k = cfg.sync_every

        if cfg.device_pairgen:
            from glint_word2vec_tpu.ops.pairgen import device_block_pairs
            W = cfg.window
            Sd = self.plan.num_data
            Bl = cfg.pairs_per_batch // Sd

            gen = jax.vmap(
                lambda tk, st, nv, lo, hi, kp, sb, wb: device_block_pairs(
                    tk, st, nv, lo, hi, kp, sb, wb,
                    window=W, num_pairs=Bl, presubsampled=True),
                in_axes=(0, 0, 0, 0, 0, None, 0, 0))

            def device_chunk(params, arrays, meta, base_step, prob, alias,
                             keep_prob, sub_bases, win_bases):
                # meta rows: [0] per-step alphas; [1:1+Sd] per-segment valid-token
                # counts. Pair counts are unknown to the host here — the device
                # derives them; exact totals ride back in the scanned metrics.
                alphas, nvalid = meta[0], meta[1:].T          # [K], [K, Sd]
                K = alphas.shape[0]
                negatives = sample_negatives_hash(
                    prob, alias, seed, base_step, neg_shape(K, Sd * Bl))
                # tie feed + negatives to the params carry (see chunk below)
                params, arrays, negatives = jax.lax.optimization_barrier(
                    (params, arrays, negatives))

                def build_batch(xs, nv):
                    ob = jax.lax.bitcast_convert_type(xs["obase"], jnp.uint32)
                    dp = gen(xs["tokens"].astype(jnp.int32), xs["starts"],
                             nv.astype(jnp.int32), ob[:, 0], ob[:, 1],
                             keep_prob, sub_bases, win_bases)
                    return {"centers": dp.centers.reshape(-1),
                            "contexts": dp.contexts.reshape(-1),
                            "mask": dp.mask.reshape(-1)}, dp.dropped_pairs.sum()

                def body(p, inp):
                    xs, alpha, nv, negs = inp
                    batch, dropped = build_batch(xs, nv)
                    new_p, metrics = inner(p, batch, negs, alpha)
                    new_p = jax.lax.with_sharding_constraint(
                        new_p, EmbeddingPair(emb_sharding, emb_sharding))
                    return new_p, (metrics, dropped)

                xs_all = (arrays, alphas, nvalid, negatives)
                if not hot_k:
                    return jax.lax.scan(body, params, xs_all)

                def body_hot(carry, inp):
                    p, slabs = carry
                    xs, alpha, nv, negs = inp
                    batch, dropped = build_batch(xs, nv)
                    new_p, metrics, slabs = inner_hot(p, slabs, batch, negs,
                                                      alpha)
                    new_p = jax.lax.with_sharding_constraint(
                        new_p, EmbeddingPair(emb_sharding, emb_sharding))
                    return (new_p, slabs), (metrics, dropped)

                return self._run_hot_scan(body_hot, params, xs_all, K)

            return jax.jit(device_chunk, donate_argnums=(0,))

        def chunk(params, arrays, meta, base_step, prob, alias):
            # scan over steps_per_dispatch stacked batches in one device dispatch:
            # per-step dispatch/transfer latency (large through a remote-TPU tunnel)
            # would otherwise dominate the ~ms step. Two hard-won TPU constraints
            # (measured 3.4M → 200M+ pairs/s on v5e, see ops/prng.py):
            #  - no jax.random (threefry) ops anywhere in this program — negatives
            #    come from the counter-based hash PRNG, drawn for the whole chunk
            #    before the scan;
            #  - the alias tables enter as jit arguments (prob, alias), never as
            #    closure constants.
            # Feed-bandwidth constraints (measured through the same tunnel):
            #  - pairs arrive as ONE packed [K, 2, B] array (possibly uint16);
            #  - the per-pair mask never ships: batches are prefix-masked by
            #    construction, so mask_k = (iota < real_k), rebuilt on device from
            #    the [2, K] meta array (row 0 alphas, row 1 real counts).
            # meta rows: [0] per-batch alphas; [1:1+S] per-segment real counts. With the
            # sharded feed (S > 1) the B axis is S contiguous per-process segments, each
            # prefix-masked on its own, so the mask is rebuilt per segment.
            alphas, reals = meta[0], meta[1:].T   # [K], [K, S] (scan runs over K)
            K = alphas.shape[0]
            if is_cbow:
                B = arrays["centers"].shape[1]
            else:
                B = arrays["pairs"].shape[2]
            negatives = sample_negatives_hash(
                prob, alias, seed, base_step, neg_shape(K, B))
            # SERIALIZATION PROPERTY: every collective in the chunk should
            # data-depend on the params carry, so a chunk dispatched behind
            # another program can never start its collectives early. The feed
            # arrays and the pre-scan sampler output are otherwise carry-
            # independent (GSPMD is free to reshard them with small
            # all-gathers), which would let chunk N+1's collectives race
            # chunk N's on XLA:CPU's shared rendezvous pool — the starvation
            # deadlock documented at _sync_collectives (whose gate is the
            # enforced fix; this barrier removes the structural exposure at
            # zero cost — params are program inputs, so within-program
            # TPU/GPU scheduling is untouched).
            params, arrays, negatives = jax.lax.optimization_barrier(
                (params, arrays, negatives))
            pos = jnp.arange(B // S, dtype=jnp.float32)

            def build_batch(xs, real):
                mask = (pos[None, :] < real[:, None]).astype(jnp.float32).reshape(-1)
                if is_cbow:
                    ctx = xs["contexts"].astype(jnp.int32)
                    # contexts are left-packed; the mask ships as a count (~40x
                    # fewer feed bytes than a [B, C] float mask)
                    nctx = xs["nctx"].astype(jnp.int32)
                    ctx_mask = (jnp.arange(ctx.shape[-1])[None, :]
                                < nctx[:, None]).astype(jnp.float32)
                    return {"centers": xs["centers"].astype(jnp.int32),
                            "contexts": ctx, "ctx_mask": ctx_mask, "mask": mask}
                prs = xs["pairs"].astype(jnp.int32)
                return {"centers": prs[0], "contexts": prs[1], "mask": mask}

            def body(p, inp):
                xs, alpha, real, negs = inp
                new_p, metrics = inner(p, build_batch(xs, real), negs, alpha)
                new_p = jax.lax.with_sharding_constraint(
                    new_p, EmbeddingPair(emb_sharding, emb_sharding))
                return new_p, metrics

            xs_all = (arrays, alphas, reals, negatives)
            if sync_k > 1:
                # local-SGD windowed dispatch (config.sync_every, docs/
                # sharding.md §Local-SGD): the chunk scans over K/k WINDOWS,
                # each a single shard_map program running k owner-local steps
                # per data shard + the one delta-merge collective. Config
                # guarantees k | steps_per_dispatch, so every dispatch
                # boundary is a merge boundary: the params carry this scan
                # hands back is always fully merged — snapshot-ring/rollback
                # and the preemption save (all of which run between
                # dispatches) can never resurrect an unmerged shard. Metrics
                # come back [W, k] and reshape to the [K] layout
                # _finish_round expects.
                W = K // sync_k

                def build_window(xs, real):          # real: [k, S]
                    mask = (pos[None, None, :] < real[:, :, None]).astype(
                        jnp.float32).reshape(sync_k, -1)
                    prs = xs["pairs"].astype(jnp.int32)   # [k, 2, B]
                    return {"centers": prs[:, 0], "contexts": prs[:, 1],
                            "mask": mask}

                def body_window(p, inp):
                    xs, alpha, real, negs = inp
                    new_p, metrics = inner(
                        p, build_window(xs, real), negs, alpha)
                    new_p = jax.lax.with_sharding_constraint(
                        new_p, EmbeddingPair(emb_sharding, emb_sharding))
                    return new_p, metrics

                xs_win = jax.tree.map(
                    lambda x: x.reshape((W, sync_k) + x.shape[1:]), xs_all)
                final_p, m = jax.lax.scan(body_window, params, xs_win)
                m = jax.tree.map(
                    lambda x: x.reshape((K,) + x.shape[2:]), m)
                return final_p, m
            if not hot_k:
                return jax.lax.scan(body, params, xs_all)

            def body_hot(carry, inp):
                p, slabs = carry
                xs, alpha, real, negs = inp
                new_p, metrics, slabs = inner_hot(
                    p, slabs, build_batch(xs, real), negs, alpha)
                new_p = jax.lax.with_sharding_constraint(
                    new_p, EmbeddingPair(emb_sharding, emb_sharding))
                return (new_p, slabs), metrics

            return self._run_hot_scan(body_hot, params, xs_all, K)

        return jax.jit(chunk, donate_argnums=(0,))

    def _build_banded_cbow_chunk(
        self,
        with_metrics: bool,
        compute_dtype: jnp.dtype,
        logits_dtype: jnp.dtype,
        seed: np.uint32,
    ) -> Callable:
        """Jitted chunk for cbow_update='banded': same feed/chunk signature as
        the device_pairgen chunk (token blocks + hash-lattice draws on device;
        keep_prob/sub_bases ride along unused — the packer presubsampled), but
        each scan step derives per-slot CBOW window intervals
        (ops/pairgen.device_cbow_windows) and applies the banded update
        (ops/cbow_banded.cbow_step_banded_core). Segments are flattened
        [Sd, T] → [Sd·T] for ONE prefix-sum pass: window intervals are
        in-block by construction, so prefix differences never leak across
        segments. The second return slot keeps the device-feed (metrics,
        dropped) shape; banded blocks have fixed example slots, so dropped
        is identically 0."""
        cfg = self.config
        from glint_word2vec_tpu.ops.cbow_banded import cbow_step_banded_core
        from glint_word2vec_tpu.ops.pairgen import device_cbow_windows
        W = cfg.window
        H = self._block_halo
        emb_sharding = self._emb_sharding
        stab = self._stabilizers if self._stabilizers.enabled else None

        win = jax.vmap(
            lambda tk, st, nv, lo, hi, wb: device_cbow_windows(
                tk, st, nv, lo, hi, wb, window=W, halo=H),
            in_axes=(0, 0, 0, 0, 0, 0))

        def banded_chunk(params, arrays, meta, base_step, prob, alias,
                         keep_prob, sub_bases, win_bases):
            del keep_prob, sub_bases  # host packer already subsampled
            alphas, nvalid = meta[0], meta[1:].T          # [K], [K, Sd]
            K = alphas.shape[0]
            negatives = sample_negatives_hash(
                prob, alias, seed, base_step, (K, cfg.negative_pool))
            # tie feed + negatives to the params carry (see _build_step's
            # chunk for the live-deadlock rationale)
            params, arrays, negatives = jax.lax.optimization_barrier(
                (params, arrays, negatives))

            def body(p, inp):
                xs, alpha, nv, negs = inp
                ob = jax.lax.bitcast_convert_type(xs["obase"], jnp.uint32)
                tok = xs["tokens"].astype(jnp.int32)
                band = win(tok, xs["starts"], nv.astype(jnp.int32),
                           ob[:, 0], ob[:, 1], win_bases)
                new_p, metrics = cbow_step_banded_core(
                    p, tok.reshape(-1),
                    band.left.reshape(-1), band.right.reshape(-1),
                    band.center.reshape(-1), band.token.reshape(-1),
                    negs, alpha, cfg.negatives, W, cfg.sigmoid_mode,
                    compute_dtype, logits_dtype, with_metrics,
                    stabilizers=stab)
                new_p = jax.lax.with_sharding_constraint(
                    new_p, EmbeddingPair(emb_sharding, emb_sharding))
                return new_p, (metrics, jnp.int32(0))

            return jax.lax.scan(body, params, (arrays, alphas, nvalid, negatives))

        return jax.jit(banded_chunk, donate_argnums=(0,))

    def _run_hot_scan(self, body_hot, params, xs, K: int):
        """Cross-step hot-row scan (config.hot_rows — ISSUE 14 / PERF.md §11):
        the chunk's scan carries the two f32 [K_hot, D] pending-delta slabs
        beside the params, and the chunk splits into ``steps_per_dispatch /
        hot_flush_every`` statically-unrolled scan segments with ONE dense
        prefix-block flush (ops/sgns.hot_flush — no scatter emitter) between
        segments and after the last. The final flush makes the returned
        params complete, so the chunk's external contract — (params, stacked
        per-step outputs) — is unchanged: checkpoints, probes, donation, and
        the heartbeat metrics path never see a pending slab. ``body_hot``
        has signature ``((params, slabs), inp) -> ((params, slabs), ys)``;
        config guarantees ``hot_flush_every`` divides ``K``."""
        hk, dp = self._hot_rows, self.padded_dim
        F = min(self._hot_flush, K)
        # slab accumulation dtype: promote(param, f32) — the R4 discipline
        # (cross-step bf16 accumulation would round away exactly the small
        # frequent-row updates the slab batches), never below the params'
        # own precision (the f64 oracle suite holds the helpers exact)
        sdt = jnp.promote_types(jnp.dtype(self.config.param_dtype),
                                jnp.float32)

        def zero_slabs():
            return (jnp.zeros((hk, dp), sdt), jnp.zeros((hk, dp), sdt))

        carry = (params, zero_slabs())
        outs = []
        for si in range(max(1, K // F)):
            seg = jax.tree.map(lambda a, si=si: a[si * F:(si + 1) * F], xs)
            carry, ys = jax.lax.scan(body_hot, carry, seg)
            p, (s0, s1) = carry
            p = EmbeddingPair(hot_flush(p.syn0, s0), hot_flush(p.syn1, s1))
            carry = (p, zero_slabs())
            outs.append(ys)
        if len(outs) == 1:
            return carry[0], outs[0]
        return carry[0], jax.tree.map(
            lambda *a: jnp.concatenate(a, axis=0), *outs)

    def _stage_dispatch_meta(self, meta: np.ndarray, base_step, *bases):
        """Explicitly stage the small per-dispatch host arrays (the meta rows,
        the PRNG base step, and any hash-lattice base vectors) as replicated
        device arrays. The compiled-step transfer contract (tools/stepaudit.py,
        docs/static-analysis.md; enforced by a scripted fit under
        ``jax.transfer_guard("disallow")``) requires every jitted-chunk
        argument to arrive on device: an implicit numpy→device transfer at
        dispatch time is exactly the silent host-transfer regression the
        auditor exists to catch. Cost: a few hundred replicated bytes per
        dispatch through the same put_global discipline as the feed arrays.

        This is also the single owner of the recovery lr backoff: every fit
        path's alphas ride meta row 0 through here, so one multiplicative
        ``_lr_scale`` (1.0 until a norm_watch="recover" firing backs it off)
        covers the host feed, both device feeds, and the sharded paths
        without touching any producer. Identical on every process — the
        scale only changes on probe rounds, which are allgather-consistent."""
        meta = np.asarray(meta, np.float32)
        if self._lr_scale != 1.0:
            meta = meta.copy()  # never mutate the producer's array in place
            meta[0] *= np.float32(self._lr_scale)
        host = {"meta": meta,
                "base": np.int32(base_step)}
        for i, b in enumerate(bases):
            host[f"b{i}"] = b
        placed = put_global(self.plan.replicated, host)
        return (placed["meta"], placed["base"],
                *[placed[f"b{i}"] for i in range(len(bases))])

    def _after_dispatch(self) -> None:
        """Collective-program serialization gate (see __init__): on the
        multi-device CPU backend, wait for the dispatched chunk's carry
        before anything else may launch a program. No-op elsewhere, so the
        host/device pipelining this trainer is built around is unchanged on
        real accelerators; on the CPU mesh the dispatch_time split becomes
        device-inclusive, which that backend never reported honestly
        anyway."""
        if self._sync_collectives:
            with self._tracer.span("device_block"):
                jax.block_until_ready(self.params)

    def _dispatch_step_fn(self, max_steps: int) -> Callable:
        """The step function for the NEXT dispatch: the fast (metrics-elided)
        twin unless a heartbeat may sample this chunk's metrics. ``max_steps``
        is an upper bound on the real steps the chunk advances, so the
        prediction can only err toward the full-metrics twin (a heartbeat never
        lands on an elided chunk)."""
        if (self._step_fn_fast is self._step_fn
                or self.global_step + max_steps - self._last_log_step
                >= self.config.heartbeat_every_steps):
            return self._step_fn
        return self._step_fn_fast

    # -- training ----------------------------------------------------------------------

    def fit(
        self,
        sentences: Sequence[np.ndarray],
        checkpoint_path: Optional[str] = None,
        checkpoint_every_steps: Optional[int] = None,
        on_heartbeat: Optional[Callable[[HeartbeatRecord], None]] = None,
        corpus_words: Optional[int] = None,
    ) -> EmbeddingPair:
        """Run the remaining iterations of training over encoded sentences.

        ``sentences``: int32 index arrays (already OOV-filtered and chunked — C4 output).
        Resumes from ``self.state`` if a prior checkpoint set it.

        ``corpus_words``: raw token count of ``sentences``, when it differs
        from what the vocabulary's counts imply — the continual case
        (docs/continual.md), where an incremental fit feeds only the corpus
        TAIL while ``vocab.counts`` carries the full merged history. The
        lr-decay clock then anneals over the fed corpus (scaled by the same
        expected-subsample-keep ratio), not over a history-sized total it
        would never reach. Default None = the corpus is the vocabulary's
        source (every non-continual fit), behavior unchanged.
        """
        cfg = self.config
        # where this fit publishes checkpoints — the SIGTERM preemption hook
        # (config.checkpoint_on_preempt) drains its emergency save here, so
        # the handler needs it before any fit path's bookkeeping runs
        self._active_checkpoint_path = checkpoint_path
        from glint_word2vec_tpu.data.pipeline import expected_kept_words
        train_words = expected_kept_words(
            self.vocab.counts, self.vocab.train_words_count, cfg.subsample_ratio)
        if corpus_words is not None:
            # per-iteration expected KEPT words of the fed corpus: the
            # vocab-wide keep ratio applied to the fed token count
            train_words = (train_words
                           / max(float(self.vocab.train_words_count), 1.0)
                           * float(corpus_words))
        total_words = float(cfg.num_iterations * train_words + 1)
        K = max(1, cfg.steps_per_dispatch)
        # banded CBOW rides the token-block feed paths (same chunk plumbing as
        # device_pairgen; its blocks overlap by ±window — see __init__)
        token_feed = cfg.device_pairgen or self._banded_cbow
        if self._feed_segments > 1 and token_feed:
            return self._fit_device_feed_sharded(
                sentences, checkpoint_path, checkpoint_every_steps, on_heartbeat,
                total_words, float(train_words), K)
        if self._feed_segments > 1:
            return self._fit_sharded(
                sentences, checkpoint_path, checkpoint_every_steps, on_heartbeat,
                total_words, K)
        if token_feed:
            return self._fit_device_feed(
                sentences, checkpoint_path, checkpoint_every_steps, on_heartbeat,
                total_words, float(train_words), K)
        if self.state.shard_progress is not None and not self.state.finished:
            # the recorded positions index a different stream than the
            # replicated pair feed — resuming here would silently mis-position
            if self.state.shard_feed == "tokens":
                raise ValueError(
                    "checkpoint was written by a token-block-feed run (its "
                    "positions index per-segment token streams); resume it "
                    "with the same feed — device_pairgen=True, or "
                    "cbow_update='banded' if it was a banded-CBOW run")
            raise ValueError(
                "checkpoint was written by a sharded-input multi-process run "
                f"({len(self.state.shard_progress)} shards); resume it with the "
                "same process count and shard_input=True, not on the "
                "replicated feed")
        start_iter = self.state.iteration
        # exact-step resume: the batch stream is deterministic per (seed, iteration,
        # shard), so skipping the recorded number of already-trained batches reproduces
        # the interrupted run's position instead of replaying the whole iteration
        skip_batches = self.state.batches_done if not self.state.finished else 0

        def chunk_stream():
            """Pure-numpy chunk assembly: batch generation, K-stacking, padding, alpha
            schedule. No JAX calls — safe to run on the producer thread."""
            for k in range(start_iter, cfg.num_iterations + 1):
                prev_words = (k - 1) * train_words
                pending: List[dict] = []
                pending_words: List[int] = []
                batches_in_iter = skip_batches if k == start_iter else 0
                to_skip = skip_batches if k == start_iter else 0

                def flush():
                    nonlocal pending, pending_words, batches_in_iter
                    real = len(pending)
                    while len(pending) < K:  # pad to the compiled chunk len, masked out
                        dummy = {name: (0 if name == "real" else np.zeros_like(arr))
                                 for name, arr in pending[0].items()}
                        pending.append(dummy)
                        pending_words.append(pending_words[-1])
                    reals = np.asarray([b["real"] for b in pending], np.float32)
                    if cfg.cbow:
                        # filled in place like the pairs branch below: stack+astype
                        # double-copies measurably throttle the producer
                        B0 = pending[0]["centers"].shape[0]
                        C0 = pending[0]["contexts"].shape[1]
                        arrays = {
                            "centers": np.empty((K, B0), self._pair_dtype),
                            "contexts": np.empty((K, B0, C0), self._pair_dtype),
                            "nctx": np.empty((K, B0), np.uint8),
                        }
                        for j, b in enumerate(pending):
                            arrays["centers"][j] = b["centers"]
                            arrays["contexts"][j] = b["contexts"]
                            arrays["nctx"][j] = b["nctx"]
                    else:
                        # one contiguous [K, 2, B] feed array (see _build_step notes),
                        # filled in place: nested np.stack + astype costs three copies
                        # of the chunk and measurably throttled the producer (~2x the
                        # raw pair-generation time at B=64k)
                        pairs = np.empty(
                            (K, 2, pending[0]["centers"].shape[0]), self._pair_dtype)
                        for j, b in enumerate(pending):
                            pairs[j, 0] = b["centers"]
                            pairs[j, 1] = b["contexts"]
                        arrays = {"pairs": pairs}
                    alphas = np.asarray([
                        alpha_schedule(float(w), total_words, cfg.learning_rate,
                                       cfg.min_alpha_factor)
                        for w in pending_words], np.float32)
                    meta = np.stack([alphas, reals])  # [2, K] — rides with the dispatch
                    # throughput counts real (unmasked) pairs, not padded batch slots
                    real_pairs = float(reals.sum())
                    batches_in_iter += real
                    chunk = dict(
                        arrays=arrays, meta=meta, real=real, iteration=k,
                        words_processed=int(pending_words[real - 1]),
                        batches_done=batches_in_iter, real_pairs=real_pairs)
                    pending, pending_words = [], []
                    return chunk

                for batch in self._batch_stream(sentences, k):
                    if to_skip:  # fast-forward already-trained batches (exact resume)
                        to_skip -= 1
                        continue
                    pending_words.append(prev_words + batch.pop("words_seen"))
                    pending.append(batch)
                    if len(pending) == K:
                        yield flush()
                if pending:
                    yield flush()

        # The reference pipelines one minibatch ahead of its RPC round-trips for the
        # same reason (mllib:428-429): host work must overlap accelerator work. Here a
        # producer thread keeps a bounded buffer of ready chunks; numpy releases the
        # GIL in its hot loops, so production genuinely overlaps dispatch. Device
        # staging rides the same thread (_stage_to_device) so the feed's wire
        # transfer overlaps device compute too — single-process prefetching only:
        # multi-process runs must keep one cross-host dispatch order (see
        # _stage_to_device), and with prefetch off the put stays in the consumer so
        # the host-wait/dispatch split keeps its documented meaning.
        staged = cfg.prefetch_chunks > 0 and jax.process_count() == 1
        # span-wrap the producer so each chunk's assembly is timed ON the
        # thread that runs it (the _threaded_iter producer when prefetching)
        stream = self._tracer.wrap_iter("producer", chunk_stream())
        if staged:
            chunks = _threaded_iter(
                self._stage_to_device(stream), cfg.prefetch_chunks)
        elif cfg.prefetch_chunks > 0:
            chunks = _threaded_iter(stream, cfg.prefetch_chunks)
        else:
            chunks = stream

        self._start_run_bookkeeping()
        chunks = iter(chunks)
        try:
            while True:
                t0 = time.perf_counter()
                chunk = next(chunks, None)
                wait = time.perf_counter() - t0
                self.host_wait_time += wait
                self._phases.add("producer_wait", wait)
                if chunk is None:
                    break
                t0 = time.perf_counter()
                if cfg.feed_consistency_check and jax.process_count() > 1:
                    # the replicated feed is the path where divergence CAN
                    # happen: every process regenerated the stream itself
                    self._assert_feed_consistent(chunk["arrays"], chunk["meta"])
                with self._tracer.span("dispatch"):
                    stacked = (chunk["arrays"] if staged else
                               put_global(self._chunk_shardings,
                                          chunk["arrays"]))
                    real = chunk["real"]
                    meta_dev, base_dev = self._stage_dispatch_meta(
                        chunk["meta"], self.global_step + 1)
                    self.params, metrics = self._dispatch_step_fn(real)(
                        self.params, stacked, meta_dev, base_dev,
                        self._table_prob, self._table_alias)
                self.dispatch_time += time.perf_counter() - t0
                self._after_dispatch()
                self._finish_round(
                    real, chunk["real_pairs"], chunk["meta"][0], metrics,
                    TrainState(iteration=chunk["iteration"],
                               words_processed=chunk["words_processed"],
                               batches_done=chunk["batches_done"]),
                    checkpoint_path, checkpoint_every_steps, on_heartbeat)
        except BaseException:
            self._abort_run()  # its docstring has the why-not-sys.exc_info
            raise
        finally:
            self._stop_profiler()
            closer = getattr(chunks, "close", None)
            if closer is not None:
                closer()

        self.state = TrainState(
            iteration=cfg.num_iterations,
            words_processed=int(cfg.num_iterations * train_words),
            finished=True, global_step=self.global_step)
        if checkpoint_path:
            self.save_checkpoint(checkpoint_path)
        self._end_run("ok")
        return self.params

    def _device_seg_blocks(self, sentences: Sequence[np.ndarray], k: int, s: int,
                           workers: Optional[int] = None):
        """[T]-token blocks of data segment s, iteration k, for the device pair
        generator — SUBSAMPLED on the host (same hashrng draws on raw ordinals as
        data/pipeline, vectorized over ~1M-raw-token slabs; a per-sentence Python
        loop measurably starved the feed), so the wire carries only kept tokens and
        the lr clock is exact. The kept stream is cut at T boundaries — a sentence
        straddling a cut loses its cross-cut window context, the same class of
        boundary as the reference's maxSentenceLength chunking (mllib:341); at
        production T (tens of thousands) that is ~0.02% of windows. Yields
        (tokens[T], start_bits, n_valid, kept_ordinal_base, kept_count).

        Deterministic per (seed, k, s) and independent of which process runs it —
        the property the sharded multi-process feed relies on (a 2-process run's
        segment s is bit-identical to a single-process run's). ``workers``
        (default ``config.producer_workers``) fans the per-slab subsample work
        across a thread pool (pipeline.ordered_pool_map): the draws are keyed
        by raw-token ordinals, so each slab is a pure function of its (slab,
        ordinal base) job and the merged stream is bit-identical at any worker
        count — only the T-boundary packing below stays serial.

        Banded-CBOW mode (self._block_halo > 0): the same kept stream is cut
        with a ±halo OVERLAP instead (pipeline.pack_halo_token_blocks) — blocks
        advance by T − 2·halo core slots, so chunk-edge windows are exact (no
        cross-cut context loss at all) and the 5th tuple element counts only
        the NEW core tokens (the lr clock must not double-count overlap)."""
        from glint_word2vec_tpu.data.hashrng import (
            STREAM_SUBSAMPLE, hash_u01_at, stream_base)
        from glint_word2vec_tpu.data.pipeline import (
            iter_sentence_slabs, ordered_pool_map, pack_halo_token_blocks,
            stream_rng)
        cfg = self.config
        if workers is None:
            workers = cfg.producer_workers
        Sd = self.plan.num_data
        T = self._tokens_per_step
        tok_dt = self._pair_dtype
        keep = self._keep_host
        rng = stream_rng(cfg.seed, k, s)
        order = np.arange(s, len(sentences), Sd)
        if cfg.shuffle:
            rng.shuffle(order)
        sub_base = stream_base(cfg.seed, STREAM_SUBSAMPLE, k, s)

        def slab_jobs():
            raw_ord = 0
            for slab in iter_sentence_slabs(sentences, order):
                yield slab, raw_ord
                raw_ord += sum(int(x.shape[0]) for x in slab)

        def run_slab(job):
            """(kept_tokens, sentence_start_flags) of one ~1M-raw-token slab —
            pure in (slab, raw ordinal base); None for an all-dropped slab."""
            slab, raw_ord = job
            tokens = np.concatenate(slab) if len(slab) > 1 else slab[0]
            lens = np.fromiter(
                (x.shape[0] for x in slab), np.int64, len(slab))
            n = tokens.shape[0]
            sids = np.repeat(np.arange(len(slab)), lens)
            if cfg.subsample_ratio > 0:
                u = hash_u01_at(sub_base, np.arange(
                    raw_ord, raw_ord + n, dtype=np.uint64))
                m = u <= keep[tokens]
                ktoks, ksids = tokens[m], sids[m]
            else:
                ktoks, ksids = tokens, sids
            if ktoks.shape[0] == 0:
                return None
            kstart = np.empty(ktoks.shape[0], bool)
            kstart[0] = True
            kstart[1:] = ksids[1:] != ksids[:-1]
            return ktoks.astype(tok_dt), kstart

        def kept_slabs():
            for res in ordered_pool_map(run_slab, slab_jobs(), workers):
                if res is not None:
                    yield res

        if self._block_halo:
            yield from pack_halo_token_blocks(
                kept_slabs(), T, self._block_halo, tok_dt)
            return

        base = 0
        rest_tok = np.empty(0, tok_dt)
        rest_start = np.empty(0, bool)

        def emit(toks, starts):
            n = toks.shape[0]
            buf = np.zeros(T, tok_dt)
            buf[:n] = toks
            bits = np.packbits(np.pad(starts, (0, T - n)), bitorder="little")
            return (buf, bits, n, base, float(n))

        for ktoks, kstart in kept_slabs():
            rest_tok = np.concatenate([rest_tok, ktoks])
            rest_start = np.concatenate([rest_start, kstart])
            while rest_tok.shape[0] >= T:
                yield emit(rest_tok[:T], rest_start[:T])
                base += T
                rest_tok = rest_tok[T:]
                rest_start = rest_start[T:].copy()
                if rest_start.shape[0]:
                    # the cut tail acts as a new sentence (device treats the
                    # leading run of a block as one regardless)
                    rest_start[0] = True
        if rest_tok.shape[0]:
            yield emit(rest_tok, rest_start)

    def _device_step_rows(self, sentences: Sequence[np.ndarray], k: int, segs,
                          skips=None, counts=None):
        """One entry per step-row over the given data segments, stacked across
        them: (tokens [n, T], start_bits [n, ·], nvalid [n] f32, obase [n, 2]
        i32, exp_kept). A segment that exhausts before the others rides as zero
        blocks (nvalid 0 — masked on device); the stream ends when every listed
        segment is exhausted. The uint64→2×int32 ordinal-base split packing
        lives only here; both the single-process and the sharded device-feed
        chunk streams consume this shape.

        ``skips`` (resume): per-segment block counts to fast-forward before
        joining — -1 means the segment already finished this iteration (empty
        from the start, no production cost). ``counts``: optional list updated
        in place with each segment's consumed-block total (skips included) —
        the per-SEGMENT positions elastic resume persists.

        Parallelism (config.producer_workers > 1): with multiple segments the
        per-segment block streams run on their own prefetching threads, gated
        by a shared semaphore so at most ``producer_workers`` segments produce
        concurrently (the ISSUE-3 multi-worker producer: segments are
        independent and deterministic per (seed, k, s), and the merge below
        consumes them in fixed segment order, so the joined step-row stream is
        bit-identical to the serial one). Single-segment calls parallelize at
        the slab level inside _device_seg_blocks instead."""
        segs = list(segs)
        T = self._tokens_per_step
        tok_dt = self._pair_dtype
        nbytes = (T + 7) // 8
        workers = self.config.producer_workers
        multi_seg = workers > 1 and len(segs) > 1
        # split the worker budget: up to `workers` segments produce at once
        # (the semaphore below), and each segment's slab work gets the
        # leftover share — with fewer segments than workers the slab fan-out
        # uses the rest instead of idling (workers=8 over 2 segments → 2
        # segment threads × 4 slab workers, not 2 × 1)
        inner_workers = max(1, workers // len(segs)) if multi_seg else workers
        iters = []
        for i, s in enumerate(segs):
            skip = 0 if skips is None else skips[i]
            if skip < 0:
                iters.append(iter(()))
                continue
            it = self._device_seg_blocks(sentences, k, s,
                                         workers=inner_workers)
            consumed = 0
            for _ in range(skip):
                if next(it, None) is None:
                    # shorter stream than the checkpointed position can only
                    # mean the corpus changed since the checkpoint — replaying
                    # silently would train the wrong data with wrong books
                    raise ValueError(
                        f"device-feed resume: segment {s} iteration {k} has "
                        f"only {consumed} blocks but the checkpoint recorded "
                        f"{skip} — the corpus does not match the checkpoint")
                consumed += 1
            iters.append(it)
            if counts is not None:
                counts[i] += consumed
        closers: List[_threaded_iter] = []
        if multi_seg:
            import threading
            sem = threading.Semaphore(workers)
            _DONE = object()

            def gated(gen):
                # hold the semaphore only while producing one block, so at
                # most `workers` segment streams burn CPU at once
                while True:
                    with sem:
                        item = next(gen, _DONE)
                    if item is _DONE:
                        return
                    yield item

            wrapped = []
            for it in iters:
                ti = _threaded_iter(gated(it), maxsize=2)
                closers.append(ti)
                wrapped.append(iter(ti))
            iters = wrapped
        try:
            while True:
                rows = []
                exp_kept = 0.0
                exhausted = 0
                for i, it in enumerate(iters):
                    blk = next(it, None)
                    if blk is None:
                        exhausted += 1
                        rows.append((np.zeros(T, tok_dt),
                                     np.zeros(nbytes, np.uint8), 0, 0, 0.0))
                    else:
                        rows.append(blk)
                        exp_kept += blk[4]
                        if counts is not None:
                            counts[i] += 1
                if exhausted == len(iters):
                    return
                tokens = np.stack([r[0] for r in rows])
                starts = np.stack([r[1] for r in rows])
                nvalid = np.asarray([r[2] for r in rows], np.float32)
                obase = np.asarray(
                    [[r[3] & 0xFFFFFFFF, r[3] >> 32] for r in rows],
                    np.uint32).view(np.int32)
                yield (tokens, starts, nvalid, obase, exp_kept)
        finally:
            for c in closers:
                c.close()

    def _fit_device_feed(
        self,
        sentences: Sequence[np.ndarray],
        checkpoint_path: Optional[str],
        checkpoint_every_steps: Optional[int],
        on_heartbeat: Optional[Callable[[HeartbeatRecord], None]],
        total_words: float,
        train_words: float,
        K: int,
    ) -> EmbeddingPair:
        """fit() for the token-block feeds: the on-device pair generator
        (config.device_pairgen) and banded CBOW (config.cbow_update="banded",
        whose blocks overlap by ±window and whose "pairs" are CBOW examples —
        the chunk/step plumbing below is shared unchanged).

        The host packs whole sentences into fixed [T]-token blocks per (step,
        data-segment) and ships raw tokens + packed sentence-start bits + ordinal
        bases — ~2.1 bytes/token ≈ 1 byte/pair vs 4 for packed pairs. Subsampling
        and window expansion happen inside the jitted chunk (ops/pairgen.py, same
        hash lattice → bit-identical stream). The lr clock advances on the
        *expected* kept-word count per step (keep_prob summed over shipped tokens) —
        deterministic, and no worse an approximation than the reference's
        ``numPartitions · wordCount`` clock (mllib:406-410); exact trained-pair and
        dropped-pair totals come back from the device at the end of the run.
        """
        cfg = self.config
        from glint_word2vec_tpu.data.hashrng import (
            STREAM_SUBSAMPLE, STREAM_WINDOW, stream_base)
        Sd = self.plan.num_data
        T = self._tokens_per_step
        tok_dt = self._pair_dtype
        seg_state = None
        if self.state.shard_progress is not None and not self.state.finished:
            if self.state.shard_feed != "tokens":
                raise ValueError(
                    "checkpoint was written by a host-feed sharded-input run "
                    "(its positions index per-process pair streams); resume it "
                    "with the same process count and device_pairgen=False")
            # elastic shrink: a multi-process device-feed checkpoint records
            # per-SEGMENT (iteration, blocks) positions — one process can pick
            # all of them up (_device_seg_resume_state validates the count).
            # Single-process-written checkpoints (batches_done > 0) keep the
            # legacy row-level skip: it rebuilds the lr clock exactly, where
            # the per-segment path is exact to < 1 clock word
            if self.state.batches_done == 0:
                seg_state = self._device_seg_resume_state()
        start_iter = (min(it for it, _ in seg_state) if seg_state
                      else self.state.iteration)
        skip_steps = (self.state.batches_done
                      if not (self.state.finished or seg_state) else 0)
        # analytic pairs/step estimate — heartbeat display only; exact totals come
        # back from the device (see end of method)
        rate_per_kept = (_cbow_examples_per_kept_token(cfg.window)
                         if self._banded_cbow
                         else _pairs_per_kept_token(cfg.window))

        def chunk_stream():
            for k in range(start_iter, cfg.num_iterations + 1):
                prev_words = (k - 1) * train_words
                sub_bases = np.asarray(
                    [stream_base(cfg.seed, STREAM_SUBSAMPLE, k, s)
                     for s in range(Sd)], np.uint32)
                win_bases = np.asarray(
                    [stream_base(cfg.seed, STREAM_WINDOW, k, s)
                     for s in range(Sd)], np.uint32)
                if seg_state:
                    # Elastic resume from per-segment positions: fast-forward
                    # each segment's block stream independently — recomputed
                    # for EVERY k (entries may sit at different iterations,
                    # e.g. an exhausted process frozen an iteration behind the
                    # rest). The skipped rows' kept counts (the within-
                    # iteration lr clock) are rebuilt from the saved word
                    # count (exact to < 1 word) for the iteration the
                    # checkpoint was saved in; earlier catch-up iterations
                    # yield no rows at all, later ones start fresh.
                    skips = [blocks if it == k else (-1 if it > k else 0)
                             for it, blocks in seg_state]
                    clock = (max(0.0, float(self.state.words_processed)
                                 - prev_words)
                             if k == self.state.iteration else 0.0)
                    steps_in_iter = max(
                        [b for it, b in seg_state if it == k], default=0)
                    to_skip = 0
                else:
                    skips = None
                    clock = 0.0
                    steps_in_iter = skip_steps if k == start_iter else 0
                    to_skip = skip_steps if k == start_iter else 0
                counts = [0] * Sd  # filled in place by _device_step_rows
                pending: List[tuple] = []
                pending_words: List[float] = []

                def flush():
                    nonlocal pending, pending_words, steps_in_iter
                    real = len(pending)
                    while len(pending) < K:
                        pending.append((np.zeros((Sd, T), tok_dt),
                                        np.zeros((Sd, (T + 7) // 8), np.uint8),
                                        np.zeros(Sd, np.float32),
                                        np.zeros((Sd, 2), np.int32), 0.0))
                        pending_words.append(pending_words[-1])
                    arrays = {
                        "tokens": np.stack([p[0] for p in pending]),
                        "starts": np.stack([p[1] for p in pending]),
                        "obase": np.stack([p[3] for p in pending]),
                    }
                    nvalid = np.stack([p[2] for p in pending])       # [K, Sd]
                    alphas = np.asarray([
                        alpha_schedule(w, total_words, cfg.learning_rate,
                                       cfg.min_alpha_factor)
                        for w in pending_words], np.float32)
                    meta = np.concatenate([alphas[None, :], nvalid.T])  # [1+Sd, K]
                    est_pairs = sum(p[4] for p in pending) * rate_per_kept
                    steps_in_iter += real
                    # per-segment positions after this chunk — what elastic
                    # resume (any process count) reads back
                    sprog = [(seg_state[s] if skips and skips[s] < 0
                              else [k, counts[s]]) for s in range(Sd)]
                    out = dict(
                        arrays=arrays, meta=meta, real=real, iteration=k,
                        words_processed=int(pending_words[real - 1]),
                        # after an elastic (per-segment) resume the joined rows
                        # are offset from the canonical stream, so a row count
                        # would mis-position a later legacy resume — persist 0
                        # and let sprog stay the authoritative position
                        batches_done=0 if seg_state else steps_in_iter,
                        est_pairs=est_pairs,
                        sub_bases=sub_bases, win_bases=win_bases, sprog=sprog)
                    pending, pending_words = [], []
                    return out

                for row in self._device_step_rows(sentences, k, range(Sd),
                                                  skips=skips, counts=counts):
                    clock += row[4]
                    if to_skip:
                        to_skip -= 1
                        continue
                    pending.append(row)
                    pending_words.append(prev_words + clock)
                    if len(pending) == K:
                        yield flush()
                if pending:
                    yield flush()

        staged = cfg.prefetch_chunks > 0  # this method is the single-process path
                                          # (multi-process device feed goes through
                                          # _fit_device_feed_sharded)
        stream = self._tracer.wrap_iter("producer", chunk_stream())
        if staged:
            chunks = _threaded_iter(
                self._stage_to_device(stream), cfg.prefetch_chunks)
        else:
            chunks = stream

        self._start_run_bookkeeping()
        chunks = iter(chunks)
        pairs_arrays: List[jax.Array] = []      # [K] per chunk, summed at the end
        dropped_arrays: List[jax.Array] = []
        est_total = 0.0
        try:
            while True:
                t0 = time.perf_counter()
                chunk = next(chunks, None)
                wait = time.perf_counter() - t0
                self.host_wait_time += wait
                self._phases.add("producer_wait", wait)
                if chunk is None:
                    break
                t0 = time.perf_counter()
                with self._tracer.span("dispatch"):
                    stacked = (chunk["arrays"] if staged else
                               put_global(self._chunk_shardings,
                                          chunk["arrays"]))
                    real = chunk["real"]
                    meta_dev, base_dev, sub_dev, win_dev = \
                        self._stage_dispatch_meta(
                            chunk["meta"], self.global_step + 1,
                            chunk["sub_bases"], chunk["win_bases"])
                    self.params, (metrics, dropped) = \
                        self._dispatch_step_fn(real)(
                            self.params, stacked, meta_dev, base_dev,
                            self._table_prob, self._table_alias,
                            self._keep_prob_dev, sub_dev, win_dev)
                self.dispatch_time += time.perf_counter() - t0
                self._after_dispatch()
                pairs_arrays.append(metrics.pairs)
                dropped_arrays.append(dropped)
                est_total += chunk["est_pairs"]
                self._finish_round(
                    real, chunk["est_pairs"], chunk["meta"][0], metrics,
                    TrainState(iteration=chunk["iteration"],
                               words_processed=chunk["words_processed"],
                               batches_done=chunk["batches_done"],
                               # per-segment positions so a multi-process run
                               # can pick this checkpoint up (elastic grow);
                               # this path's own resume uses batches_done
                               shard_progress=[[int(a), int(b)]
                                               for a, b in chunk["sprog"]],
                               shard_feed="tokens"),
                    checkpoint_path, checkpoint_every_steps, on_heartbeat)
        except BaseException:
            self._abort_run()  # its docstring has the why-not-sys.exc_info
            raise
        finally:
            self._stop_profiler()
            closer = getattr(chunks, "close", None)
            if closer is not None:
                closer()

        self._settle_device_pairgen_books(pairs_arrays, dropped_arrays, est_total)
        self.state = TrainState(
            iteration=cfg.num_iterations,
            words_processed=int(cfg.num_iterations * train_words),
            finished=True, global_step=self.global_step)
        if checkpoint_path:
            self.save_checkpoint(checkpoint_path)
        self._end_run("ok")
        return self.params

    def _settle_device_pairgen_books(
        self,
        pairs_arrays: List[jax.Array],
        dropped_arrays: List[jax.Array],
        est_total: float,
    ) -> None:
        """End-of-run accounting shared by both device-feed paths: heartbeats ran
        on the analytic pair estimate; settle the books against the exact trained
        and overflow-dropped totals the device reports."""
        if not pairs_arrays:
            return
        exact = float(jnp.concatenate(pairs_arrays).sum())
        dropped_total = float(jnp.stack(dropped_arrays).sum())
        self.pairs_trained += exact - est_total
        self._pairs_since_log = max(
            self._pairs_since_log + exact - est_total, 0.0)
        if dropped_total > 0.02 * max(exact, 1.0):
            logger.warning(
                "device pairgen dropped %.0f pairs (%.1f%% of %.0f trained) to "
                "overflow — raise tokens_per_step (or lower pairs_per_batch "
                "fill pressure)", dropped_total,
                100.0 * dropped_total / exact, exact)
        elif dropped_total:
            logger.info("device pairgen: %.0f overflow pairs dropped "
                        "(%.3f%%)", dropped_total,
                        100.0 * dropped_total / max(exact, 1.0))

    def _assert_feed_consistent(self, arrays: dict, meta: np.ndarray) -> None:
        """Debug-mode SPMD divergence detector (config.feed_consistency_check):
        every process fingerprints its ASSEMBLED global feed + meta and one
        allgather compares them. Identical step inputs on every process are the
        contract that makes the jitted update SPMD-consistent; a mismatch here
        (nondeterministic host pipeline, clock drift, corrupted transport)
        would otherwise surface only as silent training divergence. Aux-
        subsystem analog of race detection: the reference accepted races by
        design (Hogwild, SURVEY §5) — a synchronous design can verify its
        no-divergence contract instead."""
        import zlib

        from jax.experimental import multihost_utils
        h = 0
        for name in sorted(arrays):
            h = zlib.crc32(np.ascontiguousarray(arrays[name]).tobytes(), h)
        h = zlib.crc32(np.ascontiguousarray(meta).tobytes(), h)
        fps = multihost_utils.process_allgather(
            {"fp": np.asarray([h], np.int64)})["fp"][:, 0]
        if not (fps == fps[0]).all():
            raise RuntimeError(
                "SPMD feed divergence: per-process fingerprints of the "
                f"assembled global batch differ ({[int(f) for f in fps]}) — "
                "host pipelines produced different feeds (nondeterministic "
                "input ordering or clock drift); training would silently "
                "diverge from here")

    def _device_seg_resume_state(self) -> List[List[int]]:
        """Validated per-SEGMENT (iteration, blocks-consumed) resume positions
        for the device feed — [plan.num_data] entries in segment order. Fresh
        runs (and finished states) start every segment at (state.iteration, 0).
        Entries are per segment, not per process, so any process count dividing
        the mesh data degree can consume them (elastic restart)."""
        Sd = self.plan.num_data
        st = self.state
        if st.shard_progress is None or st.finished:
            if st.batches_done and not st.finished and jax.process_count() > 1:
                # a pre-elastic single-process position counts joined step ROWS
                # (zero-filled segments included) — not mappable to per-segment
                # block positions
                raise ValueError(
                    "checkpoint was written mid-iteration by a pre-elastic "
                    "device-feed run (no per-segment positions); resume it "
                    "single-process (or from an iteration boundary)")
            return [[st.iteration, 0] for _ in range(Sd)]
        if st.shard_feed != "tokens":
            # pairs-sharded positions count b_local PAIR-batches per process,
            # not token blocks; pre-round-4 checkpoints (shard_feed None) too
            raise ValueError(
                "checkpoint shard_progress indexes the host-feed pair streams "
                f"(shard_feed={st.shard_feed!r}); resume it with "
                "device_pairgen=False — token positions are a different stream")
        if len(st.shard_progress) != Sd:
            raise ValueError(
                f"checkpoint shard_progress has {len(st.shard_progress)} "
                f"entries but the mesh data degree is {Sd}; device-feed "
                "positions are per data segment — resume on a mesh with the "
                "same data degree")
        return [[int(a), int(b)] for a, b in st.shard_progress]

    def _fit_device_feed_sharded(
        self,
        sentences: Sequence[np.ndarray],
        checkpoint_path: Optional[str],
        checkpoint_every_steps: Optional[int],
        on_heartbeat: Optional[Callable[[HeartbeatRecord], None]],
        total_words: float,
        train_words: float,
        K: int,
    ) -> EmbeddingPair:
        """Multi-process fit with BOTH input sharding and the on-device pair
        generator: each process packs token blocks for its plan.num_data /
        process_count data segments only; one process_allgather per dispatch round
        ships (tokens, starts, ordinal bases, valid counts, expected-kept clock
        deltas, alive flags, stream positions) to every process, which assembles
        the identical [K, Sd, T] global token feed and derives identical alphas —
        the _fit_sharded lockstep protocol (see its docstring) carrying ~1
        byte/pair of raw tokens instead of 4 bytes/pair of packed pairs.

        Segment streams are deterministic per (seed, iteration, segment) and
        independent of the producing process (_device_seg_blocks), so the
        assembled feed — and therefore training — is bit-identical to the
        single-process device-feed run on the same mesh (tested:
        tests/test_multiprocess.py).

        Unlike _fit_sharded (which lets local streams cross iteration boundaries
        freely), this path holds an ITERATION BARRIER so the update sequence is
        bit-identical to the single-process run: every round, each process offers
        its next chunk, the round's iteration is the minimum over live offers,
        and only chunks AT that iteration are consumed — a process already in
        iteration k+1 contributes zeroed segments (exactly the zero blocks the
        single-process stream pads exhausted segments with) and retains its chunk
        for a later round. Alphas use the single-process convention
        ((k-1)·train_words + within-iteration kept cumsum), reconstructed
        identically everywhere from allgathered kept sums.

        ELASTIC RESUME: TrainState.shard_progress records, per DATA SEGMENT (not
        per process), the last consumed (iteration, blocks) position. Segments
        are the real stream unit — deterministic and process-independent — so a
        checkpoint written on N processes resumes on ANY M with
        mesh data degree % M == 0, including M=1 (the single-process device-feed
        path reads the same entries). The reference has no analog: its recovery
        story is Spark task retry against mutated PS state (SURVEY §5).

        STAGING (config.sharded_prefetch, PERF.md §10): with prefetching on,
        the per-round allgather/assembly/device-put runs one round ahead on a
        background thread under the _one_ahead_iter ticket handshake, which
        pins ONE deterministic per-process program-launch order — the
        determinism contract above is untouched because every staged value is
        still a pure function of allgathered data; only WHEN the host does the
        work moves.
        """
        from glint_word2vec_tpu.data.hashrng import (
            STREAM_SUBSAMPLE, STREAM_WINDOW, stream_base)
        cfg = self.config
        S = jax.process_count()
        pid = jax.process_index()
        Sd = self.plan.num_data
        spp = Sd // S
        own = list(range(pid * spp, (pid + 1) * spp))
        T = self._tokens_per_step
        tok_dt = self._pair_dtype
        nbytes = (T + 7) // 8

        # per-own-segment last consumed (iteration, blocks) — the elastic-resume
        # positions; fresh runs start every segment at (state.iteration, 0)
        seg_state = self._device_seg_resume_state()[pid * spp:(pid + 1) * spp]
        start_iter = min(it for it, _ in seg_state)

        rate_per_kept = (_cbow_examples_per_kept_token(cfg.window)
                         if self._banded_cbow
                         else _pairs_per_kept_token(cfg.window))

        def local_stream():
            """This process's chunks: K step-rows of spp [T]-token segment blocks
            + per-row expected-kept counts, this iteration's hash bases, and the
            per-own-segment (iteration, blocks) positions AFTER the chunk (the
            elastic-resume snapshot). Pure numpy — safe on the producer thread
            (the allgather, a device collective, must run on the main thread in
            identical order everywhere)."""
            for k in range(start_iter, cfg.num_iterations + 1):
                sub_b = np.asarray(
                    [stream_base(cfg.seed, STREAM_SUBSAMPLE, k, s) for s in own],
                    np.uint32)
                win_b = np.asarray(
                    [stream_base(cfg.seed, STREAM_WINDOW, k, s) for s in own],
                    np.uint32)
                # -1 = segment already past iteration k (finished it before the
                # checkpoint); its entry must survive the snapshot untouched
                skips = [blocks if it == k else (-1 if it > k else 0)
                         for it, blocks in seg_state]
                counts = [0] * spp  # filled in place by _device_step_rows
                pending: List[tuple] = []

                def flush():
                    nonlocal pending
                    real = len(pending)
                    while len(pending) < K:
                        pending.append((np.zeros((spp, T), tok_dt),
                                        np.zeros((spp, nbytes), np.uint8),
                                        np.zeros(spp, np.float32),
                                        np.zeros((spp, 2), np.int32), 0.0))
                    sprog = np.asarray(
                        [seg_state[i] if skips[i] < 0 else [k, counts[i]]
                         for i in range(spp)], np.int64)
                    out = dict(
                        tokens=np.stack([p[0] for p in pending]),
                        starts=np.stack([p[1] for p in pending]),
                        nvalid=np.stack([p[2] for p in pending]),
                        obase=np.stack([p[3] for p in pending]),
                        kept=np.asarray([p[4] for p in pending], np.float32),
                        sub_bases=sub_b, win_bases=win_b,
                        iteration=k, sprog=sprog, real=real)
                    pending = []
                    return out

                for row in self._device_step_rows(
                        sentences, k, own, skips=skips, counts=counts):
                    pending.append(row[:4] + (np.float32(row[4]),))
                    if len(pending) == K:
                        yield flush()
                if pending:
                    yield flush()

        lstream = self._tracer.wrap_iter("producer", local_stream())
        if cfg.prefetch_chunks > 0:
            chunks = _threaded_iter(lstream, cfg.prefetch_chunks)
        else:
            chunks = iter(lstream)

        # stage one round ahead (config.sharded_prefetch): the round generator
        # below runs on a _one_ahead_iter thread and launches the NEXT round's
        # allgather before yielding the current one, so the gather's wire
        # transfer sits ahead of the step dispatch in the device queue and the
        # host-side decode/assembly/put-DMA overlap chunk compute. The ticket
        # handshake keeps one deterministic cross-host launch order:
        # [gather_1, touch_1, gather_2], dispatch_1 + bookkeeping_1,
        # [touch_2, gather_3], dispatch_2, ... — identical on every process.
        staged = bool(cfg.sharded_prefetch and cfg.prefetch_chunks > 0)
        est_total = 0.0
        pairs_arrays: List[jax.Array] = []
        dropped_arrays: List[jax.Array] = []
        self._start_run_bookkeeping()
        beacons = self._start_peer_beacons(checkpoint_path)

        def round_stream():
            from glint_word2vec_tpu.parallel.distributed import (
                allgather_fetch, allgather_start)
            cur_sprog = np.asarray(seg_state, np.int64)  # [spp, 2] last CONSUMED
            # barrier state: the iteration currently training and its cumulative
            # kept-word clock. On resume the within-iteration clock is rebuilt
            # from the saved word count (exact to < 1 word — the int()
            # truncation of the analytic iteration base; same approximation
            # class as the saved clock itself, and resumed runs match
            # uninterrupted ones to the suite's 1e-4 standard, not bitwise)
            round_iter = self.state.iteration
            iter_kept = max(0.0, float(self.state.words_processed)
                            - (round_iter - 1) * train_words)
            held = None         # produced-but-not-yet-consumed local chunk
            exhausted = False
            zero = dict(tokens=np.zeros((K, spp, T), tok_dt),
                        starts=np.zeros((K, spp, nbytes), np.uint8),
                        nvalid=np.zeros((K, spp), np.float32),
                        obase=np.zeros((K, spp, 2), np.int32),
                        kept=np.zeros(K, np.float32),
                        sub_bases=np.zeros(spp, np.uint32),
                        win_bases=np.zeros(spp, np.uint32))

            def start_gather():
                """Collect this process's next offer and LAUNCH (not fetch) its
                allgather. The offer protocol is byte-identical to the
                pre-staging loop; only the launch/fetch split is new."""
                nonlocal held, exhausted
                if held is None and not exhausted:
                    t0 = time.perf_counter()
                    held = next(chunks, None)
                    if not staged:
                        wait = time.perf_counter() - t0
                        self.host_wait_time += wait
                        self._phases.add("producer_wait", wait)
                    if held is None:
                        exhausted = True
                offer = held if held is not None else dict(
                    zero, iteration=int(cur_sprog[:, 0].max()),
                    sprog=cur_sprog, real=0)
                return allgather_start({
                    "tokens": offer["tokens"], "starts": offer["starts"],
                    "nvalid": offer["nvalid"], "obase": offer["obase"],
                    "kept": offer["kept"],
                    "sub": offer["sub_bases"], "win": offer["win_bases"],
                    "real": np.asarray([offer["real"]], np.int32),
                    "iter": np.asarray([offer["iteration"]], np.int64),
                    "sprog": np.asarray(offer["sprog"], np.int64),
                    "alive": np.asarray([0 if exhausted else 1], np.int32),
                    "prog": cur_sprog,
                })

            pending = start_gather()
            while True:
                if beacons is not None:
                    # see _fit_sharded: a dead peer's collective never comes;
                    # check (a file stat — safe on this producer thread)
                    # before blocking on the fetch
                    beacons.check_or_raise()
                t0 = time.perf_counter()
                with self._tracer.span("allgather_fetch"):
                    g = allgather_fetch(pending)  # leading [S] process axis
                alive = g["alive"][:, 0] > 0                        # [S]
                if not alive.any():
                    # every process observes the same all-dead round and stops
                    # here; a pipelined gather for the round after may already
                    # be launched — every process launched it identically, so
                    # it executes consistently and nobody reads it
                    return
                # iteration barrier: this round trains the minimum live
                # iteration; offers from a later iteration are NOT consumed —
                # their segments ride as zeros (exactly the zero blocks the
                # single-process stream pads exhausted segments with) and
                # their owners re-offer them next round
                round_it = int(g["iter"][alive, 0].min())
                use = alive & (g["iter"][:, 0] == round_it)         # [S]
                if round_it != round_iter:
                    round_iter, iter_kept = round_it, 0.0
                usef = use.astype(np.float32)
                # segment axis assembly: [S, K, spp, ...] -> [K, S*spp=Sd, ...]
                arrays = {
                    "tokens": np.transpose(
                        g["tokens"] * use[:, None, None, None].astype(tok_dt),
                        (1, 0, 2, 3)).reshape(K, Sd, T),
                    "starts": np.transpose(
                        g["starts"] * use[:, None, None, None].astype(np.uint8),
                        (1, 0, 2, 3)).reshape(K, Sd, nbytes),
                    "obase": np.transpose(
                        g["obase"] * use[:, None, None, None].astype(np.int32),
                        (1, 0, 2, 3)).reshape(K, Sd, 2),
                }
                nvalid = np.transpose(
                    g["nvalid"] * usef[:, None, None], (1, 0, 2)).reshape(K, Sd)
                sub_bases = g["sub"].reshape(Sd)
                win_bases = g["win"].reshape(Sd)
                kept_step = (g["kept"].astype(np.float64)
                             * usef[:, None]).sum(axis=0)           # [K]
                # the single-process alpha convention: analytic iteration base
                # plus the within-iteration kept cumsum (identical on every
                # process — all inputs are allgathered values)
                clocks = ((round_it - 1) * train_words + iter_kept
                          + np.cumsum(kept_step))
                iter_kept += float(kept_step.sum())
                alphas = np.asarray(
                    [alpha_schedule(float(w), total_words, cfg.learning_rate,
                                    cfg.min_alpha_factor) for w in clocks],
                    np.float32)
                meta = np.concatenate([alphas[None, :], nvalid.T])  # [1+Sd, K]
                # used processes pad only their final chunk per iteration, so
                # real rows are prefixes; the longest prefix is the row count
                real = int(g["real"][use, 0].max())
                est_pairs = float(kept_step.sum()) * rate_per_kept

                if cfg.feed_consistency_check:
                    self._assert_feed_consistent(
                        dict(arrays, sub=sub_bases, win=win_bases), meta)
                with self._tracer.span("stage_put"):
                    stacked = put_global(self._chunk_shardings, arrays)
                    if staged and not self._sync_collectives:
                        # force the upload DMA now, overlapped with chunk
                        # compute (skipped on the CPU mesh — see
                        # _stage_to_device; the gate condition is identical on
                        # every process, so the pinned cross-process launch
                        # order stays consistent)
                        self._touch(stacked)
                if use[pid] and held is not None:
                    cur_sprog = np.asarray(held["sprog"], np.int64)
                    held = None
                # prog in THIS round's allgather predates the consumption
                # above, so each SEGMENT's persisted position comes from its
                # owner's offer if consumed, else from its last consumed
                # snapshot — a held offer was not trained
                prog = [[int(a), int(b)]
                        for s in range(S)
                        for a, b in (g["sprog"][s] if use[s] else g["prog"][s])]
                if staged:
                    # pipelining: LAUNCH the next round's gather before
                    # yielding, so it precedes this round's dispatch in every
                    # process's launch order and its transfer rides ahead of
                    # the chunk in the device queue
                    pending = start_gather()
                else:
                    self.dispatch_time += time.perf_counter() - t0
                yield dict(
                    stacked=stacked, meta=meta, real=real, est_pairs=est_pairs,
                    sub_bases=sub_bases, win_bases=win_bases, round_it=round_it,
                    words=int(clocks[max(real - 1, 0)]), prog=prog)
                if not staged:
                    pending = start_gather()

        rounds = round_stream()
        if staged:
            rounds = _one_ahead_iter(rounds)
        rounds_it = iter(rounds)
        try:
            while True:
                t0 = time.perf_counter()
                rnd = next(rounds_it, None)
                if staged:
                    # unstaged, the wait IS the round assembly — its stage/
                    # dispatch splits are attributed inside round_stream
                    wait = time.perf_counter() - t0
                    self.host_wait_time += wait
                    self._phases.add("producer_wait", wait)
                if rnd is None:
                    break
                t0 = time.perf_counter()
                with self._tracer.span("dispatch"):
                    meta_dev, base_dev, sub_dev, win_dev = \
                        self._stage_dispatch_meta(
                            rnd["meta"], self.global_step + 1,
                            rnd["sub_bases"], rnd["win_bases"])
                    self.params, (metrics, dropped) = \
                        self._dispatch_step_fn(rnd["real"])(
                            self.params, rnd["stacked"], meta_dev, base_dev,
                            self._table_prob, self._table_alias,
                            self._keep_prob_dev, sub_dev, win_dev)
                self.dispatch_time += time.perf_counter() - t0
                self._after_dispatch()
                pairs_arrays.append(metrics.pairs)
                dropped_arrays.append(dropped)
                est_total += rnd["est_pairs"]
                self._finish_round(
                    rnd["real"], rnd["est_pairs"], rnd["meta"][0], metrics,
                    TrainState(
                        iteration=rnd["round_it"],
                        words_processed=rnd["words"],
                        # meaningless across segments — resume uses the
                        # per-segment shard_progress
                        batches_done=0,
                        shard_progress=rnd["prog"], shard_feed="tokens"),
                    checkpoint_path, checkpoint_every_steps, on_heartbeat)
                if staged:
                    # round fully consumed (dispatch + any heartbeat fetch /
                    # checkpoint collectives launched) — release the stager
                    rounds.ack()
        except BaseException:
            self._abort_run()  # its docstring has the why-not-sys.exc_info
            raise
        finally:
            self._stop_profiler()
            if beacons is not None:
                beacons.stop()
            closer = getattr(rounds, "close", None)
            if closer is not None:
                closer()
            closer = getattr(chunks, "close", None)
            if closer is not None:
                closer()

        self._settle_device_pairgen_books(pairs_arrays, dropped_arrays, est_total)
        self.state = TrainState(
            iteration=cfg.num_iterations,
            words_processed=int(cfg.num_iterations * train_words),
            finished=True, global_step=self.global_step)
        if checkpoint_path:
            self.save_checkpoint(checkpoint_path)
        self._end_run("ok")
        return self.params

    def _stage_to_device(self, chunks):
        """Generator stage: place each chunk's feed arrays on device and dispatch a
        tiny consuming op so the host→device wire transfer happens HERE — on the
        producer thread when prefetching — overlapped with the main thread's step
        dispatches. Through a thin link (remote-TPU tunnel, DCN feed) argument
        upload is otherwise lazy and serializes with compute at dispatch time
        (measured: a concurrent put+consume fully hides behind device compute,
        a consumer-thread put does not).

        Single-process free-running only: with multiple processes, a
        producer-thread dispatch would race the main thread's step dispatch for
        cross-host program launch order and can deadlock the collectives — the
        multi-process device-feed path instead stages through the
        ``_one_ahead_iter`` ticket handshake (see _fit_device_feed_sharded),
        which pins one deterministic launch order; the remaining multi-process
        feeds keep the consumer-thread put."""
        for chunk in chunks:
            with self._tracer.span("stage_put"):
                stacked = put_global(self._chunk_shardings, chunk["arrays"])
            chunk["arrays"] = stacked
            # retain the forcing op's output with the chunk (never fetched — a
            # blocking fetch here stalls the producer behind the device queue,
            # measured slower; the dispatch is enough to enqueue the upload).
            # NOT on the multi-device CPU mesh: the touch's tiny cross-shard
            # reduction lowers to collectives, and a producer-THREAD program
            # racing the main thread's chunk is exactly the rendezvous-
            # starvation deadlock __init__ documents (this touch was the
            # racer observed live). There is no lazy-upload wire to force on
            # that backend anyway — device_put is a host memcpy.
            if not self._sync_collectives:
                chunk["_touch"] = self._touch(stacked)
            yield chunk

    def _touch(self, stacked):
        """Dispatch a tiny consuming op over staged feed arrays so their
        host→device upload is enqueued NOW (on the calling thread) instead of
        lazily at step-dispatch time — the transfer-forcing half of
        :meth:`_stage_to_device`, shared with the sharded round stager."""
        if not hasattr(self, "_touch_fn"):
            import operator

            def touch(arrays):
                return jax.tree.reduce(
                    operator.add,
                    jax.tree.map(
                        lambda x: x.reshape(-1)[:1].astype(jnp.float32).sum(),
                        arrays))

            self._touch_fn = jax.jit(touch)
        return self._touch_fn(stacked)

    @property
    def _needs_snapshot_ring(self) -> bool:
        """Single derived predicate for arming the snapshot ring: ANY
        consumer — nonfinite rollback or the watchdog recovery ladder —
        arms it. Pre-round-12 only nonfinite_policy=='rollback' seeded the
        ring, so every other consumer found it empty on first firing (the
        previously-dead norm_watch='recover' + nonfinite_policy='halt'
        combination; regression-tested in tests/test_stabilizers.py)."""
        return (self.config.nonfinite_policy == "rollback"
                or self.config.norm_watch == "recover")

    def _start_run_bookkeeping(self) -> None:
        self.rollbacks_performed = 0  # max_rollbacks is a per-fit() budget
        self.recoveries_performed = 0  # max_recoveries likewise
        if self._needs_snapshot_ring and not self._snapshot_ring:
            # seed the ring with the starting params so even a blowup inside
            # the first heartbeat window has a restore point
            self._snapshot_ring.append(
                (self._copy_params(self.params), self.global_step))
        self.host_wait_time = 0.0      # fit() blocked on batch production (incl. the
                                       # producer's device staging when prefetching)
        self.dispatch_time = 0.0       # fit() inside (async) step dispatch; also the
                                       # feed transfer when prefetch_chunks=0 (no
                                       # producer thread to stage on)
        self._last_log_time = time.perf_counter()
        self._last_log_step = self.global_step
        self._pairs_since_log = 0.0
        self._last_hb_host_wait = 0.0
        self._last_hb_dispatch = 0.0
        self._profiling = False
        self._profile_start_step = self.global_step
        if self.config.profile_dir:
            import jax.profiler
            jax.profiler.start_trace(self.config.profile_dir)
            self._profiling = True
            logger.info("jax.profiler trace -> %s", self.config.profile_dir)
        # run telemetry (docs/observability.md): stamp the run, arm the span
        # tracer. The tracer is process-wide (checkpoint save/load record
        # spans without a Trainer handle), cleared per run so a trace file
        # describes exactly one fit.
        import os
        self._run_ended = False
        # preemption-deadline state (config.checkpoint_on_preempt): the
        # SIGTERM handler only ARMS the deadline; _finish_round's tail
        # drains it. Reset per fit so a resumed run re-arms cleanly.
        self._preempt_deadline = None
        self._preempt_signum = 0
        # last step a checkpoint actually published at — the preempt record's
        # progress-lost-since-last-save denominator
        self._last_save_step = int(self.global_step)
        self._run_id = f"{os.getpid()}-{int(time.time())}-{self.global_step}"
        observing = self._telemetry is not None or self.config.status_port > 0
        self._tracer.configure(enabled=observing)
        self._phases.clear()
        self._tracer.attach_phases(self._phases if observing else None)
        self._last_hb_phases = self._phases.raw_snapshot()
        # per-round marks for the flight recorder's dispatch ring
        self._bb_wait_mark = 0.0
        self._bb_disp_mark = 0.0
        if self._blackbox is not None:
            self._blackbox.begin_run(self._run_id)
        self._install_run_signals()
        if self.config.status_port and self._statusd is None:
            from glint_word2vec_tpu.obs.statusd import StatusServer
            self._statusd = StatusServer(
                self.config.status_port, self.status_snapshot).start()
        if self._telemetry is not None:
            from glint_word2vec_tpu.obs.trace import clock_anchor
            self._tracer.clear()
            cfg = self.config
            self._emit(
                "run_start", run_id=self._run_id, vocab_size=self.vocab.size,
                # the clock anchor (obs/trace.py): one simultaneous
                # wall/monotonic reading so tools/obs_collect.py can place
                # this process's spans on the fleet timeline
                **clock_anchor(),
                mesh=[self.plan.num_data, self.plan.num_model],
                config={k: getattr(cfg, k) for k in (
                    "vector_size", "learning_rate", "pairs_per_batch",
                    "negatives", "negative_pool", "subsample_ratio",
                    "param_dtype", "compute_dtype", "logits_dtype", "cbow",
                    "step_lowering", "device_pairgen", "nonfinite_policy",
                    "norm_watch", "norm_watch_threshold", "norm_watch_max",
                    "norm_watch_frac", "heartbeat_every_steps",
                    "max_row_norm", "update_clip", "row_l2",
                    "recover_lr_backoff", "max_recoveries")})

    def _stop_profiler(self) -> None:
        if getattr(self, "_profiling", False):
            import jax.profiler
            jax.profiler.stop_trace()
            self._profiling = False

    # rollback re-seed: the negative-sample stream is a pure function of
    # (seed, global_step) — ops/prng.py — so jumping the counter far past any
    # step the run will legitimately reach gives the retried stretch a fresh
    # negative-sample path WITHOUT rebuilding the jitted step (the seed itself
    # is a compile-time constant). 2^22 steps is ~275B pairs at B=64k, far
    # beyond any single fit; repeated rollbacks jump again, so paths never
    # overlap.
    _ROLLBACK_STEP_JUMP = 1 << 22

    def _health_stats(self) -> dict:
        """Run the fused on-device health probe (obs/probe.py) and return its
        channel dict: the old finiteness bit PLUS per-matrix row-norm
        channels (max/mean/p99, frac over the watchdog threshold) from ONE
        reduction pass, and the host-side update-magnitude proxy (delta of
        mean_norm between consecutive probes).

        Drains in-flight chunk dispatches BEFORE launching the probe: on a
        multi-device mesh the probe's cross-shard reductions are themselves a
        collective-bearing program; dispatching it while a chunk is still at
        its collective rendezvous puts two independent collective programs in
        flight — the XLA:CPU rendezvous-starvation deadlock documented at
        _sync_collectives in __init__. Waiting on the carry is the sync the
        heartbeat fetch was already paying, so steady-state cost is
        unchanged. The result is fetched EXPLICITLY (jax.device_get) so the
        probe stays clean under the stepaudit transfer contract
        (tools/stepaudit.py runs scripted fits under jax.transfer_guard)."""
        if self._health_fn is None:
            from glint_word2vec_tpu.obs.probe import make_health_probe
            self._health_fn = make_health_probe(
                self.vocab.size, self.config.norm_watch_threshold)
        from glint_word2vec_tpu.obs.probe import stats_to_channels
        jax.block_until_ready(self.params)
        with self._tracer.span("health_probe"):
            channels = stats_to_channels(
                jax.device_get(self._health_fn(self.params)))
        prev = self._last_probe_channels
        if prev is not None:
            channels["update_mag"] = round(
                abs(channels["syn0"]["mean_norm"] - prev["syn0"]["mean_norm"])
                + abs(channels["syn1"]["mean_norm"]
                      - prev["syn1"]["mean_norm"]), 9)
        self._last_probe_channels = channels
        return channels

    def _params_finite(self) -> bool:
        return bool(self._health_stats()["finite"])

    def _copy_params(self, params: EmbeddingPair) -> EmbeddingPair:
        if self._copy_params_fn is None:
            self._copy_params_fn = jax.jit(
                lambda p: jax.tree.map(jnp.copy, p))
        return self._copy_params_fn(params)

    def _nonfinite_diagnostic(self) -> str:
        bad0 = int(jnp.sum(~jnp.isfinite(self.params.syn0)))
        bad1 = int(jnp.sum(~jnp.isfinite(self.params.syn1)))
        return (
            f"non-finite parameters at global step {self.global_step}: "
            f"{bad0} entries in syn0, {bad1} in syn1 (of "
            f"{self.padded_vocab}x{self.padded_dim} each). Likely causes, in "
            f"measured order (EVAL.md): pool-row overload "
            f"(grow negative_pool), duplicate-overload (lower subsample_ratio "
            f"~1e-4 or set duplicate_scaling=True), or learning rate too high "
            f"for {self.config.param_dtype}. Set nonfinite_policy='rollback' "
            f"to auto-recover from the last good snapshot instead of halting")

    def _nonfinite_guard(self, channels: Optional[dict] = None) -> None:
        """Heartbeat-cadence finiteness guardrail (config.nonfinite_policy).
        The probe is a separate jitted reduction over the params carry (the
        fused health probe, obs/probe.py — finiteness plus the norm channels
        in one pass), fetched alongside the heartbeat's metrics fetch (which
        already forces a device sync) — the training step functions are
        untouched, so the fast metrics-elided twin stays elided. ``channels``
        lets a caller that already probed this round (the watchdog/heartbeat
        path in _finish_round) share the fetch. On a finite probe under
        ``rollback``, the current params are snapshotted into the ring; on a
        non-finite probe the policy decides: ``halt`` raises with a
        diagnostic, ``rollback`` pops and restores the newest good snapshot
        and jumps the negative-sample counter lattice so the retried stretch
        draws different negatives (the host data stream keeps advancing — the
        updates between the snapshot and the blowup are sacrificed, the same
        accounting loss as resuming a checkpoint). Repeated blowups before the
        next finite probe step back through the older ring entries; an
        emptied ring raises."""
        cfg = self.config
        if channels is None:
            channels = self._health_stats()
        if channels["finite"]:
            self._maybe_snapshot(channels)
            return
        if cfg.nonfinite_policy == "halt":
            raise NonFiniteParamsError(self._nonfinite_diagnostic())
        if not self._snapshot_ring:
            if self.rollbacks_performed:
                raise NonFiniteParamsError(
                    f"rollback ring exhausted after "
                    f"{self.rollbacks_performed} rollback(s) — repeated "
                    f"divergence consumed every good snapshot; this needs a "
                    f"config change, not retries. "
                    + self._nonfinite_diagnostic())
            raise NonFiniteParamsError(
                self._nonfinite_diagnostic()
                + " (rollback requested but no good snapshot was taken yet "
                  "— blowup before the first probe)")
        if self.rollbacks_performed >= cfg.max_rollbacks:
            raise NonFiniteParamsError(
                f"giving up after {self.rollbacks_performed} rollbacks — the "
                f"run keeps diverging; this needs a config change, not "
                f"retries. " + self._nonfinite_diagnostic())
        snap_step, old_step = self._restore_snapshot()
        self.rollbacks_performed += 1
        logger.warning(
            "non-finite params at step %d: rolled back to the snapshot from "
            "step %d and re-seeded the negative-sample lattice (counter -> %d; "
            "rollback %d/%d)", old_step, snap_step, self.global_step,
            self.rollbacks_performed, self.config.max_rollbacks)

    def _restore_snapshot(self) -> Tuple[int, int]:
        """POP the newest snapshot-ring entry and restore it directly (no
        copy — the entry leaves the ring, so the next dispatch is free to
        donate its buffers), then jump the negative-sample counter lattice
        far past any step the run will legitimately reach so the retried
        stretch draws a fresh sample path without rebuilding the jitted step
        (the seed is a compile-time constant). Popping is what makes the
        deeper ring entries reachable: a retry that blows up again before
        the next good probe steps back to the NEXT-older snapshot instead of
        thrashing on the same one, and an emptied ring escalates to the
        caller's halt diagnostic. ONE owner for both consumers (non-finite
        rollback and watchdog recovery) so the reseed invariant cannot
        drift. Returns (snapshot_step, pre-restore global_step)."""
        params, snap_step = self._snapshot_ring.pop()
        self.params = params
        old_step = self.global_step
        self.global_step = max(self.global_step, snap_step) + \
            self._ROLLBACK_STEP_JUMP
        self.state = dc_replace(self.state, global_step=self.global_step)
        return int(snap_step), old_step

    def _maybe_snapshot(self, channels: dict) -> None:
        """Append the current params to the snapshot ring when any consumer
        needs it (the `_needs_snapshot_ring` predicate) AND the probed state
        is worth restoring: finite, and — when the watchdog is armed — not a
        state it would flag (a carry mid-blowup must never become the 'good'
        restore point the recovery then thrashes back to)."""
        if not self._needs_snapshot_ring or not channels["finite"]:
            return
        if (self.norm_watchdog.policy != "off"
                and self.norm_watchdog.would_fire(channels)):
            return
        self._snapshot_ring.append(
            (self._copy_params(self.params), self.global_step))

    def _watchdog_check(self, channels: dict) -> bool:
        """Feed one probe result to the finite-blowup watchdog and persist any
        firing to the telemetry sink — for ``halt`` the record is emitted
        BEFORE the raise, so the run log carries the evidence the exception
        message summarizes. Under ``norm_watch="recover"`` a firing runs the
        mitigate-and-recover half of the ladder (:meth:`_perform_recovery`);
        returns True when that consumed this round (the caller must not
        snapshot the pre-restore params)."""
        from glint_word2vec_tpu.train.faults import NormBlowupError
        try:
            reason = self.norm_watchdog.check(channels, self.global_step)
        except NormBlowupError:
            if self._telemetry is not None:
                self._emit(
                    "watchdog", step=self.global_step, policy="halt",
                    reason=self.norm_watchdog.last_reason or "",
                    channels=channels)
            raise
        if reason and self._telemetry is not None:
            self._emit(
                "watchdog", step=self.global_step,
                policy=self.config.norm_watch, reason=reason,
                channels=channels)
        if reason and self.config.norm_watch == "recover":
            self._perform_recovery(reason, channels)
            return True
        return False

    def _perform_recovery(self, reason: str, channels: dict) -> None:
        """The mitigate→recover half of the detect→mitigate→recover ladder
        (docs/robustness.md), run once per firing probe under
        ``norm_watch="recover"``:

        1. emit the telemetry ``recovery`` record FIRST — before any state
           mutates, so even a crash mid-recovery leaves the evidence;
        2. roll back to the newest snapshot-ring entry (popped, like the
           nonfinite path — repeated firings step back through older
           entries) and jump the negative-sample counter lattice so the
           retried stretch draws a fresh sample path;
        3. auto-engage mitigation for the resumed run: multiply the
           effective lr by ``config.recover_lr_backoff`` (compounding), and
           engage ``max_row_norm`` at ``config.norm_watch_threshold`` if no
           clamp was configured (the step functions are rebuilt — one
           recompile per engagement, logged);
        4. budget: after ``config.max_recoveries`` recoveries in one fit —
           or with no snapshot left — degrade to the ``halt`` contract
           (NormBlowupError with the full diagnostic, record emitted before
           the raise), exactly like the non-finite guardrail's exhaustion
           path."""
        from glint_word2vec_tpu.train.faults import NormBlowupError
        cfg = self.config

        def emit(action: str, snap_step: int, lr_scale: float,
                 clamp: float) -> None:
            if self._telemetry is not None:
                self._emit(
                    "recovery", step=self.global_step, action=action,
                    reason=reason, snapshot_step=snap_step,
                    recoveries_performed=self.recoveries_performed
                    + (1 if action == "rollback" else 0),
                    max_recoveries=cfg.max_recoveries,
                    lr_scale=round(lr_scale, 9), max_row_norm=clamp,
                    channels=channels)

        if self.recoveries_performed >= cfg.max_recoveries:
            emit("halt", -1, self._lr_scale, self._stabilizers.max_row_norm)
            raise NormBlowupError(
                f"recovery budget exhausted after {self.recoveries_performed}"
                f" recoveries (max_recoveries={cfg.max_recoveries}) — the "
                f"run keeps re-entering the blowup region under lr_scale="
                f"{self._lr_scale:g} and max_row_norm="
                f"{self._stabilizers.max_row_norm:g}; this needs a config "
                f"change (negative_pool/subsample_ratio/learning_rate — "
                f"EVAL.md), not more retries. Last firing: {reason}")
        if not self._snapshot_ring:
            emit("halt", -1, self._lr_scale, self._stabilizers.max_row_norm)
            raise NormBlowupError(
                f"norm_watch='recover' fired with no good snapshot left "
                f"({self.recoveries_performed} recovery(ies) already "
                f"consumed the ring) — repeated blowups before any finite "
                f"healthy probe; this needs a config change, not retries. "
                f"Last firing: {reason}")

        new_scale = self._lr_scale * cfg.recover_lr_backoff
        engage_clamp = not self._stabilizers.max_row_norm
        clamp_after = (cfg.norm_watch_threshold if engage_clamp
                       else self._stabilizers.max_row_norm)
        emit("rollback", int(self._snapshot_ring[-1][1]), new_scale,
             clamp_after)

        snap_step, old_step = self._restore_snapshot()
        self.recoveries_performed += 1
        self._lr_scale = new_scale
        if engage_clamp:
            # engage the clamp at the watchdog threshold: the boundary the
            # firing measured health by — rows at/below it are by definition
            # outside the firing signature (provenance: healthy EVAL rows
            # sit at norm 1-15, the threshold at 100)
            self._stabilizers = self._stabilizers._replace(
                max_row_norm=float(cfg.norm_watch_threshold))
            self._step_fn = self._build_step()
            self._step_fn_fast = (
                self._build_step(with_metrics=False)
                if (cfg.negative_pool > 0 and not cfg.use_pallas
                    and not (cfg.cbow and cfg.duplicate_scaling))
                else self._step_fn)
        logger.warning(
            "norm watchdog recovery %d/%d at step %d: rolled back to the "
            "snapshot from step %d, re-seeded the sample lattice (counter -> "
            "%d), lr backed off to x%g%s — firing: %s",
            self.recoveries_performed, cfg.max_recoveries, old_step,
            snap_step, self.global_step, self._lr_scale,
            (f", engaged max_row_norm={self._stabilizers.max_row_norm:g}"
             if engage_clamp else ""), reason)

    def _emit(self, kind: str, **fields) -> None:
        """One telemetry record to the sink AND the flight recorder's ring
        (obs/blackbox.py) — single owner of record assembly, so the dump's
        ring entries are byte-for-byte the records the JSONL carries."""
        if self._telemetry is not None:
            self._telemetry.emit(kind, **fields)
        if self._blackbox is not None:
            self._blackbox.observe(kind, fields)

    def _install_run_signals(self) -> None:
        """Arm the flight recorder's SIGTERM hook for the duration of fit():
        SIGTERM is the first thing a preemption/k8s eviction sends and, unlike
        SIGINT (delivered as KeyboardInterrupt, which the fit paths' abort
        handler already turns into a dump), it would otherwise kill the
        process with no artifact. Main-thread only (the signal module's
        rule); restored by _teardown_run_inspection.

        Also armed — blackbox or not — when config.checkpoint_on_preempt
        asks the fit to answer a preemption with an emergency checkpoint
        instead of just dying (docs/robustness.md)."""
        if self._blackbox is None and not self.config.checkpoint_on_preempt:
            return
        import signal
        try:
            # signal.signal returns the PRIOR handler — which is legally
            # None when a non-Python (C-level) handler was installed, so a
            # separate installed flag distinguishes "nothing to restore"
            # from "prior handler unknown" (restored as SIG_DFL, best
            # effort — leaving OUR handler installed would loop forever on
            # the re-raise below)
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, self._on_sigterm)
            self._sigterm_installed = True
        except ValueError:
            self._sigterm_installed = False  # non-main-thread fit: no hook

    def _on_sigterm(self, signum, frame) -> None:
        import os
        from glint_word2vec_tpu.obs.blackbox import FlightRecorder
        if self._blackbox is not None:
            self._blackbox.dump(FlightRecorder.signal_cause(signum),
                                extra=self._dump_context())
        # preemption-deadline checkpointing (config.checkpoint_on_preempt):
        # a handler can interrupt arbitrary host code — mid-dispatch, inside
        # a collective, halfway through a save — where launching the
        # emergency save HERE could deadlock or tear. So the handler only
        # ARMS a deadline and returns; the in-flight dispatch finishes
        # naturally and _finish_round's tail (the first point where no
        # collective is in flight) drains the carry through the normal
        # digest-verified save path via _preempt_exit. First signal wins:
        # a repeat TERM while armed just returns (the deadline is already
        # running); one arriving after the run ended falls through to the
        # die-now path below.
        if (self.config.checkpoint_on_preempt
                and not getattr(self, "_run_ended", True)
                and getattr(self, "_active_checkpoint_path", None)):
            if getattr(self, "_preempt_deadline", None) is None:
                self._preempt_deadline = (
                    time.monotonic() + self.config.preempt_deadline_s)
                self._preempt_signum = int(signum)
                logger.warning(
                    "SIGTERM at step %d: preemption deadline armed "
                    "(%.1fs) — finishing in-flight dispatch, then "
                    "emergency checkpoint", self.global_step,
                    self.config.preempt_deadline_s)
            return
        # _end_run's teardown RESTORES the pre-fit disposition (it must run
        # before the re-raise, not after — nothing after os.kill runs under
        # the default disposition), so the re-raised signal is delivered
        # with the exit semantics the sender expects: SIG_DFL dies with
        # rc = -SIGTERM, a framework's SIG_IGN/custom handler applies as if
        # the fit had never hooked the signal
        self._end_run("error")
        os.kill(os.getpid(), signum)

    def _teardown_run_inspection(self) -> None:
        """Stop the fit-scoped status endpoint and restore the SIGTERM
        disposition — idempotent, runs at every run end (ok or error,
        including from inside the SIGTERM handler itself)."""
        if self._statusd is not None:
            self._statusd.stop()
            self._statusd = None
        if getattr(self, "_sigterm_installed", False):
            import signal
            self._sigterm_installed = False
            signal.signal(
                signal.SIGTERM,
                self._prev_sigterm if self._prev_sigterm is not None
                else signal.SIG_DFL)
            self._prev_sigterm = None

    def _dump_context(self) -> dict:
        """The at-death snapshots the flight-recorder dump carries beside the
        rings: where the time went, what the spans saw, the live gauges."""
        return {"phases": self._phases.summary(),
                "spans": self._tracer.span_summary(),
                "status": self.status_snapshot()}

    def status_snapshot(self) -> dict:
        """The live-inspection gauge snapshot (obs/statusd.py serves this as
        /status.json and renders /metrics from it). Reads only plain host
        attributes and bounded rings — never device state — so a scrape can
        never interleave a collective into the dispatch pipeline."""
        hb = self.heartbeats[-1] if self.heartbeats else None
        return {
            "run_id": getattr(self, "_run_id", ""),
            "status": ("idle" if getattr(self, "_run_ended", True)
                       else "running"),
            "global_step": int(self.global_step),
            "words": int(self.state.words_processed),
            "pairs_trained": float(self.pairs_trained),
            "pairs_per_sec": float(hb.pairs_per_sec) if hb else None,
            "alpha": float(hb.alpha) if hb else None,
            "lr_scale": float(self._lr_scale),
            "recoveries": int(self.recoveries_performed),
            "rollbacks": int(self.rollbacks_performed),
            "watchdog_fires": int(self.norm_watchdog.fires),
            "heartbeats": len(self.heartbeats),
            "host_wait_s_total": round(
                getattr(self, "host_wait_time", 0.0), 3),
            "dispatch_s_total": round(
                getattr(self, "dispatch_time", 0.0), 3),
            "norms": self._last_probe_channels,
            "phases": self._phases.summary(),
        }

    @property
    def last_run_stats(self) -> dict:
        """Runtime outcome of the last fit: the robustness end state the
        EVAL harness emits into its rows, plus — when time attribution is
        armed — the per-phase rollup, so "where did the time go" rides the
        same surface as "did it recover"."""
        stats = {
            "watchdog_fires": int(self.norm_watchdog.fires),
            "rollbacks_performed": int(self.rollbacks_performed),
            "recoveries_performed": int(self.recoveries_performed),
            "lr_scale_final": float(self._lr_scale),
            "engaged_max_row_norm": float(self._stabilizers.max_row_norm),
            "engaged_update_clip": float(self._stabilizers.update_clip),
            "engaged_row_l2": float(self._stabilizers.row_l2),
        }
        phases = self._phases.summary()
        if phases:
            stats["phases"] = phases
        return stats

    def _end_run(self, status: str) -> None:
        """Emit the run_end record + export the Chrome trace (idempotent per
        _start_run_bookkeeping). The success path calls this AFTER the final
        checkpoint save so that save's span lands in the exported trace; the
        error path reaches it through _finish_run_telemetry in the fit
        ``finally`` blocks."""
        self._teardown_run_inspection()
        if getattr(self, "_run_ended", True):
            return
        self._run_ended = True
        if self._telemetry is not None:
            self._emit(
                "run_end", run_id=self._run_id, status=status,
                steps=int(self.global_step),
                pairs_trained=float(self.pairs_trained),
                host_wait_s_total=round(self.host_wait_time, 3),
                dispatch_s_total=round(self.dispatch_time, 3),
                watchdog_fires=int(self.norm_watchdog.fires),
                rollbacks=int(self.rollbacks_performed),
                recoveries=int(self.recoveries_performed),
                lr_scale=round(float(self._lr_scale), 9),
                phases=self._phases.summary(),
                spans=self._tracer.span_summary())
            try:
                self.export_trace(self.config.telemetry_path + ".trace.json")
            except OSError as e:
                # best-effort like the sink — and _end_run runs inside the
                # abort path's except clause, where a raise here would MASK
                # the original training exception
                logger.warning("trace export failed: %s", e)

    def export_trace(self, path: str) -> int:
        """Export the collected host trace spans as a Chrome-trace JSON file
        (Perfetto / chrome://tracing loadable); returns the event count. Runs
        automatically at run end when telemetry is on; callable any time for
        an on-demand snapshot of a live run."""
        return self._tracer.export_chrome_trace(path)

    def _abort_run(self) -> None:
        """Sits in every fit path's ``except BaseException: ...; raise``:
        run_end with status="error" before the raise unwinds (guardrail
        halt, watchdog halt, feed error). An ``except`` clause — NOT
        ``sys.exc_info()`` in the ``finally`` — because exc_info also
        reports an OUTER handled exception (fit() called inside an except
        block, e.g. the crash-recovery resume pattern) and would mislabel a
        successful recovery fit as an error. (Reading exc_info HERE is safe:
        this method only runs inside the except clause, where it is by
        construction the in-flight exception.) The success path emits after
        the final checkpoint save instead (see _end_run). Dumps the flight
        recorder LAST, after run_end — so the dump's event ring carries the
        terminal run_end record too."""
        import sys
        exc = sys.exc_info()[1]
        self._end_run("error")
        if self._blackbox is not None:
            from glint_word2vec_tpu.obs.blackbox import FlightRecorder
            self._blackbox.dump(
                FlightRecorder.exception_cause(exc) if exc is not None
                else None,
                extra=self._dump_context())

    def _finish_round(
        self,
        real: int,
        real_pairs: float,
        alphas: np.ndarray,            # [K] per-batch alphas of this round
        metrics: StepMetrics,
        state: TrainState,             # global_step is filled in here
        checkpoint_path: Optional[str],
        checkpoint_every_steps: Optional[int],
        on_heartbeat: Optional[Callable[[HeartbeatRecord], None]],
    ) -> None:
        """Post-dispatch bookkeeping shared by both feed modes: progress counters,
        heartbeat cadence (the reference's every-10k-words line, mllib:404-413 —
        fetching device metrics forces a sync, so it runs on a chunked cadence to keep
        the async dispatch pipeline full), the non-finite guardrail + scripted fault
        hooks (train/faults.py), and periodic checkpointing."""
        cfg = self.config
        self.global_step += real
        self._pairs_since_log += real_pairs
        self.pairs_trained += real_pairs
        self.state = dc_replace(state, global_step=self.global_step)
        # the lr scale THIS round's chunk actually dispatched under — a
        # recovery below backs _lr_scale off for the NEXT dispatch, and the
        # heartbeat must not retroactively report the new scale for a chunk
        # trained at the old one
        lr_scale_at_dispatch = self._lr_scale
        if self._blackbox is not None:
            # one tiny record per round: the finest-grained trace of what the
            # run was doing right before a death (heartbeats are 1-in-N)
            self._blackbox.note_dispatch(
                self.global_step, real,
                self.dispatch_time - self._bb_disp_mark,
                self.host_wait_time - self._bb_wait_mark)
            self._bb_disp_mark = self.dispatch_time
            self._bb_wait_mark = self.host_wait_time

        if faults.take_nan_injection(self.global_step):
            if self._poison_fn is None:
                self._poison_fn = jax.jit(lambda p: EmbeddingPair(
                    p.syn0.at[0, 0].set(jnp.asarray(jnp.nan, p.syn0.dtype)),
                    p.syn1))
            self.params = self._poison_fn(self.params)
        scale = faults.take_scale_injection(self.global_step)
        if scale:
            if self._scale_fn is None:
                self._scale_fn = jax.jit(lambda p, f: jax.tree.map(
                    lambda x: x * f.astype(x.dtype), p))
            self.params = self._scale_fn(self.params, jnp.float32(scale))
        faults.crash_at_step(self.global_step)
        faults.maybe_stall(self.global_step)

        # jax.profiler window (config.profile_steps): stop the trace once the
        # configured number of steps completed after fit start
        if (self._profiling and cfg.profile_steps
                and self.global_step - self._profile_start_step
                >= cfg.profile_steps):
            self._stop_profiler()
            logger.info("jax.profiler window closed after %d steps",
                        self.global_step - self._profile_start_step)

        ckpt_due = bool(checkpoint_path and checkpoint_every_steps
                        and self.global_step % checkpoint_every_steps < real)
        hb_due = (self.global_step - self._last_log_step
                  >= cfg.heartbeat_every_steps)
        # ONE fused probe per probing round (obs/probe.py): finiteness for the
        # guardrail + the norm channels for the watchdog and the heartbeat
        channels: Optional[dict] = None
        if hb_due and (cfg.nonfinite_policy != "none"
                       or cfg.norm_watch != "off"
                       or self._telemetry is not None):
            channels = self._health_stats()
        if cfg.nonfinite_policy != "none" and hb_due and not ckpt_due:
            # heartbeat-cadence probe; checkpoint rounds are covered by the
            # guard inside save_checkpoint itself (every save — periodic AND
            # the end-of-fit finished save — is probed exactly once, so a
            # blown-up state never overwrites the on-disk good checkpoint)
            self._nonfinite_guard(channels)
        elif (channels is not None and channels["finite"]
              and cfg.nonfinite_policy == "none"):
            # the guard isn't in play (policy "none"), but ring consumers
            # (norm_watch="recover") still need heartbeat-cadence snapshots;
            # with a policy set, checkpoint rounds snapshot through the
            # save-side guard sharing this probe
            self._maybe_snapshot(channels)
        if channels is not None and channels["finite"]:
            # the finite-blowup watchdog (config.norm_watch, obs/watch.py):
            # only meaningful on a finite carry — a non-finite one is the
            # guardrail's jurisdiction above (inf rows would trivially trip
            # every norm channel on the way down a rollback)
            self._watchdog_check(channels)

        if hb_due:
            now = time.perf_counter()
            pps = self._pairs_since_log / max(now - self._last_log_time, 1e-9)
            self._pairs_since_log = 0.0
            # EXPLICIT fetch of the [K]-sized metric vectors, then host-side
            # indexing: device-side `metrics.loss[real - 1]` dispatches a
            # gather whose index operand rides an IMPLICIT int32 host→device
            # transfer — the regression class the stepaudit transfer guard
            # disallows, reachable here only on heartbeat rounds (which the
            # audit's scripted fits are too short to hit; tests/test_obs.py
            # runs a probing fit under the guard to keep this path honest)
            with self._tracer.span("device_block"):
                loss_k, fpos_k = jax.device_get(
                    (metrics.loss, metrics.mean_f_pos))
            # per-phase attribution over THIS heartbeat window (obs/
            # phases.py): delta of the accumulator the spans + wait sites
            # have been feeding since the previous heartbeat
            phases_window = None
            if self._phases.enabled:
                phases_window = self._phases.delta(
                    self._last_hb_phases) or None
                self._last_hb_phases = self._phases.raw_snapshot()
            rec = HeartbeatRecord(
                words=self.state.words_processed,
                # the EFFECTIVE lr: recovery backoff multiplies the
                # dispatched alphas at _stage_dispatch_meta
                alpha=float(alphas[real - 1]) * lr_scale_at_dispatch,
                loss=float(loss_k[real - 1]),
                mean_f_pos=float(fpos_k[real - 1]),
                pairs_per_sec=pps,
                global_step=self.global_step,
                host_wait_s=self.host_wait_time - self._last_hb_host_wait,
                dispatch_s=self.dispatch_time - self._last_hb_dispatch,
                norms=channels,
                recoveries=self.recoveries_performed,
                lr_scale=lr_scale_at_dispatch,
                phases=phases_window,
                sync_every=int(cfg.sync_every),
                merge_round=(self.global_step // cfg.sync_every
                             if cfg.sync_every > 1 else -1))
            self._last_hb_host_wait = self.host_wait_time
            self._last_hb_dispatch = self.dispatch_time
            self.heartbeats.append(rec)
            logger.info(
                "wordCount = %d, alpha = %.6f, loss = %.4f, fPlus = %.4f, "
                "pairs/s = %.0f", rec.words, rec.alpha, rec.loss,
                rec.mean_f_pos, rec.pairs_per_sec)
            if self._telemetry is not None:
                self._emit(
                    "heartbeat", step=rec.global_step, words=rec.words,
                    alpha=rec.alpha, loss=rec.loss,
                    mean_f_pos=rec.mean_f_pos,
                    pairs_per_sec=round(rec.pairs_per_sec, 3),
                    host_wait_s=round(rec.host_wait_s, 6),
                    dispatch_s=round(rec.dispatch_s, 6),
                    recoveries=int(rec.recoveries),
                    lr_scale=round(float(rec.lr_scale), 9),
                    # local-SGD runs only: the synchronous default keeps the
                    # pre-knob record shape byte-identical
                    **({"sync_every": rec.sync_every,
                        "merge_round": rec.merge_round}
                       if rec.sync_every > 1 else {}),
                    **({"norms": channels} if channels is not None else {}),
                    **({"phases": phases_window} if phases_window else {}))
            if on_heartbeat is not None:
                on_heartbeat(rec)
            self._last_log_time, self._last_log_step = now, self.global_step

        if ckpt_due:
            # share this round's probe fetch with the save-side guard — the
            # params are unchanged since _health_stats above, and a second
            # full [V, D] reduction + sync per coincident round is the probe
            # cost this method's single-probe rule exists to avoid
            self.save_checkpoint(checkpoint_path, _channels=channels)

        # preemption drain (config.checkpoint_on_preempt): the SIGTERM
        # handler only ARMED _preempt_deadline — this is the first point
        # after it where the in-flight dispatch has completed and no
        # collective is mid-flight, so the emergency save can run the
        # normal atomic path. Never returns.
        if getattr(self, "_preempt_deadline", None) is not None:
            self._preempt_exit(checkpoint_path, channels)

    def _preempt_exit(self, checkpoint_path: Optional[str],
                      channels: Optional[dict]) -> None:
        """The deferred half of the SIGTERM preemption path (_on_sigterm
        armed it; _finish_round's tail calls it): within the remaining
        deadline budget, drain the carry through the normal digest-verified
        atomic save (save_checkpoint's np.asarray blocks on the async
        dispatch, and its nonfinite/norm guard still vetoes a blown-up
        carry — never a torn or unverified emergency save; the atomic
        protocol leaves the previous verified checkpoint in place on any
        failure). Then the ``preempt`` telemetry record, run_end with
        status="preempted", a final flight-recorder dump whose event ring
        carries both terminal records, and the re-raised signal under the
        restored disposition so the sender sees the exit code it expects
        (rc = -SIGTERM). Never returns."""
        import os
        signum = self._preempt_signum or 15
        remaining = self._preempt_deadline - time.monotonic()
        steps_since_save = int(self.global_step) - int(self._last_save_step)
        saved = False
        if checkpoint_path and steps_since_save == 0:
            # a ckpt_due save already published at this very step (the
            # coincident round) — zero progress to lose, nothing to rewrite
            saved = True
        elif checkpoint_path and remaining > 0:
            try:
                self.save_checkpoint(checkpoint_path, _channels=channels)
                saved = True
            except BaseException as e:  # noqa: BLE001 — the guard raising
                # on a non-finite carry, or I/O dying under eviction
                # pressure: fall back to the blackbox-only exit
                logger.warning(
                    "emergency checkpoint failed (%s); falling back to "
                    "blackbox-only exit", e)
        else:
            logger.warning(
                "preempt deadline missed by %.1fs — blackbox-only exit",
                max(-remaining, 0.0))
        self._emit("preempt", step=int(self.global_step), saved=saved,
                   checkpoint=checkpoint_path or "",
                   deadline_s=float(self.config.preempt_deadline_s),
                   steps_since_save=0 if saved else steps_since_save)
        self._end_run("preempted")
        if self._blackbox is not None:
            from glint_word2vec_tpu.obs.blackbox import FlightRecorder
            self._blackbox.dump(FlightRecorder.signal_cause(signum),
                                extra=self._dump_context())
        os.kill(os.getpid(), signum)

    def _start_peer_beacons(self, checkpoint_path: Optional[str]):
        """Arm the per-process liveness beacons of a multi-process fit
        (train/supervisor.py BeaconBoard; docs/robustness.md): each process
        heartbeats a tiny file beside the checkpoint path, and the
        main-thread ``check_or_raise`` before every allgather turns a dead
        peer into a clean PeerDeathError abort instead of an eternal
        collective hang (the board's watcher thread hard-exits the process
        if it IS already wedged inside the collective). Returns None when
        off (``peer_beacon_s=0``), when single-process, or when there is no
        checkpoint path to anchor the beacon directory to."""
        import os
        if self.config.peer_beacon_s <= 0 or not checkpoint_path:
            return None
        import jax
        if jax.process_count() <= 1:
            return None
        from glint_word2vec_tpu.train.supervisor import BeaconBoard
        board = BeaconBoard(
            os.path.join(os.path.dirname(os.path.abspath(checkpoint_path)),
                         "beacons"),
            process_index=jax.process_index(),
            num_processes=jax.process_count(),
            interval_s=self.config.peer_beacon_s)
        board.start()
        return board

    def _fit_sharded(
        self,
        sentences: Sequence[np.ndarray],
        checkpoint_path: Optional[str],
        checkpoint_every_steps: Optional[int],
        on_heartbeat: Optional[Callable[[HeartbeatRecord], None]],
        total_words: float,
        K: int,
    ) -> EmbeddingPair:
        """Multi-process fit with the sentence stream sharded across processes — the
        repartition analog (mllib:345), replacing the every-process-regenerates-
        everything feed.

        Protocol, one dispatch round at a time (all processes in lockstep):

        1. each process pulls its next LOCAL chunk — K batches of B/N pairs from
           ``epoch_batches(shard=pid, num_shards=N)`` — off its producer thread;
           an exhausted process substitutes a zero chunk;
        2. ONE ``process_allgather`` ships every process's (pairs, real counts, word
           deltas, alive flag, stream position) to every process — the data rides the
           fast device interconnect, not a host-side side channel;
        3. every process deterministically assembles the identical global batch
           ([K, 2, B]: N contiguous per-process segments), derives the global word
           clock from the summed deltas, and computes identical per-batch alphas —
           SPMD consistency holds because every input to the jitted step is a pure
           function of allgathered values;
        4. the round ends when the allgathered alive flags are all zero. Processes
           whose stream ended early keep dispatching fully-masked segments, so there
           is no "process 3 ran out one step early" deadlock class.

        Unequal per-process streams make a single (iteration, batches_done) pair
        meaningless, so TrainState.shard_progress records every process's position
        (from step 2, free) and resume requires the same process count.
        """
        import jax
        from jax.experimental import multihost_utils

        cfg = self.config
        S = self._feed_segments
        pid = jax.process_index()
        B = cfg.pairs_per_batch
        b_local = B // S

        start_iter = self.state.iteration
        skip = self.state.batches_done if not self.state.finished else 0
        if self.state.shard_progress is not None:
            sp = self.state.shard_progress
            if self.state.shard_feed not in (None, "pairs"):
                # device-feed positions count token-step rows, not b_local
                # pair-batches (None = pre-round-4 checkpoint, always pairs)
                raise ValueError(
                    "checkpoint shard_progress indexes the device-feed token "
                    f"streams (shard_feed={self.state.shard_feed!r}); resume "
                    "it with device_pairgen=True — pair-batch positions are a "
                    "different stream")
            if len(sp) != S:
                raise ValueError(
                    f"checkpoint shard_progress has {len(sp)} entries but this run "
                    f"has {S} processes; resume sharded-input runs with the same "
                    "process count")
            start_iter, skip = int(sp[pid][0]), int(sp[pid][1])
        elif skip:
            # a replicated-feed checkpoint's batches_done counts full-B batches of the
            # unsharded stream — there is no exact mapping onto per-process local
            # streams, so refuse rather than silently mis-position the resume
            raise ValueError(
                "checkpoint was written mid-iteration by a replicated-feed run; it "
                "cannot be resumed exactly with shard_input=True — resume with "
                "shard_input=False (or from an iteration-boundary checkpoint)")

        C = 2 * cfg.window

        def empty_feed() -> dict:
            """One schema for the local per-chunk feed arrays — used zeroed for the
            exhausted-process placeholder and as the fill target in flush()."""
            if cfg.cbow:
                return {"centers": np.zeros((K, b_local), np.int32),
                        "contexts": np.zeros((K, b_local, C), np.int32),
                        "nctx": np.zeros((K, b_local), np.int32)}
            return {"pairs": np.zeros((K, 2, b_local), np.int32)}

        def local_stream():
            """Local chunks ([K, 2, b_local] pairs, or centers/contexts/nctx arrays
            for CBOW) + per-batch real counts and word deltas. Pure numpy — safe on
            the producer thread (the allgather, a device collective, must run on the
            main thread in identical order everywhere)."""
            for k in range(start_iter, cfg.num_iterations + 1):
                pending: List[tuple] = []
                reals: List[int] = []
                deltas: List[int] = []
                prev_ws = 0
                batches_in_iter = skip if k == start_iter else 0
                to_skip = skip if k == start_iter else 0

                def flush():
                    nonlocal pending, reals, deltas, batches_in_iter
                    real = len(pending)
                    batches_in_iter += real
                    # filled in place, like the replicated flush: stacked copies
                    # throttle the producer
                    arrays = empty_feed()
                    if cfg.cbow:
                        for j, (c, x, nc) in enumerate(pending):
                            arrays["centers"][j] = c
                            arrays["contexts"][j] = x
                            arrays["nctx"][j] = nc
                    else:
                        for j, (c, x) in enumerate(pending):
                            arrays["pairs"][j, 0] = c
                            arrays["pairs"][j, 1] = x
                    while len(reals) < K:
                        reals.append(0)
                        deltas.append(0)
                    out = dict(
                        arrays=arrays,
                        reals=np.asarray(reals, np.int32),
                        deltas=np.asarray(deltas, np.int64),
                        iteration=k, batches_done=batches_in_iter)
                    pending, reals, deltas = [], [], []
                    return out

                if cfg.cbow:
                    stream = epoch_batches_cbow(
                        sentences, self.vocab, pairs_per_batch=b_local,
                        window=cfg.window, subsample_ratio=cfg.subsample_ratio,
                        seed=cfg.seed, iteration=k, shard=pid, num_shards=S,
                        shuffle=cfg.shuffle,
                        producer_workers=cfg.producer_workers)
                else:
                    stream = epoch_batches(
                        sentences, self.vocab, pairs_per_batch=b_local,
                        window=cfg.window, subsample_ratio=cfg.subsample_ratio,
                        seed=cfg.seed, iteration=k, shard=pid, num_shards=S,
                        shuffle=cfg.shuffle,
                        producer_workers=cfg.producer_workers)
                for b in stream:
                    ws = b.words_seen
                    if to_skip:  # exact resume: fast-forward already-trained batches
                        to_skip -= 1
                        prev_ws = ws
                        continue
                    if cfg.cbow:
                        pending.append((b.centers, b.contexts, b.n_ctx))
                        reals.append(b.num_real)
                    else:
                        pending.append((b.centers, b.contexts))
                        reals.append(b.num_real_pairs)
                    deltas.append(ws - prev_ws)
                    prev_ws = ws
                    if len(pending) == K:
                        yield flush()
                if pending:
                    yield flush()

        lstream = self._tracer.wrap_iter("producer", local_stream())
        if cfg.prefetch_chunks > 0:
            chunks = _threaded_iter(lstream, cfg.prefetch_chunks)
        else:
            chunks = iter(lstream)

        clock = float(self.state.words_processed)
        cur_iter, cur_batches = start_iter, skip
        exhausted = False
        self._start_run_bookkeeping()
        beacons = self._start_peer_beacons(checkpoint_path)
        zero_arrays = empty_feed()
        try:
            while True:
                t0 = time.perf_counter()
                local = None if exhausted else next(chunks, None)
                wait = time.perf_counter() - t0
                self.host_wait_time += wait
                self._phases.add("producer_wait", wait)
                if local is None:
                    exhausted = True
                    local = dict(arrays=zero_arrays,
                                 reals=np.zeros(K, np.int32),
                                 deltas=np.zeros(K, np.int64),
                                 iteration=cur_iter, batches_done=cur_batches)
                else:
                    cur_iter = local["iteration"]
                    cur_batches = local["batches_done"]

                if beacons is not None:
                    # a dead peer never reaches its allgather — entering ours
                    # would hang forever; the beacon check converts that into
                    # a clean abort the supervisor restarts the gang from
                    beacons.check_or_raise()
                t0 = time.perf_counter()
                g = multihost_utils.process_allgather({
                    **local["arrays"],
                    "reals": local["reals"],
                    "deltas": local["deltas"],
                    "alive": np.asarray([0 if exhausted else 1], np.int32),
                    "prog": np.asarray([cur_iter, cur_batches], np.int64),
                })  # every leaf gains a leading [S] process axis
                if int(g["alive"].sum()) == 0:
                    break
                reals_all = g["reals"]                              # [S, K]
                # segment s of every batch is process s's slice, matching the
                # device-side per-segment prefix masks
                if cfg.cbow:
                    feed = {
                        # [S, K, b(, C)] -> [K, S, b(, C)] -> [K, B(, C)]
                        "centers": np.transpose(g["centers"], (1, 0, 2)).reshape(
                            K, B).astype(self._pair_dtype),
                        "contexts": np.transpose(
                            g["contexts"], (1, 0, 2, 3)).reshape(
                                K, B, C).astype(self._pair_dtype),
                        "nctx": np.transpose(g["nctx"], (1, 0, 2)).reshape(
                            K, B).astype(np.uint8),
                    }
                else:
                    # [S, K, 2, b] -> [K, 2, S, b] -> [K, 2, B]
                    feed = {"pairs": np.transpose(
                        g["pairs"], (1, 2, 0, 3)).reshape(K, 2, B).astype(
                            self._pair_dtype)}
                clocks = clock + np.cumsum(g["deltas"].sum(axis=0))
                clock = float(clocks[-1])
                alphas = np.asarray(
                    [alpha_schedule(float(w), total_words, cfg.learning_rate,
                                    cfg.min_alpha_factor) for w in clocks], np.float32)
                meta = np.concatenate(
                    [alphas[None, :], reals_all.astype(np.float32)], axis=0)
                # each local stream pads only its final chunk, so per-process real
                # slots are prefixes and "any segment live" is a prefix too
                real = int((reals_all > 0).any(axis=0).sum())
                real_pairs = float(reals_all.sum())

                if cfg.feed_consistency_check:
                    self._assert_feed_consistent(feed, meta)
                with self._tracer.span("dispatch"):
                    stacked = put_global(self._chunk_shardings, feed)
                    meta_dev, base_dev = self._stage_dispatch_meta(
                        meta, self.global_step + 1)
                    self.params, metrics = self._dispatch_step_fn(real)(
                        self.params, stacked, meta_dev, base_dev,
                        self._table_prob, self._table_alias)
                self.dispatch_time += time.perf_counter() - t0
                self._after_dispatch()
                self._finish_round(
                    real, real_pairs, meta[0], metrics,
                    TrainState(
                        iteration=int(g["prog"][:, 0].min()),
                        words_processed=int(clock),
                        # batches_done is meaningless across shards (each process's
                        # local stream advances at its own rate); sharded-input
                        # resume MUST use shard_progress, so persist 0 here rather
                        # than the writing process's local count
                        batches_done=0,
                        shard_progress=[[int(a), int(b_)] for a, b_ in g["prog"]],
                        shard_feed="pairs"),
                    checkpoint_path, checkpoint_every_steps, on_heartbeat)
        except BaseException:
            self._abort_run()  # its docstring has the why-not-sys.exc_info
            raise
        finally:
            self._stop_profiler()
            if beacons is not None:
                beacons.stop()
            closer = getattr(chunks, "close", None)
            if closer is not None:
                closer()

        self.state = TrainState(
            iteration=cfg.num_iterations,
            words_processed=int(clock),
            finished=True, global_step=self.global_step)
        if checkpoint_path:
            self.save_checkpoint(checkpoint_path)
        self._end_run("ok")
        return self.params

    def _batch_stream(self, sentences: Sequence[np.ndarray], iteration: int):
        cfg = self.config
        common = dict(
            pairs_per_batch=cfg.pairs_per_batch, window=cfg.window,
            subsample_ratio=cfg.subsample_ratio, seed=cfg.seed, iteration=iteration,
            shuffle=cfg.shuffle, producer_workers=cfg.producer_workers)
        # batches are prefix-masked by construction (PairBatcher pads only the tail),
        # so only the real count ships — the device rebuilds mask = (iota < real)
        if cfg.cbow:
            for b in epoch_batches_cbow(sentences, self.vocab, **common):
                yield {"centers": b.centers, "contexts": b.contexts,
                       "nctx": b.n_ctx, "real": b.num_real,
                       "words_seen": b.words_seen}
        else:
            for b in epoch_batches(sentences, self.vocab, **common):
                yield {"centers": b.centers, "contexts": b.contexts,
                       "real": b.num_real_pairs, "words_seen": b.words_seen}

    # -- export / persistence ----------------------------------------------------------

    def unpadded_params(self) -> EmbeddingPair:
        V, D = self.vocab.size, self.config.vector_size
        return EmbeddingPair(syn0=self.params.syn0[:V, :D],
                             syn1=self.params.syn1[:V, :D])

    def save_checkpoint(self, path: str,
                        _channels: Optional[dict] = None) -> None:
        if self.config.nonfinite_policy != "none":
            # every save — periodic and the finished end-of-fit one — runs the
            # guardrail first: 'halt' refuses to replace the last good on-disk
            # checkpoint with NaNs, 'rollback' saves the restored snapshot.
            # _channels: a probe result fetched THIS round with no dispatch
            # since (the coincident heartbeat+checkpoint round) — reused so
            # the round pays one probe, not two
            self._nonfinite_guard(_channels)
        from glint_word2vec_tpu.parallel.distributed import is_multiprocess
        # additive metadata every save carries (periodic saves included, so a
        # SIGTERM mid-increment leaves the provenance in place): the continual
        # driver parks the vocab_lineage chain here (continual/loop.py)
        extra = self.extra_checkpoint_meta or None
        if self.config.sharded_checkpoint or is_multiprocess():
            # row-shards layout: each process writes its own rows, no host gather
            from glint_word2vec_tpu.train.checkpoint import save_model_sharded
            save_model_sharded(
                path, self.vocab.words, self.vocab.counts,
                self.params.syn0, self.params.syn1, self.config, self.state,
                vocab_size=self.vocab.size, vector_size=self.config.vector_size,
                extra_metadata=extra)
        else:
            p = self.unpadded_params()
            save_model(
                path, self.vocab.words, self.vocab.counts,
                np.asarray(p.syn0), np.asarray(p.syn1),
                self.config, self.state, extra_metadata=extra)
        logger.info("checkpoint saved to %s at step %d", path, self.global_step)
        # the preempt record's progress-lost denominator (docs/robustness.md)
        self._last_save_step = int(self.global_step)
        if self._telemetry is not None or self._blackbox is not None:
            # the publish-side correlation record (obs/trace.py): carries
            # the freshly-written checkpoint's publish_sig — the SAME
            # string the serving watcher and fleet router compare — so the
            # collector joins save → watcher detect → per-replica reload
            # into one causal chain. Through _emit, not the sink directly,
            # so the flight recorder's event ring mirrors it.
            from glint_word2vec_tpu.serve.reload import (
                publish_signature, publish_signature_str)
            sig = publish_signature_str(publish_signature(path))
            if sig is not None:
                self._emit("publish", publish_sig=sig, checkpoint=path,
                           step=int(self.global_step), publisher="trainer")
