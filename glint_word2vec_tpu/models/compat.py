"""Drop-in familiarity layer: the reference's class names, setters, defaults and model ops.

Mirrors the builder surface of the MLlib estimator (mllib:92-244), the ML params
(ml:40-222,234-282) and the PySpark binding (ml_glintword2vec.py:38-385) so a user of the
reference can port call sites mechanically:

    w2v = (ServerSideGlintWord2Vec()
           .setVectorSize(100).setWindowSize(5).setNumIterations(3).setSeed(1))
    model = w2v.fit(sentences)            # sentences: list of token lists
    model.findSynonyms("wien", 10)
    model.save(path); ServerSideGlintWord2VecModel.load(path)

Differences, by design (each is the TPU replacing the PS/RPC machinery, not an omission):

- ``setParameterServerHost``/``setParameterServerConfig`` (mllib:219-237) are accepted and
  ignored with a warning: there are no parameter servers. Deployment mode A (in-app PS) ==
  in-process mesh; mode B (separate PS cluster, README.md:45-57) == training on the pod +
  serving queries from checkpoints.
- ``setNumParameterServers`` maps to the mesh's model-axis size (embedding row shards).
- the Akka payload constraint ``batchSize·n·window ≤ 10000`` (mllib:154-188) is validated
  for familiarity but only warns: no RPC, no payload cap.
- ``stop(terminateOtherClients)`` releases device buffers; the flag is accepted for
  signature parity (cross-application PS termination has no analog).
- input is plain Python sequences instead of RDD/DataFrame; ``setInputCol``/
  ``setOutputCol`` exist for signature parity on dict-shaped rows.
"""

from __future__ import annotations

import logging
import warnings
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.models.estimator import Word2Vec
from glint_word2vec_tpu.models.word2vec import Word2VecModel

logger = logging.getLogger("glint_word2vec_tpu")

_MAX_MESSAGE_FLOATS = 10_000  # the reference's Akka budget (mllib:83-85) — advisory here


class ServerSideGlintWord2Vec:
    """Builder-style estimator with the reference's knob names and defaults
    (mllib:67-81,251; ml setDefault block)."""

    def __init__(self):
        self._vector_size = 100
        self._learning_rate = 0.01875
        self._num_partitions = 1
        self._num_iterations = 1
        self._min_count = 5
        self._max_sentence_length = 1000
        self._window = 5
        self._batch_size = 50
        self._n = 5
        self._subsample_ratio = 0.0  # reference default 1e-6 *behaves* as off (no-op bug)
        self._num_parameter_servers = 5
        self._parameter_server_host = ""
        self._parameter_server_config: Dict = {}
        self._unigram_table_size = 100_000_000
        self._seed = 0
        self._device_batch_set = False  # did the user touch batchSize/numPartitions?
        self._input_col = "sentence"
        self._output_col = "vector"

    # -- setters (names: mllib:92-244 and ml:234-282) ----------------------------------

    def setVectorSize(self, value: int) -> "ServerSideGlintWord2Vec":
        self._vector_size = int(value)
        return self

    def setLearningRate(self, value: float) -> "ServerSideGlintWord2Vec":
        self._learning_rate = float(value)
        return self

    setStepSize = setLearningRate  # ml naming (ml:246)

    def setNumPartitions(self, value: int) -> "ServerSideGlintWord2Vec":
        self._num_partitions = int(value)
        self._device_batch_set = True
        return self

    def setNumIterations(self, value: int) -> "ServerSideGlintWord2Vec":
        self._num_iterations = int(value)
        return self

    setMaxIter = setNumIterations  # ml naming (ml:252)

    def setSeed(self, value: int) -> "ServerSideGlintWord2Vec":
        self._seed = int(value)
        return self

    def setWindowSize(self, value: int) -> "ServerSideGlintWord2Vec":
        self._window = int(value)
        self._check_payload_constraint()
        return self

    def setMinCount(self, value: int) -> "ServerSideGlintWord2Vec":
        self._min_count = int(value)
        return self

    def setMaxSentenceLength(self, value: int) -> "ServerSideGlintWord2Vec":
        self._max_sentence_length = int(value)
        return self

    def setBatchSize(self, value: int) -> "ServerSideGlintWord2Vec":
        self._batch_size = int(value)
        self._device_batch_set = True
        self._check_payload_constraint()
        return self

    def setN(self, value: int) -> "ServerSideGlintWord2Vec":
        self._n = int(value)
        self._check_payload_constraint()
        return self

    def setSubsampleRatio(self, value: float) -> "ServerSideGlintWord2Vec":
        if value > 0:
            warnings.warn(
                "the reference's subsampling is a silent no-op at ANY setting "
                "(Int/Long division bug, see data/pipeline.py) — here "
                f"setSubsampleRatio({value}) actually subsamples, so results "
                "will differ from a reference run with the same setting; pass "
                "0.0 for behavior-faithful (no-op) parity", stacklevel=2)
        self._subsample_ratio = float(value)
        return self

    def setNumParameterServers(self, value: int) -> "ServerSideGlintWord2Vec":
        self._num_parameter_servers = int(value)
        return self

    def setParameterServerHost(self, value: str) -> "ServerSideGlintWord2Vec":
        if value:
            warnings.warn(
                "parameterServerHost is ignored: there are no parameter servers on TPU "
                "(the mesh is in-process)", stacklevel=2)
        self._parameter_server_host = value
        return self

    def setParameterServerConfig(self, value: Dict) -> "ServerSideGlintWord2Vec":
        if value:
            warnings.warn(
                "parameterServerConfig is ignored: there is no Akka transport to "
                "configure", stacklevel=2)
        self._parameter_server_config = dict(value)
        return self

    def setUnigramTableSize(self, value: int) -> "ServerSideGlintWord2Vec":
        self._unigram_table_size = int(value)
        return self

    def setInputCol(self, value: str) -> "ServerSideGlintWord2Vec":
        self._input_col = value
        return self

    def setOutputCol(self, value: str) -> "ServerSideGlintWord2Vec":
        self._output_col = value
        return self

    def _check_payload_constraint(self) -> None:
        # The reference *errors* here because Akka caps payloads (mllib:154-188); with no
        # RPC the combination is legal, so parity stops at a warning.
        if self._batch_size * self._n * self._window > _MAX_MESSAGE_FLOATS:
            warnings.warn(
                f"batchSize*n*window = {self._batch_size * self._n * self._window} "
                f"> {_MAX_MESSAGE_FLOATS} would be rejected by the reference (Akka "
                "payload cap); harmless here", stacklevel=3)

    # -- fit ---------------------------------------------------------------------------

    def to_config(self) -> Word2VecConfig:
        n_shards = self._num_parameter_servers
        import jax
        n_dev = len(jax.devices())
        kwargs = {}
        if self._device_batch_set:
            # The reference trains batchSize pairs per partition concurrently
            # (mllib:417-429), numPartitions partitions at once — so the faithful
            # device-batch mapping is their product. Only applied when the user set
            # either knob; the config default (8192) is far better for the MXU.
            pairs = max(self._batch_size * self._num_partitions, 1)
            kwargs["pairs_per_batch"] = pairs
            if pairs < 1024:
                warnings.warn(
                    f"batchSize*numPartitions = {pairs} maps to pairs_per_batch={pairs}"
                    ": tiny device batches waste the TPU (default 8192); this mapping "
                    "is faithful to the reference semantics, not fast", stacklevel=2)
        return Word2VecConfig(
            vector_size=self._vector_size,
            learning_rate=self._learning_rate,
            num_partitions=self._num_partitions,
            num_iterations=self._num_iterations,
            min_count=self._min_count,
            max_sentence_length=self._max_sentence_length,
            window=self._window,
            batch_size=self._batch_size,
            negatives=self._n,
            subsample_ratio=self._subsample_ratio,
            # drop-in parity: the reference runs any of these configs (its async
            # 50-pair minibatches never face the synchronous duplicate-overload
            # channel), so the compat surface must not hard-refuse them — keep
            # the round-4 warn-only behavior; the construction-time warning
            # still names the danger and the fix
            allow_unstable=True,
            # the reference samples n negatives per pair server-side (G3,
            # mllib:419-421) — pin the exact per-pair path rather than inheriting
            # the TPU-native config's auto-scaled shared pool
            negative_pool=0,
            num_model_shards=min(n_shards, n_dev),
            unigram_table_size=self._unigram_table_size,
            seed=self._seed,
            **kwargs,
        )

    def fit(self, sentences: Iterable[Sequence[str]]) -> "ServerSideGlintWord2VecModel":
        """sentences: iterable of token sequences, or dicts holding one under inputCol
        (the DataFrame-column analog, ml:286)."""
        sentences = [
            s[self._input_col] if isinstance(s, dict) else s for s in sentences]
        model = Word2Vec(self.to_config()).fit(sentences)
        return ServerSideGlintWord2VecModel(model, self._input_col, self._output_col)


class ServerSideGlintWord2VecModel:
    """Model wrapper with the reference's op names (mllib:460-669, ml:322-497)."""

    def __init__(self, model: Word2VecModel, input_col: str = "sentence",
                 output_col: str = "vector"):
        self._model = model
        self._input_col = input_col
        self._output_col = output_col

    @property
    def inner(self) -> Word2VecModel:
        return self._model

    def getVectors(self) -> Dict[str, np.ndarray]:
        return self._model.get_vectors()

    def transform(self, data):
        """Word → vector (mllib:511-519) for a string; sentence-average vectors
        (ml:432-460) for sequences/dicts of tokens."""
        if isinstance(data, str):
            return self._model.transform(data)
        rows = list(data)
        if rows and isinstance(rows[0], dict):
            sents = [r[self._input_col] for r in rows]
            vecs = self._model.transform_sentences(sents)
            return [{**r, self._output_col: vecs[i]} for i, r in enumerate(rows)]
        if rows and isinstance(rows[0], (list, tuple)):
            return self._model.transform_sentences(rows)
        # iterator-of-words path (mllib:529-546)
        return list(self._model.transform_words(rows))

    def findSynonyms(self, query, num: int) -> List[Tuple[str, float]]:
        return self._model.find_synonyms(query, num)

    findSynonymsArray = findSynonyms

    def analogy(self, a: str, b: str, c: str, num: int = 10):
        return self._model.analogy(a, b, c, num)

    def toLocal(self) -> Tuple[List[str], np.ndarray]:
        return self._model.to_local()

    def save(self, path: str) -> None:
        self._model.save(path)

    @classmethod
    def load(cls, path: str, parameterServerHost: str = "",
             parameterServerConfig: Optional[Dict] = None
             ) -> "ServerSideGlintWord2VecModel":
        """Signature parity with the 3 load overloads (mllib:683-725, ml:573-599,
        python ml_glintword2vec.py:353-373); the PS args are accepted and ignored."""
        if parameterServerHost or parameterServerConfig:
            warnings.warn("parameter-server arguments are ignored on load",
                          stacklevel=2)
        return cls(Word2VecModel.load(path))

    def stop(self, terminateOtherClients: bool = False) -> None:
        del terminateOtherClients  # signature parity (mllib:664-667)
        self._model.stop()
