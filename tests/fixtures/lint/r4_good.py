"""R4 good: the accumulation dtype is pinned ≥f32 in the def-use chain."""
import jax.numpy as jnp


def context_sums(rows):
    pf = jnp.promote_types(rows.dtype, jnp.float32)
    wide = rows.astype(pf)
    prefix = jnp.cumsum(wide, axis=0)
    return prefix[4:] - prefix[:-4]
