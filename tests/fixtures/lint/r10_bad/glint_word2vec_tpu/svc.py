"""R10 bad fixture: the PR 9 handler-deadlock shape. The SIGTERM handler's
call closure acquires the non-reentrant 'ring' lock that record() — a
normal path, running on the thread the signal interrupts — also holds. If
the signal lands inside record()'s critical section the handler blocks on
a lock its own thread owns, forever."""
import signal

from glint_word2vec_tpu.lockcheck import make_lock


class Recorder:
    def __init__(self):
        self._lock = make_lock("ring")
        self._events = []

    def record(self, e):
        with self._lock:
            self._events.append(e)

    def dump(self):
        with self._lock:
            return list(self._events)


class Daemon:
    def __init__(self):
        self._rec = Recorder()

    def install(self):
        signal.signal(signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        self._rec.dump()
