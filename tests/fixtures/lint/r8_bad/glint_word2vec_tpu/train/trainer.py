"""R8 bad trainer half: five dispatch-only refusals — one with no config
twin at all (cbow x use_pallas), one 'covered' only by a single-knob range
check (cbow x negative_pool), which is not coverage, one on a NEW
stabilizer knob (use_pallas x max_row_norm) whose range check in config is
likewise not combination coverage, one living in __init__ path selection
rather than _build_step (the device_pairgen class graftcheck's first run
caught in the real tree), and one on a step-cadence knob valid for one
lowering only (sync_every x step_lowering — the ISSUE-17 class) whose
config-side positivity check is not combination coverage either."""


class Trainer:
    def __init__(self, config):
        self.config = config
        if config.device_pairgen:
            if config.cbow:
                raise ValueError("device feed is skip-gram only")

    def _build_step(self):
        cfg = self.config
        if cfg.use_pallas:
            if cfg.cbow:
                raise ValueError("use_pallas is SGNS-only")
            if cfg.max_row_norm:
                raise ValueError("stabilizers are XLA-path only")
        if cfg.cbow:
            if cfg.negative_pool == 0:
                raise ValueError("cbow needs the shared pool here")
        if cfg.sync_every > 1:
            if cfg.step_lowering != "shard_map":
                raise ValueError("sync_every needs the shard_map lowering")
        return None
