from glint_word2vec_tpu.data.vocab import Vocabulary, build_vocab
from glint_word2vec_tpu.data.pipeline import (
    encode_sentences,
    subsample_sentence,
    dynamic_window_pairs,
    PairBatcher,
    epoch_batches,
)

__all__ = [
    "Vocabulary",
    "build_vocab",
    "encode_sentences",
    "subsample_sentence",
    "dynamic_window_pairs",
    "PairBatcher",
    "epoch_batches",
]
