"""The R1–R8 repo-specific rules. Each encodes one documented invariant and
names the document/PR that established it — the catalogue with examples is
docs/static-analysis.md.

| id | invariant | established by |
|----|-----------|----------------|
| R1 | no ad-hoc thread pools in library code (determinism contract)   | PERF.md §10 |
| R2 | counter-hash PRNG only in the library (no random./unseeded np)  | ops/prng.py |
| R3 | no host-sync ops inside jit/shard_map-wrapped functions         | PERF.md §4 |
| R4 | prefix accumulation reachable from params must carry ≥f32 proof | cbow_banded |
| R5 | data-plane reads go through retry_io                            | robustness  |
| R6 | trainer device placement only via the staging discipline        | sharding.md |
| R7 | contract tools print exactly one JSON line to stdout            | BASELINE.md |
| R8 | every knob-pair refused at dispatch is refused in config too    | config.py   |
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set

from tools.graftlint.engine import Finding, ModuleContext

_LIB = "glint_word2vec_tpu/"


def _name_of(func: ast.AST) -> str:
    """Dotted text of a call's func node: Name → 'x', Attribute → 'a.b.c'."""
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _walk_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------------------
# R1 — determinism contract: no ad-hoc thread pools / threads in library code.
# The only blessed owners: pipeline.ordered_pool_map (the ordered-merge pool
# primitive every parallel host path routes through) and the trainer's two
# documented producer/stager iterators. Anything else re-introduces the
# unordered-merge nondeterminism PERF.md §10 paid to remove.
# ---------------------------------------------------------------------------
class R1ThreadPools:
    id = "R1"
    _POOLS = {"ThreadPoolExecutor", "ProcessPoolExecutor", "Pool"}
    _ALLOW = {
        ("glint_word2vec_tpu/data/pipeline.py", "ordered_pool_map"),
        ("glint_word2vec_tpu/train/trainer.py", "_threaded_iter.__init__"),
        ("glint_word2vec_tpu/train/trainer.py", "_one_ahead_iter.__init__"),
        # the status endpoint's serving thread (obs/statusd.py): READ-only —
        # it renders snapshots of trainer state and never produces or orders
        # training data, so the worker-count determinism contract R1 guards
        # is untouched (docs/observability.md)
        ("glint_word2vec_tpu/obs/statusd.py", "StatusServer.start"),
        # the serving tier's two documented owners (docs/serving.md): the
        # micro-batcher worker orders request/response PAIRING only (each
        # caller gets exactly its own result; batch composition is
        # timing-dependent by design), and the hot-reload watcher stats a
        # file + invokes the swap callback — both READ-only on params, the
        # training determinism contract untouched
        ("glint_word2vec_tpu/serve/batcher.py", "BatchingScheduler.start"),
        ("glint_word2vec_tpu/serve/reload.py", "CheckpointWatcher.start"),
        # the serving FLEET's two documented owners (docs/serving.md §5,
        # ISSUE 12): each SubprocessReplica runs one stdout READER thread
        # (pairs wire responses to tickets by id — read-only on
        # everything, orders nothing), and the router runs ONE
        # prober/orchestrator thread (health probes, breaker trials,
        # dead-replica restarts, rolling reloads — read-only on model
        # params; hedging is ticket-based and spawns NO threads). Neither
        # produces or orders training data, so the worker-count
        # determinism contract R1 guards is untouched
        ("glint_word2vec_tpu/serve/fleet.py", "SubprocessReplica.start"),
        ("glint_word2vec_tpu/serve/fleet.py", "FleetRouter.__init__"),
        # the peer-liveness BEACON writer (docs/robustness.md §supervisor,
        # ISSUE 16): one daemon thread per sharded-fit process touching a
        # liveness file every peer_beacon_s and watchdogging the main
        # thread — touches no training data, orders nothing; it exists
        # precisely for when the main thread is wedged in a dead peer's
        # collective and nothing deterministic can run at all
        ("glint_word2vec_tpu/train/supervisor.py", "BeaconBoard.start"),
    }

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB)

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _name_of(node.func)
            tail = name.rsplit(".", 1)[-1]
            is_pool = tail in self._POOLS
            is_thread = name in ("threading.Thread", "Thread")
            if not (is_pool or is_thread):
                continue
            qn = ctx.qualname(node)
            if any(ctx.path == p and (qn == q or qn.endswith("." + q))
                   for p, q in self._ALLOW):
                continue
            kind = "thread pool" if is_pool else "thread"
            out.append(Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message=f"ad-hoc {kind} creation ({name}) in library code — "
                        f"route through pipeline.ordered_pool_map (the "
                        f"ordered-merge determinism contract, PERF.md §10) "
                        f"or allowlist a documented owner"))
        return out


# ---------------------------------------------------------------------------
# R2 — PRNG discipline: the library draws randomness from the counter-hash
# PRNG (ops/prng.py, position-keyed) or an explicitly seeded
# np.random.Generator. Stdlib `random` and unseeded np.random module calls
# make streams depend on process state — the exact reference bug
# (XORShift-seeded async chaos) this repo was built to remove.
# ---------------------------------------------------------------------------
class R2Prng:
    id = "R2"
    _NP_OK = {"default_rng", "SeedSequence", "Generator", "BitGenerator",
              "PCG64", "Philox"}

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB)

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "random":
                        out.append(Finding(
                            rule=self.id, path=ctx.path, line=node.lineno,
                            col=node.col_offset,
                            message="stdlib `random` import in library code "
                                    "— use the counter-hash PRNG "
                                    "(ops/prng.py) or a seeded "
                                    "np.random.Generator"))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "random":
                    out.append(Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset,
                        message="stdlib `random` import in library code — "
                                "counter-hash PRNG only"))
            elif isinstance(node, ast.Call):
                name = _name_of(node.func)
                if (name.startswith(("np.random.", "numpy.random."))
                        and name.rsplit(".", 1)[-1] not in self._NP_OK):
                    out.append(Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"unseeded module-level numpy RNG ({name}) — "
                                f"draw from an explicit "
                                f"np.random.default_rng(seed) Generator or "
                                f"the counter-hash PRNG"))
        return out


def _jit_wrapped_functions(ctx: ModuleContext):
    """FunctionDef/Lambda nodes that are jit/shard_map targets: decorated
    (`@jax.jit`, `@partial(jax.jit, ...)`), or passed by name/inline to a
    `jax.jit(...)` / `jit(...)` / `shard_map(...)` call in this module —
    PLUS the transitive closure of same-module helpers they call by name
    (ISSUE 8 satellite: obs/probe.py's `_matrix_stats` runs inside the
    jitted fused probe but is not itself a jit target, so the pre-closure
    rule never walked it)."""
    wrapper_names = ("jit", "shard_map")

    def is_wrapper(call: ast.Call) -> bool:
        tail = _name_of(call.func).rsplit(".", 1)[-1]
        return tail in wrapper_names

    wrapped_names: Set[str] = set()
    inline: List[ast.AST] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and is_wrapper(node) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Name):
                wrapped_names.add(target.id)
            elif isinstance(target, (ast.Lambda,)):
                inline.append(target)
    out: List[ast.AST] = list(inline)
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in wrapped_names:
                out.append(node)
                continue
            for dec in node.decorator_list:
                txt = ast.unparse(dec)
                if "jit" in txt.split("(")[0].split(".") or (
                        isinstance(dec, ast.Call) and any(
                            isinstance(a, (ast.Name, ast.Attribute))
                            and _name_of(a).rsplit(".", 1)[-1] == "jit"
                            for a in dec.args)):
                    out.append(node)
                    break
    # transitive closure over same-module helpers called by simple name from
    # any wrapped function (nested defs are already inside ast.walk(fn); this
    # adds the module-level/sibling helpers a trace reaches). Cross-module
    # calls stay out of scope — each module is linted on its own. Class
    # METHODS are excluded from the name map: a bare-name call cannot reach
    # them (they need an instance), and a host-only method sharing a helper's
    # name would otherwise be linted as jit context (false positives).
    defs_by_name: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                not isinstance(ctx.parents.get(node), ast.ClassDef):
            defs_by_name.setdefault(node.name, []).append(node)
    seen = set(id(fn) for fn in out)
    frontier = list(out)
    while frontier:
        fn = frontier.pop()
        for call in ast.walk(fn):
            if not (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Name)):
                continue
            for helper in defs_by_name.get(call.func.id, []):
                if id(helper) not in seen:
                    seen.add(id(helper))
                    out.append(helper)
                    frontier.append(helper)
    return out


# ---------------------------------------------------------------------------
# R3 — tracer discipline: float()/.item()/np.asarray()/time.* inside a
# jit/shard_map-wrapped function either crashes at trace time (tracer
# concretization) or, worse, silently constant-folds host state into the
# compiled program. Caught statically so it fails review, not a TPU session.
# ---------------------------------------------------------------------------
class R3TracerDiscipline:
    id = "R3"
    _BAD_CALLS = {"float", "int", "bool"}
    _BAD_ATTRS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array"}

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB)

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for fn in _jit_wrapped_functions(ctx):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = _name_of(node.func)
                bad = None
                if name in self._BAD_CALLS and node.args and not isinstance(
                        node.args[0], ast.Constant):
                    bad = f"{name}() concretizes its argument"
                elif name in self._BAD_ATTRS:
                    bad = f"{name}() forces a device→host copy"
                elif name.endswith(".item") and isinstance(
                        node.func, ast.Attribute):
                    bad = ".item() forces a device→host sync"
                elif name.startswith("time.") or name == "perf_counter":
                    bad = (f"{name}() reads the host clock at TRACE time — "
                           f"it becomes a compile-time constant")
                if bad:
                    out.append(Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"host-sync op inside a jit/shard_map-wrapped "
                                f"function: {bad}"))
        return out


# ---------------------------------------------------------------------------
# R4 — dtype discipline for prefix accumulation: a cumsum/segment-sum chain
# fed from bf16 params cancels away the very interval it computes
# (ops/cbow_banded.py module docstring has the numerics). Every
# prefix-accumulation call in the library must carry STATIC evidence of a
# ≥f32 (or integer) accumulation dtype in its argument's def-use chain.
# ---------------------------------------------------------------------------
class R4PrefixDtype:
    id = "R4"
    _TARGET_TAILS = {"cumsum", "cumsum_rows", "segment_sum",
                     "associative_scan", "cummax", "cumlogsumexp"}
    _HOST_PREFIXES = ("np.", "numpy.")  # host numpy accumulates in f64/int
    _MARKERS = ("float32", "float64", "int32", "int64", "uint32", "uint64",
                "promote_types", "f32", "f64")

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB)

    def _has_marker(self, node: ast.AST, assigns: Dict[str, ast.AST],
                    depth: int = 0) -> bool:
        if depth > 4:
            return False
        txt = ast.unparse(node)
        if any(m in txt for m in self._MARKERS):
            return True
        for name in _walk_names(node):
            rhs = assigns.get(name)
            if rhs is not None and self._has_marker(
                    rhs, {k: v for k, v in assigns.items() if k != name},
                    depth + 1):
                return True
        return False

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _name_of(node.func)
            if name.rsplit(".", 1)[-1] not in self._TARGET_TAILS:
                continue
            if name.startswith(self._HOST_PREFIXES):
                continue
            fn = ctx.enclosing_function(node)
            assigns: Dict[str, ast.AST] = {}
            if fn is not None and not isinstance(fn, ast.Lambda):
                for stmt in ast.walk(fn):
                    if isinstance(stmt, ast.Assign) and len(
                            stmt.targets) == 1 and isinstance(
                            stmt.targets[0], ast.Name):
                        assigns[stmt.targets[0].id] = stmt.value
            args_ok = node.args and all(
                self._has_marker(a, assigns) for a in node.args[:1])
            if not args_ok:
                out.append(Finding(
                    rule=self.id, path=ctx.path, line=node.lineno,
                    col=node.col_offset,
                    message=f"prefix accumulation ({name}) without static "
                            f"≥f32/int dtype evidence on its input — a bf16 "
                            f"prefix cancels the interval "
                            f"(ops/cbow_banded.py); add an explicit "
                            f".astype(...) upcast or suppress with the "
                            f"reasoning"))
        return out


def _retry_protected(ctx: ModuleContext, node: ast.AST) -> bool:
    """True if `node` is lexically inside (a) the argument subtree of a
    retry_io(...) call, or (b) a def/lambda whose NAME is passed to
    retry_io(...) anywhere in this module."""
    retry_calls = [n for n in ast.walk(ctx.tree)
                   if isinstance(n, ast.Call)
                   and _name_of(n.func).rsplit(".", 1)[-1] == "retry_io"]
    retried_names: Set[str] = set()
    for call in retry_calls:
        for arg in call.args:
            if isinstance(arg, ast.Name):
                retried_names.add(arg.id)
            for sub in ast.walk(arg):
                if sub is node:
                    return True
    cur = node
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                cur.name in retried_names:
            return True
        cur = ctx.parents.get(cur)
    return False


# ---------------------------------------------------------------------------
# R5 — robust ingest: data-plane READS (open/np.memmap in data/) go through
# train.faults.retry_io so a transient FS hiccup retries with backoff instead
# of killing an hours-long run (docs/robustness.md). Writes are exempt: the
# one-shot encode passes must NOT retry (a blind re-run would silently
# truncate — the PR-1 review finding), and they restart-from-scratch instead.
# ---------------------------------------------------------------------------
class R5RetryIO:
    id = "R5"

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB + "data/")

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _name_of(node.func)
            if name == "open":
                mode = "r"
                if len(node.args) >= 2 and isinstance(
                        node.args[1], ast.Constant):
                    mode = str(node.args[1].value)
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = str(kw.value.value)
                if not mode.startswith("r"):
                    continue  # write passes restart from scratch by design
            elif name.rsplit(".", 1)[-1] not in ("memmap", "fromfile"):
                continue
            if _retry_protected(ctx, node):
                continue
            out.append(Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message=f"bare data-plane read ({name}) not routed through "
                        f"retry_io — transient FS errors kill long runs "
                        f"(docs/robustness.md); wrap the open/mmap in "
                        f"retry_io(...)"))
        return out


# ---------------------------------------------------------------------------
# R6 — dispatch discipline: the trainer places host data on device ONLY via
# put_global / the _stage_to_device staging path, so every placement respects
# the collective-program serialization gate (_sync_collectives /
# _after_dispatch — the rendezvous-starvation deadlock, docs/sharding.md) and
# stays an EXPLICIT transfer under the stepaudit transfer contract.
# ---------------------------------------------------------------------------
class R6DispatchDiscipline:
    id = "R6"
    _BAD = {"jax.device_put", "device_put",
            "jax.make_array_from_callback",
            "jax.make_array_from_single_device_arrays"}
    _ALLOW_FNS = {"_stage_to_device"}

    def applies(self, path: str) -> bool:
        return path == _LIB + "train/trainer.py"

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _name_of(node.func) not in self._BAD:
                continue
            qn = ctx.qualname(node)
            if any(qn == a or qn.endswith("." + a) for a in self._ALLOW_FNS):
                continue
            out.append(Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message="raw device placement in the trainer — use "
                        "put_global/_stage_dispatch_meta (the staging "
                        "discipline that keeps transfers explicit and "
                        "respects the collective serialization gate, "
                        "docs/sharding.md)"))
        return out


# ---------------------------------------------------------------------------
# R7 — the exactly-one-JSON-line stdout contract of the driver-facing tools:
# the driver parses ONE machine-readable line from stdout; everything human
# goes to stderr. A stray print() corrupts the BENCH/MULTICHIP artifacts.
# ---------------------------------------------------------------------------
class R7JsonStdout:
    id = "R7"
    _CONTRACT_MODULES = {
        "bench.py", "__graft_entry__.py", "tools/hostbench.py",
        "tools/collectives.py", "tools/shard_ab.py", "tools/stepaudit.py",
        "tools/telemetry_run.py", "tools/graftcheck/__main__.py",
        "tools/run_report.py", "tools/perfgate.py", "tools/servebench.py",
        "tools/continual_run.py", "tools/fleet_run.py",
        "tools/obs_collect.py", "tools/racecheck.py",
    }

    def applies(self, path: str) -> bool:
        return path in self._CONTRACT_MODULES

    @staticmethod
    def _is_json_print(node: ast.Call) -> bool:
        return (len(node.args) == 1 and isinstance(node.args[0], ast.Call)
                and _name_of(node.args[0].func).endswith("json.dumps"))

    def check(self, ctx: ModuleContext) -> List[Finding]:
        out: List[Finding] = []
        json_prints_per_fn: Dict[str, int] = {}
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and _name_of(node.func) == "print"):
                continue
            has_file_kw = any(kw.arg == "file" for kw in node.keywords)
            if has_file_kw:
                continue  # stderr-routed (or tests would catch a stdout dup)
            if self._is_json_print(node):
                qn = ctx.qualname(node)
                json_prints_per_fn[qn] = json_prints_per_fn.get(qn, 0) + 1
                if json_prints_per_fn[qn] > 1:
                    out.append(Finding(
                        rule=self.id, path=ctx.path, line=node.lineno,
                        col=node.col_offset,
                        message=f"second print(json.dumps(...)) in {qn} — "
                                f"the stdout contract is exactly ONE JSON "
                                f"line"))
                continue
            out.append(Finding(
                rule=self.id, path=ctx.path, line=node.lineno,
                col=node.col_offset,
                message="bare print() to stdout in a JSON-contract tool — "
                        "route human output to stderr (file=sys.stderr); "
                        "stdout carries exactly one JSON line"))
        return out


# ---------------------------------------------------------------------------
# R8 — refusal-matrix parity (repo rule): every knob combination the trainer
# refuses at dispatch (__init__ path selection or _build_step) must also be
# refused by config.__post_init__ validation, so an unsupported config fails
# at CONSTRUCTION (cheap, local, before any accelerator time) and a
# checkpoint can never be written with knobs the dispatch will later refuse.
# Both matrices are parsed from the AST (conditions on config attributes
# guarding a `raise ValueError`) and diffed; dispatch-side guards that also
# test non-config state (mesh size, process count) are runtime conditions
# and are exempt from the diff.
#
# R8 is the STATIC half of the parity discipline; tools/graftcheck/ is the
# empirical twin that actually executes the lattice (it catches the guards
# this AST diff must exempt — conditions mixing config and runtime state —
# by probing a real Trainer). The cross-reference enforced here: graftcheck's
# knob registry must enumerate every config field, so the executing checker
# can never silently under-cover the surface this rule parses.
# ---------------------------------------------------------------------------
class R8RefusalParity:
    id = "R8"
    repo_rule = True

    _CONFIG = _LIB + "config.py"
    _TRAINER = _LIB + "train/trainer.py"
    _DISPATCH_FNS = {"_build_step", "_build_banded_cbow_chunk", "__init__"}
    _GRAFTCHECK_REGISTRY = "tools/graftcheck/registry.py"

    @staticmethod
    def _knobs_in(test: ast.AST, selves: Set[str],
                  fields: Set[str]) -> Optional[Set[str]]:
        """Config-field names referenced in a condition; None if the
        condition also references non-config runtime state."""
        knobs: Set[str] = set()
        pure = True
        for node in ast.walk(test):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name):
                if node.value.id in selves:
                    if node.attr in fields:
                        knobs.add(node.attr)
                    else:
                        pure = False
                elif node.value.id not in ("np", "jnp", "numpy"):
                    pure = False
            elif isinstance(node, ast.Call):
                pure = False
        return knobs if pure and knobs else None

    def _raise_matrix(self, tree: ast.Module, fn_names: Set[str],
                      selves: Set[str], fields: Set[str],
                      parents: Dict[ast.AST, ast.AST]):
        """set of frozensets: the knob set guarding each pure-config raise
        (union of every enclosing `if` condition's knobs)."""
        out = set()
        fns = [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef) and n.name in fn_names]
        for fn in fns:
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Raise) and node.exc is not None
                        and "ValueError" in ast.unparse(node.exc)):
                    continue
                knobs: Set[str] = set()
                pure = True
                cur = parents.get(node)
                while cur is not None and cur is not fn:
                    if isinstance(cur, ast.If):
                        k = self._knobs_in(cur.test, selves, fields)
                        if k is None:
                            pure = False
                            break
                        knobs |= k
                    cur = parents.get(cur)
                if pure and knobs:
                    out.add(frozenset(knobs))
        return out

    def check_repo(self, root: str) -> List[Finding]:
        cfg_path = os.path.join(root, *self._CONFIG.split("/"))
        tr_path = os.path.join(root, *self._TRAINER.split("/"))
        findings: List[Finding] = []
        try:
            with open(cfg_path, "r", encoding="utf-8") as f:
                cfg_tree = ast.parse(f.read())
            with open(tr_path, "r", encoding="utf-8") as f:
                tr_tree = ast.parse(f.read())
        except (OSError, SyntaxError) as e:
            return [Finding(rule=self.id, path=self._CONFIG, line=0, col=0,
                            message=f"cannot parse matrix sources: {e}")]

        # config dataclass fields = the knob universe
        fields: Set[str] = set()
        for node in ast.walk(cfg_tree):
            if isinstance(node, ast.ClassDef) and node.name == "Word2VecConfig":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                            stmt.target, ast.Name):
                        fields.add(stmt.target.id)
        if not fields:
            return [Finding(rule=self.id, path=self._CONFIG, line=0, col=0,
                            message="Word2VecConfig fields not found")]

        def parent_map(tree):
            p = {}
            for node in ast.walk(tree):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            return p

        cfg_matrix = self._raise_matrix(
            cfg_tree, {"__post_init__"}, {"self"}, fields,
            parent_map(cfg_tree))
        disp_matrix = self._raise_matrix(
            tr_tree, self._DISPATCH_FNS, {"cfg", "config", "self"}, fields,
            parent_map(tr_tree))

        for combo in sorted(disp_matrix, key=sorted):
            if len(combo) < 2:
                continue  # single-knob range checks live in config by design
            # covered only by a MULTI-knob config raise over a subset of these
            # knobs. Single-knob config raises are range checks (negative_pool
            # < 0, window > 127, ...) whose conditions say nothing about the
            # knob-COMBINATION the dispatch refuses — counting them as
            # coverage would blind the rule to exactly the gap class it
            # exists to catch. A config that is legitimately stricter with a
            # single-knob refusal can carry a justified suppression.
            if not any(len(cfg_combo) >= 2 and cfg_combo <= combo
                       for cfg_combo in cfg_matrix):
                findings.append(Finding(
                    rule=self.id, path=self._TRAINER, line=0, col=0,
                    message=f"knob combination refused at trainer dispatch "
                            f"but not in config.__post_init__ "
                            f"validation: {sorted(combo)} — add the "
                            f"construction-time refusal (selection-matrix "
                            f"parity; graftcheck executes the empirical "
                            f"twin of this check)"))
        findings.extend(self._check_graftcheck_registry(root, fields))
        return findings

    def _check_graftcheck_registry(self, root: str,
                                   fields: Set[str]) -> List[Finding]:
        """Cross-reference to the EXECUTING checker: every config field must
        have a knob entry in tools/graftcheck/registry.py, else graftcheck's
        lattice silently under-covers the refusal surface this rule parses.
        Skipped when the graftcheck package is absent (the R8 fixture
        mini-repos); the real tree always carries it.

        DELIBERATELY redundant with registry.registry_drift(): that gate
        runs by importing the live config (and therefore jax); this one is
        pure AST, so the lint layer keeps working when graftcheck itself is
        broken or unimportable — the two gates cross-check each other. The
        AST scan only recognizes literal ``_K("name", ...)`` entries, which
        the registry's own docstring mandates (a knob built by loop/variable
        would be flagged here — that is the desired outcome, not a bug)."""
        reg_dir = os.path.join(root, "tools", "graftcheck")
        if not os.path.isdir(reg_dir):
            return []
        reg_path = os.path.join(root, *self._GRAFTCHECK_REGISTRY.split("/"))
        try:
            with open(reg_path, "r", encoding="utf-8") as f:
                reg_tree = ast.parse(f.read())
        except (OSError, SyntaxError) as e:
            return [Finding(
                rule=self.id, path=self._GRAFTCHECK_REGISTRY, line=0, col=0,
                message=f"cannot parse the graftcheck knob registry: {e}")]
        declared: Set[str] = set()
        for node in ast.walk(reg_tree):
            if (isinstance(node, ast.Call)
                    and _name_of(node.func) in ("_K", "Knob")
                    and node.args and isinstance(node.args[0], ast.Constant)):
                declared.add(str(node.args[0].value))
        out: List[Finding] = []
        for name in sorted(fields - declared):
            out.append(Finding(
                rule=self.id, path=self._GRAFTCHECK_REGISTRY, line=0, col=0,
                message=f"config field {name!r} has no knob entry in the "
                        f"graftcheck registry — the executing lattice "
                        f"under-covers the refusal surface; declare its "
                        f"sampled domain"))
        for name in sorted(declared - fields):
            out.append(Finding(
                rule=self.id, path=self._GRAFTCHECK_REGISTRY, line=0, col=0,
                message=f"graftcheck registry knob {name!r} does not exist "
                        f"on Word2VecConfig — drop the stale entry"))
        return out


from tools.graftlint.concurrency import CONCURRENCY_RULES  # noqa: E402 — the
# graftrace layer (R9–R11 + R1 staleness) lives in its own module; imported
# at the bottom so concurrency.py can use _name_of/R1ThreadPools from here

ALL_RULES = [R1ThreadPools(), R2Prng(), R3TracerDiscipline(), R4PrefixDtype(),
             R5RetryIO(), R6DispatchDiscipline(), R7JsonStdout(),
             R8RefusalParity()] + CONCURRENCY_RULES
