"""Fused SGNS/CBOW training step — the TPU-native replacement for the reference's hot loop.

In the reference, one minibatch costs two network round-trips to the parameter servers:
``dotprod(wInput, wOutput, seed)`` computes positive/negative dot products server-side
(G3, mllib:419-421), the client turns them into scalar gradient coefficients through a
1000-entry sigmoid LUT (``getSigmoid``, mllib:292-302), and ``adjust(gPlus, gMinus,
cacheKeys)`` applies the scatter-updates server-side (G4, mllib:423-425), pipelined at most
one minibatch deep (mllib:428-429).

Here the whole thing is one jitted function: embedding gather → batched dots → sigmoid →
scatter-add updates, with negatives sampled on-device (:mod:`..ops.sampler`). Under jit the
``dotprod``/``adjust`` split disappears; under pjit the per-shard partial dot products of the
CIKM'16 scheme become XLA collectives inserted by GSPMD.

Update rule (SGD on the SGNS objective, identical to the reference's coefficients):

    f_pos = syn0[c]·syn1[x]          g_pos = (1 − σ(f_pos))·α
    f_neg = syn0[c]·syn1[z_k]        g_neg = (0 − σ(f_neg))·α
    syn0[c]    += g_pos·syn1[x] + Σ_k g_neg_k·syn1[z_k]
    syn1[x]    += g_pos·syn0[c]
    syn1[z_k]  += g_neg_k·syn0[c]

using the *pre-update* values on both sides, exactly like the server-side cache in the
reference (the ``cacheKeys`` minibatch cache exists to reuse the dotprod-time rows in
adjust). Duplicate indices within a batch accumulate via scatter-add — deterministic,
unlike the reference's accepted Hogwild races (README.md:17-19).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from glint_word2vec_tpu.ops.sampler import AliasTable, sample_negatives

MAX_EXP = 6.0  # the reference's LUT clipping range (mllib:247, EXP_TABLE_SIZE/MAX_EXP)

# divide guard for the stabilizer norm ratios — far below any row norm a
# trained embedding can reach in f32 (min normal ~1.2e-38) yet nonzero, so a
# zero row clamps with scale min(1, max/eps) = 1 instead of NaN
_STAB_EPS = 1e-30


class Stabilizers(NamedTuple):
    """In-step numeric stabilizers (config.max_row_norm / update_clip /
    row_l2 — docs/robustness.md escalation ladder). All 0.0 = OFF, and an
    off knob elides its ops from the compiled step entirely, so the
    stabilizers-off step is bit-identical to the pre-stabilizer step (tested).

    - ``max_row_norm``: per-TOUCHED-row L2 clamp applied after the scatter
      update — never a dense [V, D] renorm pass. The direct counter to the
      measured finite norm blowup (EVAL.md round-5: hot rows run orders of
      magnitude past the healthy 1-15 band while isfinite stays true).
    - ``update_clip``: per-row L2 ceiling on each pair's/example's update
      contribution (the d_in/d_pos rows of SGNS, d_hidden/d_out of CBOW),
      applied BEFORE the scatter-add. Pool-row deltas (d_Z) are deliberately
      NOT clipped: under shard_map each data shard holds only a partial d_Z
      sum, so clipping there would diverge from the single-program lowering —
      pool rows are bounded by the n/P reweight plus ``max_row_norm`` instead.
    - ``row_l2``: L2 weight decay on touched rows — each touched row scales
      by (1 − α·row_l2) once per step regardless of in-batch multiplicity.

    All norm/scale math runs in float32 regardless of param/compute dtype
    (the R4 accumulation discipline: bf16 squared norms underflow exactly
    where the blowup channel saturates).
    """

    max_row_norm: float = 0.0
    update_clip: float = 0.0
    row_l2: float = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self.max_row_norm or self.update_clip or self.row_l2)

    @property
    def post_pass(self) -> bool:
        """Whether the post-scatter touched-row pass (clamp/decay) runs."""
        return bool(self.max_row_norm or self.row_l2)


def clip_update_rows(d: jax.Array, clip: float) -> jax.Array:
    """Per-row L2 ceiling on an update-row block ``[..., D]``: rows whose L2
    norm exceeds ``clip`` rescale to exactly ``clip``; shorter rows pass
    through bit-exact (scale 1.0 round-trips the dtype). Norm math in
    ``promote_types(d.dtype, float32)`` — never below f32 (R4), never below
    the data's own precision (the f64 oracle suite holds this path exact)."""
    if not clip:
        return d
    pf = jnp.promote_types(d.dtype, jnp.float32)
    dp = d.astype(pf)
    n2 = jnp.sum(dp * dp, axis=-1, keepdims=True)
    scale = jnp.minimum(
        jnp.asarray(1.0, pf),
        jnp.asarray(clip, pf) / jnp.maximum(jnp.sqrt(n2),
                                            jnp.asarray(_STAB_EPS, pf)))
    return (dp * scale).astype(d.dtype)


def stabilize_rows(
    mat: jax.Array,       # [Vs, D] — a just-updated param matrix (or shard)
    idx: jax.Array,       # int32 [N] — touched rows; >= Vs = drop sentinel
    alpha: jax.Array,     # scalar learning rate (already decayed)
    stab: Stabilizers,
    enable: jax.Array,    # f32 scalar 1.0/0.0 — 0 on all-masked padded batches
) -> jax.Array:
    """Post-scatter touched-row stabilizer pass: gather the just-updated rows
    at ``idx``, apply the touched-row weight decay ``(1 − α·row_l2)`` then the
    ``max_row_norm`` clamp (clamping the DECAYED norm), and write the rows
    back with one scatter-set. Duplicate indices are safe by construction:
    every duplicate computes the identical replacement value (same gathered
    row → same scale), so the unordered scatter writes agree. Indices at or
    past ``mat.shape[0]`` (the caller's mask/ownership sentinel) drop — vocab
    padding rows are never touched. ``enable=0`` pins every scale to 1.0, so
    a fully-masked padded batch stays a bit-level no-op."""
    if not stab.post_pass:
        return mat
    vs = mat.shape[0]
    # norm/scale math in promote_types(dtype, f32): never below f32 (bf16
    # squared norms underflow exactly where the blowup saturates — R4),
    # never below the data's own precision (f64 oracle exactness)
    pf = jnp.promote_types(mat.dtype, jnp.float32)
    rows = mat[jnp.minimum(idx, vs - 1)].astype(pf)
    scale = jnp.ones(rows.shape[:-1], pf)
    if stab.row_l2:
        scale = scale * (jnp.asarray(1.0, pf)
                         - alpha.astype(pf) * jnp.asarray(stab.row_l2, pf))
    if stab.max_row_norm:
        norm = jnp.sqrt(jnp.sum(rows * rows, axis=-1)) * scale
        scale = scale * jnp.minimum(
            jnp.asarray(1.0, pf),
            jnp.asarray(stab.max_row_norm, pf)
            / jnp.maximum(norm, jnp.asarray(_STAB_EPS, pf)))
    scale = jnp.where(enable > 0, scale, jnp.asarray(1.0, pf))
    return mat.at[idx].set(
        (rows * scale[..., None]).astype(mat.dtype), mode="drop")


def _mask_sentinel(idx: jax.Array, gate: jax.Array, vs: int) -> jax.Array:
    """Touched-index list with gated-off slots mapped to the drop sentinel
    ``vs`` (one past the last row): a masked batch slot's placeholder index
    (0) must not drag a real row into the clamp/decay pass."""
    return jnp.where(gate > 0, idx, jnp.int32(vs))


# ---------------------------------------------------------------------------
# Cross-step hot-row accumulation (config.hot_rows — ISSUE 14, PERF.md §11).
#
# The vocabulary is sorted by descending frequency (data/vocab.py contract),
# so rows 0..K−1 are exactly the words Zipf mass concentrates the per-step
# update traffic on. The hot-row scheme diverts their updates into a small
# [K, D] float32 slab carried across the steps of a dispatch chunk:
#
#   - READS stay exact: every gather adds the slab's pending delta back
#     (hot_gather), so no step ever trains on a stale hot row — the scheme
#     changes floating-point ORDER (per-step param-dtype rounding becomes
#     one f32-accumulated add per flush window), never the update math.
#   - WRITES split (hot_scatter_add): indices < K accumulate into the slab
#     (a scatter whose target is K rows, small enough to live in VMEM/cache),
#     indices >= K take the normal [V, D] scatter with the hot candidates
#     remapped to the OOB drop sentinel — the §3-measured cheap regime.
#   - FLUSH (hot_flush): because the hot set is the CONTIGUOUS index prefix,
#     the flush is one dense [K, D] block add (static slice + add + update —
#     no scatter emitter at all), once per `hot_flush_every` steps.
#
# The slab accumulates in float32 regardless of param dtype (R4: cross-step
# bf16 accumulation would round away exactly the small frequent-row updates
# the scheme batches). The trainer flushes unconditionally at the end of
# every dispatch chunk, so the params carry leaving a chunk is always
# complete — checkpoints, probes, and donation never see a pending slab.
# ---------------------------------------------------------------------------


def hot_gather(mat: jax.Array, slab: jax.Array, idx: jax.Array,
               compute_dtype: jnp.dtype) -> jax.Array:
    """``mat[idx]`` with the hot slab's pending deltas added back for
    ``idx < K`` — the read-freshness half of the hot-row contract. ``idx``
    may be any shape; returns ``[..., D]`` in ``compute_dtype``."""
    k = slab.shape[0]
    rows = mat[idx].astype(compute_dtype)
    hot = idx < k
    pend = jnp.where(hot[..., None],
                     slab[jnp.where(hot, idx, 0)].astype(compute_dtype),
                     jnp.zeros((), compute_dtype))
    return rows + pend


def hot_scatter_add(
    mat: jax.Array,    # [V, D] param matrix
    slab: jax.Array,   # [K, D] float32 pending-delta slab
    idx: jax.Array,    # int32 [N] (flattened by the caller if needed)
    upd: jax.Array,    # [N, D] update rows (compute dtype)
) -> Tuple[jax.Array, jax.Array]:
    """Split scatter-add: rows ``idx < K`` accumulate into the f32 slab,
    the rest into the matrix; each side drops the other's candidates via the
    OOB sentinel (mode="drop"), so every update lands exactly once."""
    k = slab.shape[0]
    v = mat.shape[0]
    cold = jnp.where(idx < k, jnp.int32(v), idx)
    mat = mat.at[cold].add(upd.astype(mat.dtype), mode="drop")
    hot = jnp.where(idx < k, idx, jnp.int32(k))
    slab = slab.at[hot].add(upd.astype(slab.dtype), mode="drop")
    return mat, slab


def hot_flush(mat: jax.Array, slab: jax.Array) -> jax.Array:
    """Apply the accumulated hot-row deltas: ONE dense [K, D] block add over
    the contiguous index prefix (static slice — lowers to slice/add/update,
    zero scatter-emitter rows; the "one sorted scatter" of the design, made
    degenerate by the frequency-sorted vocabulary contract)."""
    k = slab.shape[0]
    return mat.at[:k].add(slab.astype(mat.dtype))


class EmbeddingPair(NamedTuple):
    """The two trainable matrices: input (syn0) and output (syn1neg) embeddings —
    the reference's ``BigWord2VecMatrix`` pair (G2, README.md:69)."""

    syn0: jax.Array  # [V, D] input embeddings — the word vectors the model exports
    syn1: jax.Array  # [V, D] output embeddings — negative-sampling softmax weights


class StepMetrics(NamedTuple):
    """Per-step training telemetry — superset of the reference's heartbeat, which logs
    wordCount/alpha/fPlus(0) every 10k words (mllib:411-412)."""

    loss: jax.Array       # masked mean SGNS loss
    mean_f_pos: jax.Array  # mean positive dot product (gradient-health signal)
    pairs: jax.Array      # number of real (unmasked) pairs in the batch


def init_embeddings(
    vocab_size: int,
    vector_size: int,
    key: jax.Array,
    dtype: jnp.dtype = jnp.float32,
) -> EmbeddingPair:
    """Classic word2vec init: syn0 ~ U(-0.5/D, 0.5/D), syn1 = 0 (fork-side in the
    reference; standard for SGNS — zero syn1 makes initial dots 0, σ=0.5)."""
    syn0 = jax.random.uniform(
        key, (vocab_size, vector_size), dtype=jnp.float32,
        minval=-0.5 / vector_size, maxval=0.5 / vector_size).astype(dtype)
    syn1 = jnp.zeros((vocab_size, vector_size), dtype=dtype)
    return EmbeddingPair(syn0=syn0, syn1=syn1)


def _sigmoid(f: jax.Array, mode: str) -> jax.Array:
    """σ(f); "clipped" mirrors the reference LUT saturation: σ=1 for f>6, σ=0 for f<-6
    (getSigmoid, mllib:292-302), which zeroes gradients outside ±6."""
    if mode == "clipped":
        return jnp.where(f > MAX_EXP, 1.0,
                         jnp.where(f < -MAX_EXP, 0.0, jax.nn.sigmoid(f)))
    return jax.nn.sigmoid(f)


def _log_sigmoid(f: jax.Array) -> jax.Array:
    return -jax.nn.softplus(-f)


def sgns_loss(
    params: EmbeddingPair,
    centers: jax.Array,     # int32 [B]
    contexts: jax.Array,    # int32 [B]
    negatives: jax.Array,   # int32 [B, n]
    mask: jax.Array,        # float32 [B]
    compute_dtype: jnp.dtype = jnp.float32,
) -> jax.Array:
    """Masked-mean SGNS negative log likelihood:
    −log σ(f_pos) − Σ_k log σ(−f_neg_k). ∂loss/∂f gives exactly the reference's gradient
    coefficients (up to the α scale), so SGD-via-autodiff on this loss and the manual
    :func:`sgns_step` agree — a property the unit tests assert.
    """
    e_in = params.syn0[centers].astype(compute_dtype)
    e_pos = params.syn1[contexts].astype(compute_dtype)
    e_neg = params.syn1[negatives].astype(compute_dtype)
    f_pos = jnp.sum(e_in * e_pos, axis=-1).astype(jnp.float32)
    f_neg = jnp.einsum("bd,bnd->bn", e_in, e_neg).astype(jnp.float32)
    neg_valid = (negatives != contexts[:, None]).astype(jnp.float32) * mask[:, None]
    per_pair = -_log_sigmoid(f_pos) * mask - jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
    denom = jnp.maximum(mask.sum(), 1.0)
    return per_pair.sum() / denom


def sgns_step(
    params: EmbeddingPair,
    centers: jax.Array,    # int32 [B]
    contexts: jax.Array,   # int32 [B]
    mask: jax.Array,       # float32 [B]
    key: jax.Array,
    alpha: jax.Array,      # scalar learning rate (already decayed)
    table: AliasTable,
    num_negatives: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    duplicate_scaling: bool = False,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """One synchronous SGNS update on a fixed-shape batch of (center, context) pairs.

    Negatives equal to their pair's positive context word are skipped (zero gradient), the
    classic word2vec rule the fork's server-side sampler follows. Padded pairs (mask 0)
    contribute nothing: their coefficients are multiplied by the mask before scatter.

    ``duplicate_scaling``: divide each row's accumulated update by the number of times the
    row occurs in the batch. The reference never faces this — its async 50-pair minibatches
    apply sequentially (mllib:417-429), so a frequent word's updates interleave; in one
    large synchronous batch they *sum*, and at extreme duplicate density (tiny vocab ×
    large batch) the effective per-row step is duplicates × α, which can diverge. Scaling
    makes each row take the *mean* of its pair updates — stable at any batch size, at the
    cost of slower differentiation (frequent rows see one averaged step per batch). Default
    off: textbook accumulate semantics, the reference's math.
    """
    negatives = sample_negatives(table, key, (centers.shape[0], num_negatives))
    return sgns_step_core(params, centers, contexts, mask, negatives, alpha,
                          sigmoid_mode, compute_dtype, duplicate_scaling)


def sgns_step_core(
    params: EmbeddingPair,
    centers: jax.Array,    # int32 [B]
    contexts: jax.Array,   # int32 [B]
    mask: jax.Array,       # float32 [B]
    negatives: jax.Array,  # int32 [B, n] — pre-drawn (hot path: ops.sampler.sample_negatives_hash)
    alpha: jax.Array,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    duplicate_scaling: bool = False,
    stabilizers: Optional[Stabilizers] = None,
    fused: bool = False,
    bf16_chain: bool = False,
    hot_slabs: Optional[Tuple[jax.Array, jax.Array]] = None,
):
    """:func:`sgns_step` with the negatives supplied by the caller — the form the
    trainer jits (sampling happens once per dispatch chunk, outside the scan, because
    in-program threefry is catastrophically slow on TPU; see ops/prng.py).

    ``stabilizers`` (None/all-zero = off, bit-identical step): ``update_clip``
    caps every per-pair update row (d_in, d_pos, and — per-pair negatives
    being per-pair rows — d_neg); the post-scatter pass clamps/decays the
    touched rows: syn0 at the unmasked centers, syn1 at the unmasked contexts
    plus the negatives of unmasked pairs (see :class:`Stabilizers`).

    ``fused``/``bf16_chain``/``hot_slabs``: the per-pair forms of the ISSUE-14
    step restructurings (see :func:`sgns_step_shared_core` for semantics):
    fused folds validity+mask+α into one [B, n] select with a precomputed
    scalar; bf16_chain accumulates both logit dots in promote(compute, f32)
    via ``preferred_element_type`` (the per-pair chain previously ran the
    einsum in compute dtype and upcast AFTER — chain mode is the stricter R4
    form); hot_slabs routes updates through the cross-step hot-row slabs.
    All default off; off elides the new ops entirely (bit-identical step)."""
    syn0, syn1 = params
    V = syn0.shape[0]
    if duplicate_scaling and (fused or hot_slabs is not None):
        raise ValueError("duplicate_scaling has no fused/hot-row form "
                         "(refused at config construction)")
    if hot_slabs is not None and stabilizers is not None:
        raise ValueError("stabilizers have no hot-row form (refused at "
                         "config construction)")
    if not fused:
        neg_valid = (negatives != contexts[:, None]).astype(jnp.float32) \
            * mask[:, None]

    if hot_slabs is not None:
        slab0, slab1 = hot_slabs
        e_in = hot_gather(syn0, slab0, centers, compute_dtype)    # [B, D]
        e_pos = hot_gather(syn1, slab1, contexts, compute_dtype)  # [B, D]
        e_neg = hot_gather(syn1, slab1, negatives, compute_dtype)  # [B, n, D]
    else:
        e_in = syn0[centers].astype(compute_dtype)          # [B, D]
        e_pos = syn1[contexts].astype(compute_dtype)        # [B, D]
        e_neg = syn1[negatives].astype(compute_dtype)       # [B, n, D]

    if bf16_chain:
        pf = jnp.promote_types(compute_dtype, jnp.float32)
        f_pos = jnp.einsum("bd,bd->b", e_in, e_pos,
                           preferred_element_type=pf).astype(jnp.float32)
        f_neg = jnp.einsum("bd,bnd->bn", e_in, e_neg,
                           preferred_element_type=pf).astype(jnp.float32)
    else:
        f_pos = jnp.sum(e_in * e_pos, axis=-1).astype(jnp.float32)        # [B]
        f_neg = jnp.einsum("bd,bnd->bn", e_in, e_neg).astype(jnp.float32)  # [B, n]

    # Gradient coefficients, exactly the reference's client-side math (mllib:421-425):
    # gPlus = (1 − σ(f))·α for label 1, gMinus = (0 − σ(f))·α for label 0.
    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * mask               # [B]
    if fused:
        valid = (negatives != contexts[:, None]) & (mask[:, None] > 0)
        g_neg = jnp.where(valid, _sigmoid(f_neg, sigmoid_mode) * (-alpha),
                          jnp.zeros((), f_neg.dtype))                  # [B, n]
        neg_valid = valid
    else:
        g_neg = (0.0 - _sigmoid(f_neg, sigmoid_mode)) * alpha * neg_valid  # [B, n]

    if duplicate_scaling:
        cnt0 = jnp.zeros(V, jnp.float32).at[centers].add(mask)
        cnt1 = (jnp.zeros(V, jnp.float32).at[contexts].add(mask)
                .at[negatives.reshape(-1)].add(neg_valid.reshape(-1)))
        g_pos_in = g_pos / jnp.maximum(cnt0[centers], 1.0)
        g_neg_in = g_neg / jnp.maximum(cnt0[centers], 1.0)[:, None]
        g_pos_out = g_pos / jnp.maximum(cnt1[contexts], 1.0)
        g_neg_out = g_neg / jnp.maximum(cnt1[negatives], 1.0)
    else:
        g_pos_in = g_pos_out = g_pos
        g_neg_in = g_neg_out = g_neg

    d_in = (g_pos_in[:, None].astype(compute_dtype) * e_pos
            + jnp.einsum("bn,bnd->bd", g_neg_in.astype(compute_dtype), e_neg))
    d_pos = g_pos_out[:, None].astype(compute_dtype) * e_in          # [B, D]
    d_neg = g_neg_out[..., None].astype(compute_dtype) * e_in[:, None, :]  # [B, n, D]
    if stabilizers is not None and stabilizers.update_clip:
        d_in = clip_update_rows(d_in, stabilizers.update_clip)
        d_pos = clip_update_rows(d_pos, stabilizers.update_clip)
        d_neg = clip_update_rows(d_neg, stabilizers.update_clip)

    dtype = syn0.dtype
    D = syn1.shape[1]
    if hot_slabs is not None:
        new_syn0, slab0 = hot_scatter_add(syn0, slab0, centers, d_in)
        new_syn1, slab1 = hot_scatter_add(syn1, slab1, contexts, d_pos)
        new_syn1, slab1 = hot_scatter_add(
            new_syn1, slab1, negatives.reshape(-1), d_neg.reshape(-1, D))
    else:
        new_syn0 = syn0.at[centers].add(d_in.astype(dtype))
        new_syn1 = syn1.at[contexts].add(d_pos.astype(dtype))
        new_syn1 = new_syn1.at[negatives.reshape(-1)].add(
            d_neg.reshape(-1, D).astype(dtype))
    if stabilizers is not None and stabilizers.post_pass:
        enable = (mask.sum() > 0).astype(jnp.float32)
        new_syn0 = stabilize_rows(
            new_syn0, _mask_sentinel(centers, mask, V), alpha,
            stabilizers, enable)
        idx1 = jnp.concatenate([
            _mask_sentinel(contexts, mask, V),
            _mask_sentinel(negatives,
                           jnp.broadcast_to(mask[:, None], negatives.shape),
                           V).reshape(-1)])
        new_syn1 = stabilize_rows(new_syn1, idx1, alpha, stabilizers, enable)

    denom = jnp.maximum(mask.sum(), 1.0)
    if fused:
        neg_loss = jnp.sum(
            jnp.where(neg_valid, _log_sigmoid(-f_neg),
                      jnp.zeros((), f_neg.dtype)), axis=-1)
    else:
        neg_loss = jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1)
    loss = (-_log_sigmoid(f_pos) * mask - neg_loss).sum() / denom
    metrics = StepMetrics(
        loss=loss,
        mean_f_pos=(f_pos * mask).sum() / denom,
        pairs=mask.sum(),
    )
    if hot_slabs is not None:
        return EmbeddingPair(new_syn0, new_syn1), metrics, (slab0, slab1)
    return EmbeddingPair(new_syn0, new_syn1), metrics


def shared_pool_coeffs(
    e_in: jax.Array,       # [B, D] compute_dtype
    e_pos: jax.Array,      # [B, D] compute_dtype
    Z: jax.Array,          # [P, D] compute_dtype
    contexts: jax.Array,   # int32 [B]
    negatives: jax.Array,  # int32 [P]
    mask: jax.Array,       # float32 [B]
    alpha: jax.Array,
    num_negatives: int,
    sigmoid_mode: str,
    logits_dtype: jnp.dtype,
    fused: bool = False,
    bf16_chain: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """The shared-pool logit chain: (f_pos, f_neg, neg_valid, g_pos, g_neg).

    Extracted so the GSPMD step (:func:`sgns_step_shared_core`) and the
    explicit shard_map lowering (:mod:`.sgns_shard`) run op-for-op identical
    coefficient math — the two lowerings must never drift in anything but
    collective placement.

    ``fused`` (config.fused_logits): collapse the [B, P] chain to ONE
    coefficient expression — validity (pool entry == pair's positive) and
    the batch mask fold into a single select predicate, and the
    α·negatives/P reweight folds into one precomputed scalar, so the chain
    materializes only f_neg (the dot output) and g_neg instead of also the
    float neg_valid array and its mask/α/reweight elementwise passes
    (PERF.md §11). ``neg_valid`` is then returned as the BOOL predicate —
    consumed only by the metrics twin's loss pass (dead code in the elided
    production twin). Off (default) keeps the pre-fusion chain op-for-op.

    ``bf16_chain`` (config.bf16_chain): compute the positive logit as a
    dot_general accumulating in promote(compute, f32) via
    ``preferred_element_type`` instead of a multiply + convert-to-f32 +
    reduce — same R4 accumulation discipline WITHOUT the dense f32 [B, D]
    product the sum-based form materializes in bf16 mode (the new stepaudit
    dtype-contract row pins this on the lowered module)."""
    P = negatives.shape[0]
    if bf16_chain:
        pf = jnp.promote_types(e_in.dtype, jnp.float32)
        f_pos = jnp.einsum("bd,bd->b", e_in, e_pos,
                           preferred_element_type=pf).astype(jnp.float32)
    else:
        f_pos = jnp.sum(e_in * e_pos, axis=-1).astype(jnp.float32)
    f_neg = (e_in @ Z.T).astype(logits_dtype)           # [B, P] — MXU
    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * mask
    if fused:
        valid = ((negatives[None, :] != contexts[:, None])
                 & (mask[:, None] > 0))                 # bool [B, P]
        neg_scale = (alpha * (0.0 - num_negatives / P)).astype(logits_dtype)
        g_neg = jnp.where(valid, _sigmoid(f_neg, sigmoid_mode) * neg_scale,
                          jnp.zeros((), logits_dtype))
        return f_pos, f_neg, valid, g_pos, g_neg
    neg_valid = (negatives[None, :] != contexts[:, None]).astype(logits_dtype) \
        * mask[:, None].astype(logits_dtype)
    g_neg = ((0.0 - _sigmoid(f_neg, sigmoid_mode))
             * jnp.asarray(alpha, logits_dtype) * neg_valid
             * jnp.asarray(num_negatives / P, logits_dtype))
    return f_pos, f_neg, neg_valid, g_pos, g_neg


def shared_pool_loss_terms(
    f_pos: jax.Array,      # [B] float32
    f_neg: jax.Array,      # [B, P] logits_dtype
    neg_valid: jax.Array,  # [B, P] logits_dtype
    mask: jax.Array,       # float32 [B]
    num_negatives: int,
) -> Tuple[jax.Array, jax.Array]:
    """Pre-division loss/mean_f_pos numerators (scalars). Shared by both
    lowerings; the shard_map step psums these across data shards before
    dividing by the global pair count, the single-program step divides
    directly — same math either way. ``neg_valid`` may be the classic float
    validity array or the fused chain's bool predicate (a select replaces
    the multiply — identical masking, one fewer [B, P] float array)."""
    P = f_neg.shape[-1]
    if neg_valid.dtype == jnp.bool_:
        neg_term = jnp.sum(
            jnp.where(neg_valid, _log_sigmoid(-f_neg),
                      jnp.zeros((), f_neg.dtype)),
            axis=-1, dtype=jnp.float32)
    else:
        neg_term = jnp.sum(_log_sigmoid(-f_neg) * neg_valid, axis=-1,
                           dtype=jnp.float32)
    loss_num = (-_log_sigmoid(f_pos) * mask
                - neg_term * (num_negatives / P)).sum()
    return loss_num, (f_pos * mask).sum()


def sgns_step_shared(
    params: EmbeddingPair,
    centers: jax.Array,    # int32 [B]
    contexts: jax.Array,   # int32 [B]
    mask: jax.Array,       # float32 [B]
    key: jax.Array,
    alpha: jax.Array,
    table: AliasTable,
    num_negatives: int,
    negative_pool: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """SGNS step with a batch-shared negative pool — the TPU fast path.

    Per-pair negative sampling makes the step row-access-bound: 5·B extra row gathers and
    5·B row scatters per batch dominate the step (measured ~4× the positive-pair traffic).
    Sharing ONE pool of ``negative_pool`` negatives across the whole batch turns all
    negative compute into MXU matmuls — ``f_neg = E_in @ Zᵀ`` and ``dZ = g_negᵀ @ E_in`` —
    leaving only ``negative_pool`` scatter rows. Each negative term is reweighted by
    ``num_negatives / negative_pool`` so the expected gradient matches the per-pair
    objective (the standard shared-negative estimator used by batched word2vec systems;
    the reference's own shared-seed trick, G3 mllib:419-421, is the RPC-era cousin —
    negatives shared across PS shards to avoid communicating them).

    Pool entries equal to a pair's positive context are masked per (pair, pool) entry.
    """
    negatives = sample_negatives(table, key, (negative_pool,))
    return sgns_step_shared_core(params, centers, contexts, mask, negatives, alpha,
                                 num_negatives, sigmoid_mode, compute_dtype)


def sgns_step_shared_core(
    params: EmbeddingPair,
    centers: jax.Array,    # int32 [B]
    contexts: jax.Array,   # int32 [B]
    mask: jax.Array,       # float32 [B]
    negatives: jax.Array,  # int32 [P] — pre-drawn shared pool
    alpha: jax.Array,
    num_negatives: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    duplicate_scaling: bool = False,
    logits_dtype: jnp.dtype = jnp.float32,
    with_metrics: bool = True,
    stabilizers: Optional[Stabilizers] = None,
    fused: bool = False,
    bf16_chain: bool = False,
    hot_slabs: Optional[Tuple[jax.Array, jax.Array]] = None,
):
    """:func:`sgns_step_shared` with the pool supplied by the caller (see
    :func:`sgns_step_core` for why sampling lives outside the jitted scan).

    ``fused``/``bf16_chain`` (config.fused_logits / config.bf16_chain —
    ISSUE 14): the fused coefficient chain and the f32-accumulating dot
    restructurings of :func:`shared_pool_coeffs`; both default off, and off
    elides the new ops entirely (the step is bit-identical to the
    pre-restructure release — tested). Neither supports
    ``duplicate_scaling`` (the mean-update scaling reads the per-pair
    coefficient arrays the fusion eliminates; refused at config).

    ``hot_slabs`` (config.hot_rows): the cross-step hot-row accumulation
    slabs ``(slab0, slab1)`` — f32 [K, D] pending deltas for syn0/syn1's
    first K rows, carried across the dispatch chunk's scan by the trainer.
    When given, gathers read through :func:`hot_gather` (pending deltas
    added back — no staleness), scatters split through
    :func:`hot_scatter_add`, and the return grows a third element with the
    updated slabs. Incompatible with stabilizers (the post-scatter clamp
    would measure rows missing their pending deltas; refused at config).

    ``stabilizers`` (None/all-zero = off, bit-identical step): ``update_clip``
    caps the per-pair d_in/d_pos rows (NOT the pool deltas d_Z — see
    :class:`Stabilizers` for the shard_map-parity rationale); the post-scatter
    pass clamps/decays the touched rows — syn0 at the unmasked centers, syn1
    at the unmasked contexts plus the whole shared pool (every pool row is
    part of the step's touched set by construction). The explicit shard_map
    lowering (ops/sgns_shard.py) applies the identical math owner-locally, so
    the two lowerings agree to the usual f32-reassociation tolerance.

    ``duplicate_scaling`` extends :func:`sgns_step_core`'s mean-update semantics to
    this path: each embedding row moves by the MEAN of its per-pair updates instead of
    their sum — centers/contexts divide by their occurrence count in the batch, and
    each pool row divides by its number of contributing (valid) pairs times its
    within-pool multiplicity. This bounds the per-row step at any batch size without
    subsampling, at the cost of slower differentiation of frequent rows (and, for pool
    rows, a much smaller effective negative step, since their contribution count is
    ~B). Frequency subsampling (subsample_ratio ≈ 1e-4) is usually the better fix —
    see EVAL.md.

    ``logits_dtype`` is the dtype of the [B, P] negative-logit chain (f_neg → sigmoid
    → g_neg). The default float32 matches the reference's client-side float math
    (mllib:421-425). At pool ≥ 512 the f32 chain is several full passes over a
    [B, P] array (~268 MB at B=64k/P=1024) and becomes a measurable slice of the
    step (PERF.md §4); ``bfloat16`` keeps it in half precision — gradient
    coefficients are O(α·n/P) and tolerate ~0.4% relative noise. Loss/metric
    reductions still accumulate in f32.

    ``with_metrics=False`` skips the loss/mean_f_pos side-channel (the negative
    loss term is an extra full [B, P] pass — measured ~0.3 ms at B=64k/P=512
    bf16, PERF.md §4); ``pairs`` stays exact (it is load-bearing for the
    trainer's pair accounting). The trainer dispatches this variant for chunks
    no heartbeat will sample."""
    syn0, syn1 = params
    V = syn0.shape[0]
    if duplicate_scaling and (fused or hot_slabs is not None):
        raise ValueError("duplicate_scaling has no fused/hot-row form "
                         "(refused at config construction)")
    if hot_slabs is not None and stabilizers is not None:
        raise ValueError("stabilizers have no hot-row form (refused at "
                         "config construction)")
    if hot_slabs is not None:
        slab0, slab1 = hot_slabs
        e_in = hot_gather(syn0, slab0, centers, compute_dtype)    # [B, D]
        e_pos = hot_gather(syn1, slab1, contexts, compute_dtype)  # [B, D]
        Z = hot_gather(syn1, slab1, negatives, compute_dtype)     # [P, D]
    else:
        e_in = syn0[centers].astype(compute_dtype)          # [B, D]
        e_pos = syn1[contexts].astype(compute_dtype)        # [B, D]
        Z = syn1[negatives].astype(compute_dtype)           # [P, D]

    f_pos, f_neg, neg_valid, g_pos, g_neg = shared_pool_coeffs(
        e_in, e_pos, Z, contexts, negatives, mask, alpha,
        num_negatives, sigmoid_mode, logits_dtype,
        fused=fused, bf16_chain=bf16_chain)

    if duplicate_scaling:
        cnt0 = jnp.zeros(V, jnp.float32).at[centers].add(mask)
        cnt1 = jnp.zeros(V, jnp.float32).at[contexts].add(mask)
        in_scale = 1.0 / jnp.maximum(cnt0[centers], 1.0)
        g_pos_in = g_pos * in_scale
        # keep the [B, P] chain in logits_dtype (bf16 x f32 would promote and
        # materialize the f32 array this option exists to avoid); 1/count is safe
        g_neg_in = g_neg * in_scale[:, None].astype(logits_dtype)
        g_pos_out = g_pos / jnp.maximum(cnt1[contexts], 1.0)
        # pool row p: mean over its contributing pairs, then divided by how many
        # pool slots hold the same word (their scatter-adds would otherwise sum)
        pool_mult = jnp.zeros(V, jnp.float32).at[negatives].add(1.0)[negatives]
        z_scale = 1.0 / (jnp.maximum(neg_valid.sum(axis=0, dtype=jnp.float32), 1.0)
                         * pool_mult)
    else:
        g_pos_in, g_neg_in, g_pos_out = g_pos, g_neg, g_pos
        z_scale = None

    gp_in = g_pos_in[:, None].astype(compute_dtype)
    gn_in = g_neg_in.astype(compute_dtype)
    gn = g_neg.astype(compute_dtype)
    d_in = gp_in * e_pos + gn_in @ Z                     # [B, D] — MXU
    d_pos = g_pos_out[:, None].astype(compute_dtype) * e_in
    d_Z = gn.T @ e_in                                    # [P, D] — MXU
    if z_scale is not None:
        d_Z = d_Z * z_scale[:, None].astype(compute_dtype)
    if stabilizers is not None and stabilizers.update_clip:
        d_in = clip_update_rows(d_in, stabilizers.update_clip)
        d_pos = clip_update_rows(d_pos, stabilizers.update_clip)

    dtype = syn0.dtype
    if hot_slabs is not None:
        new_syn0, slab0 = hot_scatter_add(syn0, slab0, centers, d_in)
        new_syn1, slab1 = hot_scatter_add(syn1, slab1, contexts, d_pos)
        new_syn1, slab1 = hot_scatter_add(new_syn1, slab1, negatives, d_Z)
    else:
        new_syn0 = syn0.at[centers].add(d_in.astype(dtype))
        new_syn1 = syn1.at[contexts].add(d_pos.astype(dtype))
        new_syn1 = new_syn1.at[negatives].add(d_Z.astype(dtype))
    if stabilizers is not None and stabilizers.post_pass:
        enable = (mask.sum() > 0).astype(jnp.float32)
        new_syn0 = stabilize_rows(
            new_syn0, _mask_sentinel(centers, mask, V), alpha,
            stabilizers, enable)
        idx1 = jnp.concatenate(
            [_mask_sentinel(contexts, mask, V), negatives])
        new_syn1 = stabilize_rows(new_syn1, idx1, alpha, stabilizers, enable)

    if with_metrics:
        denom = jnp.maximum(mask.sum(), 1.0)
        loss_num, fpos_num = shared_pool_loss_terms(
            f_pos, f_neg, neg_valid, mask, num_negatives)
        loss = loss_num / denom
        mean_f_pos = fpos_num / denom
    else:
        loss = mean_f_pos = jnp.float32(0.0)
    metrics = StepMetrics(
        loss=loss,
        mean_f_pos=mean_f_pos,
        pairs=mask.sum(),
    )
    if hot_slabs is not None:
        return EmbeddingPair(new_syn0, new_syn1), metrics, (slab0, slab1)
    return EmbeddingPair(new_syn0, new_syn1), metrics


def cbow_step(
    params: EmbeddingPair,
    centers: jax.Array,     # int32 [B] — predicted (output) words
    contexts: jax.Array,    # int32 [B, C] — context window, padded
    ctx_mask: jax.Array,    # float32 [B, C]
    mask: jax.Array,        # float32 [B]
    key: jax.Array,
    alpha: jax.Array,
    table: AliasTable,
    num_negatives: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    duplicate_scaling: bool = False,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """CBOW variant (BASELINE config 5): input = mean of context vectors, output = center.

    hidden = mean_c syn0[context_c]; positives are the centers, negatives sampled per
    example. Context-vector gradients are the hidden gradient divided equally (mean
    convention), scattered back to every context position.
    """
    negatives = sample_negatives(table, key, (centers.shape[0], num_negatives))
    return cbow_step_core(params, centers, contexts, ctx_mask, mask, negatives, alpha,
                          sigmoid_mode, compute_dtype, duplicate_scaling)


def cbow_step_core(
    params: EmbeddingPair,
    centers: jax.Array,     # int32 [B]
    contexts: jax.Array,    # int32 [B, C]
    ctx_mask: jax.Array,    # float32 [B, C]
    mask: jax.Array,        # float32 [B]
    negatives: jax.Array,   # int32 [B, n] — pre-drawn
    alpha: jax.Array,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    duplicate_scaling: bool = False,
    stabilizers: Optional[Stabilizers] = None,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """:func:`cbow_step` with the negatives supplied by the caller (see
    :func:`sgns_step_core` for why sampling lives outside the jitted scan).

    ``stabilizers``: ``update_clip`` caps the per-example d_hidden (before the
    mean-convention split into per-context rows — so the banded formulation
    applies the identical clipped quantity), d_out, and per-example d_neg
    rows; the post pass clamps/decays syn0 at the live context slots and syn1
    at the live centers plus the negatives of unmasked examples."""
    syn0, syn1 = params
    B, C = contexts.shape
    neg_valid = (negatives != centers[:, None]).astype(jnp.float32) * mask[:, None]

    e_ctx = syn0[contexts].astype(compute_dtype)                      # [B, C, D]
    ctx_m = ctx_mask.astype(compute_dtype)[..., None]
    ctx_n = jnp.maximum(ctx_mask.sum(axis=-1), 1.0).astype(compute_dtype)  # [B]
    hidden = (e_ctx * ctx_m).sum(axis=1) / ctx_n[:, None]             # [B, D]

    e_out = syn1[centers].astype(compute_dtype)                       # [B, D]
    e_neg = syn1[negatives].astype(compute_dtype)                     # [B, n, D]
    f_pos = jnp.sum(hidden * e_out, axis=-1).astype(jnp.float32)
    f_neg = jnp.einsum("bd,bnd->bn", hidden, e_neg).astype(jnp.float32)

    has_ctx = (ctx_mask.sum(axis=-1) > 0).astype(jnp.float32)
    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * mask * has_ctx
    g_neg = (0.0 - _sigmoid(f_neg, sigmoid_mode)) * alpha * neg_valid * has_ctx[:, None]

    V = syn0.shape[0]
    live_ctx = ctx_mask * (mask * has_ctx)[:, None]
    if duplicate_scaling:
        cnt0 = jnp.zeros(V, jnp.float32).at[contexts.reshape(-1)].add(
            live_ctx.reshape(-1))
        cnt1 = (jnp.zeros(V, jnp.float32).at[centers].add(mask * has_ctx)
                .at[negatives.reshape(-1)].add(
                    (neg_valid * has_ctx[:, None]).reshape(-1)))
        ctx_scale = (1.0 / jnp.maximum(cnt0[contexts], 1.0)).astype(compute_dtype)
        g_pos_out = g_pos / jnp.maximum(cnt1[centers], 1.0)
        g_neg_out = g_neg / jnp.maximum(cnt1[negatives], 1.0)
    else:
        ctx_scale = jnp.ones_like(contexts, compute_dtype)
        g_pos_out, g_neg_out = g_pos, g_neg

    gp = g_pos[:, None].astype(compute_dtype)
    d_hidden = gp * e_out + jnp.einsum("bn,bnd->bd", g_neg.astype(compute_dtype), e_neg)
    d_out = g_pos_out[:, None].astype(compute_dtype) * hidden
    d_neg = g_neg_out[..., None].astype(compute_dtype) * hidden[:, None, :]
    if stabilizers is not None and stabilizers.update_clip:
        d_hidden = clip_update_rows(d_hidden, stabilizers.update_clip)
        d_out = clip_update_rows(d_out, stabilizers.update_clip)
        d_neg = clip_update_rows(d_neg, stabilizers.update_clip)
    # mean convention: each context word gets d_hidden / |context|
    d_ctx = (d_hidden / ctx_n[:, None])[:, None, :] * ctx_m * ctx_scale[..., None]

    dtype = syn0.dtype
    D = syn0.shape[1]
    new_syn0 = syn0.at[contexts.reshape(-1)].add(d_ctx.reshape(-1, D).astype(dtype))
    new_syn1 = syn1.at[centers].add(d_out.astype(dtype))
    new_syn1 = new_syn1.at[negatives.reshape(-1)].add(d_neg.reshape(-1, D).astype(dtype))
    if stabilizers is not None and stabilizers.post_pass:
        enable = (mask.sum() > 0).astype(jnp.float32)
        new_syn0 = stabilize_rows(
            new_syn0,
            _mask_sentinel(contexts, live_ctx, V).reshape(-1), alpha,
            stabilizers, enable)
        idx1 = jnp.concatenate([
            _mask_sentinel(centers, mask * has_ctx, V),
            _mask_sentinel(negatives,
                           jnp.broadcast_to(mask[:, None], negatives.shape),
                           V).reshape(-1)])
        new_syn1 = stabilize_rows(new_syn1, idx1, alpha, stabilizers, enable)

    denom = jnp.maximum((mask * has_ctx).sum(), 1.0)
    neg_live = neg_valid * has_ctx[:, None]
    loss = (-_log_sigmoid(f_pos) * mask * has_ctx
            - jnp.sum(_log_sigmoid(-f_neg) * neg_live, axis=-1)).sum() / denom
    metrics = StepMetrics(
        loss=loss,
        mean_f_pos=(f_pos * mask * has_ctx).sum() / denom,
        pairs=(mask * has_ctx).sum(),
    )
    return EmbeddingPair(new_syn0, new_syn1), metrics


def cbow_step_shared_core(
    params: EmbeddingPair,
    centers: jax.Array,     # int32 [B]
    contexts: jax.Array,    # int32 [B, C]
    ctx_mask: jax.Array,    # float32 [B, C]
    mask: jax.Array,        # float32 [B]
    negatives: jax.Array,   # int32 [P] — pre-drawn shared pool
    alpha: jax.Array,
    num_negatives: int,
    sigmoid_mode: str = "exact",
    compute_dtype: jnp.dtype = jnp.float32,
    logits_dtype: jnp.dtype = jnp.float32,
    with_metrics: bool = True,
    stabilizers: Optional[Stabilizers] = None,
) -> Tuple[EmbeddingPair, StepMetrics]:
    """CBOW with a batch-shared negative pool — the CBOW analog of
    :func:`sgns_step_shared_core` (same estimator: each negative term reweighted by
    ``num_negatives / pool`` so the expected gradient matches per-example sampling;
    pool entries equal to an example's center are masked). All negative compute rides
    the MXU: ``f_neg = hidden @ Zᵀ`` and ``dZ = g_negᵀ @ hidden``. ``logits_dtype``
    and ``with_metrics`` as in :func:`sgns_step_shared_core` (the [B, P] chain /
    the trainer's metrics-elided fast twin). ``stabilizers``: clips d_hidden
    (pre mean-split, so the banded formulation matches) and d_out, never d_Z;
    post pass over the live context slots, live centers, and the whole pool."""
    syn0, syn1 = params
    P = negatives.shape[0]
    neg_valid = (negatives[None, :] != centers[:, None]).astype(logits_dtype) \
        * mask[:, None].astype(logits_dtype)

    e_ctx = syn0[contexts].astype(compute_dtype)                      # [B, C, D]
    ctx_m = ctx_mask.astype(compute_dtype)[..., None]
    ctx_n = jnp.maximum(ctx_mask.sum(axis=-1), 1.0).astype(compute_dtype)  # [B]
    hidden = (e_ctx * ctx_m).sum(axis=1) / ctx_n[:, None]             # [B, D]

    e_out = syn1[centers].astype(compute_dtype)                       # [B, D]
    Z = syn1[negatives].astype(compute_dtype)                         # [P, D]
    f_pos = jnp.sum(hidden * e_out, axis=-1).astype(jnp.float32)
    f_neg = (hidden @ Z.T).astype(logits_dtype)                       # [B, P] — MXU

    has_ctx = (ctx_mask.sum(axis=-1) > 0).astype(jnp.float32)
    g_pos = (1.0 - _sigmoid(f_pos, sigmoid_mode)) * alpha * mask * has_ctx
    g_neg = ((0.0 - _sigmoid(f_neg, sigmoid_mode))
             * jnp.asarray(alpha, logits_dtype) * neg_valid
             * has_ctx[:, None].astype(logits_dtype)
             * jnp.asarray(num_negatives / P, logits_dtype))

    gp = g_pos[:, None].astype(compute_dtype)
    gn = g_neg.astype(compute_dtype)
    d_hidden = gp * e_out + gn @ Z                                    # [B, D] — MXU
    d_out = gp * hidden
    d_Z = gn.T @ hidden                                               # [P, D] — MXU
    if stabilizers is not None and stabilizers.update_clip:
        d_hidden = clip_update_rows(d_hidden, stabilizers.update_clip)
        d_out = clip_update_rows(d_out, stabilizers.update_clip)
    # mean convention: each context word gets d_hidden / |context|
    d_ctx = (d_hidden / ctx_n[:, None])[:, None, :] * ctx_m

    dtype = syn0.dtype
    D = syn0.shape[1]
    new_syn0 = syn0.at[contexts.reshape(-1)].add(d_ctx.reshape(-1, D).astype(dtype))
    new_syn1 = syn1.at[centers].add(d_out.astype(dtype))
    new_syn1 = new_syn1.at[negatives].add(d_Z.astype(dtype))
    if stabilizers is not None and stabilizers.post_pass:
        V = syn0.shape[0]
        enable = (mask.sum() > 0).astype(jnp.float32)
        live_ctx = ctx_mask * (mask * has_ctx)[:, None]
        new_syn0 = stabilize_rows(
            new_syn0,
            _mask_sentinel(contexts, live_ctx, V).reshape(-1), alpha,
            stabilizers, enable)
        idx1 = jnp.concatenate(
            [_mask_sentinel(centers, mask * has_ctx, V), negatives])
        new_syn1 = stabilize_rows(new_syn1, idx1, alpha, stabilizers, enable)

    if with_metrics:
        denom = jnp.maximum((mask * has_ctx).sum(), 1.0)
        loss = (-_log_sigmoid(f_pos) * mask * has_ctx
                - jnp.sum(_log_sigmoid(-f_neg) * neg_valid
                          * has_ctx[:, None].astype(logits_dtype), axis=-1,
                          dtype=jnp.float32)
                * (num_negatives / P)).sum() / denom
        mean_f_pos = (f_pos * mask * has_ctx).sum() / denom
    else:
        loss = mean_f_pos = jnp.float32(0.0)
    metrics = StepMetrics(
        loss=loss,
        mean_f_pos=mean_f_pos,
        pairs=(mask * has_ctx).sum(),
    )
    return EmbeddingPair(new_syn0, new_syn1), metrics


def alpha_schedule(
    words_processed,
    total_words: float,
    learning_rate: float,
    min_alpha_factor: float = 1e-4,
):
    """Linear lr decay with floor — the reference's schedule (mllib:405-413):
    ``alpha = lr · (1 − words_processed/total)``, floored at ``lr · 1e-4``, where
    ``total = numIterations · trainWordsCount + 1`` and words_processed is the global clock
    (the reference approximates it as ``numPartitions · wordCount_partition + prior_iters``).
    Works on Python floats and jnp scalars alike.
    """
    progress = words_processed / total_words
    alpha = learning_rate * (1.0 - progress)
    floor = learning_rate * min_alpha_factor
    if isinstance(alpha, (float, int)):
        return max(float(alpha), floor)
    return jnp.maximum(alpha, floor)
