#!/usr/bin/env python
"""Serving-fleet driver CLI (docs/serving.md §5): boot N replica processes
behind a FleetRouter — health probes, circuit breakers, hedged retries,
rolling reload — off one checkpoint publish path.

Stdout carries exactly ONE JSON line (graftlint R7 — the driver contract);
human progress goes to stderr.

Usage::

    # drive a real fleet: N serve_checkpoint.py replicas + the router,
    # until --duration expires (0 = until SIGINT)
    python tools/fleet_run.py --checkpoint CK [--replicas N] [--ann]
        [--status-port P] [--telemetry PATH] [--duration S]

    # the self-contained fleet-kill drill (tier-1 + CI): tiny fit → N
    # subprocess replicas → query storm → SIGKILL one replica (breaker
    # opens, zero failed queries, replica restarts, breaker half-open →
    # closed) → 3-publish rolling-reload storm (capacity never below N-1,
    # every reload issued to a drained replica)
    python tools/fleet_run.py --smoke

Exit code 0 iff the run (or the drill's every assertion) passed.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_here))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _train_checkpoint(workdir: str, n_sentences: int, seed: int = 4):
    """A tiny trained checkpoint for the drill (the serve-reload chaos
    phase's corpus shape: 30 words, structure enough to answer top-5).
    Trainer telemetry is ON: its sink carries the run_start clock anchor
    and — crucially for the drill's collector leg — one ``publish`` record
    per checkpoint save, the trainer half of every publish chain."""
    import numpy as np

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer

    rng = np.random.default_rng(seed)
    sents = [[f"w{i}" for i in rng.integers(0, 30, 20)]
             for _ in range(n_sentences)]
    cfg = Word2VecConfig(
        vector_size=8, pairs_per_batch=128, window=3, num_iterations=1,
        steps_per_dispatch=2, heartbeat_every_steps=4, subsample_ratio=0.0,
        prefetch_chunks=0, seed=1, min_count=1,
        telemetry_path=os.path.join(workdir, "trainer.jsonl"))
    vocab = build_vocab(sents, min_count=1)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    ck = os.path.join(workdir, "publish", "ck")
    trainer.save_checkpoint(ck)
    return ck, trainer, vocab, sents


def run_smoke(workdir: str, n_sentences: int = 300,
              replicas: int = 3) -> dict:
    """The fleet-kill drill (the chaos phase calls this too). Returns the
    report dict; raises AssertionError with a named failure on any broken
    invariant."""
    import threading

    import numpy as np

    from glint_word2vec_tpu.obs.schema import validate_file
    from glint_word2vec_tpu.obs.slo import SloObjectives
    from glint_word2vec_tpu.serve.fleet import (
        CircuitBreaker, FleetRouter, ReplicaSet)

    ck, trainer, vocab, sents = _train_checkpoint(workdir, n_sentences)
    log(f"[fleet] checkpoint ready: V={vocab.size}")
    telemetry = os.path.join(workdir, "fleet.jsonl")
    # telemetry_dir arms the full observability plane per replica: sink +
    # trace spans + flight recorder — the artifact set the collector leg
    # below merges into the one incident timeline (ISSUE 13)
    rs = ReplicaSet.spawn(ck, replicas, stderr_dir=workdir,
                          telemetry_dir=workdir)
    log(f"[fleet] {replicas} replicas ready "
        f"(pids {[r.pid for r in rs.replicas]})")
    # drill-scoped SLO (obs/slo.py: same math as production, seconds-scale
    # windows + a container-tolerant latency bound — a 2-core CI host under
    # a 3-thread storm is not the 250 ms production tier)
    slo_objectives = SloObjectives(
        availability=0.999, latency_ms=2000.0, latency_target=0.99,
        short_window_s=30.0, long_window_s=300.0)
    router = FleetRouter(
        rs, checkpoint=ck, probe_s=0.1, breaker_failures=2,
        breaker_reset_s=0.5, retry_deadline_s=60.0, attempt_timeout_s=5.0,
        telemetry_path=telemetry, slo=slo_objectives)

    query_errs: list = []
    queries = [0]
    storm_on = threading.Event()
    storm_on.set()
    words = {f"w{i}" for i in range(30)}

    def storm(ci: int) -> None:
        i = 0
        while storm_on.is_set() or i == 0:
            i += 1
            try:
                res = router.synonyms(f"w{(ci * 7 + i) % 30}", 5)
                if len(res) != 5 or not all(
                        w in words and np.isfinite(s) for w, s in res):
                    query_errs.append(f"bad result: {res}")
            except Exception as e:  # noqa: BLE001 — ANY raise is the failure
                query_errs.append(f"{type(e).__name__}: {e}")
            queries[0] += 1

    clients = [threading.Thread(target=storm, args=(c,)) for c in range(3)]
    for c in clients:
        c.start()
    report: dict = {}
    try:
        # let the storm + probes settle so breakers are warm
        time.sleep(1.0)
        assert not query_errs, f"pre-kill failures: {query_errs[0]}"

        # --- 1. the kill: SIGKILL one replica mid-traffic ------------------
        victim = rs.replicas[0]
        old_pid = victim.pid
        log(f"[fleet] SIGKILL replica {victim.name} (pid {old_pid})")
        victim.kill()
        # assert on the TRANSITION HISTORY, not the instantaneous state —
        # the prober can restart + trial-close faster than a state poll
        deadline = time.monotonic() + 30
        while (not any((f, t) == ("closed", "open") for f, t, _
                       in router.breaker_transitions(victim.name))
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert any((f, t) == ("closed", "open") for f, t, _
                   in router.breaker_transitions(victim.name)), \
            (f"breaker never opened on the killed replica (transitions "
             f"{router.breaker_transitions(victim.name)})")
        log("[fleet] breaker OPEN on the victim; storm continues on "
            f"{replicas - 1} replicas")

        # --- 2. recovery: restart → half-open trial → closed ---------------
        deadline = time.monotonic() + 120
        while (router.breaker_states()[victim.name] != CircuitBreaker.CLOSED
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert router.breaker_states()[victim.name] == \
            CircuitBreaker.CLOSED, \
            (f"killed replica never recovered to CLOSED "
             f"(state {router.breaker_states()[victim.name]}, "
             f"alive {victim.alive()})")
        assert victim.alive() and victim.pid != old_pid, \
            "victim was not respawned as a new process"
        trans = router.breaker_transitions(victim.name)
        states = [t[1] for t in trans]
        assert "open" in states and "half-open" in states, \
            f"breaker skipped states: {trans}"
        last_closed = max(i for i, s in enumerate(states) if s == "closed")
        assert trans[last_closed][0] == "half-open", \
            f"final close did not come from the half-open trial: {trans}"
        log(f"[fleet] victim recovered (pid {victim.pid}); breaker "
            f"transitions: {[f'{a}->{b}' for a, b, _ in trans]}")
        assert not query_errs, \
            f"{len(query_errs)} failed queries across the kill " \
            f"(first: {query_errs[0]})"

        # --- 3. rolling-reload storm: 3 publishes, capacity >= N-1 ---------
        publishes = 3
        for p in range(publishes):
            rounds_before = router.stats()["reload_rounds"]
            trainer.save_checkpoint(ck)  # the publish signal (fresh
            # inode + mtime per atomic save — no refit needed)
            deadline = time.monotonic() + 90
            while (router.stats()["reload_rounds"] <= rounds_before
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert router.stats()["reload_rounds"] > rounds_before, \
                f"rolling reload round {p + 1} never ran"
            log(f"[fleet] rolling reload round {p + 1} done")
        st = router.stats()
        assert st["reload_rounds"] >= publishes, \
            f"only {st['reload_rounds']} rolling rounds for {publishes} " \
            f"publishes"
        assert st["min_serving_during_reloads"] >= replicas - 1, \
            (f"fleet capacity dropped below N-1 during rolling reload "
             f"(min serving {st['min_serving_during_reloads']})")
        for name, rep in st["replicas"].items():
            assert rep["reloads"] >= publishes, \
                f"replica {name} reloaded only {rep['reloads']}x " \
                f"for {publishes} publishes"
            # lease-drain per replica: every reload was issued only after
            # the router drained that replica's in-flight count to zero
            assert rep["drained_reloads"] == rep["reloads"], \
                (f"replica {name}: {rep['reloads']} reloads but only "
                 f"{rep['drained_reloads']} were drain-first")
        assert not query_errs, \
            f"{len(query_errs)} failed queries across the reload storm " \
            f"(first: {query_errs[0]})"

        # --- 4. the graceful kill: SIGTERM leaves a flight-recorder dump ---
        # SIGKILL (leg 1) can never exercise the dump path — this is the
        # half the serving flight recorder exists for (obs/blackbox.py via
        # EmbeddingService.dump_blackbox + serve_checkpoint.py's handler)
        victim2 = rs.replicas[1]
        dump_path = f"{victim2.telemetry_path}.blackbox.json"
        log(f"[fleet] SIGTERM replica {victim2.name} (pid {victim2.pid})")
        victim2.terminate()
        deadline = time.monotonic() + 30
        while not os.path.exists(dump_path) and time.monotonic() < deadline:
            time.sleep(0.05)
        assert os.path.exists(dump_path), \
            f"SIGTERM'd replica left no flight-recorder dump at {dump_path}"
        # let the prober respawn it so close() tears down a whole fleet
        deadline = time.monotonic() + 60
        while not victim2.alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert victim2.alive(), "SIGTERM'd replica was never respawned"
        assert not query_errs, \
            f"{len(query_errs)} failed queries across the graceful kill " \
            f"(first: {query_errs[0]})"
    finally:
        storm_on.clear()
        for c in clients:
            c.join()
        stats = router.stats()
        slo = router.slo_snapshot()
        slo_ok = router.slo_within_budget()
        router.close()
    assert not query_errs, f"failed queries: {query_errs[0]}"
    assert stats["failures"] == 0, \
        f"{stats['failures']} requests exhausted the retry deadline"
    assert stats["shed_single"] == 0, \
        f"{stats['shed_single']} single queries shed (fleet never saturates " \
        f"at toy scale)"
    assert queries[0] >= 100, \
        f"storm too thin ({queries[0]} queries) to prove overlap"
    summary = validate_file(telemetry)
    assert summary["ok"], f"fleet telemetry not schema-valid: " \
        f"{summary['errors'][:3]}"
    kinds = summary["kinds"]
    assert kinds.get("fleet_start") == 1 and kinds.get("fleet_end") == 1
    assert kinds.get("fleet_breaker", 0) >= 2, \
        f"breaker transitions missing from telemetry ({kinds})"
    assert kinds.get("fleet_reload", 0) >= publishes
    assert kinds.get("trace_span", 0) >= queries[0], \
        (f"router emitted {kinds.get('trace_span', 0)} spans for "
         f"{queries[0]} queries — trace propagation is off")
    assert kinds.get("fleet_slo", 0) >= 1, "no fleet_slo record"

    # --- 5. the SLO verdict: "zero failed queries" as a MEASURED objective
    assert slo["samples"] >= queries[0] - 3 * replicas, \
        f"SLO tracker missed queries ({slo['samples']}/{queries[0]})"
    assert slo_ok, f"SLO burn over budget across the storm: {slo}"

    # --- 6. the collector leg (ISSUE 13 acceptance): merge EVERY artifact
    # the drill left — router sink, N replica sinks, the trainer's sink,
    # the SIGTERM dump — and reconstruct the incident end-to-end
    from glint_word2vec_tpu.obs.collect import collect
    timeline, merged = collect([workdir], objectives=slo_objectives)
    assert len(merged["processes"]) >= replicas + 2, \
        (f"collector saw only {merged['processes']} — expected router + "
         f"{replicas} replicas + trainer")
    # a retried query's trace: the failed attempt on the SIGKILLed replica
    # AND the success elsewhere, under ONE trace id
    retried = [
        t for t in timeline["traces"].values()
        if any(s.get("name") == "attempt" and s.get("outcome") == "failed"
               and s.get("replica") == victim.name for s in t["spans"])
        and any(s.get("name") == "attempt"
                and s.get("outcome") in ("ok", "win")
                and s.get("replica") != victim.name for s in t["spans"])]
    assert retried, \
        "no merged trace shows failed-attempt-on-victim + success-elsewhere"
    # replica-side children crossed the wire: some trace carries spans from
    # BOTH the router process and a replica process
    cross = [t for t in timeline["traces"].values()
             if len({s["_process"] for s in t["spans"]}) >= 2]
    assert cross, "no trace carries spans from more than one process"
    # breaker transitions appear on the merged timeline
    merged_breakers = [e for e in timeline["events"]
                       if e["kind"] == "fleet_breaker"]
    bstates = [(e.get("from_state"), e.get("to_state"))
               for e in merged_breakers]
    assert ("closed", "open") in bstates and \
        ("half-open", "closed") in bstates, \
        f"breaker story incomplete on the merged timeline: {bstates}"
    # the publish chain: the trainer's publish record joined to fleet
    # rolling-reload rounds by publish_sig
    chained = [sig for sig, evs in timeline["publish_chains"].items()
               if {"publish"} & {e["kind"] for e in evs}
               and {"fleet_reload", "serve_reload"} & {e["kind"]
                                                      for e in evs}]
    assert chained, \
        f"no publish_sig joins trainer save to a reload " \
        f"({list(timeline['publish_chains'])})"
    # the SIGTERM dump was ingested with its signal cause
    assert any(b["cause"].get("kind") == "signal"
               for b in timeline["blackboxes"]), \
        f"no signal-cause blackbox in {merged['blackboxes']}"
    # offline SLO recompute (same burn math as the live gauge) in budget
    assert merged["slo"]["within_budget"], \
        f"offline SLO burn over budget: {merged['slo']}"

    victim_stats = stats["replicas"]["r0"]
    return {
        "ok": True,
        "replicas": replicas,
        "queries": queries[0],
        "failed_queries": 0,
        "retries": stats["retries"],
        "hedges": stats["hedges"],
        "hedge_wins": stats["hedge_wins"],
        "victim_restarts": victim_stats["restarts"],
        "breaker_transitions": [f"{a}->{b}" for a, b, _ in trans],
        "reload_rounds": stats["reload_rounds"],
        "min_serving_during_reloads": stats["min_serving_during_reloads"],
        "telemetry_kinds": kinds,
        "slo": {k: slo[k] for k in ("samples", "availability",
                                    "budget_remaining")},
        "collector": {
            "processes": merged["processes"],
            "traces": merged["traces"],
            "spans": merged["spans"],
            "attempt_outcomes": merged["attempt_outcomes"],
            "retried_traces": len(retried),
            "publish_chains": len(chained),
            "slo_within_budget": merged["slo"]["within_budget"],
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--checkpoint", default="",
                    help="publish path the replicas serve + the router "
                         "watches for rolling reloads")
    ap.add_argument("--replicas", type=int, default=None,
                    help="fleet size (default: the checkpoint's "
                         "serve_fleet_replicas knob)")
    ap.add_argument("--ann", action="store_true",
                    help="replicas serve the IVF ANN arm")
    ap.add_argument("--status-port", type=int, default=0,
                    help="> 0: serve the fleet-aggregated glint_serve_* "
                         "gauges on 127.0.0.1:<port>")
    ap.add_argument("--telemetry", default="",
                    help="write fleet_* telemetry records here (JSONL)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="serve this many seconds then exit (0 = until "
                         "SIGINT)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-contained fleet-kill drill "
                         "(tier-1/CI) in a temp dir")
    ap.add_argument("--smoke-replicas", type=int, default=3)
    ap.add_argument("--sentences", type=int, default=300)
    ap.add_argument("--workdir", default="",
                    help="--smoke working directory (default: fresh temp)")
    args = ap.parse_args()

    # single-print shape: exactly one JSON line leaves this function on
    # every path (graftlint R7)
    if args.smoke:
        workdir = args.workdir or tempfile.mkdtemp(prefix="glint_fleet_")
        os.makedirs(workdir, exist_ok=True)
        try:
            out, rc = run_smoke(workdir, args.sentences,
                                args.smoke_replicas), 0
        except AssertionError as e:
            out, rc = {"ok": False, "error": str(e)}, 1
        except Exception as e:  # noqa: BLE001 — the one-JSON-line contract
            # (R7) holds on EVERY path: a boot timeout / OSError must
            # still leave a parseable line, not an empty stdout that makes
            # CI's json.tool step mask the real failure
            out, rc = {"ok": False,
                       "error": f"{type(e).__name__}: {e}"}, 1
        finally:
            if not args.workdir:
                shutil.rmtree(workdir, ignore_errors=True)
    else:
        if not args.checkpoint:
            ap.error("--checkpoint is required (or use --smoke)")
        from glint_word2vec_tpu.serve.fleet import (
            FleetRouter, ReplicaSet, fleet_knobs_from_checkpoint)
        knobs = fleet_knobs_from_checkpoint(
            args.checkpoint, replicas=args.replicas)
        n = knobs.pop("replicas")
        log(f"[fleet] spawning {n} replicas on {args.checkpoint}")
        rs = ReplicaSet.spawn(args.checkpoint, n, ann=args.ann)
        router = FleetRouter(
            rs, checkpoint=args.checkpoint, telemetry_path=args.telemetry,
            status_port=args.status_port, **knobs)
        log("[fleet] serving; Ctrl-C to stop"
            + (f" (auto-stop in {args.duration:g}s)" if args.duration
               else ""))
        try:
            if args.duration:
                time.sleep(args.duration)
            else:
                while True:
                    time.sleep(3600)
        except KeyboardInterrupt:
            log("[fleet] stopping")
        finally:
            stats = router.stats()
            router.close()
        out, rc = {"ok": True, "replicas": n, **{
            k: stats[k] for k in ("queries", "failures", "retries",
                                  "hedges", "reload_rounds", "healthy")}}, 0
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    sys.exit(main())
