"""Serving-fleet tests (glint_word2vec_tpu/serve/fleet.py, docs/serving.md §5):

- the circuit breaker state machine (closed → open → half-open → closed,
  trial failure reopening, transition history);
- router policies over FAKE replicas (deterministic, no subprocesses):
  retry-elsewhere on failure, ServerOverloaded as "retry elsewhere not
  here", the all-saturated fast refusal, bulk-sheds-first, hedging
  first-wins, client errors (OOV) propagating without burning retries,
  the deadline-bounded NoHealthyReplicas failure;
- the in-process adopted fleet end-to-end (parity with the model, stats,
  fleet Prometheus rendering, fleet_* telemetry kinds);
- one subprocess replica on the JSON-lines protocol (id echo, publish_sig
  staleness channel, breaker opening on a SIGKILL'd process).

The full fleet-kill drill (SIGKILL under storm → zero failed queries →
restart → half-open → closed; 3-publish rolling reload at >= N-1
capacity) runs as the ``fleet-kill`` chaos phase inside the chaos smoke
(tests/test_faults.py) and standalone in CI's fleet job.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from glint_word2vec_tpu.data.vocab import Vocabulary
from glint_word2vec_tpu.models.word2vec import Word2VecModel
from glint_word2vec_tpu.obs.schema import validate_record
from glint_word2vec_tpu.obs.statusd import fleet_prometheus_text
from glint_word2vec_tpu.serve import (
    CircuitBreaker,
    EmbeddingService,
    FleetOverloaded,
    FleetRouter,
    NoHealthyReplicas,
    ReplicaSet,
)
from glint_word2vec_tpu.serve.fleet import FleetTicket, ReplicaError


def make_model(v=200, d=16, seed=0):
    rng = np.random.default_rng(seed)
    m = rng.standard_normal((v, d)).astype(np.float32)
    vocab = Vocabulary.from_words_and_counts(
        [f"w{i}" for i in range(v)], np.ones(v, np.int64))
    return Word2VecModel(vocab, jnp.asarray(m))


# -- circuit breaker -------------------------------------------------------------------


def test_breaker_state_machine():
    b = CircuitBreaker(fail_threshold=2, reset_s=0.05)
    assert b.state == "closed" and b.allows_traffic()
    b.record_failure("one")
    assert b.state == "closed"  # below threshold
    b.record_success()
    b.record_failure("one")  # success reset the consecutive count
    assert b.state == "closed"
    b.record_failure("two")
    assert b.state == "open" and not b.allows_traffic()
    assert not b.probe_due()  # cooldown running
    time.sleep(0.06)
    assert b.probe_due() and b.begin_probe()
    assert b.state == "half-open" and not b.allows_traffic()
    assert not b.begin_probe()  # one trial holds the half-open slot
    b.record_failure("trial failed")
    assert b.state == "open"  # trial failure reopens + re-arms cooldown
    assert not b.probe_due()
    time.sleep(0.06)
    assert b.begin_probe()
    b.record_success()
    assert b.state == "closed" and b.allows_traffic()
    states = [(f, t) for f, t, _ in b.transitions]
    assert states == [("closed", "open"), ("open", "half-open"),
                      ("half-open", "open"), ("open", "half-open"),
                      ("half-open", "closed")]


def test_breaker_validation():
    with pytest.raises(ValueError, match="fail_threshold"):
        CircuitBreaker(fail_threshold=0)
    with pytest.raises(ValueError, match="reset_s"):
        CircuitBreaker(reset_s=0.0)


# -- router policies over fake replicas ------------------------------------------------


class FakeReplica:
    """Deterministic scripted replica on the fleet client surface. The
    ``behavior`` callable maps a request dict to a wire-shaped response
    dict (or raises). ``delay_s`` resolves the ticket late via a timer —
    the hedging tests' slow replica."""

    def __init__(self, name, behavior, delay_s=0.0):
        self.name = name
        self.behavior = behavior
        self.delay_s = delay_s
        self.calls = []
        self.restarts = 0
        self._alive = True

    def start(self):
        return self

    def alive(self):
        return self._alive

    @property
    def pid(self):
        return None

    def submit(self, req):
        self.calls.append(req)
        t = FleetTicket(len(self.calls))
        resp = self.behavior(req)
        if self.delay_s:
            threading.Timer(self.delay_s, t.resolve, args=(resp,)).start()
        else:
            t.resolve(resp)
        return t

    def wait(self, ticket, timeout):
        if not ticket.done.wait(timeout):
            raise TimeoutError(f"{self.name}: no response")
        return ticket.response

    def abandon(self, ticket):
        pass

    def kill(self):
        self._alive = False

    def close(self):
        self._alive = False


def ok_syn(req):
    if req.get("op") == "stats":
        return {"publish_sig": "sig-1"}
    n = int(req.get("num", 10))
    return {"synonyms": [[f"s{i}", 0.5] for i in range(n)]}


def failing(req):
    raise ReplicaError("scripted failure")


def overloaded(req):
    if req.get("op") == "stats":
        return {"publish_sig": "sig-1"}
    return {"error": "ServerOverloaded: admission queue full",
            "error_type": "ServerOverloaded", "retry_after_s": 0.5}


def _router(replicas, **kw):
    kw.setdefault("probe_s", 30.0)  # keep the prober out of the way
    kw.setdefault("retry_deadline_s", 5.0)
    kw.setdefault("hedge_ms", 0.0)
    return FleetRouter(ReplicaSet(replicas, can_respawn=False), **kw)


def test_router_retries_elsewhere_and_breaker_opens():
    bad, good = FakeReplica("r0", failing), FakeReplica("r1", ok_syn)
    router = _router([bad, good], breaker_failures=2)
    try:
        for _ in range(4):
            assert len(router.synonyms("w0", 5)) == 5  # never fails
        st = router.stats()
        assert st["failures"] == 0
        assert st["retries"] >= 2  # failed attempts retried elsewhere
        # the failing replica's breaker opened after the threshold, after
        # which it is no longer picked at all
        assert router.breaker_states()["r0"] == "open"
        calls_after_open = len(bad.calls)
        router.synonyms("w0", 5)
        assert len(bad.calls) == calls_after_open
    finally:
        router.close(close_replicas=False)


def test_router_saturated_retries_elsewhere_without_breaker_blame():
    sat, good = FakeReplica("r0", overloaded), FakeReplica("r1", ok_syn)
    router = _router([sat, good])
    try:
        for _ in range(4):
            assert len(router.synonyms("w0", 5)) == 5
        # ServerOverloaded is not a breaker failure: the replica is
        # healthy, just full — its breaker must stay closed
        assert router.breaker_states()["r0"] == "closed"
        assert router.stats()["failures"] == 0
    finally:
        router.close(close_replicas=False)


def test_router_all_saturated_refuses_fast_with_hint():
    router = _router([FakeReplica("r0", overloaded),
                      FakeReplica("r1", overloaded)])
    try:
        t0 = time.monotonic()
        with pytest.raises(FleetOverloaded) as ei:
            router.synonyms("w0", 5)
        assert time.monotonic() - t0 < 1.0, "refusal was not fast"
        assert ei.value.retry_after_s == 0.5  # the min fleet-wide hint
        assert router.stats()["shed_single"] == 1
    finally:
        router.close(close_replicas=False)


def test_router_bulk_sheds_before_single():
    router = _router([FakeReplica("r0", ok_syn), FakeReplica("r1", ok_syn)])
    try:
        # one replica under saturation pressure: bulk is shed FIRST
        router._replicas[0].saturated_until = time.monotonic() + 10
        router._replicas[0].retry_after_s = 0.3
        with pytest.raises(FleetOverloaded):
            router.synonyms_batch(["w0", "w1"], 5)
        assert router.stats()["shed_bulk"] == 1
        # ...while single-query traffic still flows through the other
        assert len(router.synonyms("w0", 5)) == 5
        assert router.stats()["shed_single"] == 0
    finally:
        router.close(close_replicas=False)


def test_router_hedges_to_second_replica_first_wins():
    slow = FakeReplica("r0", ok_syn, delay_s=0.4)
    fast = FakeReplica("r1", ok_syn)
    router = _router([slow, fast], hedge_ms=20.0)
    try:
        # force the slow replica primary: the fast one reads as degraded
        router._replicas[1].degraded = True
        t0 = time.monotonic()
        res = router.synonyms("w0", 5)
        dt = time.monotonic() - t0
        assert len(res) == 5
        assert dt < 0.3, f"hedge did not cut the slow primary ({dt:.3f}s)"
        st = router.stats()
        assert st["hedges"] == 1 and st["hedge_wins"] == 1
        assert [r["op"] for r in fast.calls if r["op"] == "synonyms"], \
            "second replica never saw the hedged request"
    finally:
        router.close(close_replicas=False)


def test_hedge_failure_blames_the_answering_replica_not_the_primary():
    """Review finding (ISSUE 12): a hedged attempt whose HEDGE TARGET dies
    must feed the hedge target's breaker and let the slow-but-healthy
    primary still win — blaming the primary would open the healthy
    replica's breaker while the sick one stays routed."""

    class DeadOnWait(FakeReplica):
        def wait(self, ticket, timeout):
            if ticket.response and "synonyms" in ticket.response:
                raise ReplicaError(f"{self.name}: process exited "
                                   f"mid-request")
            return super().wait(ticket, timeout)

    slow = FakeReplica("r0", ok_syn, delay_s=0.3)
    dead = DeadOnWait("r1", ok_syn)
    router = _router([slow, dead], hedge_ms=20.0, breaker_failures=3)
    try:
        router._replicas[1].degraded = True  # force r0 primary
        res = router.synonyms("w0", 5)  # hedge fires to r1, r1 dies
        assert len(res) == 5, "slow primary must still win the attempt"
        st = router.stats()
        assert st["hedges"] == 1 and st["failures"] == 0
        # the DEAD hedge target took the breaker failure, not the primary
        assert router._replicas[1].breaker._consecutive == 1
        assert router._replicas[0].breaker._consecutive == 0
        assert router.breaker_states()["r0"] == "closed"
    finally:
        router.close(close_replicas=False)


def test_router_client_errors_propagate_without_retry():
    def oov(req):
        if req.get("op") == "stats":
            return {}
        return {"error": "KeyError: 'nope not in vocabulary'",
                "error_type": "KeyError"}

    router = _router([FakeReplica("r0", oov), FakeReplica("r1", oov)])
    try:
        with pytest.raises(KeyError, match="not in vocabulary"):
            router.synonyms("nope", 5)
        st = router.stats()
        # the caller's own error burns neither retries nor breaker health
        assert st["retries"] == 0
        assert router.breaker_states() == {"r0": "closed", "r1": "closed"}
    finally:
        router.close(close_replicas=False)


def test_router_deadline_bounds_total_failure():
    router = _router([FakeReplica("r0", failing),
                      FakeReplica("r1", failing)],
                     breaker_failures=1, retry_deadline_s=0.6)
    try:
        t0 = time.monotonic()
        with pytest.raises(NoHealthyReplicas):
            router.synonyms("w0", 5)
        dt = time.monotonic() - t0
        assert 0.4 < dt < 3.0, f"deadline not honored ({dt:.2f}s)"
        assert router.stats()["failures"] == 1
    finally:
        router.close(close_replicas=False)


def test_router_drain_excludes_replica_from_picks():
    a, b = FakeReplica("r0", ok_syn), FakeReplica("r1", ok_syn)
    router = _router([a, b])
    try:
        router._replicas[0].draining = True
        for _ in range(3):
            router.synonyms("w0", 5)
        assert not [r for r in a.calls if r["op"] == "synonyms"], \
            "draining replica still received traffic"
    finally:
        router.close(close_replicas=False)


# -- telemetry schema + prometheus -----------------------------------------------------


def test_fleet_record_kinds_validate():
    base = {"schema": 1, "t": 0.0}
    ok = [
        {**base, "kind": "fleet_start", "replicas": 3, "checkpoint": "/ck"},
        {**base, "kind": "fleet_breaker", "replica": "r0",
         "from_state": "closed", "to_state": "open", "reason": "dead"},
        {**base, "kind": "fleet_reload", "publishes": 1, "min_serving": 2,
         "replicas": 3, "seconds": 1.5},
        {**base, "kind": "fleet_stats", "queries": 10, "failures": 0,
         "retries": 1, "hedges": 2, "hedge_wins": 1, "shed": 0,
         "healthy": 3, "degraded": 0, "latency_ms": {"p50": 1.0}},
        {**base, "kind": "fleet_end", "queries": 10, "failures": 0},
    ]
    for rec in ok:
        assert validate_record(rec) == [], rec["kind"]
    bad = {**base, "kind": "fleet_stats", "queries": 10}
    assert validate_record(bad), "missing required fields must fail"


def test_fleet_prometheus_rendering():
    snap = {
        "status": "serving", "queries": 100, "failures": 0, "retries": 3,
        "hedges": 5, "hedge_wins": 4, "shed_single": 0, "shed_bulk": 1,
        "reload_rounds": 2, "healthy": 2, "degraded": 1,
        "min_serving_during_reloads": 2,
        "latency_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0, "n": 100},
        "replicas": {
            "r0": {"state": "closed", "alive": True, "degraded": False,
                   "in_flight": 1, "restarts": 0, "reloads": 2,
                   "stats": {"submitted": 50, "queue_depth": 0,
                             "latency_ms": {"p50": 0.9},
                             "ann": {"recall_at_10": 0.99}}},
            "r1": {"state": "open", "alive": False, "degraded": True,
                   "in_flight": 0, "restarts": 1, "reloads": 1,
                   "stats": None},
        },
    }
    text = fleet_prometheus_text(snap)
    for needle in (
            "glint_serve_fleet_up 1",
            "glint_serve_fleet_queries_total 100",
            "glint_serve_fleet_hedges_total 5",
            "glint_serve_fleet_healthy 2",
            "glint_serve_fleet_min_serving_during_reloads 2",
            'glint_serve_fleet_latency_ms{quantile="p99"} 3',
            'glint_serve_fleet_breaker_state{replica="r0"} 0',
            'glint_serve_fleet_breaker_state{replica="r1"} 2',
            'glint_serve_up{replica="r0"} 1',
            'glint_serve_up{replica="r1"} 0',
            'glint_serve_submitted_total{replica="r0"} 50',
            'glint_serve_latency_ms{replica="r0",quantile="p50"} 0.9',
            'glint_serve_ann_recall_at_10{replica="r0"} 0.99'):
        assert needle in text, f"{needle!r} missing from:\n{text}"
    # the text format forbids a second TYPE line per metric name — the
    # per-replica label fan-out must emit each header exactly once
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE")]
    assert len(type_lines) == len(set(type_lines)), (
        "duplicate # TYPE headers (strict Prometheus parsers reject the "
        f"whole exposition): {sorted(set(x for x in type_lines if type_lines.count(x) > 1))}")


# -- the adopted in-process fleet end-to-end -------------------------------------------


def test_adopted_fleet_parity_and_stats(tmp_path):
    models = [make_model(seed=7) for _ in range(2)]
    want = models[0].find_synonyms("w0", 5)
    svcs = [EmbeddingService(model=m, ann=False) for m in models]
    log = str(tmp_path / "fleet.jsonl")
    router = FleetRouter(ReplicaSet.adopt(svcs), probe_s=0.1,
                         hedge_ms=0.0, retry_deadline_s=10.0,
                         telemetry_path=log)
    try:
        got = router.synonyms("w0", 5)
        assert [w for w, _ in got] == [w for w, _ in want]
        np.testing.assert_allclose([s for _, s in got],
                                   [s for _, s in want], rtol=1e-5)
        rows = router.synonyms_batch(["w1", "w2"], 4)
        assert len(rows) == 2 and all(len(r) == 4 for r in rows)
        with pytest.raises(KeyError):
            router.synonyms("nope", 5)
        deadline = time.monotonic() + 5
        while (any(r["stats"] is None
                   for r in router.stats()["replicas"].values())
               and time.monotonic() < deadline):
            time.sleep(0.02)
        st = router.stats()
        assert st["healthy"] == 2 and st["failures"] == 0
        for rep in st["replicas"].values():
            assert rep["state"] == "closed"
            assert rep["stats"] is not None, "probe never cached stats"
        router.emit_stats()
    finally:
        router.close()  # closes the services; caller-owned models survive
    from glint_word2vec_tpu.obs.schema import validate_file
    summary = validate_file(log)
    assert summary["ok"], summary["errors"][:3]
    kinds = summary["kinds"]
    assert kinds.get("fleet_start") == 1
    assert kinds.get("fleet_stats") == 1
    assert kinds.get("fleet_end") == 1
    for m in models:
        m.stop()


def test_adopted_fleet_survives_one_replica_closing():
    models = [make_model(seed=s) for s in range(2)]
    svcs = [EmbeddingService(model=m, ann=False) for m in models]
    router = FleetRouter(ReplicaSet.adopt(svcs), probe_s=0.05,
                         hedge_ms=0.0, breaker_failures=2,
                         retry_deadline_s=10.0)
    try:
        assert len(router.synonyms("w0", 5)) == 5
        svcs[0].close()  # the replica "dies" (ServiceClosed surface)
        deadline = time.monotonic() + 10
        while (router.breaker_states()["r0"] != "open"
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert router.breaker_states()["r0"] == "open"
        # traffic keeps flowing on the survivor
        for _ in range(3):
            assert len(router.synonyms("w0", 5)) == 5
        assert router.stats()["failures"] == 0
    finally:
        router.close()
        for m in models:
            m.stop()


# -- one subprocess replica on the wire protocol ---------------------------------------


def _train_tiny_ck(tmp_path, seed=9):
    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.train.trainer import Trainer
    rng = np.random.default_rng(seed)
    sents = [[f"w{j}" for j in rng.integers(0, 30, 12)] for _ in range(80)]
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3,
                         negative_pool=8, steps_per_dispatch=2, seed=seed)
    trainer = Trainer(cfg, vocab)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    ck = str(tmp_path / "model")
    trainer.save_checkpoint(ck)
    return ck


def test_subprocess_replica_protocol_and_kill(tmp_path):
    """One real serve_checkpoint.py child: id-echoed JSON-lines protocol,
    the publish_sig staleness channel filled by probes, and the breaker
    opening when the process is SIGKILL'd."""
    ck = _train_tiny_ck(tmp_path)
    rs = ReplicaSet.spawn(ck, 1, stderr_dir=str(tmp_path))
    # breaker_failures=1: the FIRST dead-process probe opens the breaker.
    # At threshold 2 this test is a race the fleet can legitimately WIN —
    # with a warm page cache the prober restarts and trial-heals the
    # replica in under a second, before a second failure ever accrues
    # (observed; the multi-replica drill in fleet_run.py keeps threshold 2
    # because client traffic feeds the breaker there)
    router = FleetRouter(rs, checkpoint=ck, probe_s=0.1,
                         breaker_failures=1, breaker_reset_s=0.5,
                         hedge_ms=0.0, retry_deadline_s=5.0,
                         rolling_reload=False)
    try:
        res = router.synonyms("w0", 5)
        assert len(res) == 5 and all(np.isfinite(s) for _, s in res)
        with pytest.raises(KeyError):
            router.synonyms("definitely-not-a-word", 5)
        deadline = time.monotonic() + 10
        while (router.stats()["replicas"]["r0"]["publish_sig"] is None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        rep = router.stats()["replicas"]["r0"]
        assert rep["publish_sig"], "probe never filled the served " \
            "publish generation"
        assert not rep["degraded"], "freshly booted replica read as stale"
        # SIGKILL: probe failures open the breaker. Assert on the
        # TRANSITION HISTORY, not the instantaneous state — the prober may
        # restart + trial-close the replica faster than a state poll
        # (observed: full open → half-open → closed recovery in ~5s when
        # the relaunch boots from page cache)
        rs.replicas[0].kill()
        deadline = time.monotonic() + 20
        opened = False
        while time.monotonic() < deadline:
            trans = router.breaker_transitions("r0")
            if any((f, t) == ("closed", "open") for f, t, _ in trans):
                opened = True
                break
            time.sleep(0.05)
        assert opened, (
            f"breaker never opened on the killed replica "
            f"(transitions {router.breaker_transitions('r0')})")
    finally:
        router.close()
