// Native pair generator — the multithreaded C++ hot path of the host data pipeline.
//
// Produces the exact (bit-identical) pair stream of the numpy reference
// implementation `data/pipeline.py::_block_pairs`: frequency subsampling
// (mllib:371-379 semantics) + per-position dynamic context windows (mllib:384-388),
// with every random decision position-keyed through the murmur3-finalizer lattice
// defined in `data/hashrng.py` (the shared contract — keep the constants in sync).
//
// Why native: the numpy path needs a handful of full-block temporaries (repeat /
// cumsum / bincount) per block; this is one fused pass per sentence with zero
// allocation in the steady state, parallel over sentence ranges. Position-keyed
// randomness means any thread can draw for any token with no sequential RNG state,
// so the stream is independent of the thread count.
//
// Built as a shared library (no Python headers — plain C ABI consumed via ctypes):
//   g++ -O3 -shared -fPIC -pthread -o libpairgen.so pairgen.cpp

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

inline uint32_t mix32(uint32_t x) {
  x = (x ^ (x >> 16)) * 0x85EBCA6Bu;
  x = (x ^ (x >> 13)) * 0xC2B2AE35u;
  return x ^ (x >> 16);
}

// must match data/hashrng.py::stream_base
inline uint32_t stream_base(uint32_t seed, uint32_t stream, uint32_t iteration,
                            uint32_t shard) {
  uint32_t s = seed * 0x9E3779B9u;
  uint32_t t = stream * 0x7FEB352Du + 0x68E31DA4u;
  uint32_t c = iteration * 0x85EBCA6Bu + shard * 0xC2B2AE35u;
  return mix32(c ^ mix32(s ^ t));
}

// must match data/hashrng.py::hash_bits_at
inline uint32_t bits_at(uint32_t base, uint64_t ordinal) {
  uint32_t lo = static_cast<uint32_t>(ordinal & 0xFFFFFFFFull);
  uint32_t hi = static_cast<uint32_t>(ordinal >> 32);
  return mix32(lo ^ mix32(hi ^ 0xDEADBEEFu) ^ base);
}

// must match data/hashrng.py::hash_u01_at — (bits >> 8) is <= 2^24 (exact in f32)
// and the scale is a power of two, so this equals the numpy value bit-for-bit
inline float u01_at(uint32_t base, uint64_t ordinal) {
  return static_cast<float>(bits_at(base, ordinal) >> 8) * (1.0f / 16777216.0f);
}

constexpr uint32_t kStreamSubsample = 101;  // data/hashrng.py STREAM_SUBSAMPLE
constexpr uint32_t kStreamWindow = 102;     // data/hashrng.py STREAM_WINDOW

struct ThreadOut {
  std::vector<int32_t> centers;
  std::vector<int32_t> contexts;
  std::vector<int64_t> clock;  // kept-word ordinal LOCAL to this thread (0-based)
  int64_t kept = 0;
};

// Process sentences [s_lo, s_hi): subsample, draw windows, emit pairs.
// tok_off is the block-local index of sentence s_lo's first token.
void process_range(const int32_t* tokens, const int64_t* lengths, int64_t s_lo,
                   int64_t s_hi, int64_t tok_off, const float* keep, int32_t window,
                   bool legacy, uint32_t sub_base, uint32_t win_base,
                   uint64_t token_base, ThreadOut* out) {
  std::vector<int32_t> kept_toks;
  std::vector<int32_t> kept_b;  // window draw per kept token
  for (int64_t s = s_lo; s < s_hi; ++s) {
    const int64_t len = lengths[s];
    kept_toks.clear();
    kept_b.clear();
    for (int64_t i = 0; i < len; ++i) {
      const uint64_t ord = token_base + static_cast<uint64_t>(tok_off + i);
      const int32_t w = tokens[tok_off + i];
      if (u01_at(sub_base, ord) <= keep[w]) {
        kept_toks.push_back(w);
        kept_b.push_back(
            static_cast<int32_t>(bits_at(win_base, ord) % static_cast<uint32_t>(window)));
      }
    }
    const int64_t nk = static_cast<int64_t>(kept_toks.size());
    for (int64_t p = 0; p < nk; ++p) {
      const int32_t b = kept_b[p];
      const int64_t left = b < p ? b : p;
      int64_t right = legacy ? b - 1 : b;
      const int64_t avail = nk - 1 - p;
      if (right > avail) right = avail;
      if (right < 0) right = 0;
      const int32_t center = kept_toks[p];
      const int64_t my_clock = out->kept + p;  // kept ordinal of this center
      for (int64_t q = p - left; q < p; ++q) {
        out->centers.push_back(center);
        out->contexts.push_back(kept_toks[q]);
        out->clock.push_back(my_clock);
      }
      for (int64_t q = p + 1; q <= p + right; ++q) {
        out->centers.push_back(center);
        out->contexts.push_back(kept_toks[q]);
        out->clock.push_back(my_clock);
      }
    }
    out->kept += nk;
    tok_off += len;
  }
}

}  // namespace

extern "C" {

// Returns the number of pairs written (>= 0), or -1 if `cap` was too small.
// `out_kept` receives the number of tokens surviving subsampling.
// Caller guarantees cap >= n_tokens * max(2 * window - 2, 1) (the per-token pair bound).
int64_t glint_block_pairs(const int32_t* tokens, int64_t n_tokens,
                          const int64_t* lengths, int64_t n_sents, const float* keep,
                          int32_t window, int32_t legacy, uint32_t seed,
                          uint32_t iteration, uint32_t shard, uint64_t token_base,
                          int32_t n_threads, int32_t* out_centers,
                          int32_t* out_contexts, int64_t* out_clock, int64_t cap,
                          int64_t* out_kept) {
  if (n_tokens == 0 || n_sents == 0) {
    *out_kept = 0;
    return 0;
  }
  const uint32_t sub_base = stream_base(seed, kStreamSubsample, iteration, shard);
  const uint32_t win_base = stream_base(seed, kStreamWindow, iteration, shard);
  if (n_threads < 1) n_threads = 1;
  if (n_threads > n_sents) n_threads = static_cast<int32_t>(n_sents);

  // Partition whole sentences into ~equal-token ranges.
  std::vector<int64_t> range_lo(n_threads + 1, n_sents);
  std::vector<int64_t> range_tok(n_threads, 0);
  {
    range_lo[0] = 0;
    int64_t acc = 0, t = 1;
    const int64_t target = (n_tokens + n_threads - 1) / n_threads;
    for (int64_t s = 0; s < n_sents && t < n_threads; ++s) {
      acc += lengths[s];
      if (acc >= target * t) {
        range_lo[t] = s + 1;
        ++t;
      }
    }
    int64_t tok = 0, s = 0;
    for (int64_t i = 0; i < n_threads; ++i) {
      for (; s < range_lo[i]; ++s) tok += lengths[s];
      range_tok[i] = tok;
    }
  }

  std::vector<ThreadOut> outs(n_threads);
  {
    std::vector<std::thread> threads;
    threads.reserve(n_threads);
    for (int32_t t = 0; t < n_threads; ++t) {
      threads.emplace_back(process_range, tokens, lengths, range_lo[t],
                           range_lo[t + 1], range_tok[t], keep, window,
                           legacy != 0, sub_base, win_base, token_base, &outs[t]);
    }
    for (auto& th : threads) th.join();
  }

  int64_t n_pairs = 0, kept = 0;
  for (const auto& o : outs) {
    n_pairs += static_cast<int64_t>(o.centers.size());
    kept += o.kept;
  }
  *out_kept = kept;
  if (n_pairs > cap) return -1;

  int64_t pair_off = 0, kept_off = 0;
  for (const auto& o : outs) {
    const int64_t n = static_cast<int64_t>(o.centers.size());
    std::memcpy(out_centers + pair_off, o.centers.data(), n * sizeof(int32_t));
    std::memcpy(out_contexts + pair_off, o.contexts.data(), n * sizeof(int32_t));
    for (int64_t i = 0; i < n; ++i)
      out_clock[pair_off + i] = o.clock[i] + kept_off + 1;  // 1-based global ordinal
    pair_off += n;
    kept_off += o.kept;
  }
  return n_pairs;
}

// ABI version stamp so the Python wrapper can detect stale cached builds.
int32_t glint_pairgen_abi_version() { return 1; }

}  // extern "C"
