"""Greedy counterexample minimization (ddmin over the non-default knob set).

``predicate(kwargs) -> Optional[str]`` returns the finding key a candidate
reproduces (or None); shrinking drops knobs while the SAME key reproduces —
dropping to a *different* refusal is not the same counterexample. Knobs are
tried in sorted order and passes repeat to a fixpoint, so the result is
deterministic and minimal w.r.t. single-knob removal (the refusal matrices
are conjunctions over ≤3 knobs, where 1-minimality IS global minimality)."""

from __future__ import annotations

from typing import Callable, Dict, Optional


def shrink(kwargs: Dict, predicate: Callable[[Dict], Optional[str]],
           target_key: str, max_passes: int = 5) -> Dict:
    cur = dict(kwargs)
    for _ in range(max_passes):
        changed = False
        for name in sorted(cur):
            trial = {k: v for k, v in cur.items() if k != name}
            if predicate(trial) == target_key:
                cur = trial
                changed = True
        if not changed:
            break
    return cur
