"""IVF approximate-nearest-neighbor index over the trained embedding matrix.

The serving tier's fast arm (ROADMAP item 1): exact ``find_synonyms`` is a
full [V, D] matvec + top-k per batch — the right oracle, the wrong steady
state for millions-of-users traffic. This index buys a tunable
compute-vs-recall trade the classic IVF way:

- **build** (at load/checkpoint-publish time): unit-normalize the rows
  (cosine == dot on the unit sphere; zero-norm sharding-padding rows stay
  zero and can never enter a top-k), k-means a sampled subset into
  ``num_centroids`` coarse cells (seeded Lloyd iterations — deterministic:
  same matrix + seed → the same index), then assign every row to its
  nearest centroid, stored as one CSR-style inverted-list layout
  (``offsets [C+1]`` + ``rows [V]``);
- **search**: score the query against the C centroids, visit only the
  ``nprobe`` nearest cells, and rank the candidate rows exactly — the
  scanned fraction is ~``nprobe / C`` of the vocabulary instead of 1.0;
- **recall is measured, not assumed**: the build samples rows as queries
  and scores the index against the EXACT full-scan oracle on the same
  normalized matrix; ``stats["recall_at_10"]`` travels with the index, so
  a geometry that breaks IVF's clustering assumption (e.g. a post-blowup
  matrix) is visible at publish time — and tools/eval_quality.py records
  the same number into EVAL_RUNS rows.

Host-resident by design: the index holds ONE float32 normalized copy of
the matrix plus O(V) int32 list structure. Search is numpy (BLAS matmuls
over small candidate sets) — it deliberately does not touch the device, so
ANN queries never contend with the exact arm's device dispatches or a
co-located trainer's collectives. The exact sharded top-k
(models/word2vec.py) remains the ground-truth oracle.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger("glint_word2vec_tpu")

# chunk sizes bounding host scratch: assignment [chunk, C] and the exact-
# oracle [chunk, V] score blocks stay under ~256 MB each
_ASSIGN_BLOCK_BYTES = 256 << 20
_ORACLE_BLOCK_BYTES = 256 << 20


def _normalize_rows(m: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(unit rows, norms); zero-norm rows stay zero (cosine 0 everywhere —
    the same masking rule as the exact path's zero-norm handling)."""
    m = np.ascontiguousarray(m, dtype=np.float32)
    norms = np.linalg.norm(m, axis=1)
    out = m / np.maximum(norms, 1e-12)[:, None]
    return out, norms


def _argmax_rows(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Nearest-centroid id per row of ``x`` (both unit-normalized), with the
    [chunk, C] score block bounded."""
    C = centroids.shape[0]
    chunk = max(1, _ASSIGN_BLOCK_BYTES // max(C * 4, 1))
    out = np.empty(x.shape[0], np.int32)
    for lo in range(0, x.shape[0], chunk):
        out[lo:lo + chunk] = np.argmax(
            x[lo:lo + chunk] @ centroids.T, axis=1).astype(np.int32)
    return out


def _topk_desc(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k largest entries, sorted descending by score (ties:
    ascending index — stable across runs)."""
    n = scores.shape[0]
    if k >= n:
        cand = np.arange(n)
    else:
        cand = np.argpartition(scores, n - k)[n - k:]
    return cand[np.lexsort((cand, -scores[cand]))][:k]


class IvfIndex:
    """Built inverted-file index; see :func:`build_ivf`.

    Storage is the PACKED layout: the normalized matrix is reordered so each
    inverted list is one contiguous row block (``_packed[offsets[c]:
    offsets[c+1]]`` is cell ``c``). Probing a cell is then a sequential
    matmul over its block — the naive gather of ~nprobe/C·V scattered rows
    is DRAM-latency-bound and measured 5-10x slower at V ≥ 400k on this
    host class. ``_ids`` maps packed positions back to original row ids;
    ``_row_pos`` is the inverse (for :meth:`vector`)."""

    def __init__(self, centroids: np.ndarray, offsets: np.ndarray,
                 packed: np.ndarray, ids: np.ndarray, row_pos: np.ndarray,
                 nprobe: int, stats: Dict):
        self._centroids = centroids      # [C, D] unit rows
        self._offsets = offsets          # [C + 1] int64
        self._packed = packed            # [V, D] unit rows, list order
        self._ids = ids                  # [V] int32: packed pos -> row id
        self._row_pos = row_pos          # [V] int64: row id -> packed pos
        self.nprobe = int(nprobe)
        self.stats = stats

    @property
    def num_centroids(self) -> int:
        return int(self._centroids.shape[0])

    @property
    def num_rows(self) -> int:
        return int(self._packed.shape[0])

    def vector(self, row: int) -> np.ndarray:
        """The indexed (unit-normalized) vector of one row — lets word
        queries reuse the host copy instead of a device gather."""
        return self._packed[self._row_pos[row]]

    def search(self, queries: np.ndarray, k: int,
               nprobe: Optional[int] = None
               ) -> Tuple[np.ndarray, np.ndarray]:
        """Top-``k`` cosine rows per query over the probed cells.

        Returns ``(scores [Q, k], row_ids [Q, k])``; slots past the
        candidate count (possible only at tiny nprobe on tiny lists) carry
        ``(-inf, -1)``. ``nprobe`` overrides the index default; clamped to
        the centroid count (``nprobe >= C`` degrades to an exact scan and
        is the recall-1.0 reference point)."""
        q, _ = _normalize_rows(np.atleast_2d(np.asarray(queries, np.float32)))
        C = self.num_centroids
        npr = min(int(nprobe) if nprobe else self.nprobe, C)
        npr = max(npr, 1)
        cscore = q @ self._centroids.T                       # [Q, C]
        Q = q.shape[0]
        off = self._offsets
        scores = np.full((Q, k), -np.inf, np.float32)
        idx = np.full((Q, k), -1, np.int64)
        for r in range(Q):
            # probe cells best-first, and past the nprobe budget KEEP
            # probing until the candidate pool covers k (a tiny/uneven cell
            # must not starve the result below the requested top-k — the
            # serve-reload chaos phase caught exactly that at toy vocab)
            order = np.argsort(-cscore[r], kind="stable")
            parts, pos_parts, got = [], [], 0
            for j, c in enumerate(order):
                if j >= npr and got >= k:
                    break
                lo, hi = off[c], off[c + 1]
                if hi == lo:
                    continue
                # one contiguous matvec per probed cell (packed layout)
                parts.append(self._packed[lo:hi] @ q[r])
                pos_parts.append(np.arange(lo, hi))
                got += hi - lo
            if not parts:
                continue
            s = np.concatenate(parts)
            pos = np.concatenate(pos_parts)
            top = _topk_desc(s, min(k, s.size))
            scores[r, :top.size] = s[top]
            idx[r, :top.size] = self._ids[pos[top]]
        return scores, idx

    def measure_recall(self, query_rows: np.ndarray, k: int = 10,
                       nprobe: Optional[int] = None) -> float:
        """recall@k of this index vs the EXACT full-scan oracle on the same
        normalized matrix, querying by row id (self excluded on both arms —
        the serving semantics)."""
        qpos = self._row_pos[np.asarray(query_rows)]
        q = self._packed[qpos]
        _, ann_i = self.search(q, k + 1, nprobe)
        V = self.num_rows
        chunk = max(1, _ORACLE_BLOCK_BYTES // max(V * 4, 1))
        hits, total = 0, 0
        for lo in range(0, q.shape[0], chunk):
            block = q[lo:lo + chunk] @ self._packed.T        # [chunk, V]
            for r in range(block.shape[0]):
                qi = int(query_rows[lo + r])
                exact = [int(self._ids[p])
                         for p in _topk_desc(block[r], k + 1)
                         if self._ids[p] != qi][:k]
                ann = [i for i in ann_i[lo + r] if i >= 0 and i != qi][:k]
                hits += len(set(exact) & set(ann))
                total += len(exact)
        return hits / max(total, 1)


def auto_centroids(num_rows: int) -> int:
    """The AUTO cell count: ~4·sqrt(V), clamped so every cell averages ≥ 8
    rows and the centroid scan stays tiny next to the scan it replaces."""
    return max(1, min(int(round(4 * math.sqrt(max(num_rows, 1)))),
                      max(num_rows // 8, 1), 4096))


def auto_nprobe(num_centroids: int) -> int:
    """The AUTO probe width: ~1/12 of the cells (≈8% of the vocabulary
    scanned) — the measured recall ≥ 0.95 operating point on clustered
    embedding geometry (tools/servebench.py); tune per deployment."""
    return max(1, -(-num_centroids // 12))


def build_ivf(
    matrix: np.ndarray,
    num_centroids: int = 0,
    nprobe: int = 0,
    seed: int = 0,
    kmeans_iters: int = 4,
    train_sample: int = 65536,
    recall_queries: int = 256,
    recall_k: int = 10,
    measure_recall: bool = True,
) -> IvfIndex:
    """Build an :class:`IvfIndex` from a [V, D] embedding matrix (pass the
    UNPADDED ``model.syn0``; sharding padding would only add zero rows).

    ``num_centroids``/``nprobe`` 0 = AUTO (:func:`auto_centroids` /
    :func:`auto_nprobe` — the ``serve_ann_centroids``/``serve_ann_nprobe``
    config knobs carry the same 0-is-AUTO convention). ``measure_recall``
    scores the built index against the exact oracle on ``recall_queries``
    sampled rows; the result rides ``index.stats`` (and, from there,
    servebench JSON lines and EVAL_RUNS rows)."""
    t0 = time.perf_counter()
    normed, norms = _normalize_rows(np.asarray(matrix, np.float32))
    V = normed.shape[0]
    nonzero = np.flatnonzero(norms > 0)
    C = int(num_centroids) if num_centroids else auto_centroids(V)
    C = max(1, min(C, max(nonzero.size, 1)))
    rng = np.random.default_rng(seed)

    if nonzero.size:
        if nonzero.size > train_sample:
            train = rng.choice(nonzero, size=train_sample, replace=False)
        else:
            train = nonzero
        X = normed[train]
        centroids = X[rng.choice(X.shape[0], size=C, replace=False)].copy()
        for _ in range(max(kmeans_iters, 1)):
            assign = _argmax_rows(X, centroids)
            sums = np.zeros_like(centroids)
            np.add.at(sums, assign, X)
            counts = np.bincount(assign, minlength=C)
            live = counts > 0
            sums[live] /= counts[live, None]
            dead = np.flatnonzero(~live)
            if dead.size:
                # re-seed empty cells from random training rows so every
                # cell stays live (classic Lloyd repair, deterministic)
                sums[dead] = X[rng.choice(X.shape[0], size=dead.size)]
            centroids, _ = _normalize_rows(sums)
    else:
        # degenerate all-zero matrix: one empty-ish cell, exact fallback
        centroids = np.zeros((1, normed.shape[1]), np.float32)
        C = 1

    assign_all = _argmax_rows(normed, centroids)
    counts = np.bincount(assign_all, minlength=C)
    offsets = np.zeros(C + 1, np.int64)
    np.cumsum(counts, out=offsets[1:])
    ids = np.argsort(assign_all, kind="stable").astype(np.int32)
    packed = np.ascontiguousarray(normed[ids])   # list-contiguous layout
    row_pos = np.empty(V, np.int64)
    row_pos[ids] = np.arange(V)

    npr = int(nprobe) if nprobe else auto_nprobe(C)
    stats: Dict = {
        "centroids": C,
        "nprobe": min(npr, C),
        "rows": V,
        "mean_list_len": round(float(counts.mean()), 2) if C else 0.0,
        "max_list_len": int(counts.max()) if C else 0,
    }
    index = IvfIndex(centroids, offsets, packed, ids, row_pos,
                     min(npr, C), stats)
    if measure_recall and nonzero.size > recall_k:
        probes = rng.choice(nonzero,
                            size=min(recall_queries, nonzero.size),
                            replace=False)
        stats["recall_at_10" if recall_k == 10 else f"recall_at_{recall_k}"] \
            = round(index.measure_recall(probes, k=recall_k), 4)
        stats["recall_queries"] = int(probes.size)
    stats["build_seconds"] = round(time.perf_counter() - t0, 3)
    logger.info("IVF index built: V=%d C=%d nprobe=%d recall@%d=%s in %.2fs",
                V, C, stats["nprobe"], recall_k,
                stats.get(f"recall_at_{recall_k}"), stats["build_seconds"])
    return index
