"""Unit tests for the quality-eval harness's v2 relation machinery
(tools/eval_quality.py) — the scorer behind EVAL.md's analogy gate.

The gate's numbers steer roadmap decisions (VERDICT r4 item 4), so its scoring
must be pinned: a constructed embedding with EXACT relational geometry must
score 1.0 per family, a random one ~0, and the generator must actually plant
every family's words at its configured rate ordering."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import eval_quality as eq  # noqa: E402


def _index_for(fams):
    words = []
    for f in fams:
        words.extend(f["a"])
        words.extend(f["b"])
    return {w: i for i, w in enumerate(words)}


def test_family_names_layout():
    fams = eq.family_names()
    assert [f["key"] for f in fams] == ["freq", "many", "rare"]
    many = fams[1]
    assert many["nb_per_a"] == 2
    assert len(many["b"]) == 2 * len(many["a"])
    # names are disjoint across families and from topic/stopword patterns
    all_names = [w for f in fams for k in ("a", "b", "ra", "rb") for w in f[k]]
    assert len(set(all_names)) == len(all_names)
    assert not any(w.startswith(("t", "s_")) for w in all_names)


def test_analogy_scorer_perfect_geometry_scores_one():
    """b = a + family_offset exactly -> every family must score acc@1 = 1.0
    (incl. the 1:many family, where any b of a_j counts)."""
    fams = eq.family_names()
    index = _index_for(fams)
    rng = np.random.default_rng(0)
    D = 32
    emb = np.zeros((len(index), D), np.float32)
    for f_idx, f in enumerate(fams):
        offset = rng.standard_normal(D).astype(np.float32)
        for i, a in enumerate(f["a"]):
            base = rng.standard_normal(D).astype(np.float32)
            emb[index[a]] = base
            for k in range(f["nb_per_a"]):
                b = f["b"][i * f["nb_per_a"] + k]
                # tiny per-b jitter: distinct vectors, same offset direction
                emb[index[b]] = base + offset * (1.0 + 0.001 * k)
    out = eq.evaluate_analogies(index, emb)
    assert out["gen_version"] == eq.GEN_VERSION
    for key in ("freq", "many", "rare"):
        assert out[f"analogy_{key}_accuracy_at_1"] == 1.0, out
    assert out["analogy_accuracy_at_1"] == 1.0


def test_analogy_scorer_random_geometry_scores_zero():
    fams = eq.family_names()
    index = _index_for(fams)
    emb = np.random.default_rng(1).standard_normal(
        (len(index), 16)).astype(np.float32)
    out = eq.evaluate_analogies(index, emb)
    assert out["analogy_accuracy_at_1"] < 0.1


def test_v1_rescore_fallback():
    """Round-4 models (old ea_/eb_ names) still score through the v1 path."""
    ea, eb, _, _ = eq.relation_names()
    index = {w: i for i, w in enumerate(ea + eb)}
    rng = np.random.default_rng(2)
    D = 16
    offset = rng.standard_normal(D).astype(np.float32)
    emb = np.zeros((len(index), D), np.float32)
    for i, (a, b) in enumerate(zip(ea, eb)):
        base = rng.standard_normal(D).astype(np.float32)
        emb[index[a]] = base
        emb[index[b]] = base + offset
    out = eq.evaluate_analogies(index, emb)
    assert out["gen_version"] == 1
    assert out["analogy_accuracy_at_1"] == 1.0


def test_generator_plants_all_families(tmp_path):
    path = str(tmp_path / "c.txt")
    eq.generate_corpus(path, n_words=700_000, seed=3, v_raw=2000)
    from collections import Counter
    counts = Counter()
    with open(path) as f:
        for line in f:
            counts.update(line.split())
    fams = eq.family_names()
    occ = {f["key"]: sum(counts[w] for w in f["a"] + f["b"]) for f in fams}
    # rate ordering follows the configured weights; every family is present
    assert occ["freq"] > occ["many"] > occ["rare"] > 0, occ
    # role words mark relation sentences of their family only
    r0 = sum(counts[w] for w in fams[0]["ra"] + fams[0]["rb"])
    assert r0 > 0
    # non-relation content dominates (relation sentences are REL_SENT_FRAC)
    total = sum(counts.values())
    rel_tokens = sum(occ.values()) + sum(
        counts[w] for f in fams for w in f["ra"] + f["rb"])
    assert rel_tokens / total < 3 * eq.REL_SENT_FRAC
