"""graftrace static half: the R9–R11 lock-discipline rules + registry gates.

Two real concurrency bugs shipped through review (docs/static-analysis.md
layer 4): the PR 9 SIGTERM-handler deadlock (handler blocked on a plain
``Lock`` held by the thread it interrupted) and the PR 12 latency-ring race
(sorting a deque another thread appends to). These rules machine-check the
threading discipline the same way R1–R8 machine-check the AST idioms:

- **R9 lock-order**: every lock is constructed through
  ``glint_word2vec_tpu.lockcheck`` with a registered rank
  (:data:`lockcheck.LOCK_TABLE` — parsed here as a pure literal, the same
  contract as the graftcheck knob registry). The cross-module acquisition
  graph is built from ``with``/``.acquire()`` sites resolved through
  ``self.`` attributes plus a bounded call closure; any edge that does not
  strictly increase rank, any cycle, and any reentrant acquisition of a
  non-reentrant kind is a finding. Registry drift (unregistered
  construction, raw ``threading.Lock()`` in scanned code, stale or moved
  registry entries) fails the same rule.
- **R10 signal-handler safety**: the call closure of every installed signal
  handler (``signal.signal(SIG, h)``) may not acquire a non-reentrant lock
  that non-handler code also holds — the PR 9 bug, now structurally
  impossible. The closure walk propagates literal boolean keyword arguments
  one call deep (pruning ``if param:`` bodies), because the PR 9 fix itself
  is such a guard: ``dump_blackbox(include_stats=False)`` exists precisely
  to keep the batcher's non-reentrant condition off the handler path.
- **R11 shared-mutable discipline** (per-file): in a thread-owning class,
  every whole-collection access (mutation or ``sorted``/``list``/iteration
  read) of a shared deque/list/dict attribute must hold the same lock, or
  live in a documented snapshot helper (name/docstring says "snapshot").
  The PR 12 race is the bad fixture.

Repo-rule findings here honor the standard suppression syntax (directive
with justification on the flagged line or the line above) — the engine only
applies suppressions to per-file rules, so the repo rules in this module
re-apply them per flagged file themselves.

``R1Staleness`` rides along (ISSUE 20 satellite): an R1 allowlist entry
whose (path, qualname) no longer resolves to a def is a finding — stale
thread-owner blessings used to rot silently.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.engine import Finding, _apply_suppressions, iter_source_files
from tools.graftlint.rules import _name_of

_LIB = "glint_word2vec_tpu/"
_LOCKCHECK = _LIB + "lockcheck.py"
_FACTORIES = {"make_lock": "lock", "make_rlock": "rlock",
              "make_condition": "condition"}
_PRIMITIVES = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
_CLOSURE_DEPTH = 10


def _is_primitive_ctor(call: ast.Call) -> Optional[str]:
    """'lock'/'rlock'/'condition' if this is a raw threading primitive
    construction (threading.Lock() or bare Lock())."""
    name = _name_of(call.func)
    tail = name.rsplit(".", 1)[-1]
    if tail in _PRIMITIVES and name in (tail, "threading." + tail):
        return _PRIMITIVES[tail]
    return None


def _factory_call(call: ast.Call) -> Optional[Tuple[str, Optional[str]]]:
    """(kind, registered name or None) for lockcheck factory calls."""
    tail = _name_of(call.func).rsplit(".", 1)[-1]
    if tail not in _FACTORIES:
        return None
    name = None
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
            call.args[0].value, str):
        name = call.args[0].value
    return _FACTORIES[tail], name


class _Fn:
    """One function/method with everything the closure walk needs."""

    __slots__ = ("node", "path", "cls", "name", "params")

    def __init__(self, node: ast.AST, path: str, cls: Optional[str],
                 name: str) -> None:
        self.node = node
        self.path = path
        self.cls = cls
        self.name = name
        args = getattr(node, "args", None)
        self.params = ({a.arg for a in args.args} | {a.arg for a in
                       args.kwonlyargs} if args is not None else set())

    @property
    def key(self) -> Tuple[str, Optional[str], str]:
        return (self.path, self.cls, self.name)

    @property
    def label(self) -> str:
        return f"{self.path}:{(self.cls + '.') if self.cls else ''}{self.name}"


class _TreeIndex:
    """Whole-tree concurrency index: the lock registry, every construction
    site, per-class lock/typed attributes, and the function map the
    closure walk resolves calls through."""

    def __init__(self, root: str):
        self.root = root
        self.registry: Dict[str, dict] = {}
        self.registry_err: Optional[str] = None
        self.files: Dict[str, Tuple[ast.Module, List[str]]] = {}
        # ClassName -> (path, ClassDef); name collisions -> None (ambiguous)
        self.classes: Dict[str, Optional[Tuple[str, ast.ClassDef]]] = {}
        self.fns: Dict[Tuple[str, Optional[str], str], _Fn] = {}
        self.attr_locks: Dict[Tuple[str, str], str] = {}   # (cls, attr) -> lock
        self.mod_locks: Dict[Tuple[str, str], str] = {}    # (path, var) -> lock
        self.attr_types: Dict[Tuple[str, str], str] = {}   # (cls, attr) -> Cls
        # (path, qualname, lockname, kind, lineno)
        self.construct_sites: List[Tuple[str, str, Optional[str], str, int]] = []
        self.raw_sites: List[Tuple[str, str, str, int]] = []
        self._load()

    # -- loading ----------------------------------------------------------------------

    def _load(self) -> None:
        for abspath in iter_source_files(self.root):
            rel = os.path.relpath(abspath, self.root).replace(os.sep, "/")
            try:
                with open(abspath, "r", encoding="utf-8") as f:
                    text = f.read()
                tree = ast.parse(text)
            except (OSError, SyntaxError):
                continue  # the engine reports AST findings; nothing here
            self.files[rel] = (tree, text.splitlines())
        lc = self.files.get(_LOCKCHECK)
        if lc is None:
            self.registry_err = f"{_LOCKCHECK} not found — no lock registry"
        else:
            self._parse_registry(lc[0])
        for rel, (tree, _) in self.files.items():
            self._index_module(rel, tree)
        # second pass: typed attributes need the full class map
        for rel, (tree, _) in self.files.items():
            self._index_attr_types(rel, tree)

    def _parse_registry(self, tree: ast.Module) -> None:
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "LOCK_TABLE"):
                try:
                    table = ast.literal_eval(node.value)
                except ValueError:
                    self.registry_err = (
                        "LOCK_TABLE is not a pure literal — entries built by "
                        "code are invisible to the drift gate")
                    return
                self.registry = dict(table)
                return
        self.registry_err = "LOCK_TABLE not found in lockcheck.py"

    def _index_module(self, rel: str, tree: ast.Module) -> None:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def qualname(node: ast.AST) -> str:
            parts: List[str] = []
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    parts.append(cur.name)
                cur = parents.get(cur)
            return ".".join(reversed(parts)) or "<module>"

        def enclosing_class(node: ast.AST) -> Optional[str]:
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, ast.ClassDef):
                    return cur.name
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # nested def inside a method still belongs to the class
                    cur = parents.get(cur)
                    continue
                cur = parents.get(cur)
            return None

        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef) and parents.get(node) is tree:
                self.classes[node.name] = (
                    None if node.name in self.classes else (rel, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = enclosing_class(node)
                fn = _Fn(node, rel, cls, node.name)
                # innermost def wins on duplicate names; fine for our tree
                self.fns.setdefault(fn.key, fn)
            elif isinstance(node, ast.Call):
                fac = _factory_call(node)
                kind_raw = _is_primitive_ctor(node)
                if fac is not None:
                    kind, lname = fac
                    qn = qualname(node)
                    self.construct_sites.append(
                        (rel, qn, lname, kind, node.lineno))
                    tgt = self._assign_target(parents.get(node), node)
                    if tgt is not None:
                        mode, owner, attr = tgt
                        if lname is not None:
                            if mode == "self":
                                cls = enclosing_class(node)
                                if cls:
                                    self.attr_locks[(cls, attr)] = lname
                            elif mode == "module":
                                self.mod_locks[(rel, attr)] = lname
                            # locals resolved lexically in _LockResolver
                elif kind_raw is not None and rel != _LOCKCHECK:
                    self.raw_sites.append(
                        (rel, qualname(node), kind_raw, node.lineno))

    @staticmethod
    def _assign_target(parent: Optional[ast.AST], call: ast.Call):
        """('self', None, attr) / ('module', None, name) for `X = <call>`
        single-target (possibly annotated) assignments."""
        if isinstance(parent, ast.AnnAssign) and parent.value is call:
            t = parent.target
        elif (isinstance(parent, ast.Assign) and parent.value is call
                and len(parent.targets) == 1):
            t = parent.targets[0]
        else:
            return None
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return ("self", None, t.attr)
        if isinstance(t, ast.Name):
            return ("module", None, t.id)
        return None

    def _index_attr_types(self, rel: str, tree: ast.Module) -> None:
        for cls_entry in list(self.classes.values()):
            if cls_entry is None or cls_entry[0] != rel:
                continue
            cpath, cnode = cls_entry
            for node in ast.walk(cnode):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Attribute)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == "self"
                        and isinstance(node.value, ast.Call)):
                    continue
                tail = _name_of(node.value.func).rsplit(".", 1)[-1]
                if tail in self.classes and self.classes[tail] is not None:
                    self.attr_types[(cnode.name, node.targets[0].attr)] = tail

    # -- lock/call resolution ---------------------------------------------------------

    def resolve_lock(self, expr: ast.AST, fn: _Fn) -> Optional[str]:
        """Lock name for a with/acquire context expression, or None."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" and fn.cls:
            return self.attr_locks.get((fn.cls, expr.attr))
        if isinstance(expr, ast.Name):
            got = self.mod_locks.get((fn.path, expr.id))
            if got is not None:
                return got
            # local `x = make_lock("...")` in this function
            for node in ast.walk(fn.node):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == expr.id
                        and isinstance(node.value, ast.Call)):
                    fac = _factory_call(node.value)
                    if fac is not None and fac[1] is not None:
                        return fac[1]
        return None

    def resolve_call(self, call: ast.Call, fn: _Fn) -> Optional[_Fn]:
        """Callee _Fn for the call forms the closure walk understands."""
        f = call.func
        if isinstance(f, ast.Name):
            callee = self.fns.get((fn.path, None, f.id))
            if callee is not None:
                return callee
            cls = self.classes.get(f.id)
            if cls is not None:
                return self.fns.get((cls[0], f.id, "__init__"))
            # local nested def inside the same function
            return self.fns.get((fn.path, fn.cls, f.id))
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self" and fn.cls:
                callee = self.fns.get((fn.path, fn.cls, f.attr))
                if callee is not None:
                    return callee
                return None
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id == "self" \
                    and fn.cls:
                tcls = self.attr_types.get((fn.cls, base.attr))
                if tcls and self.classes.get(tcls):
                    return self.fns.get((self.classes[tcls][0], tcls, f.attr))
                return None
            if isinstance(base, ast.Name):
                tcls = self._local_type(base.id, fn)
                if tcls and self.classes.get(tcls):
                    return self.fns.get((self.classes[tcls][0], tcls, f.attr))
        return None

    def _local_type(self, name: str, fn: _Fn) -> Optional[str]:
        # the function's own assignments first, then the whole module — a
        # nested def (a signal handler inside main()) closes over locals of
        # its enclosing function, which are module-distant from fn.node
        scopes: List[ast.AST] = [fn.node]
        entry = self.files.get(fn.path)
        if entry is not None:
            scopes.append(entry[0])
        for scope in scopes:
            for node in ast.walk(scope):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and node.targets[0].id == name
                        and isinstance(node.value, ast.Call)):
                    tail = _name_of(node.value.func).rsplit(".", 1)[-1]
                    if tail in self.classes and self.classes[tail] is not None:
                        return tail
        return None

    # -- the bounded closure ----------------------------------------------------------

    def closure_locks(self, fn: _Fn, consts: Dict[str, bool] = None,
                      _depth: int = 0, _stack: Optional[Set] = None,
                      _memo: Optional[Dict] = None) -> Dict[str, List[str]]:
        """lock name -> call chain (labels) for every lock this function can
        acquire, walking same-tree callees up to _CLOSURE_DEPTH deep.
        ``consts`` prunes `if param:` branches for literal boolean keyword
        arguments (one level — the PR 9 include_stats=False contract)."""
        consts = consts or {}
        memo = _memo if _memo is not None else {}
        key = (fn.key, frozenset(consts.items()))
        if key in memo:
            return memo[key]
        stack = _stack if _stack is not None else set()
        if fn.key in stack or _depth > _CLOSURE_DEPTH:
            return {}
        stack = stack | {fn.key}
        out: Dict[str, List[str]] = {}

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.If):
                test = node.test
                skip_body = skip_else = False
                if isinstance(test, ast.Name) and test.id in consts:
                    skip_body = not consts[test.id]
                    skip_else = consts[test.id]
                elif (isinstance(test, ast.UnaryOp)
                        and isinstance(test.op, ast.Not)
                        and isinstance(test.operand, ast.Name)
                        and test.operand.id in consts):
                    skip_body = consts[test.operand.id]
                    skip_else = not consts[test.operand.id]
                if not skip_body:
                    for child in node.body:
                        visit(child)
                if not skip_else:
                    for child in node.orelse:
                        visit(child)
                return
            if isinstance(node, ast.With):
                for item in node.items:
                    lname = self.resolve_lock(item.context_expr, fn)
                    if lname is not None:
                        out.setdefault(lname, [fn.label])
            if isinstance(node, ast.Call):
                nm = _name_of(node.func)
                if nm.endswith(".acquire"):
                    lname = self.resolve_lock(node.func.value, fn)
                    if lname is not None:
                        out.setdefault(lname, [fn.label])
                callee = self.resolve_call(node, fn)
                if callee is not None:
                    sub_consts = {
                        kw.arg: bool(kw.value.value) for kw in node.keywords
                        if kw.arg and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, bool)
                        and kw.arg in callee.params}
                    sub = self.closure_locks(callee, sub_consts, _depth + 1,
                                             stack, memo)
                    for lname, chain in sub.items():
                        out.setdefault(lname, [fn.label] + chain)
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.If, ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                    visit(child)
                elif isinstance(child, ast.If):
                    visit(child)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt)
        memo[key] = out
        return out


def _suppressible(index: _TreeIndex, findings: List[Finding]) -> List[Finding]:
    """Repo-rule findings honor the standard suppression syntax: group by
    path and re-apply the engine's directive parser with that file's lines."""
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    out: List[Finding] = []
    for path, fs in by_path.items():
        entry = index.files.get(path)
        if entry is None:
            out.extend(fs)
            continue
        out.extend(_apply_suppressions(entry[1], fs))
    return out


# ---------------------------------------------------------------------------
# R9 — lock-order discipline + the registry drift gate. Locks are acquired
# in strictly increasing rank order (lockcheck.LOCK_TABLE); the graph is
# built from with/acquire sites plus the bounded call closure, so a
# cross-module nesting (router holds its lock and calls into the breaker)
# is an edge even though no single function shows both locks.
# ---------------------------------------------------------------------------
class R9LockOrder:
    id = "R9"
    repo_rule = True

    def check_repo(self, root: str) -> List[Finding]:
        index = _TreeIndex(root)
        findings: List[Finding] = []
        if index.registry_err:
            return [Finding(rule=self.id, path=_LOCKCHECK, line=0, col=0,
                            message=index.registry_err)]
        findings.extend(self._drift(index))
        findings.extend(self._graph(index))
        return _suppressible(index, findings)

    # -- registry drift ---------------------------------------------------------------

    def _drift(self, index: _TreeIndex) -> List[Finding]:
        out: List[Finding] = []
        seen: Dict[str, Tuple[str, str, str]] = {}
        for path, qn, lname, kind, lineno in index.construct_sites:
            if lname is None:
                out.append(Finding(
                    rule=self.id, path=path, line=lineno, col=0,
                    message="lockcheck factory call without a literal lock "
                            "name — the registry drift gate needs the string "
                            "at the construction site"))
                continue
            entry = index.registry.get(lname)
            if entry is None:
                out.append(Finding(
                    rule=self.id, path=path, line=lineno, col=0,
                    message=f"lock {lname!r} constructed here but not "
                            f"registered in lockcheck.LOCK_TABLE — register "
                            f"an owner and a rank"))
                continue
            seen[lname] = (path, qn, kind)
            if entry.get("kind") != kind:
                out.append(Finding(
                    rule=self.id, path=path, line=lineno, col=0,
                    message=f"lock {lname!r} registered as kind "
                            f"{entry.get('kind')!r} but constructed as "
                            f"{kind!r}"))
            want_site = str(entry.get("site", ""))
            have_site = f"{path}:{qn}"
            if want_site and want_site != have_site:
                out.append(Finding(
                    rule=self.id, path=path, line=lineno, col=0,
                    message=f"lock {lname!r} registered at {want_site!r} but "
                            f"constructed at {have_site!r} — update the "
                            f"registry's site in the same PR"))
        for lname, entry in sorted(index.registry.items()):
            if lname not in seen:
                out.append(Finding(
                    rule=self.id, path=_LOCKCHECK, line=0, col=0,
                    message=f"stale registry entry {lname!r} "
                            f"({entry.get('site')}) — no construction site "
                            f"in the tree; drop it or fix the site"))
        for path, qn, kind, lineno in index.raw_sites:
            out.append(Finding(
                rule=self.id, path=path, line=lineno, col=0,
                message=f"raw threading.{kind.capitalize()}() construction "
                        f"in {qn} — route through "
                        f"glint_word2vec_tpu.lockcheck (make_{kind}) so the "
                        f"lock carries a registered owner and rank"))
        return out

    # -- the acquisition graph --------------------------------------------------------

    def _graph(self, index: _TreeIndex) -> List[Finding]:
        # edges: (outer, inner) -> (path, line) of the inner acquisition
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        memo: Dict = {}

        def record(outer: str, inner: str, path: str, line: int,
                   via: str) -> None:
            edges.setdefault((outer, inner), (path, line, via))

        for fn in index.fns.values():
            self._walk_fn(index, fn, [], record, memo)

        out: List[Finding] = []
        ranks = {n: e.get("rank", 0) for n, e in index.registry.items()}
        kinds = {n: e.get("kind", "lock") for n, e in index.registry.items()}
        for (outer, inner), (path, line, via) in sorted(edges.items()):
            if outer == inner:
                if kinds.get(inner) != "rlock":
                    out.append(Finding(
                        rule=self.id, path=path, line=line, col=0,
                        message=f"reentrant acquisition of non-reentrant "
                                f"lock {inner!r} ({via}) — self-deadlock; "
                                f"make it an rlock or restructure"))
                continue
            if ranks.get(inner, 0) <= ranks.get(outer, 0):
                out.append(Finding(
                    rule=self.id, path=path, line=line, col=0,
                    message=f"lock-order inversion: {inner!r} "
                            f"(rank {ranks.get(inner)}) acquired while "
                            f"holding {outer!r} (rank {ranks.get(outer)}) "
                            f"via {via} — ranks must strictly increase "
                            f"(lockcheck.LOCK_TABLE); reorder the "
                            f"acquisitions or re-rank with the reasoning"))
        # cycles: with strictly-increasing ranks every cycle contains an
        # inversion, but report the cycle explicitly so a re-ranking "fix"
        # that leaves a loop is still caught
        out.extend(self._cycles(edges))
        return out

    def _walk_fn(self, index: _TreeIndex, fn: _Fn, held: List[str],
                 record, memo: Dict) -> None:
        def visit(node: ast.AST, held: List[str]) -> None:
            if isinstance(node, ast.With):
                acquired: List[str] = []
                for item in node.items:
                    lname = index.resolve_lock(item.context_expr, fn)
                    if lname is not None:
                        if held:
                            record(held[-1], lname, fn.path, node.lineno,
                                   fn.label)
                        acquired.append(lname)
                inner = held + acquired
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call) and held:
                callee = index.resolve_call(node, fn)
                if callee is not None:
                    sub_consts = {
                        kw.arg: bool(kw.value.value) for kw in node.keywords
                        if kw.arg and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, bool)
                        and kw.arg in callee.params}
                    for lname, chain in index.closure_locks(
                            callee, sub_consts, 1, None, memo).items():
                        record(held[-1], lname, fn.path, node.lineno,
                               " -> ".join([fn.label] + chain))
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef, ast.Lambda)):
                    visit(child, held)

        for stmt in getattr(fn.node, "body", []):
            visit(stmt, held)

    def _cycles(self, edges: Dict) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        out: List[Finding] = []
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {n: WHITE for n in
                 set(graph) | {b for bs in graph.values() for b in bs}}

        def dfs(n: str, path: List[str]) -> Optional[List[str]]:
            color[n] = GRAY
            for m in sorted(graph.get(n, ())):
                if color[m] == GRAY:
                    return path[path.index(m):] + [m] if m in path else [n, m]
                if color[m] == WHITE:
                    cyc = dfs(m, path + [m])
                    if cyc:
                        return cyc
            color[n] = BLACK
            return None

        for n in sorted(color):
            if color[n] == WHITE:
                cyc = dfs(n, [n])
                if cyc:
                    epath, eline, _ = edges[(cyc[0], cyc[1])]
                    out.append(Finding(
                        rule=self.id, path=epath, line=eline, col=0,
                        message=f"lock-acquisition cycle: "
                                f"{' -> '.join(cyc)} — potential deadlock"))
                    break
        return out


# ---------------------------------------------------------------------------
# R10 — signal-handler safety: the PR 9 bug class. A handler runs on the
# main thread AT AN ARBITRARY POINT, including inside a critical section;
# if its call closure can block on a non-reentrant lock that any normal
# path holds, the process deadlocks exactly when the dump matters most.
# ---------------------------------------------------------------------------
class R10HandlerSafety:
    id = "R10"
    repo_rule = True

    def check_repo(self, root: str) -> List[Finding]:
        index = _TreeIndex(root)
        if index.registry_err:
            return []  # R9 reports the registry problem once
        findings: List[Finding] = []
        memo: Dict = {}
        kinds = {n: e.get("kind", "lock") for n, e in index.registry.items()}
        for path, (tree, _) in sorted(index.files.items()):
            for fn in [f for f in index.fns.values() if f.path == path]:
                for node in ast.walk(fn.node):
                    if not (isinstance(node, ast.Call)
                            and _name_of(node.func) in
                            ("signal.signal", "signal")
                            and len(node.args) == 2):
                        continue
                    handler = self._resolve_handler(index, fn, node.args[1])
                    if handler is None:
                        continue
                    closure = index.closure_locks(handler, None, 0, None,
                                                  memo)
                    for lname, chain in sorted(closure.items()):
                        if kinds.get(lname) == "rlock":
                            continue
                        findings.append(Finding(
                            rule=self.id, path=path, line=node.lineno, col=0,
                            message=f"signal handler "
                                    f"{handler.label.split(':')[-1]!r} can "
                                    f"acquire non-reentrant lock {lname!r} "
                                    f"(via {' -> '.join(chain)}) — if the "
                                    f"signal lands while the interrupted "
                                    f"thread holds it, the handler "
                                    f"deadlocks (the PR 9 bug); make the "
                                    f"lock reentrant or keep it off the "
                                    f"handler path"))
        return _suppressible(index, findings)

    @staticmethod
    def _resolve_handler(index: _TreeIndex, fn: _Fn,
                         expr: ast.AST) -> Optional[_Fn]:
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" and fn.cls:
            return index.fns.get((fn.path, fn.cls, expr.attr))
        if isinstance(expr, ast.Name):
            # a def in the same function/class/module scope
            return (index.fns.get((fn.path, fn.cls, expr.id))
                    or index.fns.get((fn.path, None, expr.id)))
        return None


# ---------------------------------------------------------------------------
# R11 — shared-mutable discipline (per-file): the PR 12 bug class. In a
# class that owns a thread, a deque/list/dict attribute mutated anywhere
# must have every whole-collection access (append/pop/iterate/sorted/list)
# under ONE lock attribute — or live in a documented snapshot helper. A
# lock-free append plus a locked sorted() still races (the deque iterator
# raises RuntimeError on concurrent mutation), which is why mutation sites
# are held to the same lock as the reads.
# ---------------------------------------------------------------------------
class R11SharedMutable:
    id = "R11"
    _CTORS = {"deque", "list", "dict", "OrderedDict", "defaultdict"}
    _MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
                 "pop", "popleft", "remove", "clear", "update", "setdefault"}
    _READERS = {"sorted", "list", "tuple", "max", "min", "sum", "set"}

    def applies(self, path: str) -> bool:
        return path.startswith(_LIB)

    def check(self, ctx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        return out

    def _check_class(self, ctx, cls: ast.ClassDef) -> List[Finding]:
        if not self._owns_thread(cls):
            return []
        shared = self._shared_collections(cls)
        if not shared:
            return []
        locks = self._lock_attrs(cls)
        findings: List[Finding] = []
        for attr in sorted(shared):
            sites = self._access_sites(ctx, cls, attr, locks)
            guards = {g for _, _, g, helper in sites if not helper}
            for lineno, what, guard, helper in sites:
                if helper:
                    continue
                if guard is None:
                    findings.append(Finding(
                        rule=self.id, path=ctx.path, line=lineno, col=0,
                        message=f"{what} of shared collection "
                                f"'self.{attr}' in thread-owning class "
                                f"{cls.name} outside any lock — another "
                                f"thread mutating it concurrently corrupts "
                                f"state or raises (the PR 12 deque race); "
                                f"hold the owning lock or go through a "
                                f"documented snapshot helper"))
                elif len(guards - {None}) > 1:
                    findings.append(Finding(
                        rule=self.id, path=ctx.path, line=lineno, col=0,
                        message=f"'self.{attr}' in {cls.name} is guarded by "
                                f"different locks at different sites "
                                f"({sorted(g for g in guards if g)}) — one "
                                f"collection, one lock"))
        return findings

    def _owns_thread(self, cls: ast.ClassDef) -> bool:
        for node in ast.walk(cls):
            if isinstance(node, ast.Call):
                nm = _name_of(node.func)
                if nm in ("threading.Thread", "Thread"):
                    return True
        return False

    @staticmethod
    def _self_attr_assign(node: ast.AST):
        """(attr, value) for `self.x = v` / `self.x: T = v` assignments."""
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            t = node.target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
        else:
            return None
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return (t.attr, node.value)
        return None

    def _shared_collections(self, cls: ast.ClassDef) -> Set[str]:
        assigned: Set[str] = set()
        for node in ast.walk(cls):
            pair = self._self_attr_assign(node)
            if pair is not None:
                attr, v = pair
                is_coll = (isinstance(v, (ast.List, ast.Dict, ast.Set))
                           or (isinstance(v, ast.Call)
                               and _name_of(v.func).rsplit(".", 1)[-1]
                               in self._CTORS))
                if is_coll:
                    assigned.add(attr)
        mutated: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and \
                    node.func.attr in self._MUTATORS:
                base = node.func.value
                if isinstance(base, ast.Attribute) and isinstance(
                        base.value, ast.Name) and base.value.id == "self":
                    mutated.add(base.attr)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in tgts:
                    if isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Attribute) and isinstance(
                            t.value.value, ast.Name) and \
                            t.value.value.id == "self":
                        mutated.add(t.value.attr)
        return assigned & mutated

    def _lock_attrs(self, cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            pair = self._self_attr_assign(node)
            if pair is not None and isinstance(pair[1], ast.Call):
                if (_factory_call(pair[1]) is not None
                        or _is_primitive_ctor(pair[1]) is not None):
                    out.add(pair[0])
        return out

    def _access_sites(self, ctx, cls: ast.ClassDef, attr: str,
                      locks: Set[str]):
        """(lineno, description, guarding lock attr or None, in_helper)."""
        sites = []
        for method in [n for n in cls.body if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
            # the documented-snapshot escape: the method NAME says snapshot
            # and a docstring exists to say why it is safe — a passing
            # mention of "snapshot" in some other method's docstring is not
            # a thread-safety argument
            doc = ast.get_docstring(method) or ""
            helper = "snapshot" in method.name and bool(doc)

            def guard_of(node: ast.AST) -> Optional[str]:
                cur = ctx.parents.get(node)
                while cur is not None and cur is not method:
                    if isinstance(cur, ast.With):
                        for item in cur.items:
                            e = item.context_expr
                            if isinstance(e, ast.Attribute) and isinstance(
                                    e.value, ast.Name) and \
                                    e.value.id == "self" and e.attr in locks:
                                return e.attr
                    cur = ctx.parents.get(cur)
                return None

            def is_attr(node: ast.AST) -> bool:
                return (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self" and node.attr == attr)

            for node in ast.walk(method):
                what = None
                where = node
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and is_attr(f.value) \
                            and f.attr in self._MUTATORS:
                        what = f"mutation (.{f.attr})"
                    elif (isinstance(f, ast.Name)
                            and f.id in self._READERS and node.args):
                        a = node.args[0]
                        if is_attr(a):
                            what = f"whole-collection read ({f.id}(...))"
                        elif (isinstance(a, ast.Call) and isinstance(
                                a.func, ast.Attribute)
                                and a.func.attr in ("values", "items", "keys")
                                and is_attr(a.func.value)):
                            what = f"whole-collection read ({f.id}(...))"
                elif isinstance(node, ast.For) and is_attr(node.iter):
                    what = "iteration"
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        if is_attr(gen.iter):
                            what = "iteration (comprehension)"
                elif (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)):
                    t = node.targets[0]
                    if is_attr(t.value):
                        what = "mutation (subscript assignment)"
                if what is not None:
                    sites.append((where.lineno, what, guard_of(where),
                                  helper))
        return sites


# ---------------------------------------------------------------------------
# R1 staleness (repo rule, same id as the per-file R1): an allowlist entry
# blessing a thread owner that no longer exists used to rot silently —
# the blessing then silently covers whatever def NEXT takes that name.
# ---------------------------------------------------------------------------
class R1Staleness:
    id = "R1"
    repo_rule = True

    def __init__(self, allowlist=None):
        if allowlist is None:
            from tools.graftlint.rules import R1ThreadPools
            allowlist = R1ThreadPools._ALLOW
        self._allow = allowlist

    def check_repo(self, root: str) -> List[Finding]:
        findings: List[Finding] = []
        trees: Dict[str, Optional[ast.Module]] = {}
        for path, qual in sorted(self._allow):
            if path not in trees:
                abspath = os.path.join(root, *path.split("/"))
                try:
                    with open(abspath, "r", encoding="utf-8") as f:
                        trees[path] = ast.parse(f.read())
                except (OSError, SyntaxError):
                    trees[path] = None
            tree = trees[path]
            if tree is None:
                findings.append(Finding(
                    rule=self.id, path=path, line=0, col=0,
                    message=f"stale R1 allowlist entry: {path!r} cannot be "
                            f"parsed/found, but ({path!r}, {qual!r}) still "
                            f"blesses a thread owner there"))
                continue
            if not self._qual_exists(tree, qual):
                findings.append(Finding(
                    rule=self.id, path=path, line=0, col=0,
                    message=f"stale R1 allowlist entry: no def "
                            f"{qual!r} in {path} — the blessing would "
                            f"silently cover whatever next takes the name; "
                            f"drop or update the allowlist entry"))
        return findings

    @staticmethod
    def _qual_exists(tree: ast.Module, qual: str) -> bool:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            parts = [node.name]
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef)):
                    parts.append(cur.name)
                cur = parents.get(cur)
            if ".".join(reversed(parts)) == qual:
                return True
        return False


CONCURRENCY_RULES = [R9LockOrder(), R10HandlerSafety(), R11SharedMutable(),
                     R1Staleness()]
