"""R1 bad: ad-hoc thread pool in library code (unordered merge)."""
from concurrent.futures import ThreadPoolExecutor


def parallel_lengths(jobs, workers):
    with ThreadPoolExecutor(max_workers=workers) as pool:
        return list(pool.map(len, jobs))
