"""Candidate enumeration over the knob registry: the four tier families.

- **range**: one candidate per knob with an ``invalid`` sample — the
  construction-time range checks must refuse every one.
- **refusal groups**: exhaustive cartesian products over the refusal-relevant
  knob subsets (the selection matrices in config.py/trainer.py) — every
  documented refusal combination is EXECUTED, not just parsed.
- **pairwise**: a greedy covering array over ALL registry knobs — every
  (knob-a=value, knob-b=value) pair appears in at least one executed config.
- **sampled**: deterministic seeded mixing of full-width assignments to top
  the full sweep up past the ≥1,000 executed-config floor (boundary values
  get double weight).

All orders are deterministic (sorted knob names, seeded Generator) so two
runs of the same tree produce byte-identical reports.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterator, List, Tuple

import numpy as np

from tools.graftcheck.registry import KNOBS, config_defaults

Candidate = Tuple[str, Dict]  # (tier name, kwargs for Word2VecConfig)


# Exhaustive refusal-relevant subsets. Keys name the selection matrix they
# execute; values map knob -> the sub-domain worth crossing exhaustively
# (full registry domains where small, thinned where the full cross would
# explode without adding refusal-relevant structure).
REFUSAL_GROUPS: Dict[str, Dict[str, tuple]] = {
    "cbow-matrix": {
        "cbow": (False, True),
        "cbow_update": ("scatter", "banded"),
        "duplicate_scaling": (False, True),
        "negative_pool": (-1, 0, 64),
        "use_pallas": (False, True),
        "tokens_per_step": (0, 64),
        "window": (1, 2),
    },
    "lowering-matrix": {
        "step_lowering": ("gspmd", "shard_map"),
        "embedding_partition": ("rows", "cols"),
        "cbow": (False, True),
        "use_pallas": (False, True),
        "duplicate_scaling": (False, True),
        "negative_pool": (-1, 0, 64),
        "sharded_checkpoint": (False, True),
    },
    "pallas-stabilizers": {
        "use_pallas": (False, True),
        "max_row_norm": (0.0, 50.0),
        "update_clip": (0.0, 0.5),
        "row_l2": (0.0, 1e-4),
        "norm_watch": ("off", "warn", "recover", "halt"),
    },
    "device-feed": {
        "device_pairgen": (False, True),
        "cbow": (False, True),
        "use_pallas": (False, True),
        "window": (1, 2, 127),
        "tokens_per_step": (0, 64, 200_000),
        "shard_input": (True, False),
    },
    "auto-markers": {
        "subsample_ratio": (-1.0, 0.0, 1e-3),
        "negative_pool": (-1, 0, 64),
        "pairs_per_batch": (64, 4096),
        "cbow": (False, True),
        "duplicate_scaling": (False, True),
        "allow_unstable": (False, True),
    },
}


def range_tier() -> Iterator[Candidate]:
    for name in sorted(KNOBS):
        knob = KNOBS[name]
        if knob.invalid is not None:
            yield ("range", {name: knob.invalid})


def refusal_tier(thin: int = 1) -> Iterator[Candidate]:
    """``thin`` > 1 keeps every thin-th assignment of each group (the smoke
    tier); 1 = exhaustive (the full sweep)."""
    for gname in sorted(REFUSAL_GROUPS):
        group = REFUSAL_GROUPS[gname]
        names = sorted(group)
        for i, values in enumerate(itertools.product(
                *(group[n] for n in names))):
            if i % thin:
                continue
            yield (f"refusal:{gname}", dict(zip(names, values)))


def pairwise_tier() -> List[Candidate]:
    """Greedy pairwise covering array over every registry knob's full domain.
    Returns full-width assignments (all knobs set). Deterministic."""
    names = sorted(KNOBS)
    domains = {n: list(KNOBS[n].domain) for n in names}
    uncovered = set()
    for a, b in itertools.combinations(names, 2):
        for va, vb in itertools.product(domains[a], domains[b]):
            uncovered.add((a, _freeze(va), b, _freeze(vb)))
    rows: List[Dict] = []
    while uncovered:
        row: Dict = {}
        # rotate the fill order per row so late-alphabet knobs also get the
        # high-coverage early slots
        order = names[len(rows) % len(names):] + names[:len(rows) % len(names)]
        for name in order:
            best_v, best_gain = domains[name][0], -1
            for v in domains[name]:
                gain = 0
                for other, ov in row.items():
                    a, va, b, vb = _pairkey(name, v, other, ov)
                    if (a, va, b, vb) in uncovered:
                        gain += 1
                if gain > best_gain:
                    best_v, best_gain = v, gain
            row[name] = best_v
        newly = set()
        for (a, b) in itertools.combinations(sorted(row), 2):
            key = (a, _freeze(row[a]), b, _freeze(row[b]))
            if key in uncovered:
                newly.add(key)
        if not newly:
            # every remaining pair conflicts with greedy choices; force one
            a, va, b, vb = sorted(uncovered)[0]
            row[a] = _thaw(va, domains[a])
            row[b] = _thaw(vb, domains[b])
            for (x, y) in itertools.combinations(sorted(row), 2):
                key = (x, _freeze(row[x]), y, _freeze(row[y]))
                newly.add(key)
        uncovered -= newly
        rows.append(row)
    return [("pairwise", r) for r in rows]


def sampled_tier(n: int, seed: int = 0) -> Iterator[Candidate]:
    """Deterministic seeded full-width assignments; domain edge values are
    double-weighted (boundary bias)."""
    rng = np.random.default_rng(seed)
    names = sorted(KNOBS)
    for _ in range(n):
        row = {}
        for name in names:
            dom = list(KNOBS[name].domain)
            weights = np.ones(len(dom))
            weights[0] = weights[-1] = 2.0
            row[name] = dom[int(rng.choice(len(dom), p=weights / weights.sum()))]
        yield ("sampled", row)


def pair_count() -> int:
    names = sorted(KNOBS)
    return sum(len(KNOBS[a].domain) * len(KNOBS[b].domain)
               for a, b in itertools.combinations(names, 2))


def candidates(mode: str) -> List[Candidate]:
    """The full candidate list for one run. ``smoke`` = range + thinned
    refusal groups + pairwise; ``full`` adds exhaustive groups and the
    sampled top-up past the 1,000-config floor."""
    out: List[Candidate] = list(range_tier())
    out.extend(refusal_tier(thin=1 if mode == "full" else 7))
    out.extend(pairwise_tier())
    if mode == "full":
        floor = 1000
        deficit = max(300, floor + 50 - len(out))
        out.extend(sampled_tier(deficit))
    return out


def nondefault(kwargs: Dict) -> Dict:
    """Project a (possibly full-width) assignment onto its non-default
    entries — the shrinker's search space and the report's display form."""
    defaults = config_defaults()
    return {k: v for k, v in sorted(kwargs.items()) if v != defaults[k]}


def _freeze(v):
    return repr(v)


def _thaw(frozen, domain):
    for v in domain:
        if repr(v) == frozen:
            return v
    raise KeyError(frozen)


def _pairkey(n1, v1, n2, v2):
    if n1 < n2:
        return n1, _freeze(v1), n2, _freeze(v2)
    return n2, _freeze(v2), n1, _freeze(v1)
