"""Mode-B deployment surface: serve model ops from a checkpoint in a separate process.

The reference's mode B runs a standalone Glint PS cluster that training apps and query
clients both attach to (README.md:45-57, it spec:108-135). The TPU-native analog
(documented design call, models/compat.py): training owns the pod; QUERY serving reads
checkpoints — any number of serving processes can load the same checkpoint directory
(dense or row-shards; row-shards stream onto this process's mesh without a dense host
copy) and answer transform/find_synonyms while training continues writing newer
checkpoints alongside.

This CLI is a THIN CLIENT of the serving subsystem (glint_word2vec_tpu/serve/,
docs/serving.md): the swap-window retry logic lives in serve/reload.py (the single
owner), queries ride the request batcher, and ``--ann`` serves the IVF index arm
built at load time. The JSON-lines request/response contract below is unchanged.

Protocol: JSON-lines over stdin/stdout — one request object per line, one response
object per line (the process-boundary analog of the reference's Akka query RPCs, with
the same ops the PS served: pull / multiply+top-k, mllib:514,598):

    {"op": "synonyms", "word": "berlin", "num": 10}
    {"op": "synonyms_batch", "words": ["berlin", "wien"], "num": 10}
    {"op": "synonyms_vec", "vector": [...], "num": 10}
    {"op": "vector", "word": "berlin"}
    {"op": "reload"}                      # pick up a newer checkpoint at the same path
    {"op": "info"}
    {"op": "stats"}                       # serving-tier gauges (batcher/ANN/reloads,
                                          # incl. publish_sig — the served generation)

Any request may carry an ``"id"``: it is echoed verbatim on the response, which is
what lets the fleet router (serve/fleet.py) pair responses to tickets and discard
abandoned hedge-loser replies. Error responses are machine-readable:
``{"error": "...", "error_type": "ServerOverloaded", "retry_after_s": 0.12}`` —
the type name routes the caller's retry policy and ``retry_after_s`` is the
admission queue's measured drain-time hint (serve/batcher.py).

Usage:
    python tools/serve_checkpoint.py /path/to/checkpoint [--mesh DATAxMODEL]
        [--ann] [--nprobe N] [--watch] [--status-port P] [--telemetry PATH]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    # honor JAX_PLATFORMS even on images whose sitecustomize pins the platform
    # programmatically (env alone is not enough there — see tests/conftest.py)
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    ap = argparse.ArgumentParser()
    ap.add_argument("checkpoint")
    ap.add_argument("--mesh", default=None,
                    help="DATAxMODEL, e.g. 1x8: load row-shards straight onto this "
                         "mesh (no dense host copy)")
    ap.add_argument("--ann", action="store_true",
                    help="serve synonym queries from the IVF ANN index (built at "
                         "load/reload time; exact remains the oracle default)")
    ap.add_argument("--nprobe", type=int, default=0,
                    help="ANN cells probed per query (0 = the config/auto value)")
    ap.add_argument("--watch", action="store_true",
                    help="hot-reload automatically on the trainer's checkpoint "
                         "publish signal (the explicit reload op still works)")
    ap.add_argument("--status-port", type=int, default=0,
                    help="> 0: serve glint_serve_* gauges on 127.0.0.1:<port> "
                         "(/status.json, /metrics, /healthz)")
    ap.add_argument("--telemetry", default="",
                    help="non-empty: write serve_* telemetry records to this "
                         "JSONL path (obs/sink.py); also arms the serving "
                         "flight recorder (<path>.blackbox.json on death) "
                         "and cross-process trace spans (obs/trace.py)")
    ap.add_argument("--process-name", default="",
                    help="fleet-timeline track label for this replica's "
                         "telemetry (default serve-<pid>; the fleet spawner "
                         "passes r0/r1/...)")
    args = ap.parse_args()

    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.serve import EmbeddingService

    plan = None
    if args.mesh:
        d, m = (int(x) for x in args.mesh.lower().split("x"))
        plan = make_mesh(d, m)

    service = EmbeddingService(
        checkpoint=args.checkpoint, plan=plan, ann=args.ann,
        nprobe=args.nprobe or None, watch=args.watch,
        telemetry_path=args.telemetry, status_port=args.status_port,
        process_name=args.process_name)

    if args.telemetry:
        # the serving flight recorder's signal trigger (ISSUE-13 satellite;
        # same contract as trainer._install_run_signals): SIGTERM — the
        # graceful half of a kill, the half SIGKILL can't exercise — dumps
        # <telemetry>.blackbox.json with a serve-scoped signal cause, then
        # restores the prior disposition and re-raises so exit semantics
        # (rc -15, the fleet prober's dead-process detection) are untouched
        import signal

        from glint_word2vec_tpu.obs.blackbox import FlightRecorder

        prev_handler = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            # include_stats=False: the handler may have interrupted the
            # main thread INSIDE the batcher's non-reentrant _cv block —
            # a stats snapshot here would deadlock the dump
            service.dump_blackbox(FlightRecorder.signal_cause(signum),
                                  include_stats=False)
            signal.signal(signal.SIGTERM,
                          prev_handler if callable(prev_handler)
                          else signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_sigterm)

    def out(obj, req=None):
        # a request carrying an "id" gets it echoed on its response — the
        # fleet router (serve/fleet.py) pairs responses to tickets by id so
        # abandoned hedge-loser replies can be discarded safely
        if req is not None and "id" in req:
            obj = {**obj, "id": req["id"]}
        sys.stdout.write(json.dumps(obj) + "\n")
        sys.stdout.flush()

    info = service.info()
    out({"ready": True, "num_words": info["num_words"],
         "vector_size": info["vector_size"]})
    try:
        for line in sys.stdin:
            line = line.strip()
            if not line:
                continue
            req = None
            try:
                req = json.loads(line)
                op = req["op"]
                # cross-process trace context (obs/trace.py): a request
                # carrying {"trace": {"tid", "ps"}} gets its queue-wait /
                # batch-service / ANN-scan spans emitted into THIS replica's
                # sink under the router's trace id — the collector joins
                # them back into one causal timeline. Absent (tracing off),
                # nothing is allocated and the payloads are byte-identical.
                trace = req.get("trace")
                if op == "synonyms":
                    res = service.synonyms(req["word"], int(req.get("num", 10)),
                                           trace=trace)
                    out({"synonyms": [[w, s] for w, s in res]}, req)
                elif op == "synonyms_vec":
                    import numpy as np
                    vec = np.asarray(req["vector"], np.float32)
                    res = service.synonyms(vec, int(req.get("num", 10)))
                    out({"synonyms": [[w, s] for w, s in res]}, req)
                elif op == "synonyms_batch":
                    # many queries, one device dispatch per coalesced batch —
                    # through a thin link per-query round trips dominate
                    # (PERF.md §6); the batcher owns the coalescing now
                    res = service.synonyms_batch(
                        list(req["words"]), int(req.get("num", 10)),
                        trace=trace)
                    out({"synonyms": [[[w, s] for w, s in row] for row in res]},
                        req)
                elif op == "vector":
                    out({"vector": service.vector(req["word"]).tolist()}, req)
                elif op == "reload":
                    model = service.reload_now()
                    out({"reloaded": True, "num_words": model.num_words}, req)
                elif op == "info":
                    i = service.info()
                    out({"num_words": i["num_words"],
                         "vector_size": i["vector_size"],
                         "iteration": i["iteration"],
                         "finished": i["finished"]}, req)
                elif op == "stats":
                    out(service.stats(), req)
                elif op == "quit":
                    out({"bye": True}, req)
                    break
                else:
                    out({"error": f"unknown op {op!r}",
                         "error_type": "ValueError"}, req)
            except Exception as e:  # noqa: BLE001 — protocol errors go to the client
                # machine-readable error payload: the type name routes the
                # caller's policy (ServerOverloaded → retry elsewhere,
                # KeyError → the caller's own error) and retry_after_s is
                # the admission queue's drain-time hint (serve/batcher.py)
                # — pre-ISSUE-12 callers could only blind-retry
                err = {"error": f"{type(e).__name__}: {e}",
                       "error_type": type(e).__name__}
                retry_after = getattr(e, "retry_after_s", None)
                if retry_after is not None:
                    err["retry_after_s"] = retry_after
                out(err, req)
    except BaseException as e:
        # a fatal serve-loop error (not a per-request one — those were
        # answered above) leaves the same dump a dying trainer does
        from glint_word2vec_tpu.obs.blackbox import FlightRecorder
        service.dump_blackbox(FlightRecorder.exception_cause(e))
        raise
    finally:
        service.close()


if __name__ == "__main__":
    main()
