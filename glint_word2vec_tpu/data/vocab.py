"""Vocabulary builder (reference component C1).

Reimplements ``learnVocab`` (mllib/feature/ServerSideGlintWord2Vec.scala:258-279): count
words, drop those with count < min_count, sort by descending count, assign indices in that
order, and record the total count of retained training words (``trainWordsCount``).

The reference does this as a Spark word-count job with a driver-side collect; here it is a
single-pass host-side counter. Multi-host corpora shard by file and merge counters
(:func:`merge_counts`).
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Sequence

import numpy as np


@dataclass
class Vocabulary:
    """Immutable vocabulary: words sorted by descending corpus frequency.

    ``words[i]`` has count ``counts[i]``; ``index[word] == i``. Matches the reference's
    contract that word index order == matrix row order == descending frequency
    (mllib:261-279, save sidecar order mllib:495-496).
    """

    words: List[str]
    counts: np.ndarray  # int64 [vocab_size]
    index: Dict[str, int] = field(repr=False)
    train_words_count: int = 0

    @property
    def size(self) -> int:
        return len(self.words)

    def __len__(self) -> int:
        return len(self.words)

    def __contains__(self, word: str) -> bool:
        return word in self.index

    def get(self, word: str, default: int = -1) -> int:
        return self.index.get(word, default)

    @classmethod
    def from_words_and_counts(cls, words: Sequence[str], counts: Sequence[int]) -> "Vocabulary":
        counts = np.asarray(counts, dtype=np.int64)
        index = {w: i for i, w in enumerate(words)}
        return cls(words=list(words), counts=counts, index=index,
                   train_words_count=int(counts.sum()))

    @classmethod
    def from_counter(cls, counter: "collections.Counter[str]", min_count: int) -> "Vocabulary":
        items = [(w, c) for w, c in counter.items() if c >= min_count]
        if not items:
            raise ValueError(
                "The vocabulary size should be > 0. You may need to check the setting of "
                "min_count, which could be large enough to remove all your words in sentences.")
        # Descending count; stable on first-seen order for ties (the reference's sortWith is
        # likewise stable, mllib:266).
        items.sort(key=lambda wc: -wc[1])
        words = [w for w, _ in items]
        counts = np.fromiter((c for _, c in items), dtype=np.int64, count=len(items))
        index = {w: i for i, w in enumerate(words)}
        return cls(words=words, counts=counts, index=index,
                   train_words_count=int(counts.sum()))


def count_words(sentences: Iterable[Sequence[str]]) -> "collections.Counter[str]":
    counter: "collections.Counter[str]" = collections.Counter()
    for sentence in sentences:
        counter.update(sentence)
    return counter


def _count_slab(slab: List[Sequence[str]]) -> "collections.Counter[str]":
    """Count one slab of sentences. ``Counter`` preserves FIRST-SEEN key
    order, which the slab-order merge relies on (the descending-count
    tie-break in :meth:`Vocabulary.from_counter` ranks equal-count words by
    first appearance, mllib:266). A sort-based ``np.unique`` slab counter was
    measured SLOWER than ``Counter`` for string tokens (hash counting is
    O(n), the string sort O(n log n) with worse constants — hostbench), so
    the hash path stays."""
    counter: "collections.Counter[str]" = collections.Counter()
    for s in slab:
        counter.update(s.tolist() if isinstance(s, np.ndarray) else s)
    return counter


def merge_counts(counters: Iterable["collections.Counter[str]"]) -> "collections.Counter[str]":
    total: "collections.Counter[str]" = collections.Counter()
    for c in counters:
        total.update(c)
    return total


def count_words_parallel(
    sentences: Iterable[Sequence[str]],
    workers: int = 1,
    slab_sentences: int = 50_000,
) -> "collections.Counter[str]":
    """Per-slab parallel word counting with an ordered merge (PERF.md §10).

    Slabs of ``slab_sentences`` sentences are counted independently
    (:func:`_count_slab`) on a ``workers``-thread pool and merged IN SLAB
    ORDER, so the result — counts AND Counter iteration order (first-seen;
    the descending-count tie-break) — is identical to the serial
    :func:`count_words` at any worker count (tested).

    Honesty note (PERF.md §10): counting PYTHON string tokens is GIL-bound —
    ``Counter.update`` never releases the lock — so on stock CPython this
    fan-out is contention, not speedup (measured 0.66x at workers=4;
    a GIL-releasing np.unique slab counter measured slower outright), and
    :func:`build_vocab` therefore routes here only on free-threaded builds.
    The genuinely parallel cold path for file corpora remains the native C++
    counter (``ingest_native``, already multithreaded), which
    :func:`build_vocab` prefers when available."""
    from glint_word2vec_tpu.data.pipeline import ordered_pool_map

    def slabs():
        slab: List[Sequence[str]] = []
        for s in sentences:
            slab.append(s)
            if len(slab) >= slab_sentences:
                yield slab
                slab = []
        if slab:
            yield slab

    return merge_counts(ordered_pool_map(_count_slab, slabs(), workers))


def build_vocab(sentences: Iterable[Sequence[str]], min_count: int = 5,
                workers: int = 1) -> Vocabulary:
    """Count → filter(min_count) → sort desc → index (mllib:258-279).

    Token-file corpora take the native C++ counting pass when available
    (``native/ingest.cpp``, ~4-5× the Python tokenizer) — it returns words in
    the same first-seen order a Python ``Counter`` iterates, so the
    filter/sort below is shared and the vocabulary is identical either way.
    ``workers > 1`` routes the Python path through
    :func:`count_words_parallel` (bit-identical vocabulary, see there)."""
    from glint_word2vec_tpu.data.corpus import TokenFileCorpus
    if isinstance(sentences, TokenFileCorpus) and not sentences.lowercase:
        from glint_word2vec_tpu.data import ingest_native, native
        if ingest_native.ingest_available():
            res = ingest_native.count_words_native(
                sentences.path, native.default_threads())
            if res is not None:
                words, counts = res
                counter = collections.Counter(
                    dict(zip(words, (int(c) for c in counts))))
                return Vocabulary.from_counter(counter, min_count)
    if parallel_counting_profitable(workers):
        return Vocabulary.from_counter(
            count_words_parallel(sentences, workers), min_count)
    return Vocabulary.from_counter(count_words(sentences), min_count)


def parallel_counting_profitable(workers: int = 2) -> bool:
    """Should :func:`build_vocab` fan token counting across ``workers`` threads?

    The ONE owner of this decision (config.py's ``io_workers`` note points
    here). The evidence, so the next session on a different runtime re-measures
    instead of guessing:

    - Stock CPython: ``Counter.update`` over python string tokens never
      releases the GIL, so the slab fan-out is pure contention — MEASURED
      0.66× at ``workers=4`` on the hostbench small tier (PERF.md §10). A
      GIL-releasing ``np.unique`` slab counter measured slower outright
      (string sort O(n log n) vs hash counting O(n)). Verdict: False.
    - Free-threaded CPython (3.13+ ``--disable-gil`` builds,
      ``sys._is_gil_enabled() == False``): the contention argument vanishes
      by construction; the fan-out is expected to scale like the other slab
      pools (NOT yet measured — no free-threaded host has run hostbench).
      Verdict: True, provisionally — the first free-threaded session should
      confirm with ``tools/hostbench.py --scale small`` and update this
      docstring with the number.

    Correctness is not at stake either way: :func:`count_words_parallel` is
    bit-identical to the serial counter at any worker count (tested), so this
    helper only gates throughput.
    """
    if workers <= 1:
        return False
    import sys
    try:
        return not sys._is_gil_enabled()  # free-threaded CPython 3.13+
    except AttributeError:
        return False  # stock CPython: GIL always on


def read_corpus(path: str, lowercase: bool = False) -> Iterator[List[str]]:
    """Whitespace-tokenized line-per-sentence reader (the format of the reference's toy
    corpus, which ships pre-tokenized and lowercased; it spec:22-37)."""
    from glint_word2vec_tpu.train.faults import retry_io

    # only the open retries (graftlint R5): the line iteration is one-shot —
    # re-reading a partially consumed stream would silently duplicate lines
    with retry_io(lambda: open(path, "r", encoding="utf-8"),
                  what=f"open corpus {path!r}") as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            yield [t.lower() for t in toks] if lowercase else toks
