"""Two-process distributed training tests — the scaled-down analog of a multi-host
TPU pod run (G1/G8 replacement; reference boots its PS cluster across executors,
mllib:354-360).

Spawns 2 coordinated JAX processes, each with 4 virtual CPU devices, builds ONE global
(2, 4) mesh spanning both, and trains end-to-end through the Trainer. Two feed modes
(parallel/distributed.py):

- sharded (default): each process generates only its sentence shard; per-round
  allgathers assemble the global batch (the repartition analog, mllib:345);
- replicated: every process regenerates the full stream.

Both must finish in lockstep and agree bit-for-bit on the final
(replicated-checksummed) parameters; the sharded mode additionally proves exact-step
resume from a mid-run sharded checkpoint.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

from glint_word2vec_tpu.parallel.distributed import initialize, is_multiprocess
pid = int(sys.argv[1]); port = sys.argv[2]; mode = sys.argv[3]; workdir = sys.argv[4]
initialize(coordinator_address="127.0.0.1:" + port, num_processes=2, process_id=pid)
assert is_multiprocess()
assert jax.device_count() == 8 and jax.local_device_count() == 4

import numpy as np
from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train.trainer import Trainer

rng = np.random.default_rng(0)
words = [f"w{i}" for i in range(64)]
if mode == "varlen":
    # variable sentence lengths + odd sentence count: data segments exhaust at
    # DIFFERENT rows, driving the iteration-barrier's held-offer/use-mask path
    # (advisor r4 — fixed 12-token sentences never reach it)
    lens = rng.integers(3, 40, 201)
    sentences = [[words[j] for j in rng.integers(0, 64, L)] for L in lens]
else:
    sentences = [[words[j] for j in rng.integers(0, 64, 12)] for _ in range(200)]
vocab = build_vocab(sentences, min_count=1)
cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                     num_iterations=2, window=3, negatives=3, negative_pool=16,
                     steps_per_dispatch=2, seed=7, subsample_ratio=0.0,
                     cbow=(mode in ("cbow", "banded")),
                     cbow_update=("banded" if mode == "banded" else "scatter"),
                     device_pairgen=(mode in ("device", "device42", "dresume",
                                              "eshrink", "egrow", "varlen")),
                     shard_input=(mode in ("sharded", "resume", "cbow", "device",
                                           "device42", "dresume", "eshrink",
                                           "egrow", "varlen", "banded")),
                     # every 2-process test also exercises the SPMD divergence
                     # detector on its real feeds (must stay silent)
                     feed_consistency_check=True)
# spans both processes: 8 global devices; device42 uses a 4-wide data axis so
# each process owns TWO token segments (spp=2 in _fit_device_feed_sharded)
plan = make_mesh(4, 2) if mode in ("device42", "varlen") else make_mesh(2, 4)
encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)

import jax.numpy as jnp
def checksum_of(trainer):
    return float(jax.jit(lambda p: jnp.sum(p.syn0) + 1000.0 * jnp.sum(p.syn1))(
        trainer.params))

def stop_after_first_checkpoint(trainer, encoded, ck):
    # run fit with periodic checkpointing, aborting right after the first
    # mid-run save: leaves a valid mid-iteration checkpoint at ck
    seen = []
    class Stop(Exception):
        pass
    orig = Trainer.save_checkpoint
    def save_once(self, path):
        orig(self, path)
        seen.append(self.state.global_step)
        if len(seen) == 1:
            raise Stop()
    Trainer.save_checkpoint = save_once
    try:
        trainer.fit(encoded, checkpoint_path=ck, checkpoint_every_steps=4)
    except Stop:
        pass
    finally:
        Trainer.save_checkpoint = orig
    assert seen, "no mid-run checkpoint happened"

if mode == "fdiverge":
    # negative path of the SPMD divergence detector: process-DEPENDENT data
    # must be caught by the fingerprint allgather on every process
    trainer = Trainer(cfg, vocab, plan=plan)
    bad = {"x": np.full(8, pid, np.int32)}
    try:
        trainer._assert_feed_consistent(bad, np.zeros((2, 2), np.float32))
        print("DIVERGE missed", flush=True)
    except RuntimeError:
        print("DIVERGE caught", flush=True)
elif mode == "eshrink":
    # 2-process interrupted device-feed run; the parent resumes it on ONE process
    stop_after_first_checkpoint(Trainer(cfg, vocab, plan=plan),
                                encoded, os.path.join(workdir, "ck"))
    print("STOPPED ok", flush=True)
elif mode == "egrow":
    # resume (2 processes) from a single-process checkpoint the parent wrote
    # (dense layout — every process loads the same host arrays; Trainer places)
    ck = os.path.join(workdir, "ck")
    from glint_word2vec_tpu.train.checkpoint import load_model
    m = load_model(ck)
    st = m["train_state"]
    assert st.shard_feed == "tokens" and len(st.shard_progress) == 2
    from glint_word2vec_tpu.ops.sgns import EmbeddingPair
    t2 = Trainer(cfg, vocab, plan=plan,
                 params=EmbeddingPair(m["syn0"], m["syn1"]), train_state=st)
    t2.fit(encoded)
    print(f"CHECKSUM {checksum_of(t2):.10e} steps {t2.global_step}", flush=True)
elif mode in ("resume", "dresume"):
    # uninterrupted run -> reference params
    t_ref = Trainer(cfg, vocab, plan=plan)
    assert t_ref._feed_segments == 2
    t_ref.fit(encoded)
    want = checksum_of(t_ref)
    # interrupted run: checkpoint every 4 global steps, stop after the first save
    ck = os.path.join(workdir, "ck")
    stop_after_first_checkpoint(Trainer(cfg, vocab, plan=plan), encoded, ck)
    from glint_word2vec_tpu.train.checkpoint import load_model_header, load_params_into_plan
    header = load_model_header(ck)
    st = header["train_state"]
    assert st.shard_progress is not None and len(st.shard_progress) == 2
    from glint_word2vec_tpu.parallel.mesh import pad_dim_to_lanes, pad_vocab_for_sharding
    pv = pad_vocab_for_sharding(vocab.size, plan.num_model)
    pd = pad_dim_to_lanes(cfg.vector_size, cfg.pad_vector_to_lanes)
    syn0, syn1 = load_params_into_plan(ck, plan, pv, pd)
    from glint_word2vec_tpu.ops.sgns import EmbeddingPair
    t2 = Trainer(cfg, vocab, plan=plan, params=EmbeddingPair(syn0, syn1),
                 train_state=st)
    t2.fit(encoded)
    got = checksum_of(t2)
    assert abs(got - want) < 1e-4 * max(1.0, abs(want)), (
        f"resumed params diverge: {got!r} vs {want!r}")
    print(f"CHECKSUM {got:.10e} steps {t2.global_step}", flush=True)
else:
    trainer = Trainer(cfg, vocab, plan=plan)
    assert trainer.params.syn0.sharding.is_equivalent_to(plan.embedding, 2)
    assert trainer._feed_segments == (
        2 if mode in ("sharded", "cbow", "device", "device42", "varlen",
                      "banded") else 1)
    trainer.fit(encoded)
    checksum = checksum_of(trainer)
    assert np.isfinite(checksum)
    print(f"CHECKSUM {checksum:.10e} steps {trainer.global_step} "
          f"pairs {trainer.pairs_trained:.0f}", flush=True)
"""


def _run_two(tmp_path, mode, marker="CHECKSUM"):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = "/root/repo" + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), port, mode, str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=420)
        assert p.returncode == 0, f"worker failed:\nstdout:{out}\nstderr:{err[-3000:]}"
        outs.append(out)
    lines = [next(ln for ln in o.splitlines() if ln.startswith(marker))
             for o in outs]
    assert lines[0] == lines[1], f"processes disagree: {lines}"
    return lines[0]


def _parent_device_setup(varlen=False):
    """The worker script's corpus/config/mesh, rebuilt in the parent process
    (8 local virtual devices, single process) for cross-topology comparisons."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.data.pipeline import encode_sentences
    from glint_word2vec_tpu.data.vocab import build_vocab
    from glint_word2vec_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(0)
    words = [f"w{i}" for i in range(64)]
    if varlen:  # must mirror the worker script's "varlen" corpus exactly
        lens = rng.integers(3, 40, 201)
        sentences = [[words[j] for j in rng.integers(0, 64, L)] for L in lens]
    else:
        sentences = [[words[j] for j in rng.integers(0, 64, 12)]
                     for _ in range(200)]
    vocab = build_vocab(sentences, min_count=1)
    cfg = Word2VecConfig(vector_size=16, min_count=1, pairs_per_batch=128,
                         num_iterations=2, window=3, negatives=3,
                         negative_pool=16, steps_per_dispatch=2, seed=7,
                         subsample_ratio=0.0, device_pairgen=True,
                         shard_input=True)
    plan = make_mesh(2, 4)
    encoded = encode_sentences(sentences, vocab, cfg.max_sentence_length)

    def checksum(trainer):
        return float(jax.jit(
            lambda p: jnp.sum(p.syn0) + 1000.0 * jnp.sum(p.syn1))(
                trainer.params))

    return vocab, encoded, cfg, plan, checksum


def _interrupt_at_first_checkpoint(trainer, encoded, ck):
    """Run fit with periodic checkpointing, aborting right after the first
    mid-run save — leaves a valid mid-iteration checkpoint at ck. (The worker
    script carries its own copy; it is self-contained source text.)"""
    from glint_word2vec_tpu.train.trainer import Trainer

    seen = []

    class Stop(Exception):
        pass

    orig = Trainer.save_checkpoint

    def save_once(self, path):
        orig(self, path)
        seen.append(self.state.global_step)
        if len(seen) == 1:
            raise Stop()

    Trainer.save_checkpoint = save_once
    try:
        trainer.fit(encoded, checkpoint_path=ck, checkpoint_every_steps=4)
    except Stop:
        pass
    finally:
        Trainer.save_checkpoint = orig
    assert seen, "no mid-run checkpoint happened"


@pytest.mark.slow
def test_two_process_training_replicated_feed(tmp_path):
    _run_two(tmp_path, "replicated")


@pytest.mark.slow
def test_two_process_training_sharded_feed(tmp_path):
    """Default mode: per-process sentence shards + allgather assembly (mllib:345
    analog). Cross-process checksum agreement proves SPMD consistency of the
    assembled batches, alphas, and collective order."""
    _run_two(tmp_path, "sharded")


@pytest.mark.slow
def test_two_process_cbow_sharded_feed(tmp_path):
    """CBOW on the sharded-input feed (round-4: the allgather protocol carries the
    grouped centers/contexts/count arrays, not just packed pairs)."""
    _run_two(tmp_path, "cbow")


@pytest.mark.slow
def test_two_process_banded_cbow_bit_identity(tmp_path):
    """Banded CBOW (cbow_update='banded') on the sharded token-block feed: the
    halo-overlapped segment streams are deterministic and process-independent
    (pipeline.pack_halo_token_blocks over _device_seg_blocks), so the 2-process
    run must train on the byte-identical feed of the single-process banded run
    — asserted by matching its checksum and exact example count."""
    line = _run_two(tmp_path, "banded")
    got = float(line.split()[1])
    got_pairs = float(line.split()[5])

    from glint_word2vec_tpu.config import Word2VecConfig
    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, encoded, cfg, _, checksum = _parent_device_setup()
    cfg = Word2VecConfig.from_dict(dict(
        cfg.to_dict(), cbow=True, cbow_update="banded",
        device_pairgen=False))
    trainer = Trainer(cfg, vocab, plan=make_mesh(2, 4))
    trainer.fit(encoded)
    want = checksum(trainer)
    assert got_pairs == trainer.pairs_trained, (got_pairs, trainer.pairs_trained)
    assert abs(got - want) < 1e-6 * max(1.0, abs(want)), (got, want)


@pytest.mark.slow
@pytest.mark.parametrize("mode,mesh", [("device", (2, 4)), ("device42", (4, 2)),
                                       ("varlen", (4, 2))])
def test_two_process_device_pairgen_bit_identity(tmp_path, mode, mesh):
    """device_pairgen across processes (round-4): each process packs token blocks
    for its own data segments only; the iteration-barrier allgather protocol
    (trainer._fit_device_feed_sharded) makes the 2-process run train on the
    byte-identical feed the single-process device-feed run sees — asserted here
    by matching the single-process run's checksum and exact pair count. The
    (4, 2) mesh gives each process TWO token segments (spp=2 — exercises the
    per-own-segment assembly, positions, and hash-base slices spp=1 cannot).
    The varlen case (advisor r4) uses variable sentence lengths (3-40 tokens,
    odd sentence count), so the four data segments exhaust at different token
    rows and the barrier's hard path — held offers, use-mask zeroing of
    lagging/leading processes, per-process differing `real` counts — actually
    executes; fixed-length corpora never reach it."""
    line = _run_two(tmp_path, mode)
    got = float(line.split()[1])
    got_pairs = float(line.split()[5])

    from glint_word2vec_tpu.parallel.mesh import make_mesh
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, encoded, cfg, _, checksum = _parent_device_setup(
        varlen=(mode == "varlen"))
    trainer = Trainer(cfg, vocab, plan=make_mesh(*mesh))
    trainer.fit(encoded)
    want = checksum(trainer)
    assert got_pairs == trainer.pairs_trained, (got_pairs, trainer.pairs_trained)
    assert abs(got - want) < 1e-6 * max(1.0, abs(want)), (got, want)


@pytest.mark.slow
def test_elastic_resume_shrink_two_to_one(tmp_path):
    """ELASTIC restart, N -> 1: interrupt a 2-process device-feed run at its
    first checkpoint, then resume it on a SINGLE process. Device-feed positions
    are per data segment (process-independent), so the single process picks up
    all segments and the result matches the uninterrupted single-process run
    (to the < 1-word lr-clock rebuild tolerance)."""
    _run_two(tmp_path, "eshrink", marker="STOPPED")

    from glint_word2vec_tpu.ops.sgns import EmbeddingPair
    from glint_word2vec_tpu.parallel.mesh import (
        pad_dim_to_lanes, pad_vocab_for_sharding)
    from glint_word2vec_tpu.train.checkpoint import (
        load_model_header, load_params_into_plan)
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, encoded, cfg, plan, checksum = _parent_device_setup()
    ref = Trainer(cfg, vocab, plan=plan)
    ref.fit(encoded)
    want = checksum(ref)

    ck = str(tmp_path / "ck")
    st = load_model_header(ck)["train_state"]
    assert st.shard_feed == "tokens" and len(st.shard_progress) == 2
    pv = pad_vocab_for_sharding(vocab.size, plan.num_model)
    pd = pad_dim_to_lanes(cfg.vector_size, cfg.pad_vector_to_lanes)
    syn0, syn1 = load_params_into_plan(ck, plan, pv, pd)
    t2 = Trainer(cfg, vocab, plan=plan, params=EmbeddingPair(syn0, syn1),
                 train_state=st)
    t2.fit(encoded)
    got = checksum(t2)
    assert abs(got - want) < 1e-4 * max(1.0, abs(want)), (got, want)

    # double-resume: a checkpoint written AFTER an elastic resume has row
    # counts offset from the canonical stream, so it must persist
    # batches_done=0 and keep the per-segment positions authoritative — a
    # second resume then lands correctly too
    from glint_word2vec_tpu.train.checkpoint import load_model
    syn0b, syn1b = load_params_into_plan(ck, plan, pv, pd)
    t3 = Trainer(cfg, vocab, plan=plan, params=EmbeddingPair(syn0b, syn1b),
                 train_state=st)
    ck2 = str(tmp_path / "ck2")
    _interrupt_at_first_checkpoint(t3, encoded, ck2)
    m2 = load_model(ck2)
    st2 = m2["train_state"]
    assert st2.batches_done == 0 and st2.shard_feed == "tokens"
    t4 = Trainer(cfg, vocab, plan=plan,
                 params=EmbeddingPair(m2["syn0"], m2["syn1"]), train_state=st2)
    t4.fit(encoded)
    got2 = checksum(t4)
    assert abs(got2 - want) < 1e-4 * max(1.0, abs(want)), (got2, want)


@pytest.mark.slow
def test_elastic_resume_grow_one_to_two(tmp_path):
    """ELASTIC restart, 1 -> N: interrupt a single-process device-feed run at
    its first checkpoint (which now records per-segment positions alongside its
    own batches_done), then resume it on 2 processes; the result matches the
    uninterrupted single-process run."""
    from glint_word2vec_tpu.train.trainer import Trainer

    vocab, encoded, cfg, plan, checksum = _parent_device_setup()
    ref = Trainer(cfg, vocab, plan=plan)
    ref.fit(encoded)
    want = checksum(ref)

    # interrupted single-process run -> mid-iteration checkpoint at tmp_path/ck
    _interrupt_at_first_checkpoint(
        Trainer(cfg, vocab, plan=plan), encoded, str(tmp_path / "ck"))

    line = _run_two(tmp_path, "egrow")
    got = float(line.split()[1])
    assert abs(got - want) < 1e-4 * max(1.0, abs(want)), (got, want)


@pytest.mark.slow
def test_two_process_device_pairgen_resume(tmp_path):
    """Interrupt a 2-process device-feed run at its first mid-run checkpoint and
    resume from the row-shards checkpoint: shard_progress indexes token-step rows
    (shard_feed="tokens") and the within-iteration lr clock is rebuilt from the
    saved word count, so the resumed run matches the uninterrupted one."""
    _run_two(tmp_path, "dresume")


@pytest.mark.slow
def test_feed_consistency_detector_catches_divergence(tmp_path):
    """The SPMD feed-divergence detector (config.feed_consistency_check) must
    flag process-dependent feed content; its silent pass on real feeds is
    covered by every other 2-process test (the flag is on in the worker)."""
    line = _run_two(tmp_path, "fdiverge", marker="DIVERGE")
    assert line == "DIVERGE caught"


@pytest.mark.slow
def test_two_process_sharded_resume(tmp_path):
    """Interrupt a sharded-feed run at its first mid-run checkpoint, resume from the
    row-shards checkpoint (per-process stream positions from shard_progress), and
    match the uninterrupted run's final params exactly."""
    _run_two(tmp_path, "resume")
