"""R6 bad: raw device placement in the trainer outside the staging
discipline."""
import jax


class Trainer:
    def _fit(self, arrays):
        staged = {k: jax.device_put(v) for k, v in arrays.items()}
        return staged
