"""Row-shards persistence tests (G9 analog: PS-side shard write, mllib:493-497):
save from a sharded mesh without host gather, reload dense, reload streamed onto a
DIFFERENT mesh (the reference's load-onto-new-PS-topology path, mllib:696-725)."""

import os

import jax
import numpy as np
import pytest

from glint_word2vec_tpu.config import Word2VecConfig
from glint_word2vec_tpu.data.pipeline import encode_sentences
from glint_word2vec_tpu.data.vocab import build_vocab
from glint_word2vec_tpu.parallel.mesh import make_mesh
from glint_word2vec_tpu.train.checkpoint import (
    ShardedMatrixReader,
    load_model,
    load_params_into_plan,
)
from glint_word2vec_tpu.train.trainer import Trainer


def _small_corpus(n=120, v=50, seed=0):
    rng = np.random.default_rng(seed)
    words = [f"w{i}" for i in range(v)]
    return [[words[j] for j in rng.integers(0, v, 10)] for _ in range(n)]


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    sents = _small_corpus()
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=12, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3, negative_pool=8,
                         steps_per_dispatch=2, seed=3, sharded_checkpoint=True)
    plan = make_mesh(2, 4)  # 8-device CPU mesh: embeddings sharded 4-way over rows
    trainer = Trainer(cfg, vocab, plan=plan)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    path = str(tmp_path_factory.mktemp("ckpt") / "model")
    trainer.save_checkpoint(path)
    return trainer, vocab, cfg, path


def test_sharded_save_writes_per_shard_files(trained):
    trainer, vocab, cfg, path = trained
    shard_dir = os.path.join(path, "syn0.shards")
    files = sorted(os.listdir(shard_dir))
    assert len(files) == trainer.plan.num_model  # one file per model shard
    total_rows = 0
    for f in files:
        arr = np.load(os.path.join(shard_dir, f))
        assert arr.shape[0] < trainer.padded_vocab  # strictly partial — no full dump
        total_rows += arr.shape[0]
    assert total_rows == trainer.padded_vocab
    assert os.path.exists(os.path.join(path, "words"))  # sidecar parity kept


def test_sharded_load_dense_matches_device_state(trained):
    trainer, vocab, cfg, path = trained
    data = load_model(path)
    assert data["syn0"].shape == (vocab.size, cfg.vector_size)
    want = np.asarray(trainer.unpadded_params().syn0)
    np.testing.assert_array_equal(data["syn0"], want)
    assert data["syn1"].shape == want.shape
    assert data["train_state"].finished


def test_sharded_reader_row_ranges(trained):
    trainer, vocab, cfg, path = trained
    r = ShardedMatrixReader(os.path.join(path, "syn0.shards"))
    assert r.rows == trainer.padded_vocab
    full = r.read_all()
    np.testing.assert_array_equal(r.read(5, 17), full[5:17])
    # a read spanning a shard boundary
    per = trainer.padded_vocab // trainer.plan.num_model
    np.testing.assert_array_equal(r.read(per - 2, per + 2), full[per - 2:per + 2])


def test_bfloat16_sharded_round_trip(tmp_path):
    """bf16 params survive the row-shards round trip (round-5 regression: np.save
    writes ml_dtypes.bfloat16 as raw '|V2' void and np.load hands the void dtype
    back — the reader must re-view the bytes as bfloat16, or every read of a
    bf16 checkpoint dies with 'No cast function available')."""
    import jax.numpy as jnp
    import ml_dtypes

    sents = _small_corpus(seed=5)
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=12, min_count=1, pairs_per_batch=128,
                         num_iterations=1, window=2, negatives=3, negative_pool=8,
                         steps_per_dispatch=2, seed=4, sharded_checkpoint=True,
                         param_dtype="bfloat16", compute_dtype="bfloat16")
    plan = make_mesh(2, 4)
    trainer = Trainer(cfg, vocab, plan=plan)
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    path = str(tmp_path / "model")
    trainer.save_checkpoint(path)

    V = vocab.size
    r = ShardedMatrixReader(os.path.join(path, "syn0.shards"))
    assert r.dtype == np.dtype(ml_dtypes.bfloat16)
    want = np.asarray(trainer.params.syn0)  # padded, bf16
    np.testing.assert_array_equal(
        r.read_all().view(np.uint16), want.view(np.uint16))

    # streamed load: bit-identical over the REAL vocab rows (the loader zeroes
    # vocab-padding rows, whose random init is semantically dead); f32 load
    # (the default) is the exact upcast of the same rows
    syn0_b, _ = load_params_into_plan(path, plan, trainer.padded_vocab,
                                      trainer.padded_dim, dtype=jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(syn0_b)[:V].view(np.uint16),
                                  want[:V].view(np.uint16))
    syn0_f, _ = load_params_into_plan(path, plan, trainer.padded_vocab,
                                      trainer.padded_dim)
    np.testing.assert_array_equal(np.asarray(syn0_f)[:V],
                                  want[:V].astype(np.float32))

    # and the model-level streamed load path serves queries from it
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    m = Word2VecModel.load(path, plan=plan)
    syns = m.find_synonyms("w0", 5)
    assert len(syns) == 5 and all(np.isfinite(s) for _, s in syns)


def test_load_params_into_different_mesh(trained):
    """Stream the checkpoint onto a different topology (4x2 instead of 2x4) —
    numParameterServers retargeting, without a dense host copy."""
    trainer, vocab, cfg, path = trained
    plan2 = make_mesh(4, 2)
    from glint_word2vec_tpu.parallel.mesh import pad_vocab_for_sharding
    pv = pad_vocab_for_sharding(vocab.size, plan2.num_model)
    syn0, syn1 = load_params_into_plan(path, plan2, pv, trainer.padded_dim)
    assert syn0.shape == (pv, trainer.padded_dim)
    assert syn0.sharding.is_equivalent_to(plan2.embedding, 2)
    want = np.asarray(trainer.unpadded_params().syn0)
    got = np.asarray(syn0)[:vocab.size, :cfg.vector_size]
    np.testing.assert_array_equal(got, want)

    # and a Trainer accepts the streamed params directly (resume-on-new-mesh)
    from glint_word2vec_tpu.ops.sgns import EmbeddingPair
    t2 = Trainer(cfg, vocab, plan=plan2, params=EmbeddingPair(syn0, syn1))
    assert t2.params.syn0 is syn0  # no re-pad, no re-place
    sents = _small_corpus(40)
    t2.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    assert np.isfinite(np.asarray(t2.params.syn0)).all()


def test_feasibility_10m_shapes():
    """10M x 300 north-star shape check: per-shard bytes on an 8-way model mesh stay
    ~1.5 GB (vs 12 GB dense), computed via eval_shape — nothing is allocated."""
    from glint_word2vec_tpu.parallel.mesh import pad_vocab_for_sharding
    V, Dr, ways = 10_000_000, 384, 8
    pv = pad_vocab_for_sharding(V, ways)
    shape = jax.eval_shape(
        lambda: jax.ShapeDtypeStruct((pv, Dr), jax.numpy.float32))
    per_shard_bytes = shape.shape[0] // ways * shape.shape[1] * 4
    assert per_shard_bytes < 2 * 1024 ** 3
    assert shape.shape[0] * shape.shape[1] * 4 > 12 * 1024 ** 3  # dense would be >12 GB


def test_model_load_sharded_no_dense_copy(trained, monkeypatch):
    """Word2VecModel.load(path, plan=...) on a row-shards checkpoint must stream
    through load_params_into_plan — the dense load_model path (which materializes
    [V, D] on host, prohibitive at the 10M x 300 north star) must never run."""
    trainer, vocab, cfg, path = trained
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    from glint_word2vec_tpu.train import checkpoint as ckpt

    def boom(_path):
        raise AssertionError("dense load_model must not be called on the sharded path")

    monkeypatch.setattr(ckpt, "load_model", boom)
    plan2 = make_mesh(1, 8)  # different topology than the 2x4 writer
    model = Word2VecModel.load(path, plan=plan2)
    assert model._full0.sharding.is_equivalent_to(plan2.embedding, 2)
    assert model._full0.shape[0] % 8 == 0

    # model ops run on the sharded arrays
    want = np.asarray(trainer.unpadded_params().syn0)
    got = model.pull(list(range(vocab.size)))
    np.testing.assert_array_equal(got, want[:, :cfg.vector_size])
    w = vocab.words[0]
    syns = model.find_synonyms(w, 3)
    assert len(syns) == 3 and all(s != w for s, _ in syns)
    # padded rows are masked out of top-k: no index >= vocab.size can surface
    allv = model.find_synonyms(np.asarray(want[0]), vocab.size)
    assert all(s in vocab.words for s, _ in allv)


def test_model_load_dense_checkpoint_with_plan(tmp_path):
    """Dense checkpoints still load (and get placed) when a plan is given."""
    from glint_word2vec_tpu.models.word2vec import Word2VecModel
    sents = _small_corpus(60)
    vocab = build_vocab(sents, min_count=1)
    cfg = Word2VecConfig(vector_size=8, min_count=1, pairs_per_batch=64,
                         num_iterations=1, window=2, negatives=2, negative_pool=8,
                         steps_per_dispatch=2, seed=5)
    trainer = Trainer(cfg, vocab, plan=make_mesh(1, 1))
    trainer.fit(encode_sentences(sents, vocab, cfg.max_sentence_length))
    path = str(tmp_path / "dense")
    trainer.save_checkpoint(path)
    plan = make_mesh(2, 4)
    model = Word2VecModel.load(path, plan=plan)
    assert model._full0.sharding.is_equivalent_to(plan.embedding, 2)
    np.testing.assert_allclose(
        model.pull([0, 1]), np.asarray(trainer.unpadded_params().syn0)[:2], rtol=1e-6)


def test_estimator_resume_streams_sharded_checkpoint(trained, monkeypatch, tmp_path):
    """Word2Vec.resume(path, plan=...) on a row-shards checkpoint streams params onto
    the mesh — the dense load path (full [V, D] on one host) must never run."""
    trainer, vocab, cfg, path = trained
    from glint_word2vec_tpu.models.estimator import Word2Vec
    from glint_word2vec_tpu.train import checkpoint as ckpt

    # a mid-run checkpoint: mark unfinished so resume actually trains
    st = ckpt.TrainState(iteration=1, words_processed=0, finished=False,
                         global_step=trainer.global_step, batches_done=0)
    from glint_word2vec_tpu.train.checkpoint import save_model_sharded
    ck = str(tmp_path / "midrun")
    save_model_sharded(ck, vocab.words, vocab.counts,
                       trainer.params.syn0, trainer.params.syn1, cfg, st,
                       vocab_size=vocab.size, vector_size=cfg.vector_size)

    def boom(_path, header=None):
        raise AssertionError("dense load_model must not run on the streamed path")

    monkeypatch.setattr(ckpt, "load_model", boom)
    plan2 = make_mesh(2, 4)
    sents = _small_corpus(60)
    model = Word2Vec.resume(ck, sents, plan=plan2)
    assert model.num_words == vocab.size
    assert np.isfinite(model.pull([0, 1])).all()
